"""Protocol-trace runtime oracle: the ``collective`` telemetry events
``guarded_collective`` emits under ``protocol_trace`` (ISSUE 16), and
the ``fmtrace --collectives`` diff that turns per-rank streams into a
divergence verdict. Ends with the real 2-process acceptance run: a
traced dist_train whose ranks must post bit-identical sequences."""

import json
import os
import sys

import numpy as np
import pytest

import fast_tffm_tpu.parallel.liveness as liveness
from fast_tffm_tpu.obs.sink import read_events
from fast_tffm_tpu.obs.telemetry import RunTelemetry, activate
from fast_tffm_tpu.parallel.liveness import (enable_protocol_trace,
                                             guarded_collective,
                                             protocol_trace_enabled)
from tools.fmtrace import collective_sequences, diff_collectives
from tools.fmtrace import main as fmtrace_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_trace_state(monkeypatch):
    """The enable override and the env parse are module-global caches;
    every test starts from the unset state."""
    monkeypatch.delenv("FM_PROTOCOL_TRACE", raising=False)
    monkeypatch.setattr(liveness, "_PROTOCOL_TRACE", None)
    monkeypatch.setattr(liveness, "_PROTOCOL_ENV", None)
    monkeypatch.setattr(liveness, "_PROTOCOL_SEQ", 0)


def test_trace_switch_precedence(tmp_path, monkeypatch):
    """enable_protocol_trace() beats the env, the env beats the active
    run's knob, and the default is off."""
    assert not protocol_trace_enabled()
    monkeypatch.setenv("FM_PROTOCOL_TRACE", "1")
    # The env parse is cached once per process (the check sits on every
    # collective); flip the cache back to unset to re-read it.
    monkeypatch.setattr(liveness, "_PROTOCOL_ENV", None)
    assert protocol_trace_enabled()
    enable_protocol_trace(False)  # explicit override wins over env
    assert not protocol_trace_enabled()
    enable_protocol_trace(True)
    assert protocol_trace_enabled()
    # Back to unset: the active telemetry's knob is the fallback.
    monkeypatch.setattr(liveness, "_PROTOCOL_TRACE", None)
    monkeypatch.setattr(liveness, "_PROTOCOL_ENV", None)
    monkeypatch.delenv("FM_PROTOCOL_TRACE")
    tel = RunTelemetry(str(tmp_path / "m.jsonl"), meta={},
                       protocol_trace=True)
    with activate(tel):
        assert protocol_trace_enabled()
    assert not protocol_trace_enabled()
    tel.close(0)


def test_env_off_values_do_not_enable(monkeypatch):
    for raw in ("", "0", "false", "no", " False "):
        monkeypatch.setattr(liveness, "_PROTOCOL_ENV", None)
        monkeypatch.setenv("FM_PROTOCOL_TRACE", raw)
        assert not protocol_trace_enabled(), repr(raw)


def test_guarded_collective_emits_ordered_events(tmp_path):
    """Each traced wrap emits one ``collective`` event BEFORE the op
    runs, with a per-process monotonic seq, the protocol label, and the
    wrapped callable's name."""
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={"process_index": 0},
                       protocol_trace=True)

    def agree(x):
        return x

    with activate(tel):
        assert guarded_collective(agree, 7, label="demo/agree") == 7
        assert guarded_collective(agree, 8, label="demo/pick") == 8
        # Not a collective program: excluded from the protocol stream.
        assert guarded_collective(agree, 9, label="score/fetch",
                                  collective=False) == 9
    tel.close(0)
    evs = [r for r in read_events(path) if r.get("event") == "collective"]
    assert [(e["seq"], e["label"], e["op"]) for e in evs] == [
        (1, "demo/agree", "agree"), (2, "demo/pick", "agree")]


def test_trace_off_emits_nothing(tmp_path):
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={"process_index": 0})
    with activate(tel):
        guarded_collective(lambda x: x, 1, label="demo/agree")
    tel.close(0)
    assert not [r for r in read_events(path)
                if r.get("event") == "collective"]


def _shard(tmp_path, name, pid, labels, start_seq=1):
    """A minimal telemetry stream: run_start naming the rank, then one
    ``collective`` event per label."""
    path = str(tmp_path / name)
    with open(path, "w") as fh:
        fh.write(json.dumps({"event": "run_start", "t": 0.0,
                             "meta": {"process_index": pid}}) + "\n")
        for i, label in enumerate(labels):
            fh.write(json.dumps({"event": "collective", "t": float(i),
                                 "seq": start_seq + i,
                                 "label": label}) + "\n")
    return path


def test_collective_sequences_orders_by_seq(tmp_path):
    # Seq counters, not file order, define the protocol order.
    path = str(tmp_path / "m.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps({"event": "run_start", "t": 0.0,
                             "meta": {"process_index": 3}}) + "\n")
        for seq, label in ((2, "b"), (1, "a"), (3, "c")):
            fh.write(json.dumps({"event": "collective", "seq": seq,
                                 "label": label}) + "\n")
    assert collective_sequences([path]) == {3: ["a", "b", "c"]}


def test_diff_collectives_identical_and_divergent(tmp_path, capsys):
    a = _shard(tmp_path, "m.jsonl", 0, ["ckpt/agree", "train/step"])
    b = _shard(tmp_path, "m.jsonl.p1", 1, ["ckpt/agree", "train/step"])
    assert diff_collectives(collective_sequences([a, b]),
                            out=sys.stdout) == 0
    assert "sequences identical" in capsys.readouterr().out

    c = _shard(tmp_path, "n.jsonl.p1", 1, ["ckpt/agree", "ckpt/bcast"])
    assert diff_collectives(collective_sequences([a, c]),
                            out=sys.stdout) == 1
    out = capsys.readouterr().out
    assert "DIVERGE at position 1" in out
    assert "rank 0: train/step" in out and "rank 1: ckpt/bcast" in out


def test_diff_collectives_short_stream_and_empty(tmp_path, capsys):
    a = _shard(tmp_path, "m.jsonl", 0, ["ckpt/agree", "train/step"])
    b = _shard(tmp_path, "m.jsonl.p1", 1, ["ckpt/agree"])
    assert diff_collectives(collective_sequences([a, b]),
                            out=sys.stdout) == 1
    out = capsys.readouterr().out
    assert "rank 1: <end of sequence>" in out
    assert diff_collectives({}, out=sys.stdout) == 1
    assert "no collective events" in capsys.readouterr().out


def test_fmtrace_cli_collectives_flag(tmp_path, capsys):
    a = _shard(tmp_path, "m.jsonl", 0, ["ckpt/agree"])
    b = _shard(tmp_path, "m.jsonl.p1", 1, ["ckpt/agree"])
    assert fmtrace_main(["--collectives", a, b]) == 0
    c = _shard(tmp_path, "d.jsonl", 0, ["ckpt/agree"])
    d = _shard(tmp_path, "d.jsonl.p1", 1, ["train/step"])
    assert fmtrace_main(["--collectives", c, d]) == 1
    # No trace output file side effects in diff mode.
    assert not [f for f in os.listdir(tmp_path)
                if f.endswith(".trace.json")]


@pytest.mark.slow
def test_two_process_run_posts_identical_sequences(tmp_path, rng,
                                                   monkeypatch):
    """ISSUE 16 acceptance: a REAL 2-process train run under
    ``FM_PROTOCOL_TRACE`` (the worker subprocesses inherit it; the
    ``protocol_trace`` knob is the config spelling of the same switch)
    yields per-rank collective sequences that ``fmtrace --collectives``
    proves bit-identical — the runtime ground truth for everything
    R014 checks statically."""
    from tests.test_multiprocess import (_free_port, _launch_mode,
                                         _rerun_on_worker_signal)
    monkeypatch.setenv("FM_PROTOCOL_TRACE", "1")

    @_rerun_on_worker_signal(times=2)
    def _run(workdir):
        lines = []
        for _ in range(97):
            nnz = rng.integers(2, 8)
            ids = rng.choice(64, size=nnz, replace=False)
            lines.append(" ".join(
                ["1" if rng.random() < 0.5 else "0"]
                + [f"{i}:{rng.random():.3f}" for i in ids]))
        data = workdir / "train.txt"
        data.write_text("\n".join(lines) + "\n")
        model = workdir / "model" / "fm"
        metrics = workdir / "m.jsonl"
        coord = _free_port()
        cfg = workdir / "dist.cfg"
        cfg.write_text(f"""
[General]
vocabulary_size = 64
factor_num = 4
model_file = {model}

[Train]
train_files = {data}
validation_files = {data}
epoch_num = 2
batch_size = 32
learning_rate = 0.1
shuffle = False
metrics_file = {metrics}
protocol_trace = true

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
""")
        return _launch_mode(cfg, "train"), metrics

    outs, metrics = _run(tmp_path)
    assert any("training done" in o for o in outs)
    shards = [str(metrics), str(metrics) + ".p1"]
    assert all(os.path.exists(s) for s in shards), shards
    seqs = collective_sequences(shards)
    assert sorted(seqs) == [0, 1]
    assert seqs[0] and seqs[0] == seqs[1], (
        f"rank0={seqs[0][:10]}... rank1={seqs[1][:10]}...")
    assert fmtrace_main(["--collectives"] + shards) == 0
