"""Bad-line policy: tolerant parsing (Python + C++ salvage), the
tracker's counting/quarantine/breaker, pipeline-level skip accounting,
and the file/lineno provenance in strict-mode ParseErrors."""

import dataclasses
import json
import os

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.badlines import (MIN_BAD_LINES_TO_TRIP,
                                         BadInputError, BadLineTracker)
from fast_tffm_tpu.data.cparser import parse_lines_salvage
from fast_tffm_tpu.data.parser import ParseError, parse_lines
from fast_tffm_tpu.data.pipeline import (_fast_path_eligible,
                                         batch_iterator,
                                         gil_bound_iteration)


def _cfg(tmp_path, train_file, **overrides):
    base = dict(vocabulary_size=50, factor_num=2, batch_size=8,
                epoch_num=1, shuffle=False,
                train_files=(str(train_file),),
                model_file=str(tmp_path / "model" / "fm"))
    base.update(overrides)
    return FmConfig(**base)


def _write(path, lines):
    path.write_text("\n".join(lines) + "\n")
    return str(path)


GOOD = [f"1 {i % 40}:1.0 {(i + 3) % 40}:0.5" for i in range(40)]


# --- parser tolerant mode -----------------------------------------------


def test_parse_lines_tolerant_skips_and_records():
    bads = []
    lines = list(GOOD[:3]) + ["x 1:1", "0 2:zz"] + list(GOOD[3:6])
    block = parse_lines(lines, 50, bad_lines=bads)
    assert block.batch_size == 6
    assert [b[0] for b in bads] == [3, 4]
    assert "bad label" in bads[0][2] and "bad value" in bads[1][2]


def test_parse_lines_tolerant_rolls_back_partial_example():
    # The bad token is mid-line: the label and the first good token
    # must not leak into the block.
    bads = []
    block = parse_lines(["1 2:1.0 3:zz 4:1.0", "0 5:2.0"], 50,
                        bad_lines=bads)
    assert block.batch_size == 1
    assert block.labels.tolist() == [0.0]
    assert block.ids.tolist() == [5]
    assert len(bads) == 1


def test_parse_lines_strict_mode_unchanged():
    with pytest.raises(ParseError, match="line 1"):
        parse_lines(["0 1:1", "nope"], 50)


def test_salvage_matches_python_on_good_lines():
    bads = []
    lines = list(GOOD[:5]) + ["##broken##"] + list(GOOD[5:9])
    got = parse_lines_salvage(lines, 50, bad_lines=bads)
    want = parse_lines(list(GOOD[:9]), 50)
    assert len(bads) == 1 and bads[0][0] == 5
    np.testing.assert_array_equal(got.labels, want.labels)
    np.testing.assert_array_equal(got.poses, want.poses)
    np.testing.assert_array_equal(got.ids, want.ids)
    np.testing.assert_array_equal(got.vals, want.vals)


def test_salvage_clean_block_uses_fast_path_output():
    got = parse_lines_salvage(list(GOOD[:4]), 50, bad_lines=[])
    want = parse_lines(list(GOOD[:4]), 50)
    np.testing.assert_array_equal(got.ids, want.ids)


# --- tracker -------------------------------------------------------------


def test_tracker_breaker_names_worst_file(tmp_path):
    t = BadLineTracker("skip", max_bad_fraction=0.01)
    t.count_ok(100)
    for i in range(MIN_BAD_LINES_TO_TRIP - 1):
        t.record("ok_ish.txt" if i == 0 else "rotten.txt", i + 1,
                 "raw", "err")
    with pytest.raises(BadInputError) as ei:
        t.record("rotten.txt", 99, "raw", "err")
    msg = str(ei.value)
    assert "rotten.txt" in msg and "max_bad_fraction" in msg


def test_tracker_below_floor_never_trips():
    t = BadLineTracker("skip", max_bad_fraction=0.0)
    for i in range(MIN_BAD_LINES_TO_TRIP - 1):
        t.record("f.txt", i + 1, "raw", "err")  # 100% bad, under floor


def test_tracker_quarantine_dedupes(tmp_path):
    q = str(tmp_path / "q.jsonl")
    t = BadLineTracker("quarantine", 1.0, quarantine_file=q)
    t.count_ok(1000)
    for _ in range(3):  # same line seen on three epochs
        t.record("f.txt", 7, "raw line", "bad label")
    t.record("f.txt", 9, "other", "bad value")
    t.close()
    recs = [json.loads(ln) for ln in open(q)]
    assert [(r["file"], r["lineno"]) for r in recs] == [
        ("f.txt", 7), ("f.txt", 9)]
    assert recs[0]["raw"] == "raw line"
    assert t.bad == 4  # every occurrence still counts


def test_tracker_health_events_rate_limited(tmp_path):
    from fast_tffm_tpu.obs.sink import read_events
    from fast_tffm_tpu.obs.telemetry import RunTelemetry, activate
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})
    t = BadLineTracker("skip", 1.0)
    t.count_ok(10000)
    with activate(tel):
        for i in range(100):
            t.record("f.txt", i + 1, "raw", "err")
    tel.close(0)
    evs = [e for e in read_events(path)
           if e.get("event") == "health"
           and e.get("status") == "bad_input"]
    # Power-of-two schedule: bad counts 1, 2, 4, 8, 16, 32, 64 emit.
    assert [e["bad_lines"] for e in evs] == [1, 2, 4, 8, 16, 32, 64]
    assert tel.registry.snapshot()["counters"][
        "pipeline/bad_lines"] == 100


# --- pipeline integration ------------------------------------------------


def test_pipeline_skip_counts_exact(tmp_path):
    lines = list(GOOD)
    lines[5] = "x bad"
    lines[17] = "0 3:zz"
    p = _write(tmp_path / "t.txt", lines)
    cfg = _cfg(tmp_path, p, bad_line_policy="skip",
               max_bad_fraction=0.5)
    n = sum(b.num_real for b in batch_iterator(cfg, [p], epochs=1))
    assert n == len(lines) - 2


def test_pipeline_quarantine_records_absolute_linenos(tmp_path):
    lines = list(GOOD)
    lines[11] = "##garbage##"
    p = _write(tmp_path / "t.txt", lines)
    cfg = _cfg(tmp_path, p, bad_line_policy="quarantine",
               max_bad_fraction=0.5)
    list(batch_iterator(cfg, [p], epochs=1))
    from fast_tffm_tpu.data.badlines import quarantine_path
    recs = [json.loads(ln) for ln in open(quarantine_path(cfg))]
    assert [(r["file"], r["lineno"]) for r in recs] == [(p, 12)]
    assert recs[0]["raw"] == "##garbage##"


def test_pipeline_breaker_aborts_naming_file(tmp_path):
    lines = ["completely broken"] * 30 + list(GOOD[:10])
    p = _write(tmp_path / "rot.txt", lines)
    cfg = _cfg(tmp_path, p, bad_line_policy="skip",
               max_bad_fraction=0.01)
    with pytest.raises(BadInputError, match="rot.txt"):
        list(batch_iterator(cfg, [p], epochs=1))


def test_keep_empty_skip_preserves_line_alignment(tmp_path):
    # Predict's contract: one example per input line even when a line
    # is bad — it becomes a zero-feature example, never a dropped row.
    lines = list(GOOD[:10])
    lines[4] = "broken line here"
    p = _write(tmp_path / "t.txt", lines)
    cfg = _cfg(tmp_path, p, bad_line_policy="skip",
               max_bad_fraction=0.5)
    n = sum(b.num_real for b in batch_iterator(
        cfg, [p], training=False, epochs=1, keep_empty=True))
    assert n == len(lines)


def test_multi_epoch_run_scoped_tracker(tmp_path):
    lines = list(GOOD)
    lines[3] = "zzz"
    p = _write(tmp_path / "t.txt", lines)
    cfg = _cfg(tmp_path, p, bad_line_policy="skip",
               max_bad_fraction=0.5)
    tracker = BadLineTracker.from_config(cfg)
    for _ in range(3):
        list(batch_iterator(cfg, [p], epochs=1, bad_lines=tracker))
    assert tracker.bad == 3
    assert tracker.total == 3 * len(lines)
    tracker.close()


# --- strict-mode provenance (satellite: findable bad lines) -------------


def test_fast_path_error_names_file_and_line(tmp_path):
    a = _write(tmp_path / "a.txt", GOOD[:20])
    lines = list(GOOD[:15])
    lines[6] = "1 3:bogus_value"
    b = _write(tmp_path / "b.txt", lines)
    cfg = _cfg(tmp_path, a)
    with pytest.raises(ParseError) as ei:
        list(batch_iterator(cfg, [a, b], epochs=1))
    msg = str(ei.value)
    assert f"{b} line 7" in msg, msg
    assert "bogus_value" in msg


def test_generic_path_error_names_file_and_line(tmp_path):
    # Weight sidecars force the generic (per-line Python) path.
    lines = list(GOOD[:12])
    lines[9] = "x no good"
    p = _write(tmp_path / "t.txt", lines)
    w = _write(tmp_path / "t.weights", ["1.0"] * len(lines))
    cfg = _cfg(tmp_path, p)
    with pytest.raises(ParseError) as ei:
        list(batch_iterator(cfg, [p], weight_files=[w], epochs=1))
    assert f"{p} line 10" in str(ei.value), str(ei.value)


def test_sharded_error_carries_shard_note(tmp_path):
    lines = list(GOOD)
    lines[35] = "###"
    p = _write(tmp_path / "t.txt", lines)
    cfg = _cfg(tmp_path, p)
    raised = None
    for shard in range(2):
        try:
            list(batch_iterator(cfg, [p], epochs=1, shard_index=shard,
                                num_shards=2))
        except ParseError as e:
            raised = str(e)
    assert raised is not None
    assert f"{p} line 36" in raised, raised
    assert "shard" in raised


# --- routing + config ----------------------------------------------------


def test_tolerant_policy_gates_off_streaming_fast_path(tmp_path):
    cfg = _cfg(tmp_path, "x")
    assert _fast_path_eligible(cfg, ())
    tol = dataclasses.replace(cfg, bad_line_policy="skip")
    assert not _fast_path_eligible(tol, ())
    # gil_bound answer stays consistent with the path actually taken.
    assert gil_bound_iteration(tol) or not os.path.exists(
        os.path.join(os.path.dirname(__file__), "..", "fast_tffm_tpu",
                     "data", "_parser.so"))


def test_config_rejects_bad_knobs(tmp_path):
    with pytest.raises(ValueError, match="bad_line_policy"):
        FmConfig(bad_line_policy="ignore")
    with pytest.raises(ValueError, match="max_bad_fraction"):
        FmConfig(max_bad_fraction=1.5)
    with pytest.raises(ValueError, match="io_retries"):
        FmConfig(io_retries=-1)
    with pytest.raises(ValueError, match="io_backoff_seconds"):
        FmConfig(io_backoff_seconds=-0.1)


def test_config_file_accepts_fault_knobs(tmp_path):
    from fast_tffm_tpu.config import load_config
    cfg_path = tmp_path / "fm.cfg"
    cfg_path.write_text(
        "[Train]\nbad_line_policy = quarantine\n"
        "max_bad_fraction = 0.05\nio_retries = 4\n"
        "io_backoff_seconds = 0.25\n")
    cfg = load_config(str(cfg_path))
    assert cfg.bad_line_policy == "quarantine"
    assert cfg.max_bad_fraction == 0.05
    assert cfg.io_retries == 4
    assert cfg.io_backoff_seconds == 0.25


# --- review-fix regressions ---------------------------------------------


def test_chunk_read_retry_never_skips_bytes(tmp_path):
    """A partial buffered read advances the file position before
    raising; the retry must seek back to the chunk start or bytes are
    silently lost (truncated/merged lines — corrupted training data)."""
    import builtins
    import errno
    from fast_tffm_tpu.data.pipeline import _iter_owned_chunks
    from fast_tffm_tpu.utils.retry import RetryPolicy
    p = tmp_path / "t.txt"
    content = b"".join(b"%d 1:1.0 2:0.5\n" % i for i in range(2000))
    p.write_bytes(content)

    class PartialThenFail:
        """File wrapper: the first read consumes some bytes, then
        raises a retryable OSError — the NFS partial-read shape."""

        def __init__(self, fh):
            self.fh = fh
            self.fired = False

        def read(self, n=-1):
            if not self.fired:
                self.fired = True
                self.fh.read(37)  # advance underlying position
                raise OSError(errno.EIO, "injected partial read")
            return self.fh.read(n)

        def seek(self, *a):
            return self.fh.seek(*a)

        def tell(self):
            return self.fh.tell()

        def __enter__(self):
            return self

        def __exit__(self, *a):
            self.fh.close()

    real_open = builtins.open

    def wrapping(file, *a, **k):
        fh = real_open(file, *a, **k)
        if str(file) == str(p):
            return PartialThenFail(fh)
        return fh

    builtins.open = wrapping
    try:
        got = b"".join(_iter_owned_chunks(
            str(p), 0, len(content),
            retry=RetryPolicy(retries=2, backoff_seconds=0.0)))
    finally:
        builtins.open = real_open
    assert got == content


def test_spill_requeue_does_not_double_count(tmp_path):
    """A UniqOverflow spill requeues the chunk tail; those lines must
    not pass through the tracker twice (inflated totals would dilute
    the breaker and break skip-count == injected-count)."""
    from fast_tffm_tpu.data.pipeline import batch_iterator
    n_lines, feats = 32, 40
    lines = [" ".join(["1"] + [f"{i * feats + j}:1.0"
                               for j in range(feats)])
             for i in range(n_lines)]
    lines[5] = "##bad##"
    lines[20] = "1 0:##bad##"
    p = _write(tmp_path / "dense.txt", lines)
    cfg = _cfg(tmp_path, p, vocabulary_size=n_lines * feats,
               bad_line_policy="skip", max_bad_fraction=0.5,
               max_features_per_example=feats, batch_size=8)
    tracker = BadLineTracker.from_config(cfg)
    batches = list(batch_iterator(cfg, [p], epochs=1,
                                  fixed_shape=True, uniq_bucket=64,
                                  bad_lines=tracker))
    # Spills definitely happened: 8 lines x 40 uniques >> 64.
    assert len(batches) > (n_lines - 2 + 7) // 8
    assert sum(b.num_real for b in batches) == n_lines - 2
    assert tracker.total == n_lines, tracker.total
    assert tracker.bad == 2
    tracker.close()


def test_validation_sweeps_share_run_tracker(tmp_path):
    """train()'s per-epoch validation sweeps must reuse the run-scoped
    tracker: the same bad validation line across N epochs quarantines
    ONCE (per-sweep fresh trackers would append it every epoch)."""
    from fast_tffm_tpu.data.badlines import quarantine_path
    from fast_tffm_tpu.train import train
    tlines = list(GOOD)
    vlines = list(GOOD[:16])
    vlines[3] = "##bad validation line##"
    tp = _write(tmp_path / "train.txt", tlines)
    vp = _write(tmp_path / "val.txt", vlines)
    cfg = _cfg(tmp_path, tp, bad_line_policy="quarantine",
               max_bad_fraction=0.5, epoch_num=3,
               validation_files=(vp,))
    train(cfg)
    recs = [json.loads(ln) for ln in open(quarantine_path(cfg))]
    assert [(r["file"], r["lineno"]) for r in recs] == [(vp, 4)]
