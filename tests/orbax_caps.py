"""Capability probes for the installed orbax (ISSUE 3 satellite).

The container pins whatever orbax it pins; several checkpoint features
this repo exercises moved across orbax versions. Rather than skip by
version number (fragile: features land and regress independently of
versions), each probe asks the LIBRARY ITSELF whether the capability
exists — by signature inspection where the API surface answers, by a
tiny behavioral save/restore probe where only behavior does. Tests
that need a capability `skipif` on the probe, so on a capable install
they run (and a real regression fails them), and on this install the
skip reason names exactly what is missing.
"""

import functools
import inspect


def orbax_supports_partial_restore() -> bool:
    """PyTreeRestore(partial_restore=True) — required by
    CheckpointState.restore_partial (the table-without-accumulator
    restore the offload predict path uses)."""
    import orbax.checkpoint as ocp
    return ("partial_restore"
            in inspect.signature(ocp.args.PyTreeRestore).parameters)


@functools.lru_cache(maxsize=1)
def orbax_enforces_template_shapes() -> bool:
    """Whether StandardRestore REJECTS a template whose array shapes
    disagree with the checkpoint. Older installs silently restore the
    SAVED shape (warning about sharding-from-file), so the repo's
    actionable shape-mismatch error can never trigger. Behavioral
    probe: no API surface answers this."""
    import tempfile

    import jax
    import numpy as np
    import orbax.checkpoint as ocp
    with tempfile.TemporaryDirectory() as d:
        mngr = ocp.CheckpointManager(d)
        try:
            mngr.save(0, args=ocp.args.StandardSave(
                {"a": np.zeros((4, 2), np.float32)}))
            mngr.wait_until_finished()
            try:
                mngr.restore(0, args=ocp.args.StandardRestore(
                    {"a": jax.ShapeDtypeStruct((4, 3), np.float32)}))
            except Exception:
                return True
            return False
        finally:
            mngr.close()
