"""Timeline/health layer (ISSUE 3): span tracing semantics and cost
discipline, watchdog stall detection (fake clock AND a real stalled
CPU train run), non-finite-loss detection at the barrier fetch, crash
forensics, fmstat's health verdict, and the JSONL -> Perfetto
round-trip."""

import json
import os
import time

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs.health import Watchdog
from fast_tffm_tpu.obs.sink import JsonlSink, read_events
from fast_tffm_tpu.obs.telemetry import (RunTelemetry, activate, active,
                                         make_telemetry)
from fast_tffm_tpu.obs.trace import span

from tests.test_e2e import make_dataset


# ------------------------------------------------------------------ spans

def test_span_is_noop_without_active_run():
    import contextlib
    cm = span("anything", step=1)
    assert isinstance(cm, contextlib.nullcontext)
    with cm:
        pass  # and it is actually enterable


def test_span_is_noop_when_run_does_not_trace(tmp_path):
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={}, trace_spans=False)
    with activate(tel):
        with span("train/step", step=1):
            pass
    tel.close()
    assert [e for e in read_events(path) if e["event"] == "span"] == []


def test_spans_emit_and_nest_by_containment(tmp_path):
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={}, trace_spans=True)
    with activate(tel):
        with span("outer", step=3):
            with span("inner"):
                time.sleep(0.01)
    tel.close()
    spans = [e for e in read_events(path) if e["event"] == "span"]
    # inner exits first, so it lands first in the stream
    assert [s["name"] for s in spans] == ["inner", "outer"]
    inner, outer = spans
    assert outer["step"] == 3
    assert inner["tid"] == outer["tid"]  # same thread = same track
    # time containment is what makes Perfetto nest them
    assert outer["ts"] <= inner["ts"]
    assert (inner["ts"] + inner["dur"]
            <= outer["ts"] + outer["dur"] + 1e-6)
    assert inner["dur"] >= 0.01


def test_span_records_exception_and_propagates(tmp_path):
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={}, trace_spans=True)
    with activate(tel):
        with pytest.raises(RuntimeError):
            with span("doomed"):
                raise RuntimeError("boom")
    tel.close()
    s = [e for e in read_events(path) if e["event"] == "span"][0]
    assert s["error"] == "RuntimeError"


# --------------------------------------------------------------- watchdog

class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_watchdog_stall_and_recovery_under_fake_clock(tmp_path):
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(path, meta={})
    clock = FakeClock()
    w = Watchdog(sink, stall_seconds=10.0,
                 stacks_path=path + ".stacks", clock=clock)
    w.beat(5)
    clock.t += 9.0
    assert w.check() is None          # within budget: armed, silent
    clock.t += 2.0
    assert w.check() == "stalled"     # 11s since the beat
    assert w.check() is None          # one event per episode, no spam
    clock.t += 50.0
    assert w.check() is None
    w.beat(6)                          # progress resumes
    assert w.check() == "recovered"
    sink.close()
    health = [e for e in read_events(path) if e["event"] == "health"]
    assert [h["status"] for h in health] == ["stalled", "recovered"]
    st = health[0]
    assert st["last_step"] == 5
    assert st["stalled_seconds"] == pytest.approx(11.0)
    assert st["stacks_file"] == path + ".stacks"
    # the all-thread stack dump reached disk while still stalled
    dump = open(path + ".stacks").read()
    assert "stall after" in dump and "Current thread" in dump
    assert health[1]["outage_seconds"] == pytest.approx(61.0)


def test_watchdog_arms_from_construction(tmp_path):
    """A run wedged in SETUP (restore against dead storage) has never
    beaten; the watchdog must still fire."""
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(path, meta={})
    clock = FakeClock()
    w = Watchdog(sink, stall_seconds=5.0,
                 stacks_path=path + ".stacks", clock=clock)
    clock.t += 6.0
    assert w.check() == "stalled"
    assert w.stall_events == 1
    sink.close()


# ---------------------------------------------------- non-finite detection

def test_nonfinite_loss_detected_at_barrier(tmp_path):
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(path, meta={})
    sink.add_scalar("train/loss", 3, 0.5)
    sink.add_scalar("train/loss", 4, float("nan"))
    sink.add_scalar("train/loss", 6, float("inf"))
    sink.add_scalar("validation/auc", 6, 0.9)
    sink.barrier()
    sink.close()
    evs = list(read_events(path))
    health = [e for e in evs if e["event"] == "health"]
    assert len(health) == 1
    h = health[0]
    assert h["status"] == "nonfinite_loss"
    assert h["name"] == "train/loss"
    assert (h["step_first"], h["step_last"], h["count"]) == (4, 6, 2)
    # the scalar events themselves still land (forensics wants the raw
    # series too)
    assert len([e for e in evs if e["event"] == "scalar"]) == 4


def test_nonfinite_device_scalar_detected(tmp_path):
    """The real train shape: the loss is a DEVICE scalar, fetched only
    at the barrier — detection must ride that same fetch."""
    import jax.numpy as jnp
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(path, meta={})
    sink.add_scalar("train/loss", 1, jnp.float32(jnp.nan))
    sink.barrier()
    sink.close()
    health = [e for e in read_events(path) if e["event"] == "health"]
    assert [h["status"] for h in health] == ["nonfinite_loss"]


# -------------------------------------------------------- crash forensics

def test_crash_event_carries_traceback_and_ring(tmp_path):
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})
    tel.sink.emit("span", {"name": "pipeline/build"})
    tel.count("train/steps", 3)
    try:
        raise ValueError("table exploded")
    except ValueError as e:
        tel.record_crash(e, step=7)
    tel.close(7)
    evs = list(read_events(path))
    assert evs[-1]["event"] == "run_end"  # sink still closes cleanly
    crash = [e for e in evs if e["event"] == "crash"][0]
    assert crash["step"] == 7
    assert "ValueError: table exploded" in crash["traceback"]
    names = [r.get("event") for r in crash["recent_events"]]
    assert "span" in names and "run_start" in names


def _train_cfg(tmp_path, rng, **kw):
    make_dataset(tmp_path / "train.txt", 128, rng)
    make_dataset(tmp_path / "val.txt", 64, rng)
    base = dict(vocabulary_size=200, factor_num=4, batch_size=32,
                learning_rate=0.1, epoch_num=2, shuffle=False,
                train_files=(str(tmp_path / "train.txt"),),
                validation_files=(str(tmp_path / "val.txt"),),
                model_file=str(tmp_path / "m" / "fm"),
                metrics_file="auto", metrics_flush_steps=2, log_steps=0)
    base.update(kw)
    return FmConfig(**base)


def test_train_crash_writes_crash_event_and_fmstat_verdict(
        tmp_path, rng, monkeypatch, capsys):
    cfg = _train_cfg(tmp_path, rng)
    from fast_tffm_tpu import train as train_mod

    def boom(*a, **k):
        raise RuntimeError("mid-epoch crash")

    monkeypatch.setattr(train_mod, "evaluate", boom)
    with pytest.raises(RuntimeError, match="mid-epoch crash"):
        train_mod.train(cfg)
    assert active() is None
    path = cfg.model_file + ".metrics.jsonl"
    evs = list(read_events(path))
    crash = [e for e in evs if e["event"] == "crash"]
    assert len(crash) == 1
    assert "mid-epoch crash" in crash[0]["traceback"]
    assert crash[0]["recent_events"]
    assert evs[-1]["event"] == "run_end"
    # fmstat health verdict: CRASHED, naming the error
    from tools.fmstat import main as fmstat_main
    assert fmstat_main([path]) == 0
    out = capsys.readouterr().out
    assert "health: CRASHED" in out
    assert "mid-epoch crash" in out


# --------------------------------------- acceptance: stalled CPU train run

def test_stalled_train_run_emits_health_and_stacks(tmp_path, rng,
                                                   monkeypatch, capsys):
    """ISSUE 3 acceptance: a deliberately stalled CPU train run (input
    iterator sleeps past watchdog_stall_seconds) produces a
    `health: stalled` event plus a .stacks all-thread dump, and fmstat
    reports STALLED."""
    cfg = _train_cfg(tmp_path, rng, watchdog_stall_seconds=0.25,
                     epoch_num=1)
    from fast_tffm_tpu import train as train_mod
    real_prefetch = train_mod.prefetch

    def stalling_prefetch(it, **kw):
        inner = real_prefetch(it, **kw)

        def gen():
            for i, batch in enumerate(inner):
                if i == 2:
                    time.sleep(1.0)  # 4x the stall budget
                yield batch
        return gen()

    monkeypatch.setattr(train_mod, "prefetch", stalling_prefetch)
    train_mod.train(cfg)
    path = cfg.model_file + ".metrics.jsonl"
    health = [e for e in read_events(path) if e["event"] == "health"]
    stalls = [h for h in health if h["status"] == "stalled"]
    assert stalls, f"no stall event in {health}"
    assert stalls[0]["stalled_seconds"] >= 0.25
    stacks = path + ".stacks"
    assert os.path.exists(stacks)
    dump = open(stacks).read()
    assert "Current thread" in dump  # faulthandler's all-thread format
    # the run RECOVERED after the sleep and finished; fmstat still
    # surfaces the episode.  A slow first jit compile can trip an extra
    # stalled/recovered pair at last_step == -1 before any step runs, so
    # pin the injected mid-run episode rather than the episode count.
    assert [h["status"] for h in health].count("recovered") >= 1
    mid_run = [h for h in stalls if h.get("last_step", -1) >= 0]
    assert mid_run, f"no mid-run stall episode in {health}"
    from tools.fmstat import main as fmstat_main
    assert fmstat_main([path]) == 0
    assert "health: STALLED" in capsys.readouterr().out


# ------------------------------------------- zero-fetch cost discipline

def test_watchdog_and_spans_add_zero_midstream_fetches(tmp_path, rng,
                                                       monkeypatch):
    """ISSUE 3 acceptance: enabling the watchdog + span tracing must
    not add a single mid-stream device fetch — bulk_fetch still runs
    ONLY at the two epoch barriers, same as with them off
    (test_obs.test_train_metrics_zero_midstream_fetches)."""
    import fast_tffm_tpu.utils.fetch as fetch
    calls = []
    real = fetch.bulk_fetch

    def counting(pairs, consume):
        calls.append(len(pairs))
        return real(pairs, consume)

    monkeypatch.setattr(fetch, "bulk_fetch", counting)
    cfg = _train_cfg(tmp_path, rng, metrics_flush_steps=1,
                     trace_spans=True, watchdog_stall_seconds=30.0)
    from fast_tffm_tpu.train import train
    train(cfg)
    # 2 epochs: each barrier drains (loss x4/epoch + auc x1) in ONE call
    assert calls == [5, 5]
    # and the stream actually carries spans (tracing was on)
    spans = [e for e in read_events(cfg.model_file + ".metrics.jsonl")
             if e["event"] == "span"]
    assert {s["name"] for s in spans} >= {
        "pipeline/build", "train/step", "train/validation",
        "checkpoint/save", "obs/barrier_flush", "fetch/bulk"}


# -------------------------------------------------- fmstat health verdicts

def test_clean_run_health_ok(tmp_path, rng, capsys):
    cfg = _train_cfg(tmp_path, rng)
    from fast_tffm_tpu.train import train
    train(cfg)
    from tools.fmstat import main as fmstat_main
    assert fmstat_main([cfg.model_file + ".metrics.jsonl"]) == 0
    assert "health: OK" in capsys.readouterr().out


def test_nonfinite_verdict_and_hard_kill_detail(tmp_path, capsys):
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(path, meta={})
    sink.add_scalar("train/loss", 9, float("nan"))
    sink.barrier()   # writes health + scalars ... but no run_end:
    del sink         # emulate a hard-killed process (no close())
    from tools.fmstat import main as fmstat_main
    assert fmstat_main([path]) == 0
    out = capsys.readouterr().out
    assert "health: NONFINITE" in out
    assert "no run_end" in out
    # --json carries the verdict for scripting
    assert fmstat_main(["--json", path]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["health"]["verdict"] == "NONFINITE"


# -------------------------------------------------- JSONL -> Perfetto

def test_fmtrace_roundtrip_multiworker(tmp_path):
    """Spans + gauges + health from two worker shard files convert to
    trace-event JSON: one pid per process, one named tid per thread,
    X slices with microsecond ts/dur."""
    chief = str(tmp_path / "m.jsonl")
    shard = chief + ".p1"
    for p, path in ((0, chief), (1, shard)):
        tel = RunTelemetry(path, meta={"kind": "train",
                                       "process_index": p},
                           trace_spans=True)
        with activate(tel):
            with span("train/step", step=1):
                time.sleep(0.002)
            with span("checkpoint/save"):
                pass
        tel.set("train/examples_per_sec_window", 1000.0 + p)
        tel.close(1)
    out_path = str(tmp_path / "out.trace.json")
    from tools.fmtrace import main as fmtrace_main
    assert fmtrace_main([chief, shard, "-o", out_path]) == 0
    doc = json.load(open(out_path))
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {0, 1}
    assert {e["name"] for e in xs} == {"train/step", "checkpoint/save"}
    step0 = [e for e in xs if e["name"] == "train/step"
             and e["pid"] == 0][0]
    assert step0["dur"] >= 2000  # microseconds
    assert step0["args"]["step"] == 1
    # process/thread naming metadata present
    pn = [e for e in evs if e["ph"] == "M"
          and e["name"] == "process_name"]
    assert {e["pid"] for e in pn} == {0, 1}
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in evs)
    # gauges became counter tracks, unit-labeled (PR 17), with their
    # last value re-emitted at run_end so short runs render
    cs = [e for e in evs if e["ph"] == "C"
          and e["name"] == "train/examples_per_sec_window [1/s]"]
    assert {e["args"]["value"] for e in cs} == {1000.0, 1001.0}
    # run_start/run_end instants frame each track
    assert any(e["ph"] == "i" and e["name"] == "run_end" for e in evs)


def test_fmtrace_covers_real_train_run(tmp_path, rng):
    """ISSUE 3 acceptance: a normal CPU run with trace_spans on yields
    a JSONL that fmtrace converts with pipeline/step/checkpoint spans
    present."""
    cfg = _train_cfg(tmp_path, rng, trace_spans=True, save_steps=4)
    from fast_tffm_tpu.train import train
    train(cfg)
    out_path = str(tmp_path / "t.json")
    from tools.fmtrace import convert
    n = convert([cfg.model_file + ".metrics.jsonl"], out_path)
    assert n > 0
    evs = json.load(open(out_path))["traceEvents"]
    names = {e["name"] for e in evs if e["ph"] == "X"}
    assert {"pipeline/build", "train/step", "train/validation",
            "checkpoint/save", "checkpoint/restore"} <= names
    # the pipeline spans ran on their own (prefetch) track
    tid_by_name = {}
    for e in evs:
        if e["ph"] == "M" and e["name"] == "thread_name":
            tid_by_name[e["args"]["name"]] = e["tid"]
    assert "prefetch" in tid_by_name
    build = [e for e in evs if e["ph"] == "X"
             and e["name"] == "pipeline/build"][0]
    assert build["tid"] == tid_by_name["prefetch"]


# ------------------------------------------------------------ knobs

def test_config_knobs_parse_and_validate(tmp_path):
    import textwrap
    cfg_path = tmp_path / "c.cfg"
    cfg_path.write_text(textwrap.dedent("""\
        [General]
        vocabulary_size = 100
        [Train]
        train_files = x.txt
        trace_spans = true
        watchdog_stall_seconds = 42.5
    """))
    from fast_tffm_tpu.config import load_config
    cfg = load_config(str(cfg_path))
    assert cfg.trace_spans is True
    assert cfg.watchdog_stall_seconds == 42.5
    with pytest.raises(ValueError, match="watchdog_stall_seconds"):
        FmConfig(watchdog_stall_seconds=-1.0)


def test_make_telemetry_wires_watchdog_and_spans(tmp_path):
    cfg = FmConfig(metrics_file=str(tmp_path / "m.jsonl"),
                   trace_spans=True, watchdog_stall_seconds=30.0)
    tel = make_telemetry(cfg, "train")
    try:
        assert tel.trace_spans is True
        assert tel.watchdog is not None
        assert tel.watchdog.stacks_path == str(
            tmp_path / "m.jsonl") + ".stacks"
        t0 = tel.watchdog._beat
        tel.heartbeat(12)
        assert tel.watchdog._beat[1] == 12 and tel.watchdog._beat != t0
    finally:
        tel.close()
    # close() stopped the thread
    assert tel.watchdog._thread is None


def test_health_verdict_scopes_to_latest_run(tmp_path, capsys):
    """The sink appends, so a fixed metrics path accumulates runs: an
    old crash must not brand a later clean rerun CRASHED."""
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={"kind": "train"})
    try:
        raise RuntimeError("old bug")
    except RuntimeError as e:
        tel.record_crash(e)
    tel.close()
    # rerun appends a clean run to the same file
    tel2 = RunTelemetry(path, meta={"kind": "train"})
    tel2.count("train/steps", 5)
    tel2.close(5)
    from tools.fmstat import main as fmstat_main
    assert fmstat_main([path]) == 0
    assert "health: OK" in capsys.readouterr().out


def test_nonfinite_nonloss_scalar_is_not_a_health_event(tmp_path):
    """A NaN validation AUC is a legitimate value (a shard with no
    positives/negatives); only LOSS scalars escalate to health."""
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(path, meta={})
    sink.add_scalar("validation/auc", 4, float("nan"))
    sink.barrier()
    sink.close()
    evs = list(read_events(path))
    assert [e for e in evs if e["event"] == "health"] == []
    assert [e for e in evs if e["event"] == "scalar"]  # still recorded


def test_watchdog_stop_emits_pending_recovery(tmp_path):
    """A stall that recovers within the final poll interval still gets
    its 'recovered' event at stop() — a clean finish must not read as
    'NOT recovered'."""
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(path, meta={})
    clock = FakeClock()
    w = Watchdog(sink, stall_seconds=5.0,
                 stacks_path=path + ".stacks", clock=clock)
    clock.t += 6.0
    assert w.check() == "stalled"
    w.beat(9)        # recovery lands after the last poll...
    w.stop()         # ...and stop()'s final check records it
    sink.close()
    health = [e for e in read_events(path) if e["event"] == "health"]
    assert [h["status"] for h in health] == ["stalled", "recovered"]
