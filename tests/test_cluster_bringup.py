"""Cluster bring-up hardening (ISSUE 5 satellite): the coordinator
handshake runs in a bounded retry loop — a coordinator that is still
booting doesn't hang workers forever, and exhaustion names the
coordinator address and the process that failed to join. Pure-logic
tests: the initialize callable, sleep, and clock are injected."""

import pytest

from fast_tffm_tpu.parallel.distributed import (
    CONNECT_ATTEMPT_CAP_SECONDS, CONNECT_RETRY_SLEEP_SECONDS,
    initialize_with_retry)


class FakeClock:
    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.sleeps.append(s)
        self.t += s


def test_succeeds_after_transient_failures():
    """The staggered-start case: the coordinator comes up on the third
    attempt; the worker joins instead of dying on the first refusal."""
    clock = FakeClock()
    calls = []

    def init(**kw):
        calls.append(kw)
        if len(calls) < 3:
            raise RuntimeError("UNAVAILABLE: failed to connect")

    attempts = initialize_with_retry(
        init, address="head:9476", num_processes=4, process_id=2,
        timeout_seconds=600.0, sleep=clock.sleep, clock=clock)
    assert attempts == 3
    assert len(calls) == 3
    # every attempt targets the same cluster identity
    for kw in calls:
        assert kw["coordinator_address"] == "head:9476"
        assert kw["num_processes"] == 4
        assert kw["process_id"] == 2
    # per-attempt handshake budget is capped, not the whole budget
    assert calls[0]["initialization_timeout"] == int(
        CONNECT_ATTEMPT_CAP_SECONDS)
    assert clock.sleeps == [CONNECT_RETRY_SLEEP_SECONDS] * 2


def test_exhaustion_names_coordinator_and_process():
    clock = FakeClock()

    def init(**kw):
        raise RuntimeError("DEADLINE_EXCEEDED: deadline exceeded")

    with pytest.raises(RuntimeError) as ei:
        initialize_with_retry(
            init, address="coord.example:8476", num_processes=8,
            process_id=5, timeout_seconds=10.0, sleep=clock.sleep,
            clock=clock)
    msg = str(ei.value)
    assert "coord.example:8476" in msg
    assert "process 5" in msg
    assert "cluster_connect_timeout_seconds=10" in msg
    assert "DEADLINE_EXCEEDED" in msg  # the underlying cause survives
    assert ei.value.__cause__ is not None


def test_attempt_timeout_shrinks_to_remaining_budget():
    """The last attempt's jax-level timeout must not overrun the total
    budget: with 90 s left of a fresh 90 s budget, the first attempt
    gets 60 (the cap); after it fails at t=70, the next gets ~18."""
    clock = FakeClock()
    calls = []

    def init(**kw):
        calls.append(kw["initialization_timeout"])
        if len(calls) == 1:
            clock.t += 70.0  # a slow hang inside the handshake
            raise RuntimeError("UNAVAILABLE")

    initialize_with_retry(init, address="h:1", num_processes=2,
                          process_id=1, timeout_seconds=90.0,
                          sleep=clock.sleep, clock=clock)
    assert calls[0] == int(CONNECT_ATTEMPT_CAP_SECONDS)
    assert calls[1] <= 90 - 70  # bounded by what's left


def test_zero_budget_never_calls_initialize():
    clock = FakeClock()
    clock.t = 5.0

    def init(**kw):
        raise AssertionError("must not be called")

    with pytest.raises(RuntimeError, match="failed to join"):
        initialize_with_retry(init, address="h:1", num_processes=2,
                              process_id=0, timeout_seconds=0.0,
                              sleep=clock.sleep,
                              clock=lambda: clock.t + 1.0)


def test_exhaustion_emits_cluster_bringup_failed_event(tmp_path):
    """ISSUE 6 satellite: exhaustion writes a ``health:
    cluster_bringup_failed`` event to the telemetry stream BEFORE
    raising — a job that never formed must be visible to fmstat
    post-mortems, not just to whoever read the process's stderr."""
    import json
    from fast_tffm_tpu.obs.telemetry import RunTelemetry, activate
    clock = FakeClock()

    def init(**kw):
        raise RuntimeError("UNAVAILABLE: connect refused")

    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})
    with activate(tel):
        with pytest.raises(RuntimeError):
            initialize_with_retry(
                init, address="coord:9476", num_processes=4,
                process_id=2, timeout_seconds=10.0, sleep=clock.sleep,
                clock=clock)
    tel.close()
    with open(path) as fh:
        events = [json.loads(ln) for ln in fh if ln.strip()]
    fails = [e for e in events
             if e.get("status") == "cluster_bringup_failed"]
    assert len(fails) == 1
    assert fails[0]["coordinator"] == "coord:9476"
    assert fails[0]["process_index"] == 2
    assert fails[0]["attempts"] >= 1
    assert "UNAVAILABLE" in fails[0]["error"]
    # counted too, so fmstat's merged counters surface it
    metrics = [e for e in events if e.get("event") == "metrics"]
    assert metrics[-1]["counters"]["cluster/bringup_failures"] == 1


def test_exhaustion_without_telemetry_still_raises():
    clock = FakeClock()

    def init(**kw):
        raise RuntimeError("DEADLINE_EXCEEDED")

    with pytest.raises(RuntimeError, match="failed to join"):
        initialize_with_retry(init, address="h:1", num_processes=2,
                              process_id=1, timeout_seconds=5.0,
                              sleep=clock.sleep, clock=clock)
