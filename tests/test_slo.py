"""Closed-loop SLO observability (README "SLOs & quality gate"):
the per-publish quality collector's math, the publish gate's decision
contract (first-publish min-AUC-only, NaN holds, broadcast-identical
across workers), the gate/retention interaction (a held step later
GC'd leaves the pointer valid), the declarative SLO spec + evaluator +
`fmstat slo` CLI, the Prometheus exposition format, `fmstat --follow`,
and the GATE-HELD verdict's place in the severity ladder."""

import io
import json
import math
import os

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs.quality import (LOGLOSS_EPS, PublishGate,
                                       QualityStats)
from fast_tffm_tpu.obs.slo import (SloSpec, evaluate_slos, overall,
                                   render_slo)


# --- QualityStats math -----------------------------------------------------


def _sigmoid(s):
    return 1.0 / (1.0 + np.exp(-np.asarray(s, np.float64)))


def test_quality_stats_logistic_math():
    s = np.array([0.0, 2.0, -1.0])
    y = np.array([0.0, 1.0, 1.0])
    w = np.array([1.0, 2.0, 0.5])
    q = QualityStats("logistic")
    q.update(s, y, w)
    p = np.clip(_sigmoid(s), LOGLOSS_EPS, 1 - LOGLOSS_EPS)
    loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
    assert q.loss == pytest.approx((w * loss).sum() / w.sum())
    assert q.calibration == pytest.approx(
        (w * _sigmoid(s)).sum() / (w * y).sum())


def test_quality_stats_mse_math():
    s = np.array([0.2, 0.9])
    y = np.array([0.0, 1.0])
    w = np.ones(2)
    q = QualityStats("mse")
    q.update(s, y, w)
    assert q.loss == pytest.approx(((s - y) ** 2).mean())
    # mse calibration: raw score mass over label mass
    assert q.calibration == pytest.approx(s.sum() / y.sum())


def test_quality_stats_empty_and_no_positives():
    q = QualityStats()
    assert q.loss is None and q.calibration is None
    q.update(np.array([1.0]), np.array([0.0]), np.array([1.0]))
    assert q.loss is not None
    assert q.calibration is None  # zero label mass: undefined, not inf


def test_quality_stats_sums_roundtrip_and_incremental():
    a = QualityStats("logistic")
    b = QualityStats("logistic")
    rng = np.random.default_rng(7)
    s = rng.normal(size=40)
    y = (rng.uniform(size=40) < 0.5).astype(float)
    w = rng.uniform(0.5, 2.0, size=40)
    a.update(s, y, w)
    for i in range(0, 40, 7):  # chunked feeding matches one-shot
        b.update(s[i:i + 7], y[i:i + 7], w[i:i + 7])
    assert b.sums() == pytest.approx(a.sums())
    c = QualityStats("logistic")
    c.load_sums(a.sums())
    assert c.loss == a.loss and c.calibration == a.calibration
    with pytest.raises(ValueError):
        c.load_sums(np.zeros(3))


def test_quality_sums_survive_hi_lo_float32_transit():
    """The lockstep merge ships every f64 as a (hi, lo) float32 pair
    (train.evaluate_distributed); the quality sums ride the same
    payload, so they must reconstruct through that transit."""
    q = QualityStats()
    q.update(np.full(1000, 3.3), np.ones(1000), np.full(1000, 1.7))
    payload = q.sums()
    hi = payload.astype(np.float32)
    lo = (payload - hi.astype(np.float64)).astype(np.float32)
    back = hi.astype(np.float64) + lo.astype(np.float64)
    assert back == pytest.approx(payload, rel=1e-12)


# --- evaluate(collect=) rides the existing sweep ---------------------------


def _eval_cfg(tmp_path, **over):
    base = dict(vocabulary_size=100, factor_num=4, batch_size=16,
                epoch_num=1, learning_rate=0.1, shuffle=False, seed=0,
                log_steps=0,
                train_files=(os.path.join(str(tmp_path), "t.txt"),),
                model_file=os.path.join(str(tmp_path), "model", "fm"))
    base.update(over)
    return FmConfig(**base)


def _write_lines(path, n, seed=0, vocab=100):
    rng = np.random.default_rng(seed)
    labels = []
    with open(path, "w") as fh:
        for _ in range(n):
            y = int(rng.integers(0, 2))
            labels.append(y)
            feats = sorted(rng.choice(vocab, size=3, replace=False))
            fh.write(f"{y} " + " ".join(f"{i}:1.0" for i in feats)
                     + "\n")
    return np.asarray(labels, np.float64)


def test_evaluate_collect_matches_manual_sweep(tmp_path):
    """The collector consumes the SAME score chunks the AUC update
    does: loss/calibration from evaluate(collect=) must equal the
    values computed from an independent scoring pass, and the returned
    AUC must be unchanged by the collector's presence."""
    from fast_tffm_tpu.data.pipeline import batch_iterator
    from fast_tffm_tpu.models.fm import (ModelSpec, batch_args,
                                         init_table,
                                         make_batch_scorer)
    from fast_tffm_tpu.train import evaluate
    cfg = _eval_cfg(tmp_path)
    labels = _write_lines(cfg.train_files[0], 60, seed=5)
    table = init_table(cfg, 0)
    stats = QualityStats(cfg.loss_type)
    auc_c, n = evaluate(cfg, table, cfg.train_files, collect=stats)
    auc_plain, _ = evaluate(cfg, table, cfg.train_files)
    assert n == 60 and auc_c == auc_plain
    score_fn = make_batch_scorer(ModelSpec.from_config(cfg))
    chunks = []
    for b in batch_iterator(cfg, cfg.train_files, training=False,
                            epochs=1):
        args = batch_args(b)
        args.pop("labels"), args.pop("weights")
        chunks.append(np.asarray(score_fn(table, args))[:b.num_real])
    scores = np.concatenate(chunks).astype(np.float64)
    want = QualityStats(cfg.loss_type)
    want.update(scores, labels, np.ones_like(labels))
    assert stats.sums() == pytest.approx(want.sums(), rel=1e-9)


# --- PublishGate decision contract ----------------------------------------


def test_gate_first_publish_uses_min_auc_only():
    g = PublishGate(min_auc=0.8, max_drop=0.05)
    # No baseline yet: only the absolute floor applies.
    d = g.decide(0.82, step=10)
    assert not d["held"] and d["baseline"] is None
    d = g.decide(0.7, step=10)
    assert d["held"] and "publish_min_auc" in d["reasons"][0]
    # Baseline only moves on note_published, never on decide.
    assert g.baseline is None


def test_gate_drop_vs_last_published():
    g = PublishGate(min_auc=0.0, max_drop=0.05)
    d0 = g.decide(0.9, step=1)
    assert not d0["held"]  # no baseline, no min floor: passes
    g.note_published(0.9)
    assert not g.decide(0.86, step=2)["held"]  # within the budget
    d = g.decide(0.84, step=3)
    assert d["held"] and "dropped" in d["reasons"][0]
    # A held decision never becomes the baseline; recovery is judged
    # against the last PUBLISHED AUC.
    assert g.baseline == 0.9
    assert not g.decide(0.89, step=4)["held"]


def test_gate_nan_auc_holds_configured_gate():
    g = PublishGate(min_auc=0.5)
    assert g.decide(float("nan"), step=1)["held"]
    g2 = PublishGate(max_drop=0.1)
    g2.note_published(0.9)
    assert g2.decide(float("nan"), step=1)["held"]
    # NaN never becomes a baseline (it would disarm the drop check).
    g2.note_published(float("nan"))
    assert g2.baseline == 0.9
    # The sharp corner: a max_drop-ONLY gate on its very FIRST publish
    # (no baseline, no min floor) — neither threshold comparison fires,
    # but an unevaluable model must still hold a configured gate.
    g3 = PublishGate(max_drop=0.1)
    d = g3.decide(float("nan"), step=1)
    assert d["held"] and "unevaluable" in d["reasons"][0]
    assert not g3.decide(0.8, step=2)["held"]  # a real AUC still passes


def test_gate_baseline_persists_beside_pointer(tmp_path):
    """The drop baseline survives a restart: it is written beside the
    `published` pointer on each successful publish and a fresh gate
    re-arms from it — a preempt-resume must not exempt its first
    publish from publish_max_auc_drop."""
    from fast_tffm_tpu.checkpoint import (read_gate_baseline,
                                          write_gate_baseline)
    d = str(tmp_path)
    assert read_gate_baseline(d) is None  # pre-first-publish state
    write_gate_baseline(d, 0.912345)
    assert read_gate_baseline(d) == pytest.approx(0.912345)
    # A resumed gate armed from the file holds a post-restart drop.
    g = PublishGate(max_drop=0.05)
    g.note_published(read_gate_baseline(d))
    assert g.decide(0.80, step=9)["held"]
    assert not g.decide(0.88, step=9)["held"]
    # Garbled file degrades to the baseline-free first-publish state,
    # never a crash.
    (tmp_path / "gate_baseline").write_text("not a float\n")
    assert read_gate_baseline(d) is None


def test_gate_from_config():
    assert PublishGate.from_config(FmConfig()) is None
    cfg = FmConfig(run_mode="stream", stream_dir="/tmp/x",
                   publish_interval_seconds=1.0,
                   validation_files=("v.txt",), publish_min_auc=0.6)
    g = PublishGate.from_config(cfg)
    assert g is not None and g.min_auc == 0.6


def test_gate_config_requires_stream_validation_publishing():
    with pytest.raises(ValueError, match="validation_files"):
        FmConfig(run_mode="stream", stream_dir="/tmp/x",
                 publish_interval_seconds=1.0, publish_min_auc=0.5)
    with pytest.raises(ValueError, match="run_mode = stream"):
        FmConfig(publish_min_auc=0.5,
                 validation_files=("v.txt",))
    with pytest.raises(ValueError, match="publish_interval_seconds"):
        FmConfig(run_mode="stream", stream_dir="/tmp/x",
                 validation_files=("v.txt",),
                 publish_max_auc_drop=0.1)


def test_publish_quality_eval_knob_validation():
    # off conflicts with a configured gate (the gate IS the sweep).
    with pytest.raises(ValueError, match="publish_quality_eval"):
        FmConfig(run_mode="stream", stream_dir="/tmp/x",
                 publish_interval_seconds=1.0,
                 validation_files=("v.txt",), publish_min_auc=0.5,
                 publish_quality_eval="off")
    # on needs somewhere (and some cadence) to sweep.
    with pytest.raises(ValueError, match="publish_quality_eval = on"):
        FmConfig(publish_quality_eval="on")
    with pytest.raises(ValueError, match="unknown publish_quality_eval"):
        FmConfig(publish_quality_eval="sometimes")
    # auto + gate / on + stream corpus are both legal.
    FmConfig(run_mode="stream", stream_dir="/tmp/x",
             publish_interval_seconds=1.0,
             validation_files=("v.txt",), publish_min_auc=0.5)
    FmConfig(run_mode="stream", stream_dir="/tmp/x",
             publish_interval_seconds=1.0,
             validation_files=("v.txt",), publish_quality_eval="on")


def test_gate_decisions_broadcast_identical_across_workers():
    """The multi-host contract: the chief's decision dict survives the
    JSON wire (broadcast_blob) byte-exactly, a follower applying the
    wire decision stays in lockstep with the chief through a
    pass/hold/recover sequence, and the single-process broadcast is
    the identity."""
    from fast_tffm_tpu.data.stream import broadcast_blob
    chief = PublishGate(min_auc=0.6, max_drop=0.1)
    follower = PublishGate(min_auc=0.6, max_drop=0.1)
    for step, auc in enumerate([0.9, 0.85, 0.3, 0.88, 0.7]):
        d = chief.decide(auc, step)
        # identity when process_count == 1 — the same call sites run
        # unchanged in single-process mode
        assert broadcast_blob(d, "test/gate") is d
        wire = json.loads(json.dumps(d))
        assert wire == d  # JSON-safe: what the chief decides is what
        # every worker receives
        assert follower.decide(auc, step) == d  # deterministic too
        if not wire["held"]:
            chief.note_published(d["auc"])
            follower.note_published(wire["auc"])
        assert follower.baseline == chief.baseline
    # The poisoned step (0.3) held on both checks; recovery at 0.88
    # passed against the 0.85 baseline; 0.7 holds again.
    assert chief.decide(0.7, 9)["held"]


# --- gate + retention + walk-back interaction ------------------------------


def test_held_step_gcd_pointer_still_valid(tmp_path):
    """A held step is saved (by periodic saves) but never published;
    once recovery publishes a newer step, retention GC eventually
    deletes the held step — and the published pointer must still name
    a live, verifiable step, with the quarantine walk-back restoring
    past the torn newest step without ever touching the pointer."""
    import jax
    from fast_tffm_tpu.checkpoint import (CheckpointState,
                                          list_step_dirs,
                                          read_published,
                                          verify_step_dir)
    from fast_tffm_tpu.models.fm import init_accumulator, init_table
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    from fast_tffm_tpu.train import checkpoint_template, ckpt_state
    cfg = _eval_cfg(tmp_path, vocabulary_size=50, factor_num=2)
    model = cfg.model_file
    ckpt = CheckpointState(model, max_to_keep=3, verify="size")

    def save(step):
        t = init_table(cfg, step)
        a = init_accumulator(cfg)
        ckpt.save(step, *ckpt_state(cfg, t, a),
                  vocabulary_size=cfg.vocabulary_size, wait=True)

    save(1)
    assert ckpt.publish_step(1) is not None          # good publish
    save(2)                                          # HELD: no publish
    save(3)
    assert ckpt.publish_step(3) is not None          # recovery publish
    save(4)                                          # GCs step 1
    save(5)                                          # GCs held step 2
    ckpt.close()
    steps = list_step_dirs(model + ".ckpt")
    assert 2 not in steps, steps                     # held step GC'd
    assert read_published(model + ".ckpt") == 3      # pointer valid...
    assert 3 in steps
    assert verify_step_dir(model + ".ckpt", 3, "size") is None
    # ...and the walk-back path is unaffected: tear the newest step,
    # restore quarantines it and lands on step 4 — the pointer never
    # moves off 3.
    assert truncate_checkpoint(model, seed=0)
    ckpt2 = CheckpointState(model, max_to_keep=3, verify="size")
    restored = ckpt2.restore(template=checkpoint_template(cfg))
    ckpt2.close()
    assert restored is not None and int(restored["step"]) == 4
    assert read_published(model + ".ckpt") == 3
    assert verify_step_dir(model + ".ckpt", 3, "size") is None
    del jax  # imported for the device backend side effect only


def test_gate_hold_pauses_retention_and_final_save_spares_pointer(
        tmp_path):
    """The hold/retention interplay end-to-end through the real CLI
    (the slo-soak runs without save_steps, so this is the one test
    that executes the risk arm, the periodic-save pause, and the
    margin=2 reserve): with save_steps minting checkpoints while a
    poisoned burst holds the gate, periodic saves must PAUSE (the
    logged warning) and the mandatory final save on STOP — taken while
    still holding, so the exit publish is skipped too — must NOT evict
    the published last-good step."""
    import subprocess
    import sys
    import time as _time
    from fast_tffm_tpu.checkpoint import (read_published,
                                          verify_step_dir)
    from tools.fmchaos import _corpus_lines, _write_corpus
    wd = str(tmp_path)
    sd = os.path.join(wd, "stream")
    os.makedirs(sd)
    val = os.path.join(wd, "val.txt")
    _write_corpus(val, 200, 1)
    shard_i = [0]

    def write_shard(lines):
        p = os.path.join(sd, f"part-{shard_i[0]:03d}.txt")
        shard_i[0] += 1
        with open(p, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        open(p + ".done", "w").close()

    def flip(line):
        y, rest = line.split(" ", 1)
        return f"{1 - int(y)} {rest}"

    write_shard(_corpus_lines(400, 0))
    cfg_path = os.path.join(wd, "gate.cfg")
    model = os.path.join(wd, "model", "fm")
    log = os.path.join(wd, "trainer.log")
    with open(cfg_path, "w") as fh:
        fh.write(f"""
[General]
vocabulary_size = 200
factor_num = 4
model_file = {model}
log_file = {log}

[Train]
run_mode = stream
stream_dir = {sd}
stream_poll_seconds = 0.05
seal_policy = done
shuffle = false
epoch_num = 1
batch_size = 32
learning_rate = 0.1
log_steps = 0
save_steps = 3
metrics_file = {os.path.join(wd, 'metrics.jsonl')}
metrics_flush_steps = 2
publish_interval_seconds = 0.2
publish_min_auc = 0.7
validation_files = {val}
""")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    out_path = os.path.join(wd, "trainer.out")
    ckpt_dir = model + ".ckpt"
    with open(out_path, "w") as out:
        proc = subprocess.Popen(
            [sys.executable, "run_tffm.py", "train", cfg_path],
            cwd=repo, env=env, stdout=out, stderr=subprocess.STDOUT)
    try:
        def tail():
            try:
                return open(out_path).read()[-3000:]
            except OSError:
                return "<no output>"

        def wait_for(fn, what, deadline_s=150.0):
            deadline = _time.monotonic() + deadline_s
            while not fn():
                assert proc.poll() is None, (
                    f"trainer exited before {what}:\n{tail()}")
                assert _time.monotonic() < deadline, (
                    f"timed out waiting for {what}\n{tail()}")
                _time.sleep(0.02)

        wait_for(lambda: read_published(ckpt_dir) is not None,
                 "first publish")
        write_shard([flip(ln) for ln in _corpus_lines(1600, 3)])
        wait_for(lambda: "GATE HELD" in tail(), "gate hold")
        # More poisoned steps while holding: periodic saves keep
        # attempting, and the pause must kick in before retention can
        # touch the published step.
        write_shard([flip(ln) for ln in _corpus_lines(1600, 4)])
        wait_for(lambda: "pausing periodic saves" in tail(),
                 "retention pause")
        pub = read_published(ckpt_dir)
        open(os.path.join(sd, "STOP"), "w").close()
        assert proc.wait(timeout=150) == 0, tail()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    # Still holding at exit: the exit publish was skipped...
    text = open(out_path).read()
    assert "exit publish skipped" not in text  # (no preemption here)
    assert read_published(ckpt_dir) == pub
    # ...and the mandatory final save did NOT evict the last-good
    # step: the pointer names a live, integrity-passing checkpoint.
    assert os.path.isdir(os.path.join(ckpt_dir, str(pub))), (
        f"published step {pub} was GC'd by the final save:\n"
        f"{sorted(os.listdir(ckpt_dir))}")
    assert verify_step_dir(ckpt_dir, pub, "size") is None


# --- SloSpec + evaluator ---------------------------------------------------


def test_slo_spec_config_gauges_roundtrip():
    from fast_tffm_tpu.obs.registry import MetricsRegistry
    cfg = FmConfig(slo_publish_staleness_seconds=30.0, slo_p99_ms=250.0,
                   slo_min_auc=0.8, slo_max_bad_fraction=0.01)
    spec = SloSpec.from_config(cfg)
    assert not spec.empty
    reg = MetricsRegistry()
    spec.emit_gauges(reg)
    g = reg.snapshot()["gauges"]
    assert g == {"slo/publish_staleness_seconds": 30.0,
                 "slo/p99_ms": 250.0, "slo/min_auc": 0.8,
                 "slo/max_bad_fraction": 0.01}
    assert SloSpec.from_summary({"gauges": g}) == spec
    # Unset objectives emit nothing: absence IS the unset marker.
    reg2 = MetricsRegistry()
    SloSpec.from_config(FmConfig()).emit_gauges(reg2)
    assert reg2.snapshot()["gauges"] == {}
    assert SloSpec.from_config(FmConfig()).empty


def _summary(gauges=None, counters=None, hists=None):
    return {"gauges": gauges or {}, "counters": counters or {},
            "hists": hists or {}}


def test_evaluate_slos_pass_fail_skip():
    spec = SloSpec(publish_staleness_seconds=5.0, p99_ms=100.0,
                   min_auc=0.8, max_bad_fraction=0.01)
    rows = evaluate_slos(spec, _summary(
        gauges={"stream/last_publish_age_seconds": 2.0,
                "quality/auc": 0.9},
        counters={"train/examples": 980.0,
                  "pipeline/bad_lines": 20.0},
        hists={"serve/request_latency_ms": {"p99": 42.0}}))
    by = {r.objective: r for r in rows}
    assert len(rows) == 4
    assert by["publish staleness"].status == "PASS"
    assert by["serve latency p99"].status == "PASS"
    assert by["validation AUC"].status == "PASS"
    assert by["bad-line fraction"].status == "FAIL"  # 20/1000 > 0.01
    assert by["bad-line fraction"].measured == pytest.approx(0.02,
                                                             abs=1e-6)
    assert overall(rows) == "FAIL"
    # Missing data is SKIP, never a silent pass.
    rows2 = evaluate_slos(spec, _summary())
    assert {r.status for r in rows2} == {"SKIP"}
    assert overall(rows2) == "PASS"  # nothing FAILED; table shows SKIP
    # NaN quality FAILS a quality bound.
    rows3 = evaluate_slos(SloSpec(min_auc=0.5), _summary(
        gauges={"quality/auc": float("nan")}))
    assert rows3[0].status == "FAIL"
    # An unset spec evaluates nothing.
    assert evaluate_slos(SloSpec(), _summary()) == []
    assert overall([]) == "EMPTY"


def test_bad_fraction_prefers_train_examples_denominator():
    """A gated stream sweeps validation at EVERY publish, inflating
    pipeline/examples; the bad-fraction denominator must be the
    TRAINED stream, or repeated sweeps dilute a real violation."""
    from fast_tffm_tpu.obs.slo import measured_bad_fraction
    m = measured_bad_fraction(_summary(counters={
        "pipeline/bad_lines": 10.0,
        "train/examples": 990.0,
        "pipeline/examples": 990.0 + 200 * 240.0,  # + 200 sweeps
    }))
    assert m == pytest.approx(0.01)
    # Streams without a train loop (predict-only) fall back to the
    # pipeline counter rather than SKIPping.
    m2 = measured_bad_fraction(_summary(counters={
        "pipeline/bad_lines": 1.0, "pipeline/examples": 99.0}))
    assert m2 == pytest.approx(0.01)
    assert measured_bad_fraction(_summary()) is None


def test_slo_auc_fallback_to_validation_gauge():
    spec = SloSpec(min_auc=0.5)
    rows = evaluate_slos(spec, _summary(
        gauges={"validation/auc": 0.7}))
    assert rows[0].status == "PASS" and rows[0].measured == 0.7
    # quality/auc wins when both exist (the fresher publish-time gauge)
    rows = evaluate_slos(spec, _summary(
        gauges={"validation/auc": 0.7, "quality/auc": 0.4}))
    assert rows[0].status == "FAIL" and rows[0].measured == 0.4


def test_render_slo_table_and_empty():
    spec = SloSpec(min_auc=0.8)
    rows = evaluate_slos(spec, _summary(gauges={"quality/auc": 0.9}))
    text = render_slo(spec, rows)
    assert "validation AUC" in text and ">= 0.8" in text
    assert "PASS" in text and "overall: PASS" in text
    assert "no SLO objectives configured" in render_slo(SloSpec(), [])


def _write_metrics(path, gauges=(), counters=(), latencies=()):
    from fast_tffm_tpu.obs.registry import MetricsRegistry
    from fast_tffm_tpu.obs.sink import JsonlSink
    from fast_tffm_tpu.serve.server import LATENCY_BUCKETS_MS
    reg = MetricsRegistry()
    for k, v in dict(gauges).items():
        reg.set(k, v)
    for k, v in dict(counters).items():
        reg.count(k, v)
    for v in latencies:
        reg.observe("serve/request_latency_ms", v,
                    bounds=LATENCY_BUCKETS_MS)
    sink = JsonlSink(str(path))
    sink.emit_metrics(10, reg.snapshot())
    sink.close()


def test_fmstat_slo_cli(tmp_path, capsys):
    from tools.fmstat import main as fmstat_main
    m = tmp_path / "m.jsonl"
    _write_metrics(
        m,
        gauges={"slo/publish_staleness_seconds": 30.0,
                "slo/p99_ms": 500.0, "slo/min_auc": 0.8,
                "slo/max_bad_fraction": 0.01,
                "stream/last_publish_age_seconds": 1.5,
                "quality/auc": 0.93},
        counters={"pipeline/examples": 1000.0},
        latencies=[3.0, 4.0, 120.0])
    assert fmstat_main(["slo", str(m), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["overall"] == "PASS"
    assert len(out["objectives"]) == 4
    assert out["spec"]["min_auc"] == 0.8
    assert "health" in out
    # Human table form.
    assert fmstat_main(["slo", str(m)]) == 0
    text = capsys.readouterr().out
    assert "overall: PASS" in text and "health:" in text
    # A failing objective exits 1 — the scriptable deployment check.
    bad = tmp_path / "bad.jsonl"
    _write_metrics(bad, gauges={"slo/min_auc": 0.8,
                                "quality/auc": 0.5})
    assert fmstat_main(["slo", str(bad)]) == 1
    # A DECLARED objective with no supporting data exits 2 (not 0): a
    # monitor must not read green when the measuring shard went
    # missing. --allow-skip opts back into 0 for split-stream setups.
    skipped = tmp_path / "skip.jsonl"
    _write_metrics(skipped, gauges={"slo/p99_ms": 100.0})
    assert fmstat_main(["slo", str(skipped)]) == 2
    assert fmstat_main(["slo", str(skipped), "--allow-skip"]) == 0
    # A stream with NO slo/* gauges at all (rotated/truncated metrics
    # file) is the silent-green hazard in its purest form: exit 2.
    empty = tmp_path / "empty.jsonl"
    _write_metrics(empty, counters={"train/examples": 10.0})
    assert fmstat_main(["slo", str(empty)]) == 2
    assert fmstat_main(["slo", str(empty), "--allow-skip"]) == 0
    capsys.readouterr()


def test_fmstat_slo_cli_config_spec(tmp_path, capsys):
    """--config reads the spec from a config file instead of the
    stream's gauges — evaluating yesterday's stream against today's
    objectives."""
    from tools.fmstat import main as fmstat_main
    m = tmp_path / "m.jsonl"
    _write_metrics(m, gauges={"quality/auc": 0.75})
    cfgp = tmp_path / "slo.cfg"
    cfgp.write_text("[SLO]\nslo_min_auc = 0.9\n")
    assert fmstat_main(["slo", str(m), "--config", str(cfgp)]) == 1
    capsys.readouterr()


# --- Prometheus exposition -------------------------------------------------


def test_prometheus_text_format_pin():
    from fast_tffm_tpu.obs.prom import metric_name, prometheus_text
    from fast_tffm_tpu.obs.registry import MetricsRegistry
    assert metric_name("serve/request_latency_ms") == \
        "fm_serve_request_latency_ms"
    assert metric_name("a-b.c d") == "fm_a_b_c_d"
    reg = MetricsRegistry()
    reg.count("serve/requests", 3)
    reg.set("serve/served_step", 41.0)
    for v in (0.6, 1.5, 1.5):
        reg.observe("serve/queue_depth", v, bounds=(1.0, 2.0))
    text = prometheus_text(reg.snapshot())
    assert text == (
        "# TYPE fm_serve_requests counter\n"
        "fm_serve_requests 3\n"
        "# TYPE fm_serve_served_step gauge\n"
        "fm_serve_served_step 41\n"
        "# TYPE fm_serve_queue_depth histogram\n"
        'fm_serve_queue_depth_bucket{le="1"} 1\n'
        'fm_serve_queue_depth_bucket{le="2"} 3\n'
        'fm_serve_queue_depth_bucket{le="+Inf"} 3\n'
        "fm_serve_queue_depth_sum 3.6\n"
        "fm_serve_queue_depth_count 3\n")


def test_prometheus_nonfinite_and_float_values():
    from fast_tffm_tpu.obs.prom import prometheus_text
    text = prometheus_text({"counters": {},
                            "gauges": {"g/nan": float("nan"),
                                       "g/inf": float("inf"),
                                       "g/f": 0.25},
                            "hists": {}})
    assert "fm_g_nan NaN" in text
    assert "fm_g_inf +Inf" in text
    assert "fm_g_f 0.25" in text


# --- fmstat --follow -------------------------------------------------------


def test_fmstat_follow_renders_and_tolerates_missing(tmp_path):
    from tools.fmstat import _follow
    m = tmp_path / "live.jsonl"
    out = io.StringIO()
    # Nothing there yet: the watch loop waits instead of dying.
    _follow([str(m)], interval=0.0, out=out, iterations=1)
    assert "waiting for" in out.getvalue()
    _write_metrics(m, counters={"train/examples": 64.0,
                                "train/steps": 2.0})
    out2 = io.StringIO()
    _follow([str(tmp_path / "live.jsonl*")], interval=0.0, out=out2,
            iterations=2)
    body = out2.getvalue()
    assert body.count("-- fmstat --follow") == 2
    assert "verdict:" in body and "examples" in body


# --- GATE-HELD in the verdict ladder --------------------------------------


def _verdict_summary(health=(), crash=(), gauges=None, counters=None,
                     run_ends=1):
    return {"meta": {}, "metas": [], "runs": 1, "events": 1,
            "spans": 0, "run_starts": 1, "run_ends": run_ends,
            "health_events": list(health), "crash_events": list(crash),
            "counters": counters or {}, "hists": {},
            "gauges": gauges or {}, "gauges_by_process": {},
            "scalars": []}


_HOLD = {"status": "gate_held", "step": 75, "auc": 0.1,
         "reasons": ["AUC 0.1 below publish_min_auc 0.7"]}


def test_gate_held_verdict_and_ranking():
    from fast_tffm_tpu.obs.attribution import health_verdict
    hv = health_verdict(_verdict_summary(health=[_HOLD]))
    assert hv["verdict"] == "GATE-HELD (x1)"
    assert "step 75" in hv["detail"]
    # Severity ladder: CRASHED / STALLED outrank a hold...
    hv = health_verdict(_verdict_summary(
        health=[_HOLD], crash=[{"error": "boom"}]))
    assert hv["verdict"] == "CRASHED"
    hv = health_verdict(_verdict_summary(
        health=[_HOLD, {"status": "stalled", "stalled_seconds": 9,
                        "stacks_file": "x"}]))
    assert hv["verdict"] == "STALLED"
    # ...but a hold outranks (and usually explains) STALE PUBLISH.
    hv = health_verdict(_verdict_summary(
        health=[_HOLD],
        gauges={"stream/publish_interval_seconds": 1.0,
                "stream/last_publish_age_seconds": 100.0}))
    assert hv["verdict"] == "GATE-HELD (x1)"


def test_health_notes_for_informational_kinds():
    from fast_tffm_tpu.obs.attribution import health_verdict
    hv = health_verdict(_verdict_summary(
        health=[{"status": "bad_input", "file": "x", "count": 3},
                {"status": "collective_slow"},
                {"status": "some_future_kind"}]))
    assert hv["verdict"] == "OK"
    assert "bad_input" in hv["detail"]
    assert "collective_slow" in hv["detail"]
    assert "some_future_kind" in hv["detail"]  # unrecognized → loud


def test_quality_section_renders():
    from fast_tffm_tpu.obs.attribution import attribution, render
    s = _verdict_summary(
        counters={"quality/evals": 4.0, "quality/eval_seconds": 0.4,
                  "quality/examples": 960.0,
                  "quality/gate_held": 1.0},
        gauges={"quality/auc": 0.91, "quality/loss": 0.33,
                "quality/calibration": 1.02})
    att = attribution(s)
    assert att["quality_evals"] == 4.0
    assert att["quality_auc"] == 0.91
    text = render(s)
    assert "QUALITY (per-publish eval + gate)" in text
    assert "publishes gate-held" in text
    # And absent on a stream that never ran the loop.
    assert "QUALITY" not in render(_verdict_summary())


def test_math_isnan_guard_in_results():
    """evaluate_slos treats NaN measurements as failures without
    raising — the comparison path must be explicit, not coincidental."""
    rows = evaluate_slos(SloSpec(p99_ms=10.0), _summary(
        hists={"serve/request_latency_ms": {"p99": float("nan")}}))
    assert rows[0].status == "FAIL"
    assert math.isnan(rows[0].measured)
