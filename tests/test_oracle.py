"""The oracle must be right before anything is tested against it: check it
against brute-force definitions computed a completely different way."""

import itertools

import numpy as np
import pytest

from fast_tffm_tpu.models import oracle


def rand_table(rng, v=20, k=3):
    return rng.normal(size=(v, k + 1)).astype(np.float64)


def brute_force_fm(table, ids, vals, order):
    """Direct sum over feature subsets of size 2..order (and the linear
    term) — O(n^order), no clever identities."""
    k = table.shape[1] - 1
    score = sum(table[i, k] * x for i, x in zip(ids, vals))
    n = len(ids)
    for t in range(2, order + 1):
        for combo in itertools.combinations(range(n), t):
            prod = np.ones(k)
            for j in combo:
                prod = prod * table[ids[j], :k] * vals[j]
            score += prod.sum()
    return score


def test_order2_identity_vs_brute_force(rng):
    table = rand_table(rng)
    for _ in range(20):
        n = rng.integers(1, 8)
        ids = rng.integers(0, 20, size=n)
        vals = rng.normal(size=n)
        fast = oracle.fm_score(table, ids, vals, order=2)
        slow = brute_force_fm(table, ids, vals, 2)
        assert fast == pytest.approx(slow, rel=1e-9, abs=1e-9)


def test_order2_with_repeated_ids(rng):
    # repeated feature ids are legal; identity must still hold
    table = rand_table(rng)
    ids, vals = [3, 3, 5], [1.0, 2.0, 0.5]
    assert oracle.fm_score(table, ids, vals) == pytest.approx(
        brute_force_fm(table, ids, vals, 2), rel=1e-9)


@pytest.mark.parametrize("order", [2, 3, 4])
def test_anova_vs_brute_force(rng, order):
    table = rand_table(rng)
    for _ in range(10):
        n = int(rng.integers(1, 7))
        ids = rng.integers(0, 20, size=n)
        vals = rng.normal(size=n)
        fast = oracle.fm_score(table, ids, vals, order=order)
        slow = brute_force_fm(table, ids, vals, order)
        assert fast == pytest.approx(slow, rel=1e-8, abs=1e-8)


def test_ffm_brute_force(rng):
    field_num, k, v = 3, 2, 10
    table = rng.normal(size=(v, field_num * k + 1)).astype(np.float64)
    ids, fields, vals = [1, 4, 7], [0, 2, 1], [0.5, 1.0, 2.0]
    got = oracle.ffm_score(table, field_num, ids, fields, vals)
    # manual: linear + pairwise with field-selected vectors
    want = sum(table[i, -1] * x for i, x in zip(ids, vals))
    for a, b in itertools.combinations(range(3), 2):
        va = table[ids[a], :field_num * k].reshape(field_num, k)[fields[b]]
        vb = table[ids[b], :field_num * k].reshape(field_num, k)[fields[a]]
        want += float(va @ vb) * vals[a] * vals[b]
    assert got == pytest.approx(want, rel=1e-12)


def test_regularization_unique_rows(rng):
    table = rand_table(rng)
    batch = [([1, 2, 2], [1.0, 1.0, 1.0]), ([2, 3], [1.0, 1.0])]
    k = table.shape[1] - 1
    reg = oracle.regularization(table, batch, 0.5, 0.25)
    rows = table[[1, 2, 3]]
    want = 0.5 * np.sum(rows[:, :k] ** 2) + 0.25 * np.sum(rows[:, k] ** 2)
    assert reg == pytest.approx(want, rel=1e-12)


def test_logistic_loss_matches_naive():
    scores = np.array([0.0, 2.0, -3.0])
    labels = np.array([1.0, 0.0, 1.0])
    naive = np.mean([np.log(1 + np.exp(-s)) if y == 1 else
                     np.log(1 + np.exp(s))
                     for s, y in zip(scores, labels)])
    assert oracle.logistic_loss(scores, labels) == pytest.approx(
        float(naive), rel=1e-9)


def test_grad_fd_sanity(rng):
    # finite-diff grad of the linear weight of a single-feature example
    # has a closed form: dL/dw = sigmoid(s) - y times x (mean over batch=1)
    table = np.zeros((5, 3))
    table[2] = [0.0, 0.0, 0.5]        # w=0.5, v=0
    batch = [([2], [2.0])]
    labels = np.array([1.0])
    g = oracle.grad_fd(table, batch, labels)
    s = 1.0  # w*x = 0.5*2
    sig = 1 / (1 + np.exp(-s))
    assert g[2, 2] == pytest.approx((sig - 1.0) * 2.0, rel=1e-4)
    assert np.all(g[[0, 1, 3, 4]] == 0)
