"""Device-memory ledger + capacity planner (obs/memory.py, ISSUE 18):
one sizing formula, owner-tagged ledger gauges that never touch the
device, once-per-episode pressure events, OOM forensics at the
dispatch sites, the fmstat capacity planner cross-checked against the
LIVE ledger on real train/serve runs, and the serve reload spike /
capacity-degrade path."""

import json
import os

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs import memory as mem
from fast_tffm_tpu.obs.sink import read_events
from fast_tffm_tpu.obs.telemetry import RunTelemetry

pytestmark = pytest.mark.filterwarnings(
    "ignore::DeprecationWarning")


@pytest.fixture(autouse=True)
def _clean_ledger(monkeypatch):
    """The ledger and the fake-capacity env are process-global: every
    test starts from an empty book and a capacity-less backend."""
    monkeypatch.delenv(mem.FAKE_CAPACITY_ENV, raising=False)
    mem.LEDGER.reset()
    yield
    mem.LEDGER.reset()


def _corpus(path, n, vocab, seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        y = int(rng.integers(0, 2))
        feats = sorted(rng.choice(vocab, size=4, replace=False))
        lines.append(f"{y} " + " ".join(f"{i}:1.0" for i in feats))
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")


def _train_cfg(tmp_path, **kw):
    _corpus(str(tmp_path / "train.txt"), 128, 4000)
    base = dict(vocabulary_size=4000, factor_num=8, batch_size=32,
                learning_rate=0.1, epoch_num=1, shuffle=False,
                max_features_per_example=16, bucket_ladder=(8, 16),
                train_files=(str(tmp_path / "train.txt"),),
                model_file=str(tmp_path / "m" / "fm"),
                metrics_file="auto", metrics_flush_steps=2,
                log_steps=0)
    base.update(kw)
    return FmConfig(**base)


# ------------------------------------------ table_bytes consolidation

def test_table_bytes_is_the_one_sizing_formula():
    cfg = FmConfig(vocabulary_size=1000, factor_num=4)
    assert mem.table_bytes(cfg) == cfg.num_rows * cfg.row_dim * 4
    ffm = FmConfig(vocabulary_size=1000, factor_num=4, field_num=3,
                   model_type="ffm")
    assert ffm.row_dim == 4 * 3 + 1
    assert mem.table_bytes(ffm) == ffm.num_rows * ffm.row_dim * 4
    # Explicit rows/dim for call sites with no config in scope, and
    # dtype_bytes for the planner's f16/int8 what-ifs.
    assert mem.table_bytes(rows=10, dim=5) == 200
    assert mem.table_bytes(cfg, dtype_bytes=2) \
        == cfg.num_rows * cfg.row_dim * 2


def test_lookup_memory_report_reads_through_the_seam(monkeypatch):
    """Satellite 3 (R018 migration): lookup.memory_report's device
    numbers come from obs/memory.device_memory_stats — unmeasured on
    the CPU container, the injected value under FM_FAKE_HBM_BYTES."""
    from fast_tffm_tpu.lookup import memory_report
    rep = memory_report()
    assert rep["device_in_use_mb"] is None  # unmeasured, never fake 0
    assert rep["device_limit_mb"] is None
    mem.LEDGER.register("table", 8 << 20)
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, str(64 << 20))
    rep = memory_report()
    assert rep["device_in_use_mb"] == 8
    assert rep["device_limit_mb"] == 64


# --------------------------------------------------- ledger semantics

def test_ledger_register_release_peak():
    L = mem.LEDGER
    L.register("table", 100)
    L.register("acc", 50)
    assert L.live_bytes() == 150
    assert L.peak_bytes() == 150
    L.register("table", 80)           # upsert, not accumulate
    assert L.live_bytes() == 130
    L.release("acc")
    assert L.live_bytes() == 80
    assert L.peak_bytes() == 150      # watermark survives releases
    L.release("never_registered")     # idempotent
    L.reset()
    assert L.live_bytes() == 0 and L.peak_bytes() == 0


def test_ledger_host_owners_excluded_from_device_total():
    L = mem.LEDGER
    L.register("table", 100)
    L.register("offload_table", 10_000, host=True)
    assert L.live_bytes() == 100
    assert L.host_owners() == {"offload_table": 10_000}
    # Re-registering on the other book moves the owner, not doubles it.
    L.register("offload_table", 10_000)
    assert L.live_bytes() == 10_100
    assert L.host_owners() == {}


def test_pressure_episode_fires_once_until_rearmed():
    L = mem.LEDGER
    assert L.begin_pressure_episode() is True
    assert L.begin_pressure_episode() is False
    L.end_pressure_episode()
    assert L.begin_pressure_episode() is True


# --------------------------------------------- the memory_stats seam

def test_seam_reports_none_on_cpu_and_env_injects_capacity(
        monkeypatch):
    assert mem.device_memory_stats() is None  # CPU container policy
    assert mem.device_capacity_bytes() is None
    mem.LEDGER.register("table", 300)
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, "1000")
    stats = mem.device_memory_stats()
    assert stats == {"bytes_limit": 1000, "bytes_in_use": 300}
    assert mem.device_capacity_bytes() == 1000


# ------------------------------------------------------ mem/* gauges

def test_ledger_gauges_empty_until_first_registration():
    assert mem.ledger_gauges() == {}


def test_ledger_gauges_rows(monkeypatch):
    mem.LEDGER.register("table", 100)
    mem.LEDGER.register("offload_acc", 40, host=True)
    rows = mem.ledger_gauges()
    assert rows["mem/table_bytes"] == 100.0
    assert rows["mem/offload_acc_bytes"] == 40.0
    assert rows["mem/live_bytes"] == 100.0
    assert rows["mem/host_live_bytes"] == 40.0
    assert rows["mem/peak_bytes"] == 100.0
    assert "mem/capacity_bytes" not in rows  # no capacity on CPU
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, "1000")
    rows = mem.ledger_gauges()
    assert rows["mem/capacity_bytes"] == 1000.0
    assert rows["mem/utilization_fraction"] == pytest.approx(0.1)


def test_mem_gauges_add_zero_device_fetches(tmp_path, monkeypatch):
    """THE acceptance pin: a flush that carries the full mem/* surface
    performs NO bulk_fetch — the ledger is host ints end to end,
    exactly the ``anatomy_gauges`` contract."""
    import fast_tffm_tpu.utils.fetch as fetch
    calls = []
    monkeypatch.setattr(fetch, "bulk_fetch",
                        lambda pairs, consume: calls.append(len(pairs))
                        or [])
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, "10000")
    mem.LEDGER.register("table", 800)
    mem.LEDGER.register("wire_buffers", 200)
    tel = RunTelemetry(str(tmp_path / "m.jsonl"), meta={},
                       flush_steps=1)
    tel.maybe_flush(1)
    tel.barrier_flush(2)
    tel.close()
    assert calls == []  # zero device fetches, ever
    evs = [e for e in read_events(str(tmp_path / "m.jsonl"))
           if e.get("event") == "metrics"]
    g = evs[-1]["gauges"]
    assert g["mem/table_bytes"] == 800.0
    assert g["mem/wire_buffers_bytes"] == 200.0
    assert g["mem/live_bytes"] == 1000.0
    assert g["mem/capacity_bytes"] == 10000.0


def test_empty_ledger_keeps_streams_byte_identical(tmp_path):
    """Pre-ledger consumers (and bare-registry tests) see no mem/*
    rows at all when nothing ever registered."""
    tel = RunTelemetry(str(tmp_path / "m.jsonl"), meta={},
                       flush_steps=1)
    tel.count("steps")
    tel.maybe_flush(1)
    tel.close()
    evs = [e for e in read_events(str(tmp_path / "m.jsonl"))
           if e.get("event") == "metrics"]
    assert not [k for e in evs for k in e["gauges"]
                if k.startswith("mem/")]


# --------------------------------------------------- pressure events

def test_hbm_pressure_emits_once_per_episode(tmp_path, monkeypatch):
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, "1000")
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={}, flush_steps=1,
                       mem_pressure_fraction=0.5)
    mem.LEDGER.register("table", 600)     # 60% > 50% -> crossing
    tel.maybe_flush(1)
    tel.maybe_flush(2)                    # inside the episode: silent
    mem.LEDGER.register("table", 100)     # back below: re-arm
    tel.maybe_flush(3)
    mem.LEDGER.register("table", 900)     # second crossing
    tel.maybe_flush(4)
    tel.close()
    evs = list(read_events(path))
    pressure = [e for e in evs if e.get("event") == "health"
                and e.get("status") == "hbm_pressure"]
    assert len(pressure) == 2
    ev = pressure[0]
    assert ev["live_bytes"] == 600
    assert ev["capacity_bytes"] == 1000
    assert ev["threshold"] == 0.5
    assert ev["owners"] == {"table": 600}
    last = [e for e in evs if e.get("event") == "metrics"][-1]
    assert last["counters"]["mem/pressure_events"] == 2


def test_pressure_off_by_default_and_without_capacity(tmp_path,
                                                      monkeypatch):
    path = str(tmp_path / "m.jsonl")
    mem.LEDGER.register("table", 999)
    # Knob 0 -> no event even with capacity present.
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, "1000")
    tel = RunTelemetry(path, meta={}, flush_steps=1)
    tel.maybe_flush(1)
    tel.close()
    # Knob set but no capacity (CPU) -> no event either.
    monkeypatch.delenv(mem.FAKE_CAPACITY_ENV)
    tel = RunTelemetry(path + "2", meta={}, flush_steps=1,
                       mem_pressure_fraction=0.5)
    tel.maybe_flush(1)
    tel.close()
    for p in (path, path + "2"):
        assert not [e for e in read_events(p)
                    if e.get("event") == "health"]


def test_mem_pressure_fraction_knob_validates():
    cfg = FmConfig(mem_pressure_fraction=0.9)
    assert cfg.mem_pressure_fraction == 0.9
    with pytest.raises(ValueError, match="mem_pressure_fraction"):
        FmConfig(mem_pressure_fraction=1.5)


# ---------------------------------------------------- OOM forensics

def test_is_oom_matches_runtime_spellings():
    assert mem.is_oom(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert mem.is_oom(RuntimeError("Resource exhausted: hbm"))
    assert mem.is_oom(mem.HbmExhaustedError("wrapped"))
    assert not mem.is_oom(RuntimeError("INVALID_ARGUMENT"))


def test_oom_guard_wraps_with_ledger_and_hint():
    mem.LEDGER.register("table", 4 << 20)
    mem.LEDGER.register("adagrad_acc", 4 << 20)
    with pytest.raises(mem.HbmExhaustedError) as ei:
        with mem.oom_guard("train/step"):
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
    msg = str(ei.value)
    assert "train/step" in msg
    assert "table" in msg and "adagrad_acc" in msg
    assert "fmstat capacity" in msg
    assert isinstance(ei.value.__cause__, RuntimeError)


def test_oom_guard_passes_other_errors_and_never_double_wraps():
    with pytest.raises(ValueError):
        with mem.oom_guard("x"):
            raise ValueError("not an oom")
    inner = mem.HbmExhaustedError("already attributed")
    with pytest.raises(mem.HbmExhaustedError) as ei:
        with mem.oom_guard("outer"):
            with mem.oom_guard("inner"):
                raise inner
    assert ei.value is inner


def test_injected_oom_at_train_dispatch_names_owners(tmp_path,
                                                     monkeypatch):
    """Acceptance: a RESOURCE_EXHAUSTED at the train dispatch site
    surfaces the per-owner breakdown in the wrapped error AND a crash
    event in the stream."""
    import fast_tffm_tpu.train as train_mod

    def exploding_maker(*maker_args, **maker_kw):
        def step(table, acc, **kw):
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory while trying to "
                "allocate 1073741824 bytes.")
        return step

    # The test harness fakes several CPU devices, so the session may
    # build either the plain or the mesh step — explode both makers
    # (the sharded one is imported locally inside the session).
    import fast_tffm_tpu.parallel.sharded as sharded_mod
    monkeypatch.setattr(train_mod, "make_train_step", exploding_maker)
    monkeypatch.setattr(sharded_mod, "make_sharded_train_step",
                        exploding_maker)
    cfg = _train_cfg(tmp_path)
    with pytest.raises(mem.HbmExhaustedError) as ei:
        train_mod.train(cfg)
    msg = str(ei.value)
    assert "device out of memory at train/step" in msg
    assert "table" in msg and "adagrad_acc" in msg
    assert "fmstat capacity" in msg
    crash = [e for e in read_events(cfg.model_file + ".metrics.jsonl")
             if e.get("event") == "crash"]
    assert crash
    assert "RESOURCE_EXHAUSTED" in crash[0]["error"]


# ------------------------------------------------- capacity planner

def test_parse_what_if():
    assert mem.parse_what_if("") == {}
    assert mem.parse_what_if(
        "vocabulary_size=1000, dtype=f16,shards=4") \
        == {"vocabulary_size": 1000, "dtype": "f16", "shards": 4}
    with pytest.raises(ValueError, match="key=value"):
        mem.parse_what_if("vocab:1000")
    with pytest.raises(ValueError, match="dtype"):
        mem.parse_what_if("dtype=f13")


def test_plan_train_owners_and_overrides():
    cfg = FmConfig(vocabulary_size=1000, factor_num=4, batch_size=32,
                   max_features_per_example=16)
    p = mem.plan(cfg, "train")
    tbl = mem.table_bytes(cfg)
    assert p["owners"]["table"] == tbl
    assert p["owners"]["adagrad_acc"] == tbl
    assert p["owners"]["wire_buffers"] == 2 * (32 * 16 * 8 + 32 * 4)
    assert p["total_bytes"] == sum(p["owners"].values())
    assert p["verdict"].startswith("UNKNOWN")  # no capacity on CPU
    # Overrides: vocab scales rows; f16 halves the table but the
    # Adagrad accumulator stays f32; shards divide the per-device row.
    p2 = mem.plan(cfg, "train", {"vocabulary_size": 2000})
    assert p2["owners"]["table"] == 2001 * cfg.row_dim * 4
    p3 = mem.plan(cfg, "train", {"dtype": "f16"})
    assert p3["owners"]["table"] == tbl // 2
    assert p3["owners"]["adagrad_acc"] == tbl
    p4 = mem.plan(cfg, "train", {"shards": 4})
    assert p4["owners"]["table"] == -(-tbl // 4)


def test_plan_serve_and_offload_host_owners():
    cfg = FmConfig(vocabulary_size=1000, factor_num=4)
    p = mem.plan(cfg, "serve")
    tbl = mem.table_bytes(cfg)
    assert p["owners"] == {"serve_table": tbl,
                           "serve_reload_transient": tbl}
    off = FmConfig(vocabulary_size=1000, factor_num=4, lookup="host",
                   dedup="host")
    po = mem.plan(off, "train")
    assert "table" not in po["owners"]
    # Same owner tags the train session registers (host book).
    assert po["host_owners"]["offload_table"] == tbl
    assert po["host_owners"]["offload_acc"] == tbl
    assert po["total_bytes"] == po["owners"]["wire_buffers"]


def test_plan_verdict_against_capacity(monkeypatch):
    cfg = FmConfig(vocabulary_size=1000, factor_num=4)
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, str(1 << 30))
    assert mem.plan(cfg, "serve")["verdict"] == "FITS"
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, "1024")
    p = mem.plan(cfg, "serve")
    assert p["verdict"] == "EXCEEDS"
    text = mem.render_plan(p)
    assert "serve_table" in text
    assert "predicted device total" in text
    assert "verdict: EXCEEDS" in text


def test_preflight_refuses_oversized_and_noop_without_capacity(
        monkeypatch):
    cfg = FmConfig(vocabulary_size=100_000, factor_num=8)
    mem.preflight_capacity(cfg, "train")  # CPU: no capacity, no-op
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, "65536")
    with pytest.raises(ValueError) as ei:
        mem.preflight_capacity(cfg, "train")
    msg = str(ei.value)
    assert "predicted device total" in msg
    assert "fmstat capacity" in msg and "--what-if" in msg
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, str(1 << 34))
    mem.preflight_capacity(cfg, "train")  # fits: silent


def test_train_preflight_fails_fast(tmp_path, monkeypatch):
    """Satellite 2: the oversized config is refused BEFORE any device
    allocation, with the planner breakdown in the error."""
    from fast_tffm_tpu.train import train
    cfg = _train_cfg(tmp_path, vocabulary_size=100_000)
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, "65536")
    with pytest.raises(ValueError, match="predicted device total"):
        train(cfg)


# ------------------------------- plan vs live ledger (the 10% check)

def test_plan_within_10pct_of_live_ledger_train(tmp_path):
    """Acceptance: the from-config prediction agrees with the ledger
    a REAL train run registered, within 10%, for the default train
    shape."""
    from fast_tffm_tpu.train import train
    cfg = _train_cfg(tmp_path)
    train(cfg)
    live = 0.0
    for ev in read_events(cfg.model_file + ".metrics.jsonl"):
        if ev.get("event") == "metrics":
            live = max(live, ev["gauges"].get("mem/live_bytes", 0.0))
    assert live > 0
    p = mem.plan(cfg, "train")
    assert p["total_bytes"] == pytest.approx(live, rel=0.10)
    # The model state itself is predicted exactly.
    assert p["owners"]["table"] == mem.table_bytes(cfg)


def _served(tmp_path, **overrides):
    """A published checkpoint + a live ScorerServer against it."""
    from fast_tffm_tpu.checkpoint import CheckpointState
    from fast_tffm_tpu.serve import ScorerServer
    cfg = FmConfig(vocabulary_size=4000, factor_num=4,
                   max_features_per_example=16, bucket_ladder=(8, 16),
                   serve_max_batch=8, serve_poll_seconds=60.0,
                   model_file=str(tmp_path / "m" / "fm"), **overrides)
    rng = np.random.default_rng(0)
    table = rng.standard_normal(
        (cfg.ckpt_rows, cfg.row_dim)).astype(np.float32) * 0.01
    ckpt = CheckpointState(cfg.model_file)
    for step in (1, 2):
        ckpt.save(step, table, np.full_like(table, 0.1),
                  vocabulary_size=cfg.vocabulary_size, wait=True)
    ckpt.publish_step(1)
    ckpt.close()
    return cfg, ScorerServer(cfg, watch=False)


def test_plan_within_10pct_of_live_ledger_serve(tmp_path):
    cfg, server = _served(tmp_path)
    try:
        live = mem.LEDGER.owners()
        p = mem.plan(cfg, "serve")
        assert live["serve_table"] == pytest.approx(
            p["owners"]["serve_table"], rel=0.10)
        # Steady-state serving holds ONE table; the transient is plan
        # headroom, not resident state.
        assert "serve_reload_table" not in live
    finally:
        server.close()
    assert mem.LEDGER.owners() == {}  # close releases its owners


# ------------------------------------------- serve reload spike path

def test_serve_reload_spike_gauges_old_plus_new(tmp_path):
    """Acceptance: a real hot reload's serve/reload_peak_bytes shows
    the old+new transient."""
    cfg, server = _served(tmp_path)
    try:
        old = mem.LEDGER.owners()["serve_table"]
        assert server.reload_step(2)
        g = server._reg.snapshot()["gauges"]
        assert g["serve/reload_peak_bytes"] == float(
            old + mem.LEDGER.owners()["serve_table"])
        assert g["serve/reload_peak_bytes"] == pytest.approx(
            2 * mem.table_bytes(cfg))
    finally:
        server.close()


def test_reload_exceeding_capacity_degrades_to_counted_failure(
        tmp_path, monkeypatch):
    """A reload whose old+new transient would not fit is REFUSED on
    the keep-serving path: reload_failures counts it, the old step
    keeps serving, and nothing was allocated."""
    cfg, server = _served(tmp_path)
    try:
        resident = mem.LEDGER.live_bytes()
        # Room for the old table plus half a new one: the swap's
        # old+new transient cannot fit.
        monkeypatch.setenv(mem.FAKE_CAPACITY_ENV,
                           str(resident + mem.table_bytes(cfg) // 2))
        assert not server.reload_step(2)
        snap = server._reg.snapshot()
        assert snap["counters"]["serve/reload_failures"] == 1
        assert snap["gauges"]["serve/served_step"] == 1.0
        assert "serve_reload_table" not in mem.LEDGER.owners()
        # With headroom restored the same reload succeeds.
        monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, str(1 << 30))
        assert server.reload_step(2)
        assert server._reg.snapshot()["gauges"]["serve/served_step"] \
            == 2.0
    finally:
        server.close()


def test_server_startup_preflight_fails_fast(tmp_path, monkeypatch):
    from fast_tffm_tpu.checkpoint import CheckpointState
    from fast_tffm_tpu.serve import ScorerServer
    cfg = FmConfig(vocabulary_size=4000, factor_num=4,
                   max_features_per_example=16, bucket_ladder=(8, 16),
                   serve_max_batch=8,
                   model_file=str(tmp_path / "m" / "fm"))
    table = np.zeros((cfg.ckpt_rows, cfg.row_dim), dtype=np.float32)
    ckpt = CheckpointState(cfg.model_file)
    ckpt.save(1, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    ckpt.publish_step(1)
    ckpt.close()
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, "4096")
    with pytest.raises(ValueError, match="predicted device total"):
        ScorerServer(cfg, watch=False)


# ------------------------------------------------ fmstat / fmtrace

def _write_cfg_file(tmp_path, vocab=1000):
    p = tmp_path / "t.cfg"
    p.write_text(f"""
[General]
vocabulary_size = {vocab}
factor_num = 4
model_file = {tmp_path}/model/fm

[Train]
train_files = {tmp_path}/train.txt
batch_size = 32
max_features_per_example = 16
""")
    return str(p)


def test_fmstat_capacity_cli(tmp_path, capsys):
    from tools.fmstat import main
    cfg_path = _write_cfg_file(tmp_path)
    assert main(["capacity", cfg_path]) == 0
    out = capsys.readouterr().out
    assert "capacity plan (train)" in out
    assert "predicted device total" in out
    assert "UNKNOWN" in out  # CPU: no capacity
    # --what-if + --capacity-bytes: verdict + exit code track EXCEEDS.
    assert main(["capacity", cfg_path, "--kind", "serve",
                 "--what-if", "vocabulary_size=1000000,dtype=f16",
                 "--capacity-bytes", str(1 << 30)]) == 0
    assert "FITS" in capsys.readouterr().out
    assert main(["capacity", cfg_path, "--capacity-bytes",
                 "1024"]) == 1
    assert "EXCEEDS" in capsys.readouterr().out


def test_fmstat_capacity_json(tmp_path, capsys):
    from tools.fmstat import main
    cfg_path = _write_cfg_file(tmp_path)
    assert main(["capacity", cfg_path, "--json", "--what-if",
                 "shards=2"]) == 0
    p = json.loads(capsys.readouterr().out)
    assert p["kind"] == "train"
    assert p["overrides"] == {"shards": 2}
    assert p["total_bytes"] == sum(p["owners"].values())


def test_fmtrace_fraction_counter_unit():
    from tools.fmtrace import counter_track
    assert counter_track("mem/utilization_fraction") \
        == "mem/utilization_fraction [ratio]"
    assert counter_track("mem/live_bytes") == "mem/live_bytes [B]"


# --------------------------------------------- fmstat MEMORY section

def test_memory_table_from_gauges():
    from fast_tffm_tpu.obs.attribution import memory_table
    assert memory_table({"gauges": {}}) is None
    t = memory_table({
        "gauges": {"mem/table_bytes": 80.0, "mem/live_bytes": 100.0,
                   "mem/peak_bytes": 200.0,
                   "mem/capacity_bytes": 1000.0,
                   "mem/utilization_fraction": 0.1,
                   "serve/reload_peak_bytes": 160.0},
        "counters": {"mem/pressure_events": 2.0}})
    assert t["owners"] == {"table": 80.0}
    assert t["live_bytes"] == 100.0
    assert t["peak_bytes"] == 200.0
    assert t["capacity_bytes"] == 1000.0
    assert t["pressure_events"] == 2.0
    assert t["reload_peak_bytes"] == 160.0


def test_render_memory_section_and_pressure_verdict(tmp_path,
                                                    monkeypatch):
    """End to end through the REAL stream: a pressured run renders a
    MEMORY section and an HBM-PRESSURE verdict (ranked below DEGRADED,
    above STALE PUBLISH)."""
    from fast_tffm_tpu.obs.attribution import (health_verdict, render,
                                               summarize)
    from fast_tffm_tpu.train import train
    cfg = _train_cfg(tmp_path, mem_pressure_fraction=0.5)
    resident = 2 * mem.table_bytes(cfg)
    monkeypatch.setenv(mem.FAKE_CAPACITY_ENV, str(int(resident / 0.6)))
    train(cfg)
    summary = summarize([cfg.model_file + ".metrics.jsonl"])
    v = health_verdict(summary)
    assert v["verdict"].startswith("HBM-PRESSURE")
    assert "fmstat capacity" in v["detail"]
    text = render(summary)
    assert "MEMORY" in text
    assert "live / peak" in text


def test_pressure_ranks_below_worker_loss():
    from fast_tffm_tpu.obs.attribution import health_verdict
    pressure = {"status": "hbm_pressure", "fraction": 0.95,
                "threshold": 0.9, "owners": {"table": 100}}
    lost = {"status": "worker_lost",
            "lost": [{"process_index": 1}]}
    v = health_verdict({"health_events": [pressure, lost],
                        "run_starts": 1, "run_ends": 1})
    assert v["verdict"].startswith("DEGRADED")
    v = health_verdict({"health_events": [pressure],
                        "run_starts": 1, "run_ends": 1})
    assert v["verdict"].startswith("HBM-PRESSURE")
