"""Device math (ops/interaction.py) vs the NumPy oracle, through the real
pipeline (bucketed padding, host-side unique)."""

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.parser import ParsedBlock
from fast_tffm_tpu.data.pipeline import make_device_batch
from fast_tffm_tpu.models import oracle
from fast_tffm_tpu.ops.interaction import (batch_reg, ffm_batch_scores,
                                           fm_batch_scores, gather_rows)

V, K = 50, 4


def random_batch(rng, n, max_nnz=6, with_fields=False, field_num=3):
    examples, blocks = [], dict(labels=[], poses=[0], ids=[], vals=[],
                                fields=[])
    for _ in range(n):
        nnz = int(rng.integers(1, max_nnz + 1))
        ids = rng.choice(V, size=nnz, replace=False)
        vals = rng.normal(size=nnz)
        blocks["labels"].append(float(rng.integers(0, 2)))
        blocks["ids"].extend(ids.tolist())
        blocks["vals"].extend(vals.tolist())
        blocks["poses"].append(len(blocks["ids"]))
        if with_fields:
            flds = rng.integers(0, field_num, size=nnz)
            blocks["fields"].extend(flds.tolist())
            examples.append((ids.tolist(), flds.tolist(), vals.tolist()))
        else:
            examples.append((ids.tolist(), vals.tolist()))
    block = ParsedBlock(
        labels=np.array(blocks["labels"], np.float32),
        poses=np.array(blocks["poses"], np.int32),
        ids=np.array(blocks["ids"], np.int32),
        vals=np.array(blocks["vals"], np.float32),
        fields=(np.array(blocks["fields"], np.int32) if with_fields
                else None))
    return examples, block


def make_cfg(**kw):
    kw.setdefault("vocabulary_size", V)
    kw.setdefault("factor_num", K)
    kw.setdefault("batch_size", 8)
    kw.setdefault("bucket_ladder", (8,))
    return FmConfig(**kw)


def padded_table(rng, cfg):
    t = rng.normal(size=(cfg.num_rows, cfg.row_dim)).astype(np.float32) * 0.3
    t[-1] = 0.0
    return t


@pytest.mark.parametrize("order", [2, 3])
def test_scores_match_oracle(rng, order):
    cfg = make_cfg(order=order)
    examples, block = random_batch(rng, 5)
    b = make_device_batch(block, cfg)
    table = padded_table(rng, cfg)
    gathered = gather_rows(table, b.uniq_ids)
    got = np.asarray(fm_batch_scores(gathered, b.local_idx, b.vals,
                                     order=order))
    want = oracle.batch_scores(table[:-1].astype(np.float64), examples,
                               order=order)
    np.testing.assert_allclose(got[:b.num_real], want, rtol=2e-4, atol=2e-4)
    # padded dummy examples score exactly 0
    np.testing.assert_array_equal(got[b.num_real:], 0.0)


def test_ffm_scores_match_oracle(rng):
    field_num = 3
    cfg = make_cfg(model_type="ffm", field_num=field_num)
    examples, block = random_batch(rng, 4, with_fields=True,
                                   field_num=field_num)
    b = make_device_batch(block, cfg)
    table = padded_table(rng, cfg)
    gathered = gather_rows(table, b.uniq_ids)
    got = np.asarray(ffm_batch_scores(gathered, field_num, b.local_idx,
                                      b.fields, b.vals))
    want = np.array([
        oracle.ffm_score(table[:-1].astype(np.float64), field_num, i, f, x)
        for i, f, x in examples])
    np.testing.assert_allclose(got[:b.num_real], want, rtol=2e-4, atol=2e-4)


def test_reg_matches_oracle(rng):
    cfg = make_cfg()
    examples, block = random_batch(rng, 5)
    b = make_device_batch(block, cfg)
    table = padded_table(rng, cfg)
    gathered = gather_rows(table, b.uniq_ids)
    got = float(batch_reg(gathered, b.uniq_ids, V, 0.1, 0.05))
    want = oracle.regularization(table[:-1].astype(np.float64),
                                 examples, 0.1, 0.05)
    assert got == pytest.approx(want, rel=1e-4)


def test_empty_example_scores_zero(rng):
    cfg = make_cfg()
    # one real example, rest padding; a dummy has no features
    _, block = random_batch(rng, 1)
    b = make_device_batch(block, cfg)
    table = padded_table(rng, cfg)
    got = np.asarray(fm_batch_scores(gather_rows(table, b.uniq_ids),
                                     b.local_idx, b.vals))
    assert np.all(got[1:] == 0.0)
