"""Sharded input correctness: byte-range partitioning (each worker reads
only its ~1/N of the bytes — SURVEY.md §3.2's per-worker input shards),
the C++ fast path staying engaged for multi-shard input (VERDICT round-1
item #1), and the fixed unique-bucket spill protocol (item #2)."""

import numpy as np
import pytest

# Capability skip (ISSUE 3 triage): the container may not ship
# hypothesis; without this the module is a COLLECTION ERROR that hides
# real regressions elsewhere in the suite.
pytest.importorskip("hypothesis",
                    reason="hypothesis not installed in this container")
from hypothesis import given, settings, strategies as st  # noqa: E402

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.parser import WHITESPACE
from fast_tffm_tpu.data.pipeline import (_iter_lines, batch_iterator,
                                         probe_uniq_bucket,
                                         shard_byte_range)


def _shard_lines(path, num_shards, keep_empty=False):
    return [
        [line for line, _, _ in _iter_lines([path], (), i, num_shards,
                                            keep_empty=keep_empty)]
        for i in range(num_shards)
    ]


@settings(max_examples=60, deadline=None)
@given(lines=st.lists(st.text(alphabet=st.characters(
    blacklist_characters="\n\r", blacklist_categories=("Cs",)),
    max_size=24), max_size=40),
    num_shards=st.integers(1, 5), trailing_newline=st.booleans())
def test_byte_range_partition_property(tmp_path_factory, lines, num_shards,
                                       trailing_newline):
    """Every non-blank line lands in exactly one shard, and shard
    concatenation preserves file order (ranges are contiguous)."""
    tmp = tmp_path_factory.mktemp("p")
    content = "\n".join(lines) + ("\n" if trailing_newline and lines else "")
    p = tmp / "f.txt"
    p.write_text(content, encoding="utf-8")
    shards = _shard_lines(str(p), num_shards)
    merged = [ln for shard in shards for ln in shard]
    # Blankness is judged by the libsvm separator set (parser.WHITESPACE,
    # pinned to the C++ is_ws) — a line of ASCII control separators like
    # \x1f is DATA (a parse error downstream), not a blank line.
    expected = [ln for ln in lines if ln.strip(WHITESPACE)]
    assert merged == expected


def test_byte_ranges_cover_file(tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("a\nbb\nccc\n")
    n = 3
    ranges = [shard_byte_range(str(p), i, n) for i in range(n)]
    assert ranges[0][0] == 0
    assert ranges[-1][1] == 9
    for (s0, e0), (s1, _) in zip(ranges, ranges[1:]):
        assert e0 == s1


def _write_indexed(tmp_path, n, vocab, feats_per_line, seed=0):
    """Line i: label i with feats_per_line distinct ids (line-dependent),
    so batches can be mapped back to source lines exactly."""
    rng = np.random.default_rng(seed)
    lines = []
    per_line = []
    for i in range(n):
        ids = rng.choice(vocab, size=feats_per_line, replace=False)
        per_line.append(set(int(x) for x in ids))
        lines.append(" ".join([str(i)] + [f"{j}:0.5" for j in ids]))
    p = tmp_path / "train.txt"
    p.write_text("\n".join(lines) + "\n")
    return str(p), per_line


def _examples(batches):
    """{label -> set of feature ids} reconstructed from device batches."""
    out = {}
    for b in batches:
        for r in range(b.num_real):
            mask = b.vals[r] != 0
            ids = b.uniq_ids[b.local_idx[r][mask]]
            key = int(b.labels[r])
            assert key not in out, "example emitted twice"
            out[key] = set(int(x) for x in ids)
    return out


def test_fast_path_serves_sharded_input(tmp_path, monkeypatch):
    """num_shards=2 must stream through the C++ BatchBuilder — no
    per-line Python parsing — and the two shards exactly partition the
    data."""
    import fast_tffm_tpu.data.cparser as cparser
    import fast_tffm_tpu.data.parser as parser
    path, per_line = _write_indexed(tmp_path, n=103, vocab=4096,
                                    feats_per_line=5)
    cfg = FmConfig(vocabulary_size=4096, batch_size=16, shuffle=False,
                   max_features_per_example=8, bucket_ladder=(8,))

    def _boom(*a, **k):
        raise AssertionError("per-line Python parse on the fast path")

    monkeypatch.setattr(parser, "parse_lines", _boom)
    monkeypatch.setattr(cparser, "parse_lines_fast", _boom)
    batches = []
    for shard in range(2):
        batches += list(batch_iterator(cfg, [path], training=True,
                                       epochs=1, shard_index=shard,
                                       num_shards=2, fixed_shape=True,
                                       uniq_bucket=256))
    got = _examples(batches)
    assert got == {i: s for i, s in enumerate(per_line)}


def test_sharded_equals_unsharded(tmp_path):
    path, per_line = _write_indexed(tmp_path, n=77, vocab=512,
                                    feats_per_line=4, seed=1)
    cfg = FmConfig(vocabulary_size=512, batch_size=16, shuffle=False,
                   max_features_per_example=8, bucket_ladder=(8,))
    one = _examples(batch_iterator(cfg, [path], training=True, epochs=1))
    two = {}
    for shard in range(2):
        two.update(_examples(batch_iterator(
            cfg, [path], training=True, epochs=1, shard_index=shard,
            num_shards=2)))
    assert one == two == {i: s for i, s in enumerate(per_line)}


@pytest.mark.parametrize("force_generic", [False, True])
def test_uniq_bucket_spill(tmp_path, monkeypatch, force_generic):
    """With a deliberately small unique bucket, batches close early
    (spill) but every example still trains exactly once and every batch
    keeps the same static shapes — on both the C++ and generic paths."""
    path, per_line = _write_indexed(tmp_path, n=60, vocab=100_000,
                                    feats_per_line=8, seed=2)
    cfg = FmConfig(vocabulary_size=100_000, batch_size=16, shuffle=False,
                   max_features_per_example=8, bucket_ladder=(8,))
    if force_generic:
        import fast_tffm_tpu.data.cparser as cparser

        def _unavailable(*a, **k):
            raise RuntimeError("forced generic path")

        monkeypatch.setattr(cparser, "BatchBuilder", _unavailable)
    # 16 examples x 8 fresh ids would need ~128 uniques; bucket 64
    # forces each batch to close after ~7 examples.
    batches = list(batch_iterator(cfg, [path], training=True, epochs=1,
                                  fixed_shape=True, uniq_bucket=64))
    assert all(len(b.uniq_ids) == 64 for b in batches)
    assert all(b.local_idx.shape == (16, 8) for b in batches)
    assert all(b.num_real >= 1 for b in batches)
    assert len(batches) > 60 // 16  # spill produced extra batches
    assert _examples(batches) == {i: s for i, s in enumerate(per_line)}


def test_uniq_bucket_too_small_for_one_example(tmp_path):
    path, _ = _write_indexed(tmp_path, n=4, vocab=100_000,
                             feats_per_line=8, seed=3)
    cfg = FmConfig(vocabulary_size=100_000, batch_size=4, shuffle=False,
                   max_features_per_example=8, bucket_ladder=(8,))
    with pytest.raises(Exception, match="uniq_bucket|max_uniq|unique-row"):
        list(batch_iterator(cfg, [path], training=True, epochs=1,
                            fixed_shape=True, uniq_bucket=8))


def test_probe_uniq_bucket_within_2x(tmp_path):
    """VERDICT done-criterion: the probed fixed bucket stays within 2x
    of the bucket a single-process run would fit for the same data."""
    from fast_tffm_tpu.data.pipeline import _uniq_ladder
    # Realistic density: ids reused across lines (categorical features
    # repeat heavily in CTR data), so batch uniques << B*L.
    rng = np.random.default_rng(4)
    lines = []
    for i in range(512):
        ids = rng.choice(4096, size=39, replace=False)
        lines.append(" ".join(["1"] + [f"{j}:1" for j in ids]))
    path = tmp_path / "t.txt"
    path.write_text("\n".join(lines) + "\n")
    cfg = FmConfig(vocabulary_size=1 << 20, batch_size=512, shuffle=False,
                   max_features_per_example=64, bucket_ladder=(64,))
    ub = probe_uniq_bucket(cfg, [str(path)])
    assert ub >= 64 and (ub & (ub - 1)) == 0
    # Single-process fitted bucket for the same (sole) batch:
    batches = list(batch_iterator(cfg, [str(path)], training=True,
                                  epochs=1))
    fitted = len(batches[0].uniq_ids)
    assert ub <= 2 * fitted, (ub, fitted)
    # And it is drastically below the worst-case ladder top.
    assert ub <= _uniq_ladder(512, 64)[-1] // 4


def test_config_validates_uniq_bucket():
    with pytest.raises(ValueError, match="uniq_bucket"):
        FmConfig(uniq_bucket=100)
    with pytest.raises(ValueError, match="uniq_bucket"):
        FmConfig(uniq_bucket=32)
    # A bucket one example could overflow must be rejected up front (it
    # would otherwise kill one worker mid-run between collectives).
    with pytest.raises(ValueError, match="max_features_per_example"):
        FmConfig(uniq_bucket=128, max_features_per_example=256)
    FmConfig(uniq_bucket=128, max_features_per_example=64)  # ok


def test_weighted_byte_range_partition(tmp_path):
    """Weight-files input shards by byte range like the unweighted path
    (round 4; previously index-modulo over a FULL read — N workers each
    reading every byte): every (line, weight) pair lands in exactly one
    shard, correctly paired across blank data lines and shard
    boundaries, and concatenation preserves order."""
    data = tmp_path / "d.txt"
    wts = tmp_path / "w.txt"
    lines, weights = [], []
    rng = np.random.default_rng(5)
    for i in range(97):
        if i % 13 == 7:
            lines.append("")           # blank: skipped, consumes a weight
        else:
            lines.append(f"1 {i}:1")
        weights.append(round(float(rng.random()) + 0.5, 3))
    data.write_text("\n".join(lines) + "\n")
    wts.write_text("\n".join(str(w) for w in weights) + "\n")

    expected = [(ln, w) for ln, w in zip(lines, weights) if ln]
    for num_shards in (1, 2, 3, 5):
        got = []
        for i in range(num_shards):
            got.extend(
                (line.rstrip("\n"), w)
                for line, w, _ in _iter_lines([str(data)], [str(wts)],
                                              i, num_shards))
        assert [g[0] for g in got] == [e[0] for e in expected], num_shards
        assert [g[1] for g in got] == pytest.approx(
            [e[1] for e in expected]), num_shards
