"""Mesh-sharded train step == single-device step, bit-for-bit-ish.

SURVEY.md §4: the JAX analogue of the reference's localhost-PS smoke test
is a fake multi-device CPU mesh. These tests run the same batches through
the unsharded jitted step and the 8-device sharded step (data-parallel,
row-sharded table) and require matching results — the property the
reference *cannot* have (its PS updates are async/racy by design).
"""

import jax
import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import batch_iterator
from fast_tffm_tpu.models.fm import (ModelSpec, batch_args, init_accumulator,
                                     init_table, make_score_fn,
                                     make_train_step)
from fast_tffm_tpu.parallel.sharded import (init_sharded_state, make_mesh,
                                            make_sharded_score_fn,
                                            make_sharded_train_step,
                                            shard_batch)


def _write_data(tmp_path, n=96, seed=3, field_aware=False):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        nnz = rng.integers(1, 12)
        ids = rng.choice(64, size=nnz, replace=False)
        parts = ["1" if rng.random() < 0.5 else "0"]
        for fid in ids:
            if field_aware:
                parts.append(f"{rng.integers(0, 4)}:{fid}:{rng.random():.3f}")
            else:
                parts.append(f"{fid}:{rng.random():.3f}")
        lines.append(" ".join(parts))
    p = tmp_path / "train.txt"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _cfg(path, **kw):
    base = dict(vocabulary_size=64, factor_num=4, batch_size=16,
                train_files=(path,), epoch_num=1, shuffle=False,
                learning_rate=0.1, factor_lambda=1e-4, bias_lambda=1e-4)
    base.update(kw)
    return FmConfig(**base)


@pytest.mark.parametrize("model_axis", [1, 2])
def test_sharded_step_matches_single_device(tmp_path, model_axis):
    path = _write_data(tmp_path)
    cfg = _cfg(path)
    spec = ModelSpec.from_config(cfg)
    mesh = make_mesh(jax.devices()[:8], model_axis=model_axis)

    table_s, acc_s = init_sharded_state(cfg, mesh, seed=0)
    # Same seed, same init values on the single-device path (sharded table
    # may carry dead pad rows past num_rows for divisibility).
    table_1 = init_table(cfg, 0)
    acc_1 = init_accumulator(cfg)
    np.testing.assert_allclose(np.asarray(table_s)[:cfg.num_rows],
                               np.asarray(table_1), rtol=0, atol=0)

    step_1 = make_train_step(spec)
    step_s = make_sharded_train_step(spec, mesh)
    for batch in batch_iterator(cfg, cfg.train_files, training=True):
        args = batch_args(batch)
        table_1, acc_1, loss_1, scores_1 = step_1(table_1, acc_1, **args)
        placed = shard_batch(mesh, **args)
        table_s, acc_s, loss_s, scores_s = step_s(table_s, acc_s, **placed)
        np.testing.assert_allclose(float(loss_s), float(loss_1),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(scores_s),
                                   np.asarray(scores_1),
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(table_s)[:cfg.num_rows],
                               np.asarray(table_1), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(acc_s)[:cfg.num_rows],
                               np.asarray(acc_1), rtol=1e-4, atol=1e-6)


def test_sharded_score_matches(tmp_path):
    path = _write_data(tmp_path, seed=5)
    cfg = _cfg(path)
    spec = ModelSpec.from_config(cfg)
    mesh = make_mesh(jax.devices()[:4])
    table = init_table(cfg, 1)
    table_s, _ = init_sharded_state(cfg, mesh, seed=1)  # same values + pad
    score_1 = make_score_fn(spec)
    score_s = make_sharded_score_fn(spec, mesh)
    for batch in batch_iterator(cfg, cfg.train_files, training=False):
        args = batch_args(batch)
        args.pop("labels"), args.pop("weights")
        s1 = np.asarray(score_1(table, **args))
        ss = np.asarray(score_s(table_s, **shard_batch(mesh, **args)))
        np.testing.assert_allclose(ss, s1, rtol=1e-4, atol=1e-5)


def test_sharded_ffm_step(tmp_path):
    path = _write_data(tmp_path, seed=7, field_aware=True)
    cfg = _cfg(path, model_type="ffm", field_num=4)
    spec = ModelSpec.from_config(cfg)
    mesh = make_mesh(jax.devices()[:8], model_axis=2)
    table_1 = init_table(cfg, 0)
    acc_1 = init_accumulator(cfg)
    table_s, acc_s = init_sharded_state(cfg, mesh, seed=0)
    step_1 = make_train_step(spec)
    step_s = make_sharded_train_step(spec, mesh)
    for batch in batch_iterator(cfg, cfg.train_files, training=True):
        args = batch_args(batch)
        table_1, acc_1, loss_1, _ = step_1(table_1, acc_1, **args)
        placed = shard_batch(mesh, **args)
        table_s, acc_s, loss_s, _ = step_s(table_s, acc_s, **placed)
        np.testing.assert_allclose(float(loss_s), float(loss_1),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(table_s)[:cfg.num_rows],
                               np.asarray(table_1), rtol=1e-4, atol=1e-6)


def test_ladder_overflow_stays_power_of_two(tmp_path):
    """The uniq ladder's top rung must stay a power of two so the U axis
    always divides the data axis even when every id is distinct."""
    path = _write_data(tmp_path, n=16, seed=9)
    cfg = _cfg(path, batch_size=16, max_features_per_example=8,
               bucket_ladder=(8,))
    spec = ModelSpec.from_config(cfg)
    mesh = make_mesh(jax.devices()[:8])
    table_s, acc_s = init_sharded_state(cfg, mesh)
    step_s = make_sharded_train_step(spec, mesh)
    loss = None
    for batch in batch_iterator(cfg, cfg.train_files, training=True):
        assert len(batch.uniq_ids) % 8 == 0
        args = batch_args(batch)
        table_s, acc_s, loss, _ = step_s(table_s, acc_s,
                                         **shard_batch(mesh, **args))
    assert np.isfinite(float(loss))


def test_shard_batch_rejects_indivisible_batch(tmp_path):
    path = _write_data(tmp_path, n=10, seed=11)
    cfg = _cfg(path, batch_size=10)
    mesh = make_mesh(jax.devices()[:8])
    for batch in batch_iterator(cfg, cfg.train_files, training=True):
        with pytest.raises(ValueError, match="divisible"):
            shard_batch(mesh, **batch_args(batch))
        break


def test_export_npz_slices_padded_table(tmp_path):
    from fast_tffm_tpu.checkpoint import export_npz
    cfg = _cfg(str(tmp_path / "unused.txt"))
    mesh = make_mesh(jax.devices()[:8])
    table_s, _ = init_sharded_state(cfg, mesh)
    assert np.asarray(table_s).shape[0] % 8 == 0  # padded for the mesh
    out = tmp_path / "table.npz"
    export_npz(table_s, str(out), vocabulary_size=cfg.vocabulary_size)
    arr = np.load(out)["table"]
    assert arr.shape == (cfg.vocabulary_size, cfg.row_dim)
    np.testing.assert_allclose(
        arr, np.asarray(table_s)[:cfg.vocabulary_size])


def test_sharded_predict_roundtrip(tmp_path):
    """Mesh-train to a checkpoint, then mesh-predict from it: the table
    restores ROW-SHARDED (each device holds 1/8 of the rows — never
    densified on one device, the config-#5 scaling requirement) and the
    scores match single-device scoring of the same checkpoint."""
    from fast_tffm_tpu.predict import load_table, predict, predict_scores
    from fast_tffm_tpu.train import train
    path = _write_data(tmp_path, n=96, seed=17)
    cfg = _cfg(path, epoch_num=2, model_file=str(tmp_path / "m" / "fm"),
               predict_files=(path,), score_path=str(tmp_path / "score"))
    train(cfg)

    mesh = make_mesh()
    table_s = load_table(cfg, mesh)
    assert int(table_s.shape[0]) == cfg.ckpt_rows
    shard_rows = {s.data.shape[0] for s in table_s.addressable_shards}
    assert shard_rows == {cfg.ckpt_rows // 8}, shard_rows

    raw_s = predict_scores(cfg, table_s, [path], mesh=mesh)
    raw_1 = predict_scores(cfg, load_table(cfg), [path])
    np.testing.assert_allclose(raw_s, raw_1, rtol=1e-4, atol=1e-5)

    written = predict(cfg)  # the driver path picks the mesh itself
    scores = np.loadtxt(written[0])
    assert len(scores) == 96
    np.testing.assert_allclose(
        scores, 1.0 / (1.0 + np.exp(-raw_1)), rtol=1e-3, atol=1e-4)


def test_pallas_kernel_on_mesh_matches_xla(tmp_path):
    """kernel='pallas' survives the sharded jit (the kernel runs under
    shard_map over the data axis — GSPMD cannot partition a pallas_call
    itself) and produces the same step as the XLA scorer: same loss,
    same scores, same updated table, on the 8-device mesh."""
    path = _write_data(tmp_path, n=16, seed=13)
    mesh = make_mesh(jax.devices()[:8])
    results = {}
    for kernel in ("pallas", "xla"):
        cfg = _cfg(path, batch_size=16, kernel=kernel)
        spec = ModelSpec.from_config(cfg)
        table_s, acc_s = init_sharded_state(cfg, mesh)
        step_s = make_sharded_train_step(spec, mesh)
        for batch in batch_iterator(cfg, cfg.train_files, training=True):
            table_s, acc_s, loss, scores = step_s(
                table_s, acc_s, **shard_batch(mesh, **batch_args(batch)))
        results[kernel] = (float(loss), np.asarray(scores),
                           np.asarray(table_s))
    loss_p, scores_p, table_p = results["pallas"]
    loss_x, scores_x, table_x = results["xla"]
    assert np.isfinite(loss_p)
    np.testing.assert_allclose(loss_p, loss_x, rtol=1e-5)
    np.testing.assert_allclose(scores_p, scores_x, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(table_p, table_x, rtol=1e-4, atol=1e-7)


def test_sharded_order3_step_matches_single_device(tmp_path):
    """Order-3 ANOVA-kernel FM (BASELINE config #4) under the mesh: the
    lax.scan interaction partitions like the order-2 einsum — sharded
    losses and updated table match the single-device step."""
    path = _write_data(tmp_path, seed=7)
    cfg = _cfg(path, order=3)
    spec = ModelSpec.from_config(cfg)
    mesh = make_mesh(jax.devices()[:8])

    table_s, acc_s = init_sharded_state(cfg, mesh, seed=0)
    table_1, acc_1 = init_table(cfg, 0), init_accumulator(cfg)
    step_1 = make_train_step(spec)
    step_s = make_sharded_train_step(spec, mesh)
    for batch in batch_iterator(cfg, cfg.train_files, training=True):
        args = batch_args(batch)
        table_1, acc_1, loss_1, _ = step_1(table_1, acc_1, **args)
        table_s, acc_s, loss_s, _ = step_s(table_s, acc_s,
                                           **shard_batch(mesh, **args))
        np.testing.assert_allclose(float(loss_s), float(loss_1),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(table_s)[:cfg.num_rows],
                               np.asarray(table_1), rtol=1e-4, atol=1e-6)
