"""C++ parser vs Python parser: bit-identical outputs on the same input
(the golden-parity contract both docstrings promise)."""

import os

import numpy as np
import pytest

from fast_tffm_tpu.data import cparser
from fast_tffm_tpu.data.parser import ParseError, parse_lines

pytestmark = pytest.mark.skipif(not cparser.available(),
                                reason="C++ parser failed to build")


def assert_parity(lines, vocab, **kw):
    py = parse_lines(lines, vocab, **kw)
    cc = cparser.parse_lines_fast(lines, vocab, **kw)
    np.testing.assert_array_equal(cc.labels, py.labels)
    np.testing.assert_array_equal(cc.poses, py.poses)
    np.testing.assert_array_equal(cc.ids, py.ids)
    np.testing.assert_array_equal(cc.vals, py.vals)
    if py.fields is None:
        assert cc.fields is None
    else:
        np.testing.assert_array_equal(cc.fields, py.fields)


def assert_error_message_parity(lines, vocab, **kw):
    """Both parsers reject AND produce the identical message (the error
    text is part of the parity contract: it names the line and the
    offending value the way Python renders it)."""
    with pytest.raises(ParseError) as py_err:
        parse_lines(lines, vocab, **kw)
    with pytest.raises(ParseError) as cc_err:
        cparser.parse_lines_fast(lines, vocab, **kw)
    assert str(cc_err.value) == str(py_err.value)


def test_basic_parity():
    assert_parity(["1 3:0.5 7:2.0 1", "0 2", "1 9:1.5"], 100)


def test_default_val_and_blank_lines():
    assert_parity(["1 5", "", "0 6:2", "   ", "1 7"], 10)


def test_hash_parity():
    lines = ["1 user_a:2.0 item_b click:0.5", "0 user_c", "1 123 456:7.5"]
    assert_parity(lines, 999983, hash_feature_id=True)


def test_float_formats():
    assert_parity(["1 1:0.5 2:-1.5 3:1e-3 4:2E2 5:.5 6:5."], 10)


def test_labels():
    assert_parity(["-1 2", "0.5 3", "1e0 4"], 10)


def test_truncation_parity():
    line = "1 " + " ".join(f"{i}:1" for i in range(50))
    assert_parity([line], 100, max_features_per_example=8)
    # tokens after the cap are not validated (Python breaks out)
    assert_parity(["1 1:1 2:2 3:3:3:3"], 100, max_features_per_example=2)


def test_error_parity():
    for bad in (["x 1:2"], ["1 a:2"], ["1 50"], ["1 1:2:3"], ["1 1:xyz"],
                ["1 -3:1"]):
        with pytest.raises(ParseError):
            parse_lines(bad, 10)
        with pytest.raises(ParseError):
            cparser.parse_lines_fast(bad, 10)


def test_threaded_error_lineno_rebase_large_blob():
    """A parse error landing in a LATER shard of a genuinely
    multi-shard parse (>64KB blob, so the threaded path really splits)
    must report the ABSOLUTE line number: later shards parse with
    relative linenos and are rebased after the join from earlier
    shards' line counts — this pins the rebase math on both consumers
    (block parse and streaming builder feed)."""
    n = 6000
    lines = [f"1 {i % 499}:0.25 {(i * 7) % 499}:1" for i in range(n)]
    bad_at = n - 100  # deep in the last shard at T=4
    lines[bad_at] = "1 botched:token"
    # Block-parse surface (0-based linenos, matching Python enumerate).
    with pytest.raises(ParseError) as py_err:
        parse_lines(lines, 500)
    with pytest.raises(ParseError) as cc_err:
        cparser.parse_lines_fast(lines, 500, num_threads=4)
    assert str(cc_err.value) == str(py_err.value)
    assert f"line {bad_at}:" in str(cc_err.value)
    # Streaming-builder surface (1-based linenos): the T=4 feed must
    # report the same absolute line as the T=1 feed.
    blob = ("\n".join(lines) + "\n").encode()
    assert len(blob) > (64 << 10)  # the threaded gate must be open
    want, err_w = _run_builder(blob, [blob], 1)
    got, err_g = _run_builder(blob, [blob], 4)
    assert err_w is not None and err_g is not None
    assert err_w == err_g
    assert f"line {bad_at + 1}:" in err_g
    _assert_batches_equal(got, want)


def test_overlong_int_error_message_parity():
    """Integer-syntax ids beyond int64 must report OUT OF RANGE with
    Python's arbitrary-precision rendering, not 'non-integer' (found by
    differential fuzz: C++'s int64 parse overflowed to a syntax error
    while Python's int() parsed and range-checked)."""
    for bad in (["1 999999999999999999999:1"],     # 21 digits
                ["1 1000000000000000000:1"],       # 19 digits, fits int64
                ["1 9223372036854775808:1"],       # int64 max + 1
                ["1 -999999999999999999999:1"],    # negative overlong
                ["1 +999999999999999999999:1"],    # sign stripped in repr
                ["1 0000999999999999999999999:1"],  # zero-padded overlong
                ["1 55:1"]):                       # plain out of range
        assert_error_message_parity(bad, 50)
    # FFM field: same class through the field branch.
    for bad in (["1 99999999999999999999:3:1"],
                ["1 -99999999999999999999:3:1"],
                ["1 7:3:1"]):
        assert_error_message_parity(bad, 50, field_aware=True, field_num=4)


def test_random_fuzz_parity(rng):
    vocab = 10000
    lines = []
    for _ in range(500):
        n = int(rng.integers(1, 30))
        toks = []
        for _ in range(n):
            fid = int(rng.integers(0, vocab))
            if rng.uniform() < 0.5:
                toks.append(f"{fid}:{rng.normal():.6g}")
            else:
                toks.append(str(fid))
        lines.append(f"{int(rng.integers(0, 2))} " + " ".join(toks))
    assert_parity(lines, vocab)
    assert_parity(lines, vocab, hash_feature_id=True)


def test_multithreaded_ordering(rng):
    # enough data to engage multiple threads (>64KB blob)
    lines = [f"{i % 2} {i % 997}:1 {(i * 7) % 997}:0.5 pad_{i}:2"
             for i in range(20000)]
    py = parse_lines(lines, 997, hash_feature_id=True)
    cc = cparser.parse_lines_fast(lines, 997, hash_feature_id=True,
                                  num_threads=8)
    np.testing.assert_array_equal(cc.labels, py.labels)
    np.testing.assert_array_equal(cc.poses, py.poses)
    np.testing.assert_array_equal(cc.ids, py.ids)
    np.testing.assert_array_equal(cc.vals, py.vals)


def test_empty_input():
    cc = cparser.parse_lines_fast([], 10)
    assert cc.batch_size == 0
    assert len(cc.ids) == 0


def test_ffm_parity():
    lines = ["1 0:3:0.5 1:7:2.0 2:1", "0 1:2", "1 0:9:1.5"]
    assert_parity(lines, 100, field_aware=True, field_num=3)


def test_ffm_hash_parity():
    lines = ["1 0:user_a:2.0 1:item_b 2:click:0.5", "0 2:123:7.5"]
    assert_parity(lines, 999983, hash_feature_id=True,
                  field_aware=True, field_num=3)


def test_ffm_truncation_parity():
    line = "1 " + " ".join(f"{i % 4}:{i}:1" for i in range(50))
    assert_parity([line], 100, field_aware=True, field_num=4,
                  max_features_per_example=8)


def test_ffm_error_parity():
    kw = dict(field_aware=True, field_num=3)
    for bad in (["1 5"],          # no field separator
                ["1 x:2:1"],      # bad field
                ["1 9:2:1"],      # field out of range
                ["1 0:2:1:4"],    # too many colons
                ["1 0:abc:1"],    # non-int id without hashing
                ["1 0:50:1"]):    # id out of range (vocab 10)
        with pytest.raises(ParseError):
            parse_lines(bad, 10, **kw)
        with pytest.raises(ParseError):
            cparser.parse_lines_fast(bad, 10, **kw)


def test_ffm_fuzz_parity(rng):
    vocab, F = 10000, 7
    lines = []
    for _ in range(500):
        n = int(rng.integers(1, 20))
        toks = []
        for _ in range(n):
            fld = int(rng.integers(0, F))
            fid = int(rng.integers(0, vocab))
            if rng.uniform() < 0.5:
                toks.append(f"{fld}:{fid}:{rng.normal():.6g}")
            else:
                toks.append(f"{fld}:{fid}")
        lines.append(f"{int(rng.integers(0, 2))} " + " ".join(toks))
    assert_parity(lines, vocab, field_aware=True, field_num=F)
    assert_parity(lines, vocab, field_aware=True, field_num=F,
                  hash_feature_id=True)


def test_zero_padded_ids_parse_like_python():
    """Leading zeros must not count toward the digit limit (Python int()
    parity): '000...05' is id 5."""
    from fast_tffm_tpu.data.cparser import parse_lines_fast
    from fast_tffm_tpu.data.parser import parse_lines
    lines = ["1 0000000000000000005:1.5 7:2.0"]
    a = parse_lines_fast(lines, 100)
    b = parse_lines(lines, 100)
    assert a.ids.tolist() == b.ids.tolist() == [5, 7]
    assert a.vals.tolist() == b.vals.tolist()


@pytest.mark.slow
def test_stale_so_missing_symbols_rebuilds(tmp_path, monkeypatch):
    """A stale .so whose mtime postdates the source (mtime-preserving
    deploy) but which predates the current symbols/ABI must trigger a
    rebuild from source, not silent fallback — the loader's
    fm_abi_version contract."""
    import shutil
    import subprocess
    # A decoy library with none of our symbols plays the "old binary".
    src = tmp_path / "decoy.cc"
    src.write_text('extern "C" int decoy() { return 1; }\n')
    decoy = tmp_path / "decoy.so"
    subprocess.run(["g++", "-shared", "-fPIC", "-o", str(decoy), str(src)],
                   check=True, capture_output=True)
    so = tmp_path / "_parser.so"
    shutil.copy(cparser._SRC, tmp_path / "_parser.cc")
    shutil.copy(decoy, so)
    # Make the stale .so look NEWER than the source.
    future = os.path.getmtime(tmp_path / "_parser.cc") + 10
    os.utime(so, (future, future))

    monkeypatch.setattr(cparser, "_SO", str(so))
    monkeypatch.setattr(cparser, "_SRC", str(tmp_path / "_parser.cc"))
    monkeypatch.setattr(cparser, "_lib", None)
    monkeypatch.setattr(cparser, "_load_error", None)
    lib = cparser._load()
    assert lib.fm_abi_version() == cparser._ABI_VERSION


@pytest.mark.slow
def test_abi_version_mismatch_refuses(tmp_path, monkeypatch):
    """If even a rebuild can't produce the expected ABI (wrapper and
    source disagree), the loader must refuse — never run mismatched
    argument layouts."""
    import shutil
    so = tmp_path / "_parser.so"
    shutil.copy(cparser._SRC, tmp_path / "_parser.cc")
    monkeypatch.setattr(cparser, "_SO", str(so))
    monkeypatch.setattr(cparser, "_SRC", str(tmp_path / "_parser.cc"))
    monkeypatch.setattr(cparser, "_lib", None)
    monkeypatch.setattr(cparser, "_load_error", None)
    monkeypatch.setattr(cparser, "_ABI_VERSION", 999)
    with pytest.raises(RuntimeError, match="stale ABI"):
        cparser._load()


def test_float_grammar_parity_edges():
    """Lexical edges where Python float() and strtod historically
    disagree: hex floats and nan payloads rejected, overflow reads as
    inf, underflow as ~0 — identical on both parsers."""
    assert_parity(["1 1:1e400 2:-1e400 3:1e-400 4:Infinity 5:NAN 6:inf"],
                  10)
    for bad in (["1 1:0x10"], ["1 1:nan(box)"], ["1 1:1_0"], ["0x1 1:1"],
                ["1 1:infin"]):
        with pytest.raises(ParseError):
            parse_lines(bad, 10)
        with pytest.raises(ParseError):
            cparser.parse_lines_fast(bad, 10)


# --- threaded streaming BatchBuilder (feed parse threads) -------------------


def _run_builder(blob, chunks, num_threads, **kw):
    """Drive a BatchBuilder over byte chunks; returns (batches, error)."""
    bb = cparser.BatchBuilder(4, 8, 500, num_threads=num_threads, **kw)
    out, tail = [], b""

    def feed_all(dat):
        off = 0
        while True:
            full, consumed = bb.feed(dat, off)
            off += consumed
            if not full:
                break
            out.append(bb.finish())
        return dat[off:]

    try:
        for c in chunks:
            tail = feed_all(tail + c)
        if tail:
            feed_all(tail + b"\n")
        final = bb.finish()
        if final[0]:
            out.append(final)
        return out, None
    except ParseError as e:
        return out, str(e)


def _assert_batches_equal(got, want):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        n = g[0]
        assert n == w[0]
        np.testing.assert_array_equal(g[1][:n], w[1][:n])  # labels
        if w[2] is None:
            assert g[2] is None
        else:
            np.testing.assert_array_equal(g[2], w[2])      # uniq
        np.testing.assert_array_equal(g[3], w[3])          # local_idx
        np.testing.assert_array_equal(g[4], w[4])          # vals
        if w[5] is not None:
            np.testing.assert_array_equal(g[5], w[5])      # fields


def _builder_corpus(rng, n_lines=37, field_aware=False, blanks=True):
    lines = []
    for i in range(n_lines):
        if blanks and i % 9 == 4:
            lines.append("")
            continue
        nnz = int(rng.integers(0, 7))
        ids = rng.choice(500, size=nnz, replace=False)
        toks = [str(int(rng.integers(0, 2)))]
        for j in ids:
            t = f"{j}:{rng.random():.3f}"
            if field_aware:
                t = f"{int(rng.integers(0, 3))}:{t}"
            toks.append(t)
        lines.append(" ".join(toks))
    return ("\n".join(lines) + "\n").encode()


@pytest.mark.parametrize("kw", [
    dict(),
    dict(hash_feature_id=True),
    dict(raw_ids=True),
    dict(keep_empty=True),
    dict(field_aware=True, field_num=3),
    dict(max_uniq=16, max_features_per_example=8),
])
def test_threaded_builder_matches_serial(rng, kw):
    """T=4 feed parsing (parallel parse + serial drain) produces
    byte-identical batches to T=1 in every builder mode, across chunked
    feeds (VERDICT r3 next-round #3)."""
    blob = _builder_corpus(rng, field_aware=kw.get("field_aware", False))
    want, err_w = _run_builder(blob, [blob], 1, **kw)
    for chunks in ([blob], [blob[:97], blob[97:301], blob[301:]],
                   [blob[i:i + 53] for i in range(0, len(blob), 53)]):
        got, err_g = _run_builder(blob, chunks, 4, **kw)
        assert (err_w is None) == (err_g is None)
        _assert_batches_equal(got, want)


def test_threaded_builder_defers_parse_error(rng):
    """A bad line mid-stream: the threaded path emits every batch that
    precedes the error, then raises — exactly the serial path's
    observable behavior (errors are deferred to their turn, not raised
    at parse time)."""
    good = _builder_corpus(rng, n_lines=11, blanks=False)
    blob = good + b"1 bad:token:xx:yy\n" + _builder_corpus(
        rng, n_lines=7, blanks=False)
    want, err_w = _run_builder(blob, [blob], 1)
    got, err_g = _run_builder(blob, [blob[:40], blob[40:]], 4)
    assert err_w is not None and err_g is not None
    assert err_w == err_g  # same message incl. the 1-based line number
    _assert_batches_equal(got, want)


def test_threaded_builder_scales(rng):
    """host-side build rate must scale with parse threads (>= 1.5x at
    T=4). Skipped where the cores to show it don't exist."""
    if (os.cpu_count() or 1) < 4:
        pytest.skip("needs >= 4 cores to measure scaling")
    import time
    lines = []
    for i in range(40000):
        ids = rng.choice(100000, size=39, replace=False)
        lines.append("1 " + " ".join(f"{j}:1.5" for j in ids))
    blob = ("\n".join(lines) + "\n").encode()

    def rate(T):
        bb = cparser.BatchBuilder(8192, 48, 1 << 20, num_threads=T,
                                  max_features_per_example=48)
        t0 = time.perf_counter()
        off = 0
        while True:
            full, consumed = bb.feed(blob, off)
            off += consumed
            if not full:
                break
            bb.finish()
        bb.finish()
        return len(lines) / (time.perf_counter() - t0)

    # Same-window INTERLEAVED pairs (the repo's own A/B doctrine —
    # see kernel_probe / the verify notes): each trial measures T=1
    # and T=4 back to back and the best PAIRED ratio decides, so a
    # lucky T=1 sample in one window can't inflate the denominator
    # against a T=4 sample from a slower window (best-of-each-side did
    # exactly that and flaked). The bar is 1.15x, not the ~2x a quiet
    # 4-core box shows: this guard exists to catch the threaded path
    # accidentally SERIALIZING (~1.0x), and the ambient ratio on this
    # shared host swings 1.15x-2x minute to minute — a tighter bar
    # flakes the tier-1 gate on load it can't control.
    ratios = []
    for _ in range(5):
        r1 = rate(1)
        ratios.append(rate(4) / r1)
    assert max(ratios) >= 1.15, (
        f"T=4/T=1 paired ratios {[f'{r:.2f}' for r in ratios]}")
