"""Protocol model checker (fmlint R014–R017): unit behaviors on
synthetic projects, plus the four acceptance mutants planted into the
REAL modules through the ``overlay=`` seam — divergent restore
collective (R014), thread-reachable collective (R015), serve lock-order
inversion (R016), and a lock held across a device fetch (R017) — each
producing exactly one finding naming the offending call/lock pair while
unmutated HEAD stays clean."""

import os
import textwrap

from tools.fmlint.core import run_paths
from tools.fmlint.project import (collective_ops, load_project,
                                  parse_files, protocol_automaton)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FM = os.path.join(REPO, "fast_tffm_tpu")


def _project(tmp_path, files):
    paths = []
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
        if rel.endswith(".py"):
            paths.append(str(p))
    return str(tmp_path), paths


def _findings(tmp_path, files, rule=None):
    root, _ = _project(tmp_path, files)
    found = run_paths([root])
    if rule is not None:
        found = [f for f in found if f.rule == rule]
    return found


# R014 is scoped to the protocol modules; synthetic projects reuse a
# real suffix so the scope gate admits them.
_PROTO = "pkg/fast_tffm_tpu/checkpoint.py"

_WALKBACK = """\
    from jax.experimental import multihost_utils

    def bcast(v):
        return multihost_utils.broadcast_one_to_all(v)

    class M:
        def _attempt_restore(self):
            return 1, None

        def walk(self):
            restored, err = self._attempt_restore()
            if err is None:
                return restored
            return bcast(0)
"""


def test_r014_branch_on_local_restore_outcome(tmp_path):
    """The PR 4 walk-back bug class: branching on a per-process
    restore outcome with a collective in only one continuation."""
    found = _findings(tmp_path, {_PROTO: _WALKBACK}, rule="R014")
    assert len(found) == 1, found
    assert "diverges on per-process data" in found[0].message
    assert "bcast()" in found[0].message


def test_r014_agreed_condition_is_clean(tmp_path):
    """Same shape, but the condition routes through an allgather
    (the _all_agree pattern) — uniform, so no finding."""
    found = _findings(tmp_path, {_PROTO: """\
        from jax.experimental import multihost_utils

        def bcast(v):
            return multihost_utils.broadcast_one_to_all(v)

        def agree(flag):
            return bool(multihost_utils.process_allgather(flag).all())

        class M:
            def _attempt_restore(self):
                return 1, None

            def walk(self):
                restored, err = self._attempt_restore()
                if agree(err is None):
                    return restored
                return bcast(0)
    """}, rule="R014")
    assert found == [], found


def test_r014_raise_arm_is_sanctioned(tmp_path):
    """A raise-terminated arm with no collectives is the die-loudly
    path the liveness guard bounds — exempt by design."""
    found = _findings(tmp_path, {_PROTO: """\
        from jax.experimental import multihost_utils

        def bcast(v):
            return multihost_utils.broadcast_one_to_all(v)

        class M:
            def _attempt_restore(self):
                return 1, None

            def walk(self):
                restored, err = self._attempt_restore()
                if err is not None:
                    raise err
                return bcast(0)
    """}, rule="R014")
    assert found == [], found


def test_r014_loop_carried_divergence(tmp_path):
    """A collective inside a loop whose trip count is per-process:
    the shape R007 (single-branch) cannot see."""
    found = _findings(tmp_path, {_PROTO: """\
        from jax.experimental import multihost_utils

        def bcast(v):
            return multihost_utils.broadcast_one_to_all(v)

        class M:
            def _attempt_restore(self):
                return 1, None

            def walk(self):
                n, _ = self._attempt_restore()
                while n > 0:
                    bcast(n)
                    n -= 1
    """}, rule="R014")
    assert len(found) == 1, found
    assert "different iteration counts" in found[0].message


def test_r014_swallowed_exception_arm(tmp_path):
    """A handler that swallows a failure of a collective-bearing try
    body leaves this rank's sequence a prefix of its peers'."""
    src = """\
        from jax.experimental import multihost_utils

        def bcast(v):
            return multihost_utils.broadcast_one_to_all(v)

        def step():
            try:
                bcast(1)
            except Exception:
                {handler}
    """
    found = _findings(tmp_path, {_PROTO: src.format(handler="pass")},
                      rule="R014")
    assert len(found) == 1, found
    assert "swallows a failure" in found[0].message
    # The escalating twin re-raises: the guard converts the death to a
    # bounded diagnosed exit, so the sequence never silently shortens.
    found = _findings(tmp_path, {_PROTO: src.format(handler="raise")},
                      rule="R014")
    assert found == [], found


def test_r015_thread_target_closure(tmp_path):
    found = _findings(tmp_path, {"m.py": """\
        import threading
        from jax.experimental import multihost_utils

        def work():
            multihost_utils.process_allgather(1)

        def start():
            threading.Thread(target=work).start()
    """}, rule="R015")
    assert len(found) == 1, found
    assert "process_allgather" in found[0].message
    assert "thread-reachable" in found[0].message


def test_r016_lock_order_cycle_and_consistent_twin(tmp_path):
    src = """\
        import threading
        _lock_a = threading.Lock()
        _lock_b = threading.Lock()

        def f():
            with _lock_a:
                with _lock_b:
                    pass

        def g():
            with {first}:
                with {second}:
                    pass
    """
    found = _findings(
        tmp_path / "inv",
        {"m.py": src.format(first="_lock_b", second="_lock_a")},
        rule="R016")
    assert len(found) == 1, found
    assert "m._lock_a" in found[0].message and "m._lock_b" in found[0].message
    # Consistent global order: no cycle.
    found = _findings(
        tmp_path / "ok",
        {"m.py": src.format(first="_lock_a", second="_lock_b")},
        rule="R016")
    assert found == [], found


def test_r016_interprocedural_edge(tmp_path):
    """The second edge of the cycle runs through a call made under a
    lock into a function that takes the other lock."""
    found = _findings(tmp_path, {"m.py": """\
        import threading
        _lock_a = threading.Lock()
        _lock_b = threading.Lock()

        def inner():
            with _lock_a:
                pass

        def f():
            with _lock_a:
                with _lock_b:
                    pass

        def g():
            with _lock_b:
                inner()
    """}, rule="R016")
    assert len(found) == 1, found
    assert "inner()" in found[0].message


def test_r017_lock_across_fetch_and_snapshot_twin(tmp_path):
    found = _findings(tmp_path, {"m.py": """\
        import threading
        import jax
        _lock = threading.Lock()

        def f(x):
            with _lock:
                return jax.device_get(x)
    """}, rule="R017")
    assert len(found) == 1, found
    assert "device_get" in found[0].message and "m._lock" in found[0].message
    # Snapshot-under-the-lock, block-after: the sanctioned shape.
    found = _findings(tmp_path, {"m.py": """\
        import threading
        import jax
        _lock = threading.Lock()
        _state = {"x": None}

        def f():
            with _lock:
                x = _state["x"]
            return jax.device_get(x)
    """}, rule="R017")
    assert found == [], found


def test_r017_lock_across_collective(tmp_path):
    found = _findings(tmp_path, {"m.py": """\
        import threading
        from jax.experimental import multihost_utils
        _lock = threading.Lock()

        def f(x):
            with _lock:
                return multihost_utils.process_allgather(x)
    """}, rule="R017")
    assert len(found) == 1, found
    assert "process_allgather" in found[0].message


def test_collective_ops_and_automaton(tmp_path):
    """The protocol model itself: ordered labeled tokens, and the
    automaton rendering used by ``fmlint --protocol``."""
    _, paths = _project(tmp_path, {_PROTO: """\
        from jax.experimental import multihost_utils
        from fast_tffm_tpu.parallel.liveness import guarded_collective

        def agree(flag):
            return guarded_collective(
                multihost_utils.process_allgather, flag,
                label="demo/agree")

        def driver(n):
            guarded_collective(multihost_utils.broadcast_one_to_all,
                               n, label="demo/pick")
            for i in range(n):
                agree(i)
    """})
    proj = load_project(parse_files(paths))
    (q,) = [q for q in proj.functions if q.endswith(".driver")]
    fn = proj.functions[q]
    ops = collective_ops(proj, fn, fn.node.body)
    assert ops[0] == "guarded_collective[demo/pick]"
    assert ops[1].endswith(".agree()")
    text = "\n".join(protocol_automaton(proj, q))
    assert "guarded_collective[demo/pick]" in text
    assert "for <line" in text
    # depth-1 inlining expands agree()'s own labeled op
    assert "guarded_collective[demo/agree]" in text


# --- acceptance mutants against the REAL modules ---------------------------

def _read(path):
    with open(path, encoding="utf-8") as fh:
        return fh.read()


def test_head_is_clean_of_protocol_rules():
    """Negative twin for all four mutants: the unmutated package holds
    no R014–R017 findings (the repo gate pins the full surface; this
    pins the rules specifically so a mutant test failure can't be
    confused with pre-existing noise)."""
    found = [f for f in run_paths([FM])
             if f.rule in ("R014", "R015", "R016", "R017")]
    assert found == [], "\n".join(f.render() for f in found)


def test_r014_mutant_unagreed_restore_walkback():
    """Plant the PR 4 bug class into the REAL walk-back: drop the
    _all_agree collective so each rank branches on its own restore
    outcome — one R014 naming the diverging call pair."""
    ckpt = os.path.join(FM, "checkpoint.py")
    src = _read(ckpt)
    needle = "if self._all_agree(err is None):"
    assert src.count(needle) == 1, "mutation site drifted"
    found = run_paths([FM], overlay={
        ckpt: src.replace(needle, "if err is None:")})
    assert [f.rule for f in found] == ["R014"], \
        "\n".join(f.render() for f in found)
    msg = found[0].message
    assert found[0].path.endswith("checkpoint.py")
    assert "_restore_newest_intact" in msg
    assert "_broadcast_int()" in msg  # the unmatched peer-side op


def test_r015_mutant_collective_on_thread():
    """Move the epoch-override broadcast into a threading.Thread
    target closure — one R015 at the relocated guarded_collective."""
    ckpt = os.path.join(FM, "checkpoint.py")
    src = _read(ckpt)
    needle = """\
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            from fast_tffm_tpu.parallel.liveness import guarded_collective
            override = int(guarded_collective(
                multihost_utils.broadcast_one_to_all,
                np.int64(override), label="checkpoint/epoch_override"))"""
    assert needle in src, "mutation site drifted"
    mutant = """\
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            from fast_tffm_tpu.parallel.liveness import guarded_collective
            import threading
            box = {}

            def _bg():
                box["v"] = int(guarded_collective(
                    multihost_utils.broadcast_one_to_all,
                    np.int64(override),
                    label="checkpoint/epoch_override"))

            t = threading.Thread(target=_bg)
            t.start()
            t.join()
            override = box["v"]"""
    found = run_paths([FM], overlay={ckpt: src.replace(needle, mutant)})
    assert [f.rule for f in found] == ["R015"], \
        "\n".join(f.render() for f in found)
    assert "guarded_collective" in found[0].message
    assert "_bg is thread-reachable" in found[0].message


def test_r016_mutant_serve_lock_inversion():
    """Invert the serve dispatcher/reload lock order (submit nests the
    table lock under the submit lock while the reload path nests them
    the other way) — one R016 naming both locks with a witness site
    for each direction."""
    srv = os.path.join(FM, "serve", "server.py")
    src = _read(srv)
    o1 = """\
        with self._submit_lock:
            if self._closed:
                raise RuntimeError("ScorerServer is closed")
            self._q.put(pending)"""
    n1 = """\
        with self._submit_lock:
            if self._closed:
                raise RuntimeError("ScorerServer is closed")
            with self._table_lock:
                self._q.put(pending)"""
    o2 = """\
        with self._table_lock:
            self._table = table
            self._vocab_map = vmap
            self._served_step = int(step)"""
    n2 = """\
        with self._table_lock:
            with self._submit_lock:
                self._table = table
                self._vocab_map = vmap
                self._served_step = int(step)"""
    assert o1 in src and o2 in src, "mutation sites drifted"
    found = run_paths([FM], overlay={
        srv: src.replace(o1, n1).replace(o2, n2)})
    assert [f.rule for f in found] == ["R016"], \
        "\n".join(f.render() for f in found)
    msg = found[0].message
    assert "ScorerServer._submit_lock" in msg
    assert "ScorerServer._table_lock" in msg
    assert "submit()" in msg and "_load_step()" in msg


def test_r017_mutant_fetch_under_table_lock():
    """Hold the serve table lock across the score fetch (undoing the
    snapshot-then-release design) — one R017 naming the lock and the
    blocking device_get."""
    srv = os.path.join(FM, "serve", "server.py")
    src = _read(srv)
    needle = """\
            with self._table_lock:
                table = self._table
                step = self._served_step
                vmap = self._vocab_map
            with span("serve/flush", examples=n, rung=rung):
                t_pad = time.perf_counter()
                batch = make_device_batch(block, self._build_cfg,
                                          batch_size=rung,
                                          raw_ids=True)
                if vmap is not None:
                    batch = vmap.remap(batch)
                t_dev = time.perf_counter()
                reg.observe("serve/pad_ms", (t_dev - t_pad) * 1000.0,
                            bounds=LATENCY_BUCKETS_MS)
                raw = np.asarray(jax.device_get(
                    self._scorer.score_batch(table, batch)))[:n]"""
    mutant = """\
            with self._table_lock:
                table = self._table
                step = self._served_step
                vmap = self._vocab_map
                with span("serve/flush", examples=n, rung=rung):
                    batch = make_device_batch(block, self._build_cfg,
                                              batch_size=rung,
                                              raw_ids=True)
                    if vmap is not None:
                        batch = vmap.remap(batch)
                    raw = np.asarray(jax.device_get(
                        self._scorer.score_batch(table, batch)))[:n]"""
    assert needle in src, "mutation site drifted"
    found = run_paths([FM], overlay={srv: src.replace(needle, mutant)})
    assert [f.rule for f in found] == ["R017"], \
        "\n".join(f.render() for f in found)
    msg = found[0].message
    assert "device_get" in msg
    assert "_flush()" in msg and "ScorerServer._table_lock" in msg


# --- tooling: parse cache, --changed closure, CLI flags ---------------------


def test_parse_cache_roundtrip_and_invalidation(tmp_path):
    """The (mtime, size)-keyed AST cache serves unchanged files and
    invalidates edited ones; the overlay seam never touches it."""
    from tools.fmlint.core import _parse_one
    cache = str(tmp_path / "cache")
    p = tmp_path / "a.py"
    p.write_text("x = 1\n")
    src1, tree1, _ = _parse_one(str(p), cache_dir=cache)
    assert src1 == "x = 1\n" and tree1 is not None
    assert len(os.listdir(cache)) == 1
    # Warm hit returns the same content.
    src2, _tree2, _ = _parse_one(str(p), cache_dir=cache)
    assert src2 == src1
    # An edit (size + mtime change) invalidates.
    p.write_text("y = 2  # edited\n")
    src3, _, _ = _parse_one(str(p), cache_dir=cache)
    assert src3 == "y = 2  # edited\n"
    # Overlay source bypasses the cache and does not poison it.
    src4, _, _ = _parse_one(str(p), source="z = 3\n", cache_dir=cache)
    assert src4 == "z = 3\n"
    src5, _, _ = _parse_one(str(p), cache_dir=cache)
    assert src5 == "y = 2  # edited\n"


def test_full_sweep_wall_time_budget(tmp_path):
    """ISSUE 16 satellite: the whole-program sweep over the real
    surface stays inside an interactive wall-time budget, cold cache
    included (the R014 taint-timeline memoization and the AST cache
    are what hold this line as the surface grows)."""
    import time
    from tools.fmlint.core import default_paths, run_paths
    cache = str(tmp_path / "cache")
    t0 = time.perf_counter()
    cold = run_paths(default_paths(), cache_dir=cache)
    cold_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    warm = run_paths(default_paths(), cache_dir=cache)
    warm_s = time.perf_counter() - t0
    assert [f.render() for f in cold] == [f.render() for f in warm]
    # ~4s on the dev box; 6x headroom for slow CI before it trips.
    assert cold_s < 25.0, f"cold sweep took {cold_s:.1f}s"
    assert warm_s < 25.0, f"warm sweep took {warm_s:.1f}s"


def test_changed_closure_reverse_imports(tmp_path, monkeypatch):
    """--changed lints the dirty file plus everything that imports it,
    transitively — and nothing else."""
    import tools.fmlint.core as core
    root, paths = _project(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/base.py": "X = 1\n",
        "pkg/mid.py": "from pkg.base import X\nY = X + 1\n",
        "pkg/top.py": "from pkg import mid\nZ = mid.Y\n",
        "pkg/other.py": "W = 0\n",
    })
    base = str(tmp_path / "pkg" / "base.py")
    monkeypatch.setattr(core, "_git_dirty_files", lambda _root: [base])
    closure = core.changed_closure([root])
    names = sorted(os.path.basename(f) for f in closure)
    assert names == ["base.py", "mid.py", "top.py"]
    monkeypatch.setattr(core, "_git_dirty_files", lambda _root: [])
    assert core.changed_closure([root]) == []


def test_cli_json_out_and_profile(tmp_path, capsys):
    import json

    from tools.fmlint.core import main
    ok = tmp_path / "clean.py"
    ok.write_text("x = 1\n")
    art = str(tmp_path / "findings.json")
    assert main([str(ok), "--no-cache", "--no-baseline",
                 "--json-out", art, "--profile"]) == 0
    doc = json.load(open(art))
    assert doc["count"] == 0 and doc["findings"] == []
    err = capsys.readouterr().err
    assert "per-stage/per-rule wall time" in err and "total" in err


def test_cli_protocol_dump_and_unknown(capsys):
    """--protocol prints the ordered collective automaton for a real
    entry point; a typo'd qualname exits 2 with close matches."""
    from tools.fmlint.core import main
    assert main(["--protocol",
                 "fast_tffm_tpu.data.stream.exchange_watermarks"]) == 0
    out = capsys.readouterr().out
    assert "guarded_collective[stream/watermark_len]" in out
    assert "guarded_collective[stream/watermark_merge]" in out
    assert main(["--protocol", "exchange_watermarks"]) == 2
    err = capsys.readouterr().err
    assert "close matches" in err \
        and "fast_tffm_tpu.data.stream.exchange_watermarks" in err


def test_changed_mode_defers_catalog_drift_rules(tmp_path, monkeypatch):
    """--changed lints a SUBSET, where "emitted nowhere on the
    surface" proves nothing — the catalog-drift rules (R009/R012) are
    deferred to the full sweep instead of false-positive firing when
    the emitting module is outside the closure."""
    from tools.fmlint.core import run_paths
    from tools.fmlint.xrules import r009_config_drift, r012_health_catalog
    assert r009_config_drift.needs_full_surface
    assert r012_health_catalog.needs_full_surface
    # The real repo subset that reproduced the misfire: attribution.py
    # (the catalog) without obs/quality.py (the gate_held emitter).
    attribution = os.path.join(FM, "obs", "attribution.py")
    full = run_paths([attribution])
    assert any(f.rule == "R012" for f in full), \
        "subset misfire shape drifted — pick another probe module"
    assert [f for f in run_paths([attribution], partial=True)
            if f.rule in ("R009", "R012")] == []
