"""The jitted train step vs the oracle: gradients (finite differences) and
a full Adagrad update on touched rows; loss decreases on a learnable toy
problem."""

import numpy as np
import pytest

import jax

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import make_device_batch
from fast_tffm_tpu.data.parser import parse_lines
from fast_tffm_tpu.models import oracle
from fast_tffm_tpu.models.fm import (ModelSpec, batch_args, init_accumulator,
                                     init_table, make_train_step)

V, K = 30, 3
CFG = FmConfig(vocabulary_size=V, factor_num=K, batch_size=4,
               bucket_ladder=(4, 8), learning_rate=0.1,
               factor_lambda=0.01, bias_lambda=0.02, adagrad_init=0.1)


def toy_batch():
    lines = ["1 3:0.5 7:1.0 9:2.0", "0 3:1.0 12:0.5", "1 20:1.0",
             "0 7:0.25 20:1.0"]
    block = parse_lines(lines, V)
    batch = [([3, 7, 9], [0.5, 1.0, 2.0]), ([3, 12], [1.0, 0.5]),
             ([20], [1.0]), ([7, 20], [0.25, 1.0])]
    labels = np.array([1.0, 0.0, 1.0, 0.0])
    return make_device_batch(block, CFG), batch, labels


def scatter_dense(uniq_ids, grad_rows, num_rows):
    g = np.zeros((num_rows, grad_rows.shape[1]), dtype=np.float64)
    for u, row in zip(uniq_ids, grad_rows):
        if u < V:
            g[u] += row
    return g


def test_step_matches_oracle_adagrad():
    spec = ModelSpec.from_config(CFG)
    table0 = np.asarray(init_table(CFG, seed=1))
    acc0 = np.asarray(init_accumulator(CFG))
    b, batch, labels = toy_batch()

    step = make_train_step(spec)
    t1, a1, loss, scores = step(jax.numpy.asarray(table0),
                                jax.numpy.asarray(acc0), **batch_args(b))
    t1, a1 = np.asarray(t1), np.asarray(a1)

    # oracle: dense FD grad -> dense adagrad
    g = oracle.grad_fd(table0[:-1].astype(np.float64), batch, labels,
                       factor_lambda=CFG.factor_lambda,
                       bias_lambda=CFG.bias_lambda)
    want_t, want_a = oracle.adagrad_step(
        table0[:-1].astype(np.float64), acc0[:-1].astype(np.float64), g,
        CFG.learning_rate)

    np.testing.assert_allclose(t1[:-1], want_t, rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(a1[:-1], want_a, rtol=2e-3, atol=2e-4)
    # the dead padding row never moves
    np.testing.assert_array_equal(t1[-1], 0.0)
    np.testing.assert_allclose(a1[-1], CFG.adagrad_init)

    # loss value matches oracle
    s = oracle.batch_scores(table0[:-1].astype(np.float64), batch)
    want_loss = (oracle.logistic_loss(s, labels)
                 + oracle.regularization(table0[:-1].astype(np.float64),
                                         batch, CFG.factor_lambda,
                                         CFG.bias_lambda))
    assert float(loss) == pytest.approx(want_loss, rel=1e-4)


def test_untouched_rows_unchanged():
    spec = ModelSpec.from_config(CFG)
    table0 = np.asarray(init_table(CFG, seed=1))
    acc0 = np.asarray(init_accumulator(CFG))
    b, batch, _ = toy_batch()
    step = make_train_step(spec)
    t1, a1, _, _ = step(jax.numpy.asarray(table0), jax.numpy.asarray(acc0),
                        **batch_args(b))
    touched = {3, 7, 9, 12, 20}
    untouched = [i for i in range(V) if i not in touched]
    np.testing.assert_array_equal(np.asarray(t1)[untouched],
                                  table0[untouched])
    np.testing.assert_array_equal(np.asarray(a1)[untouched],
                                  acc0[untouched])


def test_zero_weight_examples_do_not_train():
    spec = ModelSpec.from_config(CFG)
    table0 = init_table(CFG, seed=2)
    acc0 = init_accumulator(CFG)
    # batch of 1 real + 3 dummies: only ids {5} may change
    block = parse_lines(["1 5:1.0"], V)
    b = make_device_batch(block, CFG)
    step = make_train_step(spec)
    t1, _, _, _ = step(table0, acc0, **batch_args(b))
    t0, t1 = np.asarray(init_table(CFG, seed=2)), np.asarray(t1)
    changed = np.where(np.any(t0 != t1, axis=1))[0]
    assert changed.tolist() == [5]


def test_fractional_weights_keep_weighted_mean_loss():
    """A batch whose TOTAL weight is in (0, 1) must still get the
    weighted-MEAN data loss the docstring promises: the old floor of
    1.0 on sum(w) silently rescaled loss and gradients by the batch's
    weight mass for fractional weight_files (review finding). Scaling
    all weights by a constant must leave the data loss unchanged."""
    import dataclasses
    spec = dataclasses.replace(ModelSpec.from_config(CFG),
                               factor_lambda=0.0, bias_lambda=0.0)
    block = parse_lines(["1 5:1.0 7:0.5", "0 9:2.0"], V)
    step = make_train_step(spec)
    losses = []
    for scale in (1.0, 0.1):  # sum(w) = 2.0 vs 0.2 (< 1.0)
        b = make_device_batch(block, CFG)
        args = batch_args(b)
        args["weights"] = np.asarray(args["weights"]) * scale
        # fresh state per call: the step donates table/acc
        _, _, loss, _ = step(init_table(CFG, seed=4),
                             init_accumulator(CFG), **args)
        losses.append(float(loss))
    assert losses[0] == pytest.approx(losses[1], rel=1e-5)


def test_loss_decreases_on_toy_problem():
    rng = np.random.default_rng(0)
    spec = ModelSpec.from_config(CFG)
    table = init_table(CFG, seed=3)
    acc = init_accumulator(CFG)
    step = make_train_step(spec)
    # learnable rule: label = 1 iff feature 1 present (else feature 2)
    lines = []
    for _ in range(64):
        y = int(rng.integers(0, 2))
        fid = 1 if y else 2
        extra = int(rng.integers(10, 20))
        lines.append(f"{y} {fid}:1 {extra}:1")
    losses = []
    for epoch in range(15):
        for i in range(0, 64, 4):
            block = parse_lines(lines[i:i + 4], V)
            b = make_device_batch(block, CFG)
            table, acc, loss, _ = step(table, acc, **batch_args(b))
            losses.append(float(loss))
    assert np.mean(losses[-16:]) < 0.55 * np.mean(losses[:16])


def test_ffm_step_matches_fd_oracle():
    """FFM backward (jax.grad through the field-bucketed interaction)
    against dense finite differences of oracle.ffm_score + loss + reg,
    pushed through one Adagrad step — the FFM analogue of
    test_step_matches_oracle_adagrad."""
    Vf, F, Kf = 16, 3, 2
    cfg = FmConfig(vocabulary_size=Vf, factor_num=Kf, model_type="ffm",
                   field_num=F, batch_size=4, bucket_ladder=(4, 8),
                   learning_rate=0.1, factor_lambda=0.01, bias_lambda=0.02,
                   adagrad_init=0.1)
    spec = ModelSpec.from_config(cfg)
    lines = ["1 0:3:0.5 1:7:1.0 2:9:2.0", "0 0:3:1.0 2:12:0.5",
             "1 1:15:1.0", "0 2:7:0.25 0:15:1.0"]
    batch = [([3, 7, 9], [0, 1, 2], [0.5, 1.0, 2.0]),
             ([3, 12], [0, 2], [1.0, 0.5]),
             ([15], [1], [1.0]),
             ([7, 15], [2, 0], [0.25, 1.0])]
    labels = np.array([1.0, 0.0, 1.0, 0.0])
    block = parse_lines(lines, Vf, field_aware=True, field_num=F)
    b = make_device_batch(block, cfg)

    table0 = np.asarray(init_table(cfg, seed=4))
    acc0 = np.asarray(init_accumulator(cfg))
    step = make_train_step(spec)
    t1, a1, loss, _ = step(jax.numpy.asarray(table0),
                           jax.numpy.asarray(acc0), **batch_args(b))
    t1 = np.asarray(t1)

    t64 = table0[:-1].astype(np.float64)

    def total(t):
        s = np.array([oracle.ffm_score(t, F, ids, flds, vals)
                      for ids, flds, vals in batch])
        uniq = np.unique(np.concatenate([ids for ids, _, _ in batch]))
        v, w = t[uniq, :-1], t[uniq, -1]
        return (oracle.logistic_loss(s, labels)
                + cfg.factor_lambda * np.sum(v * v)
                + cfg.bias_lambda * np.sum(w * w))

    eps = 1e-5
    g = np.zeros_like(t64)
    touched = np.unique(np.concatenate([ids for ids, _, _ in batch]))
    for r in touched:
        for c in range(t64.shape[1]):
            t = t64.copy()
            t[r, c] += eps
            up = total(t)
            t[r, c] -= 2 * eps
            g[r, c] = (up - total(t)) / (2 * eps)

    want_t, _ = oracle.adagrad_step(t64, acc0[:-1].astype(np.float64), g,
                                    cfg.learning_rate)
    np.testing.assert_allclose(t1[:-1], want_t, rtol=2e-3, atol=2e-4)
    assert float(loss) == pytest.approx(total(t64), rel=1e-4)
