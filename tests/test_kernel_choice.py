"""kernel=auto must follow the measured (L, dedup) regime matrix
(BASELINE.md "Kernel-choice matrix"), not a blanket Pallas-on-TPU rule —
round-4 review: the old policy picked a measured-slower kernel in half
the matrix's cells (Pallas 0.67x XLA at L=48/dedup=device)."""

import dataclasses

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.models.fm import ModelSpec, resolved_kernel
from fast_tffm_tpu.ops.kernel_choice import auto_kernel


def test_auto_kernel_matrix_cells():
    # the four measured cells, verbatim
    assert auto_kernel("device", 48) == "xla"     # 0.67x cell
    assert auto_kernel("host", 48) == "xla"       # 0.94x
    assert auto_kernel("host", 64) == "xla"       # 0.87x
    assert auto_kernel("device", 64) == "pallas"  # 1.42x
    # extrapolation: sub-tile widths never pick pallas; larger
    # device-dedup buckets keep the winner
    assert auto_kernel("device", 32) == "xla"
    assert auto_kernel("device", 128) == "pallas"
    assert auto_kernel("host", 256) == "xla"


def _spec(**kw):
    base = dict(model_type="fm", order=2, factor_num=8, field_num=0,
                vocabulary_size=1024, loss_type="logistic",
                factor_lambda=0.0, bias_lambda=0.0, learning_rate=0.01,
                kernel="auto", dedup="device")
    base.update(kw)
    return ModelSpec(**base)


def test_resolved_kernel_policy():
    s = _spec()
    assert resolved_kernel(s, 48) == "xla"
    assert resolved_kernel(s, 64) == "pallas"
    assert resolved_kernel(_spec(dedup="host"), 64) == "xla"
    # explicit config always beats the matrix
    assert resolved_kernel(_spec(kernel="pallas"), 48) == "pallas"
    assert resolved_kernel(_spec(kernel="xla"), 64) == "xla"
    # non-2nd-order / ffm never run the pallas kernel
    assert resolved_kernel(_spec(order=3, kernel="pallas"), 64) == "xla"
    assert resolved_kernel(
        _spec(model_type="ffm", field_num=4, kernel="pallas"), 64) == "xla"


def test_from_config_keeps_auto_only_on_tpu(monkeypatch):
    import jax
    # CPU backend (the test env): auto resolves to xla at config time
    assert ModelSpec.from_config(FmConfig()).kernel == "xla"
    # TPU backend: auto SURVIVES so _scores can decide per bucket
    import fast_tffm_tpu.models.fm as fm_mod
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert ModelSpec.from_config(FmConfig()).kernel == "auto"
    # ...but not where the fused kernel doesn't apply
    assert ModelSpec.from_config(FmConfig(order=3)).kernel == "xla"


def test_scores_dispatch_follows_resolution(monkeypatch):
    """The trace-time dispatch in _scores must route through
    resolved_kernel — pin it by intercepting the pallas entry point."""
    import fast_tffm_tpu.ops.pallas_fm as pallas_mod
    from fast_tffm_tpu.models.fm import _scores
    calls = []
    real = pallas_mod.fm_batch_scores_pallas

    def spy(*a, **k):
        calls.append(True)
        return real(*a, **k)

    monkeypatch.setattr(pallas_mod, "fm_batch_scores_pallas", spy)
    U, D = 16, 9
    gathered = np.random.default_rng(0).normal(
        size=(U, D)).astype(np.float32)
    for L, expect_pallas in ((48, False), (64, True)):
        calls.clear()
        local_idx = np.zeros((4, L), np.int32)
        vals = np.zeros((4, L), np.float32)
        _scores(_spec(), gathered, local_idx, vals, None)
        assert bool(calls) == expect_pallas, (L, calls)
