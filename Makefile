# Build-system parity with the reference's Makefile (SURVEY.md §2 "Build
# system"): the reference compiles its C++ TF ops into a shared object;
# here the only ahead-of-time artifact is the C++ parser/dedup extension
# (the TPU compute kernels are JIT-compiled by XLA/Pallas at runtime).
#
#   make            build the parser extension
#   make test       run the test suite
#   make bench      run the benchmark (one JSON line)
#   make bench-host standalone host-only 1/2/4-worker sweep of the
#                   parallel data plane (no device needed)
#   make bench-predict  standalone predict line: cross-file streaming
#                   scorer trials + its host_threads 1/2/4 sweep
#   make bench-vocab    admission-path overhead: train e2e at
#                   vocab_mode=admit vs fixed (target <= 5% cost)
#   make bench-wire standalone wire-format sweep: padded-wide vs
#                   packed-wide vs packed-narrow on h2d_only and e2e,
#                   with bytes/example on the wire
#   make bench-memory  device-memory ledger profile: bytes/row,
#                   planner-vs-ledger and peak-vs-model ratios off a
#                   real train run, serve reload spike off a real
#                   hot reload
#   make bench-fleet  serving-fleet latency line: client-side p50/p99
#                   and req/s through the failover proxy at 1 vs 3
#                   replicas (real child processes), scaling factor
#                   pinned as throughput_x
#   make lint       fmlint whole-program pass (R000-R017) over
#                   fast_tffm_tpu/, tools/, run_tffm.py, bench.py;
#                   writes the machine-readable findings artifact to
#                   .fmlint_cache/findings.json and prints per-rule
#                   wall time (--profile)
#   make chaos      fault-injection soak scenarios on CPU (fmchaos)
#   make stream-soak  the streaming run-mode scenarios standalone
#                   (torn writes / SIGTERM+resume / truncation)
#   make serve      run the online scorer on sample.cfg (needs a
#                   published checkpoint: fmckpt publish, or a stream
#                   trainer with publish_interval_seconds)
#   make serve-soak the serving chaos scenario standalone (concurrent
#                   requests across a hot reload, bit-identical to
#                   batch predict)
#   make slo-soak   the closed-loop SLO scenario standalone: gated
#                   stream trainer + live writer + concurrent serving
#                   + a poisoned burst the publish gate must catch
#   make grow-soak  the elastic GROW scenarios standalone: SIGKILL a
#                   worker, shrink, admit a --join replacement back to
#                   full membership (bit-identical to an uninterrupted
#                   control), plus the joiner-dies-mid-rendezvous leg
#   make bench-multihost  multi-host scaling-efficiency row: real 1-
#                   and 2-process localhost clusters, per-worker rate
#   make bench-diff OLD=a.json NEW=b.json  per-row regression diff of
#                   two bench artifacts (exit 1 past TOLERANCE=0.85)
#   make anatomy METRICS=path.jsonl  clock-aligned cross-rank step
#                   anatomy report from a traced run's metrics shards
#                   (fmtrace --anatomy; needs trace_spans = true)
#   make clean

CXX ?= g++
CXXFLAGS ?= -O3 -march=native -std=c++17 -shared -fPIC -pthread

SO := fast_tffm_tpu/data/_parser.so
SRC := fast_tffm_tpu/data/_parser.cc

all: $(SO)

$(SO): $(SRC)
	$(CXX) $(CXXFLAGS) -o $@ $<

test: $(SO)
	python -m pytest tests/ -q

bench: $(SO)
	python bench.py

bench-host: $(SO)
	JAX_PLATFORMS=cpu python bench.py --host-sweep

bench-predict: $(SO)
	python bench.py --predict

bench-vocab: $(SO)
	python bench.py --vocab

bench-wire: $(SO)
	python bench.py --wire

bench-memory: $(SO)
	JAX_PLATFORMS=cpu python bench.py --memory

bench-fleet: $(SO)
	JAX_PLATFORMS=cpu python bench.py --fleet

lint:
	python -m tools.fmlint --profile --json-out .fmlint_cache/findings.json

chaos: $(SO)
	JAX_PLATFORMS=cpu python -m tools.fmchaos

stream-soak: $(SO)
	JAX_PLATFORMS=cpu python -m tools.fmchaos stream-soak stream-truncate

serve: $(SO)
	python run_tffm.py serve sample.cfg

serve-soak: $(SO)
	JAX_PLATFORMS=cpu python -m tools.fmchaos serve-soak

slo-soak: $(SO)
	JAX_PLATFORMS=cpu python -m tools.fmchaos slo-soak

grow-soak: $(SO)
	JAX_PLATFORMS=cpu python -m tools.fmchaos kill-then-grow grow-joiner-dies

bench-multihost: $(SO)
	JAX_PLATFORMS=cpu python bench.py --multihost

TOLERANCE ?= 0.85
bench-diff:
	python bench.py --compare $(OLD) $(NEW) --tolerance $(TOLERANCE)

METRICS ?= metrics.jsonl
anatomy:
	python -m tools.fmtrace --anatomy $(METRICS) $(wildcard $(METRICS).p*)

clean:
	rm -f $(SO)

.PHONY: all test bench bench-host bench-predict bench-vocab bench-wire bench-memory bench-fleet bench-multihost bench-diff anatomy lint chaos stream-soak serve serve-soak slo-soak grow-soak clean
