"""Model assembly: table init, scoring, loss, and the jitted train step.

This is the analogue of the reference's in-driver graph build (SURVEY.md
§3.1): gather unique rows -> scorer -> loss + reg -> Adagrad sparse apply.
The whole step is one ``jax.jit`` so, like the reference's single
``sess.run`` per step, Python touches nothing per-step but the loop.

Differences from the reference, by design (SURVEY §7):
- updates are synchronous (no async PS staleness),
- batches are fixed-shape/bucketed, deduplicated on the host,
- the optimizer is a hand-rolled *sparse* Adagrad: full-size accumulator
  (row-sharded like the table in parallel/), but per-step work touches
  only the batch's unique rows — the equivalent of TF's
  ``sparse_apply_adagrad`` on IndexedSlices (SURVEY §3.1).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import DeviceBatch
from fast_tffm_tpu.ops.interaction import (batch_reg, ffm_batch_scores,
                                           fm_batch_scores)


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """The static (hashable) subset of FmConfig the jitted step closes
    over; one compiled executable per (spec, batch shape bucket)."""
    model_type: str
    order: int
    factor_num: int
    field_num: int
    vocabulary_size: int
    loss_type: str
    factor_lambda: float
    bias_lambda: float
    learning_rate: float
    kernel: str = "xla"
    # "host": the pipeline dedups ids and ships (uniq_ids, local_idx).
    # "device": the pipeline ships raw ids [B, L] and the step runs
    # jnp.unique on device — ~40% less H2D per step (no uniq_ids array,
    # and the pipeline skips its dedup pass) for ~3 us of TPU sort.
    # Only the single-device jit paths support "device" (mesh/offload/
    # multi-process need the host-side unique contract).
    dedup: str = "host"

    @classmethod
    def from_config(cls, cfg: FmConfig) -> "ModelSpec":
        kernel = cfg.kernel
        if kernel == "pallas" and (cfg.model_type == "ffm"
                                   or cfg.order != 2):
            # The fused Pallas kernel covers 2nd-order FM only; an
            # explicit `kernel = pallas` on FFM/order>2 would otherwise
            # silently run XLA (the same silent-config-betrayal pattern
            # as the old mesh coercion). Warn and make the spec honest.
            import warnings
            warnings.warn(
                f"kernel = pallas is only implemented for 2nd-order FM; "
                f"model_type={cfg.model_type!r} order={cfg.order} runs "
                "the XLA scorer instead")
            kernel = "xla"
        if kernel == "auto":
            # Where the fused Pallas kernel applies (2nd-order FM on a
            # native-TPU backend), 'auto' SURVIVES into the spec and
            # _scores resolves it per bucket width at trace time from
            # the measured (L, dedup) matrix (ops/kernel_choice.py) —
            # the round-4 always-Pallas policy picked a measured-slower
            # kernel in half the matrix's cells. Interpret mode off-TPU
            # is a correctness fallback, not a fast path, so auto
            # resolves to XLA here.
            if not (cfg.model_type == "fm" and cfg.order == 2
                    and jax.default_backend() == "tpu"):
                kernel = "xla"
        dedup = cfg.dedup
        if dedup == "auto":
            # Device dedup wherever it applies: the plain single-device
            # jit (mesh, offload, and multi-process all rely on the
            # host-side unique contract).
            dedup = ("device" if jax.device_count() == 1
                     and cfg.lookup == "device" else "host")
        return cls(model_type=cfg.model_type, order=cfg.order,
                   factor_num=cfg.factor_num, field_num=cfg.field_num,
                   vocabulary_size=cfg.vocabulary_size,
                   loss_type=cfg.loss_type, factor_lambda=cfg.factor_lambda,
                   bias_lambda=cfg.bias_lambda,
                   learning_rate=cfg.learning_rate, kernel=kernel,
                   dedup=dedup)

    @property
    def row_dim(self) -> int:
        if self.model_type == "ffm":
            return self.factor_num * self.field_num + 1
        return self.factor_num + 1


def init_table(cfg: FmConfig, seed: int = 0) -> jax.Array:
    """[vocab+1, D] uniform(-init_value_range, +init_value_range) — the
    reference's init (SURVEY §2 "Model parameters") — with the final
    padding row zeroed (it must stay dead)."""
    key = jax.random.PRNGKey(seed)
    t = jax.random.uniform(
        key, (cfg.num_rows, cfg.row_dim), dtype=jnp.float32,
        minval=-cfg.init_value_range, maxval=cfg.init_value_range)
    return t.at[-1].set(0.0)


def init_accumulator(cfg: FmConfig) -> jax.Array:
    """Adagrad accumulator, full table size, constant-initialised (TF
    Adagrad's initial_accumulator_value; cfg.adagrad_init)."""
    return jnp.full((cfg.num_rows, cfg.row_dim), cfg.adagrad_init,
                    dtype=jnp.float32)


def resolved_kernel(spec: ModelSpec, L: int) -> str:
    """The kernel a (spec, bucket-width-L) executable actually runs —
    the ONE resolution of ``kernel = auto`` (trace-time, per bucket:
    the bucketed pipeline compiles one executable per (spec, L), so
    each bucket independently gets the kernel the measured matrix says
    wins at its width; ops/kernel_choice.py). Shared by _scores and by
    bench.py's per-line regime stamp so the stamp can't drift from the
    dispatch."""
    if spec.model_type == "ffm":
        return "xla"  # field-bucketed XLA scorer; no Pallas FFM kernel
    kernel = spec.kernel
    if kernel == "auto":
        from fast_tffm_tpu.ops.kernel_choice import auto_kernel
        kernel = auto_kernel(spec.dedup, L)
    if kernel == "pallas" and spec.order != 2:
        kernel = "xla"  # from_config warns; direct specs stay honest
    return kernel


def _scores(spec: ModelSpec, gathered: jax.Array, local_idx: jax.Array,
            vals: jax.Array, fields: Optional[jax.Array],
            mesh=None) -> jax.Array:
    """``mesh`` (sharded paths only) lets the Pallas kernel run under
    shard_map over the data axis — GSPMD cannot partition a pallas_call
    itself (parallel/sharded.py binds it; None = single-device jit)."""
    if spec.model_type == "ffm":
        return ffm_batch_scores(gathered, spec.field_num, local_idx,
                                fields, vals)
    if resolved_kernel(spec, vals.shape[-1]) == "pallas":
        from fast_tffm_tpu.ops.pallas_fm import fm_batch_scores_pallas
        return fm_batch_scores_pallas(gathered, local_idx, vals, mesh=mesh)
    return fm_batch_scores(gathered, local_idx, vals, order=spec.order)


def _per_example_loss(spec: ModelSpec, scores: jax.Array,
                      labels: jax.Array) -> jax.Array:
    if spec.loss_type == "logistic":
        # Stable sigmoid cross-entropy with {0,1} labels (the reference's
        # classification loss; SURVEY §2 "Loss + optimizer").
        return (jnp.maximum(scores, 0.0) - scores * labels
                + jnp.log1p(jnp.exp(-jnp.abs(scores))))
    return jnp.square(scores - labels)


def loss_and_scores(spec: ModelSpec, gathered: jax.Array,
                    labels: jax.Array, weights: jax.Array,
                    uniq_ids: jax.Array, local_idx: jax.Array,
                    vals: jax.Array, fields: Optional[jax.Array],
                    mesh=None) -> Tuple[jax.Array, jax.Array]:
    """Weighted-mean data loss + batch-active L2 reg. Zero-weight padding
    examples drop out of both value and gradient."""
    scores = _scores(spec, gathered, local_idx, vals, fields, mesh=mesh)
    per = _per_example_loss(spec, scores, labels)
    # Exact-zero guard ONLY for the all-padding filler batch (sum(w)=0,
    # numerator 0 — the distributed lockstep's zero-weight filler). Any
    # nonzero total weight — however tiny (fractional weight_files) —
    # divides exactly, preserving the weighted-mean contract. DOUBLE
    # where, not a subnormal floor: TPUs flush f32 subnormals to zero,
    # so max(0, 1e-38) would still divide 0/0 and divide's VJP would
    # inject NaN into the table gradient even though the forward value
    # is masked (and CPU tests can't see it — CPUs keep subnormals).
    wsum = weights.sum()
    nonzero = wsum > 0.0
    den = jnp.where(nonzero, wsum, 1.0)
    data_loss = jnp.where(nonzero, (per * weights).sum() / den, 0.0)
    reg = batch_reg(gathered, uniq_ids, spec.vocabulary_size,
                    spec.factor_lambda, spec.bias_lambda)
    return data_loss + reg, scores


def _device_dedup(spec: ModelSpec, raw_idx: jax.Array):
    """On-device unique for dedup='device' batches: ``raw_idx`` holds
    RAW feature ids [B, L] (pad cells = pad_id). U = B*L + 1 is static
    and >= any possible unique count + the pad slot, so jnp.unique's
    size-truncation can never drop an id. pad_id is the largest value
    (ids < vocab) so it sorts into the tail next to the fill slots —
    the same "padding slots hold pad_id" invariant the host path keeps.
    """
    flat = raw_idx.ravel()
    uniq, inv = jnp.unique(flat, size=flat.shape[0] + 1,
                           fill_value=spec.vocabulary_size,
                           return_inverse=True)
    return (uniq.astype(jnp.int32),
            inv.reshape(raw_idx.shape).astype(jnp.int32))


def sparse_adagrad_apply(table: jax.Array, acc: jax.Array,
                         uniq_ids: jax.Array, grad_rows: jax.Array,
                         lr: float) -> Tuple[jax.Array, jax.Array]:
    """acc[rows] += g²; table[rows] -= lr * g / sqrt(acc[rows]).

    ``uniq_ids`` are unique except padding slots, whose gradient rows are
    already masked to zero, so duplicate scatter-adds at the dead row are
    no-ops and the dense-Adagrad semantics on touched rows are exact.
    """
    acc = acc.at[uniq_ids].add(jnp.square(grad_rows))
    upd = -lr * grad_rows * lax.rsqrt(acc[uniq_ids])
    return table.at[uniq_ids].add(upd), acc


def grad_body(spec: ModelSpec, gathered, labels, weights, uniq_ids,
              local_idx, vals, fields=None, *, mesh=None):
    """The device-side compute between a lookup backend's ``gather`` and
    ``apply_grad`` (lookup.py): loss/scores plus gradients w.r.t. the
    gathered ``[U, D]`` rows, padding rows masked to zero.

    This is the seam the reference gets from TF autodiff stopping at the
    embedding_lookup boundary (SURVEY §3.2: workers compute IndexedSlices
    row gradients; where the rows *live* — PS task, device shard, host
    RAM — is the backend's business). ``train_step_body`` composes it
    with the in-jit device backend; HostOffloadLookup composes it with a
    host-RAM store.
    """
    def loss_fn(g):
        return loss_and_scores(spec, g, labels, weights, uniq_ids,
                               local_idx, vals, fields, mesh=mesh)

    (loss, scores), grad = jax.value_and_grad(
        loss_fn, has_aux=True)(gathered)
    live = (uniq_ids < spec.vocabulary_size).astype(grad.dtype)[:, None]
    return loss, scores, grad * live


@functools.lru_cache(maxsize=None)
def make_grad_fn(spec: ModelSpec):
    """Jitted grad_body: (gathered, labels, weights, uniq_ids, local_idx,
    vals[, fields]) -> (loss, scores, grad_rows). The offload train path:
    only [U, D] rows and their gradients ever cross the host boundary."""
    return jax.jit(functools.partial(grad_body, spec))


def train_step_body(spec: ModelSpec, table, acc, labels, weights, uniq_ids,
                    local_idx, vals, fields=None, *, mesh=None):
    """One full training step (gather -> loss -> grad -> sparse Adagrad).

    Pure function of arrays; jitted directly by make_train_step and jitted
    with mesh shardings by parallel/sharded.py — single source of truth for
    the step semantics either way. The gather + apply pair here IS the
    device lookup backend, fused into the jit (lookup.py documents the
    seam; grad_body is the shared middle).

    With ``spec.dedup == 'device'`` the caller ships RAW ids in
    ``local_idx`` and ``uniq_ids=None``; the unique pass runs here on
    device (_device_dedup) instead of on the host.
    """
    if spec.dedup == "device":
        if uniq_ids is not None:  # trace-time: batches must be raw-ids
            raise ValueError(
                "dedup=device step got a host-deduped batch (uniq_ids is "
                "set); build batches with raw_ids=True — slot indices "
                "read as feature ids would silently corrupt training")
        uniq_ids, local_idx = _device_dedup(spec, local_idx)
    # fmlint: disable=R011 -- the jitted step BELOW the slot seam:
    # uniq_ids reaching here are already physical rows (the data
    # plane remapped them in admit mode)
    gathered = table[uniq_ids]
    loss, scores, grad = grad_body(spec, gathered, labels, weights,
                                   uniq_ids, local_idx, vals, fields,
                                   mesh=mesh)
    table, acc = sparse_adagrad_apply(table, acc, uniq_ids, grad,
                                      spec.learning_rate)
    return table, acc, loss, scores


@functools.lru_cache(maxsize=None)
def make_train_step(spec: ModelSpec):
    """Build the jitted train step. Signature:
    (table, acc, labels, weights, uniq_ids, local_idx, vals, fields)
      -> (table, acc, loss, scores)
    Buffers are donated; one executable per batch-shape bucket. Cached per
    spec so repeated train()/evaluate() calls reuse compiled code."""
    return jax.jit(functools.partial(train_step_body, spec),
                   donate_argnums=(0, 1))


def _unpack_wire(spec: ModelSpec, L: int, uniq_ids, lengths, flat_idx,
                 flat_vals, flat_fields=None):
    """Device-side wire unpack shared by the packed step/score bodies:
    rebuild the [B, L] rectangles (wire.unpack_rectangles) with the
    padding sentinel this batch shape uses — the uniq table's last slot
    in host-dedup mode, the model pad id (vocabulary_size) in raw-ids
    mode. Narrow-mode f16 values upcast to f32 here, BEFORE any model
    math."""
    from fast_tffm_tpu.wire import unpack_rectangles
    pad = (spec.vocabulary_size if uniq_ids is None
           else uniq_ids.shape[0] - 1)
    return unpack_rectangles(L, pad, lengths, flat_idx, flat_vals,
                             flat_fields)


def packed_train_step_body(spec: ModelSpec, L: int, table, acc, labels,
                           weights, uniq_ids, lengths, flat_idx,
                           flat_vals, flat_fields=None, *, mesh=None):
    """One training step from the PACKED wire format (wire.py): unpack
    the flat CSR back into the padded rectangles on-device, then run
    the exact train_step_body — same compute graph, ~padding-waste
    fewer bytes across the wall. ``L`` is static (one executable per
    (spec, B, L, flat rung, U))."""
    local_idx, vals, fields = _unpack_wire(spec, L, uniq_ids, lengths,
                                           flat_idx, flat_vals,
                                           flat_fields)
    labels = labels.astype(jnp.float32)
    weights = weights.astype(jnp.float32)
    return train_step_body(spec, table, acc, labels, weights, uniq_ids,
                           local_idx, vals, fields, mesh=mesh)


@functools.lru_cache(maxsize=None)
def make_packed_train_step(spec: ModelSpec):
    """Jitted packed train step. Signature:
    (L, table, acc, labels, weights, uniq_ids, lengths, flat_idx,
     flat_vals[, flat_fields]) -> (table, acc, loss, scores)
    ``L`` static, table/acc donated (call them positionally)."""
    return jax.jit(functools.partial(packed_train_step_body, spec),
                   static_argnums=(0,), donate_argnums=(1, 2))


def packed_score_body(spec: ModelSpec, L: int, table, uniq_ids, lengths,
                      flat_idx, flat_vals, flat_fields=None, *,
                      mesh=None):
    """Inference forward from the packed wire format: unpack, then the
    exact score_body dispatch (raw gather for dedup=device, uniq gather
    otherwise) — BIT-identical scores to the padded wire in wide
    mode."""
    local_idx, vals, fields = _unpack_wire(spec, L, uniq_ids, lengths,
                                           flat_idx, flat_vals,
                                           flat_fields)
    return score_body(spec, table, uniq_ids, local_idx, vals, fields,
                      mesh=mesh)


@functools.lru_cache(maxsize=None)
def make_packed_score_fn(spec: ModelSpec):
    """Jitted packed inference: (L, table, uniq_ids, lengths, flat_idx,
    flat_vals[, flat_fields]) -> raw scores [B]. ``L`` static."""
    return jax.jit(functools.partial(packed_score_body, spec),
                   static_argnums=(0,))


def packed_rows_score_body(spec: ModelSpec, L: int, gathered, lengths,
                           flat_idx, flat_vals, flat_fields=None, *,
                           mesh=None):
    """Offload-score half of the packed wire (lookup.py's seam): the
    backend gathered ``[U, D]`` rows on the HOST from the withheld
    uniq_ids (WireBatch.host_uniq); only those rows plus the flat CSR
    cross the wall. Padding indexes the gathered block's last row —
    the same pad-slot contract rows_score_body inherits from the
    padded wire."""
    from fast_tffm_tpu.wire import unpack_rectangles
    local_idx, vals, fields = unpack_rectangles(
        L, gathered.shape[0] - 1, lengths, flat_idx, flat_vals,
        flat_fields)
    return rows_score_body(spec, gathered, local_idx, vals, fields,
                           mesh=mesh)


@functools.lru_cache(maxsize=None)
def make_packed_rows_score_fn(spec: ModelSpec):
    """Jitted packed offload inference: (L, gathered, lengths, flat_idx,
    flat_vals[, flat_fields]) -> raw scores [B]. ``L`` static."""
    return jax.jit(functools.partial(packed_rows_score_body, spec),
                   static_argnums=(0,))


def rows_score_body(spec: ModelSpec, gathered, local_idx, vals,
                    fields=None, *, mesh=None):
    """Inference forward from already-gathered rows — the score-side half
    of the lookup seam (offload predict: host gathers, device scores)."""
    return _scores(spec, gathered, local_idx, vals, fields, mesh=mesh)


@functools.lru_cache(maxsize=None)
def make_rows_score_fn(spec: ModelSpec):
    """Jitted rows_score_body: (gathered, local_idx, vals[, fields]) ->
    raw scores [B]."""
    return jax.jit(functools.partial(rows_score_body, spec))


def score_body(spec: ModelSpec, table, uniq_ids, local_idx, vals,
               fields=None, *, mesh=None):
    """Inference forward (gather -> scorer). Shared by the single-device
    and mesh-sharded score functions — single source of truth, like
    train_step_body. dedup='device': raw ids in ``local_idx``,
    ``uniq_ids=None`` — and NO device unique: dedup buys the forward
    pass nothing (its U is padded to B*L+1, so ``table[uniq]`` moves
    the same bytes a direct raw gather moves) while its sort-based
    ``jnp.unique`` over B*L ids dominated the whole predict sweep
    (measured on the bench chip: 179 ms vs 5.3 ms per B=8192 batch —
    the single biggest term of BENCH_r05's 15x predict-vs-train gap).
    The direct gather is BIT-identical: same table rows summed in the
    same slot order. Training keeps ``_device_dedup`` — the backward
    scatter needs unique rows for exact sparse Adagrad."""
    if spec.dedup == "device":
        if uniq_ids is not None:
            raise ValueError(
                "dedup=device scorer got a host-deduped batch (uniq_ids "
                "is set); build batches with raw_ids=True")
        B, L = local_idx.shape
        # fmlint: disable=R011 -- raw-gather scorer below the slot
        # seam: admit-mode callers remapped local_idx already
        gathered = table[local_idx.ravel()]
        idx = jnp.arange(B * L, dtype=jnp.int32).reshape(B, L)
        return rows_score_body(spec, gathered, idx, vals, fields,
                               mesh=mesh)
    # fmlint: disable=R011 -- score path below the slot seam (ids
    # already physical)
    gathered = table[uniq_ids]
    return rows_score_body(spec, gathered, local_idx, vals, fields,
                           mesh=mesh)


@functools.lru_cache(maxsize=None)
def make_score_fn(spec: ModelSpec):
    """Jitted inference: (table, uniq_ids, local_idx, vals, fields) ->
    raw scores [B] (the predict driver applies sigmoid for logistic).
    Cached per spec — callers may re-request it per file/epoch."""
    return jax.jit(functools.partial(score_body, spec))


def ships_raw_batches(spec: ModelSpec, mesh=None, backend=None) -> bool:
    """Whether an inference path should build raw-ids batches for this
    spec — the one place the policy lives (mesh and offload paths
    require the host-dedup contract regardless of spec.dedup; a drifted
    copy of this condition is exactly how a dedup=device scorer ends up
    fed host-deduped batches)."""
    return spec.dedup == "device" and mesh is None and backend is None


def make_batch_scorer(spec: ModelSpec, mesh=None, backend=None):
    """The one dispatch over the three inference paths — plain jit,
    mesh-sharded, lookup-backend offload (lookup.py) — shared by
    evaluate() and predict_scores() so a new backend wires in exactly
    once. Returns ``score(table, args) -> jax.Array`` (device-resident,
    [B] raw scores) where ``args`` is a batch_args() dict WITHOUT
    labels/weights (consumed destructively: the offload path pops
    uniq_ids).

    Deliberately does NOT materialize to numpy: a per-batch host fetch
    is a full device round-trip that collapses async dispatch
    pipelining (measured 30x+ throughput loss on a tunnelled chip —
    see train.py's deferred loss logging). Callers batch their fetches
    with jax.device_get over many scores at once."""
    if backend is not None:
        rows_fn = make_rows_score_fn(spec)

        def score(table, args):
            gathered = backend.gather(args.pop("uniq_ids"))
            return rows_fn(gathered, **args)
    elif mesh is not None:
        from fast_tffm_tpu.parallel.sharded import (make_sharded_score_fn,
                                                    shard_batch)
        fn = make_sharded_score_fn(spec, mesh)

        def score(table, args):
            return fn(table, **shard_batch(mesh, **args))
    else:
        fn = make_score_fn(spec)

        def score(table, args):
            return fn(table, **args)
    return score


def batch_args(batch: DeviceBatch) -> Dict[str, np.ndarray]:
    args = dict(labels=batch.labels, weights=batch.weights,
                uniq_ids=batch.uniq_ids, local_idx=batch.local_idx,
                vals=batch.vals)
    if batch.fields is not None:
        args["fields"] = batch.fields
    return args
