from fast_tffm_tpu.models import oracle  # noqa: F401
