"""Pure-NumPy factorization-machine oracle — ground truth for every test.

Implements exactly the math the reference's C++ ``fm_scorer`` computes
(SURVEY.md §3.5, corroborated by BASELINE.json's north_star):

    linear  = sum_j w[id_j] * x_j
    pair    = 1/2 * sum_f [ (sum_j v[id_j,f] x_j)^2 - sum_j v[id_j,f]^2 x_j^2 ]
    score_e = linear + pair
    reg     = factor_lambda * sum_{unique rows} ||v||^2
            + bias_lambda   * sum_{unique rows} w^2

plus the two capability extensions required by BASELINE.json configs #3/#4:
higher-order FM via the ANOVA kernel and field-aware FM (per-field latent
tables). Everything is straightforward O(k * nnz) / O(L^2 k) loops — slow,
obvious, and trusted.

Examples are (ids, vals) lists; tables are dense numpy arrays with the
reference's row layout ``[vocab, k + 1]`` — k latent factors then one
linear weight per row (SURVEY §2 "Model parameters").
"""
# fmlint: disable-file=R011 -- the oracle IS reference math on a dense
# table callers index by physical row; tests hand it already-mapped ids


from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

Example = Tuple[Sequence[int], Sequence[float]]          # (ids, vals)
FFMExample = Tuple[Sequence[int], Sequence[int], Sequence[float]]  # (+fields)


def fm_score(table: np.ndarray, ids: Sequence[int],
             vals: Sequence[float], order: int = 2) -> float:
    """Score one example. table: [V, k+1] (v factors cols 0..k-1, w col k)."""
    ids = np.asarray(ids, dtype=np.int64)
    x = np.asarray(vals, dtype=np.float64)
    k = table.shape[1] - 1
    v = table[ids, :k].astype(np.float64)        # [n, k]
    w = table[ids, k].astype(np.float64)         # [n]
    score = float(np.dot(w, x))
    if order == 2:
        s = v.T @ x                              # [k]
        q = (v * v).T @ (x * x)                  # [k]
        score += 0.5 * float(np.sum(s * s - q))
    else:
        score += _anova_interactions(v, x, order)
    return score


def _anova_interactions(v: np.ndarray, x: np.ndarray, order: int) -> float:
    """Sum over interaction degrees 2..order of the ANOVA kernel.

    ANOVA kernel A_t(z_1..z_n) = sum over subsets of size t of the product,
    computed per latent dim with the classic DP: a[t] += a[t-1] * z_j,
    iterating t downward per feature. Degree-2 term equals the
    (Σv)²−Σv² identity's result, which the tests assert.
    """
    n, k = v.shape
    total = 0.0
    z = v * x[:, None]                           # [n, k]
    a = np.zeros((order + 1, k), dtype=np.float64)
    a[0] = 1.0
    for j in range(n):
        for t in range(min(j + 1, order), 0, -1):
            a[t] += a[t - 1] * z[j]
    for t in range(2, order + 1):
        total += float(np.sum(a[t]))
    return total


def ffm_score(table: np.ndarray, field_num: int, ids: Sequence[int],
              fields: Sequence[int], vals: Sequence[float]) -> float:
    """Field-aware FM: row layout [V, field_num*k + 1]; v[i, f] is the
    latent vector feature i uses when interacting with a feature of field f.

        score = sum_j w_j x_j
              + sum_{i<j} <v[id_i, field_j], v[id_j, field_i]> x_i x_j
    """
    ids = np.asarray(ids, dtype=np.int64)
    flds = np.asarray(fields, dtype=np.int64)
    x = np.asarray(vals, dtype=np.float64)
    k = (table.shape[1] - 1) // field_num
    w = table[ids, -1].astype(np.float64)
    score = float(np.dot(w, x))
    n = len(ids)
    for i in range(n):
        vi = table[ids[i], : field_num * k].reshape(field_num, k)
        for j in range(i + 1, n):
            vj = table[ids[j], : field_num * k].reshape(field_num, k)
            score += float(np.dot(vi[flds[j]], vj[flds[i]])) * x[i] * x[j]
    return score


def batch_scores(table: np.ndarray, batch: List[Example],
                 order: int = 2) -> np.ndarray:
    return np.array([fm_score(table, ids, vals, order) for ids, vals in batch],
                    dtype=np.float64)


def regularization(table: np.ndarray, batch: List[Example],
                   factor_lambda: float, bias_lambda: float) -> float:
    """L2 over rows touched by the batch, each unique row counted once
    (SURVEY §3.5: the reference's scorer emits this alongside the scores)."""
    uniq = np.unique(np.concatenate(
        [np.asarray(ids, dtype=np.int64) for ids, _ in batch]
        if batch else [np.zeros(0, dtype=np.int64)]))
    k = table.shape[1] - 1
    v = table[uniq, :k].astype(np.float64)
    w = table[uniq, k].astype(np.float64)
    return float(factor_lambda * np.sum(v * v) + bias_lambda * np.sum(w * w))


def _weighted_mean(per: np.ndarray,
                   weights: np.ndarray | None) -> float:
    """The trainer's weighted-mean contract (fm.loss_and_scores):
    sum(per*w)/sum(w), tiny floor only for the all-zero-weight case.
    Plain mean when no weights — the two coincide at unit weights."""
    if weights is None:
        return float(np.mean(per))
    w = np.asarray(weights, dtype=np.float64)
    return float((per * w).sum() / max(w.sum(), 1e-8))


def logistic_loss(scores: np.ndarray, labels: np.ndarray,
                  weights: np.ndarray | None = None) -> float:
    """Weighted-MEAN sigmoid cross-entropy with {0,1} labels (matching
    the trainer's normalization, not mean-over-batch)."""
    scores = np.asarray(scores, dtype=np.float64)
    labels = np.asarray(labels, dtype=np.float64)
    # log(1 + exp(-yz)) in the stable form used by TF's
    # sigmoid_cross_entropy_with_logits: max(z,0) - z*y + log1p(exp(-|z|))
    per = np.maximum(scores, 0) - scores * labels + np.log1p(
        np.exp(-np.abs(scores)))
    return _weighted_mean(per, weights)


def mse_loss(scores: np.ndarray, labels: np.ndarray,
             weights: np.ndarray | None = None) -> float:
    per = (np.asarray(scores, np.float64) - np.asarray(labels, np.float64)) ** 2
    return _weighted_mean(per, weights)


def grad_fd(table: np.ndarray, batch: List[Example], labels: np.ndarray,
            factor_lambda: float = 0.0, bias_lambda: float = 0.0,
            order: int = 2, loss: str = "logistic",
            eps: float = 1e-5,
            weights: np.ndarray | None = None) -> np.ndarray:
    """Finite-difference dLoss/dTable over batch-touched rows — the oracle
    for the backward pass (the reference's ``fm_grad``). Dense [V, k+1];
    rows not touched by the batch are exactly zero. ``weights`` rides
    the loss's weighted-mean normalization (the trainer's contract)."""
    loss_fn = logistic_loss if loss == "logistic" else mse_loss

    def total(t):
        s = batch_scores(t, batch, order)
        return loss_fn(s, labels, weights) + regularization(
            t, batch, factor_lambda, bias_lambda)

    g = np.zeros_like(table, dtype=np.float64)
    touched = np.unique(np.concatenate(
        [np.asarray(ids, dtype=np.int64) for ids, _ in batch]))
    for r in touched:
        for c in range(table.shape[1]):
            t = table.astype(np.float64).copy()
            t[r, c] += eps
            up = total(t)
            t[r, c] -= 2 * eps
            dn = total(t)
            g[r, c] = (up - dn) / (2 * eps)
    return g


def adagrad_step(table: np.ndarray, acc: np.ndarray, grad: np.ndarray,
                 lr: float) -> Tuple[np.ndarray, np.ndarray]:
    """Reference optimizer: Adagrad with sparse per-row application
    (SURVEY §2 "Loss + optimizer"). Dense oracle form; grad rows of
    untouched rows are zero so acc/table only change where touched —
    which requires guarding the zero-grad entries: with acc 0 there
    too, grad/sqrt(acc) is 0/0 = NaN and would poison every untouched
    row (the trainer never hits this because adagrad_init > 0)."""
    acc = acc + grad * grad
    update = np.divide(grad, np.sqrt(acc),
                       out=np.zeros_like(grad), where=grad != 0)
    table = table - lr * update
    return table, acc
