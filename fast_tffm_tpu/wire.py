"""Wire-format layer: how a built batch crosses the host->device wall.

ROADMAP item 2 named the next hard ceiling after the parallel host
plane: ``h2d_only`` sits two orders of magnitude under ``device_only``
(BENCH_r05: 4.1M vs 387M ex/s), so every end-to-end gain is gated on
bytes-per-example — and the pipeline already *measures* the lever
(``padding-waste``, ``dedup-hit``, ``train/h2d_bytes``) without acting
on it. This module acts on it:

- ``wire_format = padded`` (default): the fixed-shape ``[B, L]``
  rectangles ship exactly as they always have — bit-identical to every
  prior release, pinned by parity tests.
- ``wire_format = packed``: the wire carries the CSR *substance*
  instead of mostly-padding rectangles — flat values + per-example
  lengths (+ the dedup'd uniq table in host-dedup mode), bucketed to a
  quarter-octave flat ladder so jit shapes stay static — and the jitted
  step/score programs rebuild the padded rectangles on-device
  (``unpack_rectangles``; models/fm.py folds it into the compiled
  programs), where the reconstruction is a scatter that costs
  essentially nothing next to the transfer it replaces.
- ``wire_dtypes = narrow`` (packed only): values/weights ship float16
  and upcast to f32 on device before any model math (ids are int32
  end-to-end already; labels stay f32) — half the value bytes for one
  rounding step on the inputs.

The encoder is also where the depth-2 **double-buffered dispatch**
lives: ``WireEncoder.device_put`` issues an explicit async H2D for the
encoded arrays, so while step N executes on the device's compute
stream, the host loop is already encoding and transferring batch N+1
on the copy stream — transfers stop serializing inside the step
dispatch (train.py and scoring.score_sweep both route through it).

One encoder, every surface: train steps, the cross-file predict sweep,
and the serving flush path all go through ``WireEncoder`` — fmlint's
R013 enforces that no train/predict/serve module ships ad-hoc
``jax.device_put`` rectangles around it.

Scope: packed applies to the single-device jit paths (the mesh and
multi-process lockstep paths assemble padded *global* arrays, and the
offload TRAIN step gathers on the host) — ``resolve_wire`` is the one
resolution point and downgrades with a warning, like ``dedup = auto``
resolution. The offload SCORE path does ship packed: only the gathered
rows plus the flat CSR cross the wall.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import numpy as np

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import DeviceBatch
from fast_tffm_tpu.obs.telemetry import batch_payload_bytes

# Narrow-mode wire dtype for values/weights. float16 keeps a 10-bit
# mantissa (libsvm values and example weights are near-unit magnitude);
# everything upcasts to f32 on device BEFORE any model math, so the
# only precision cost is one rounding step on the inputs.
NARROW_VALUE_DTYPE = np.float16

# Smallest flat-ladder rung: tiny serve flushes (one short request)
# must not blow a wide floor past their own rectangle.
FLAT_LADDER_FLOOR = 8


def flat_bucket(nnz: int) -> int:
    """Quarter-octave flat-array bucket covering ``nnz`` feature cells
    — the packed wire's static-shape ladder for the train/predict
    streams (one compiled executable per (batch shape, flat rung), same
    philosophy as the L/U ladders). Rungs are ``m * 2^(k-3)`` for
    ``m in {5, 6, 7, 8}``: four per octave, so the flat array's own
    padding never exceeds 25% (a power-of-two ladder wastes up to 100%,
    which on a dense corpus would hand back most of what packing saved
    — the Criteo-39 shape sits at 80% rectangle fill), while a steady
    stream still touches only the handful of rungs around its density.
    """
    if nnz <= FLAT_LADDER_FLOOR:
        return FLAT_LADDER_FLOOR
    k = (nnz - 1).bit_length()     # 2^(k-1) < nnz <= 2^k
    base = 1 << (k - 3)            # quarter-octave step
    return -(-nnz // base) * base


def rect_fraction_rungs(B: int, L: int):
    """The SERVE flat ladder for one [B, L] compile cell: power-of-two
    fractions of the rectangle (B*L/8 .. B*L) plus the floor — at most
    five rungs, so pre-compiling every (batch rung x width rung x flat
    rung) keeps the server's no-recompile guarantee at ~5x the padded
    warmup matrix instead of the fine ladder's ~50x. Transfer is not
    the serve path's bound (latency is), so the coarser ladder only
    trades some savings for a bounded warmup."""
    cells = B * L
    out = {FLAT_LADDER_FLOOR}
    for j in (3, 2, 1, 0):
        out.add(max(FLAT_LADDER_FLOOR, cells >> j))
    return tuple(sorted(out))


def flat_rungs(B: int, L: int):
    """Alias used by the serve warmup: every flat rung a [B, L] flush
    can encode to under the serve (rect-fraction) ladder."""
    return rect_fraction_rungs(B, L)


@dataclasses.dataclass(frozen=True)
class WireSpec:
    """The resolved (format, dtypes) pair a dispatch path runs under."""
    format: str = "padded"   # "padded" | "packed"
    dtypes: str = "wide"     # "wide" | "narrow"

    @property
    def packed(self) -> bool:
        return self.format == "packed"

    @property
    def narrow(self) -> bool:
        return self.dtypes == "narrow"

    def describe(self) -> str:
        return f"{self.format}-{self.dtypes}"


def resolve_wire(cfg: FmConfig, mesh=None, backend=None,
                 multi_process: Optional[bool] = None,
                 train: bool = False) -> WireSpec:
    """The ONE resolution of the wire knobs for a dispatch path — a
    drifted copy of this condition is exactly how a packed encoder ends
    up feeding a padded-global-array assembler. Mirrors the
    ``dedup = auto`` resolution style: paths that require the padded
    layout (mesh sharding, multi-process lockstep, offload TRAIN — its
    host gather consumes numpy uniq_ids and its step ships gathered
    rows, not batch rectangles) resolve back to padded-wide with a
    warning instead of failing a long job at dispatch time. The offload
    SCORE path supports packed (only flat CSR + gathered rows cross the
    wall), so ``train=False`` keeps it."""
    spec = WireSpec(cfg.wire_format, cfg.wire_dtypes)
    if not spec.packed:
        return spec
    if multi_process is None:
        import jax
        multi_process = jax.process_count() > 1
    blockers = []
    if mesh is not None:
        blockers.append("mesh sharding assembles padded shard arrays")
    if multi_process:
        blockers.append("multi-process lockstep assembles padded "
                        "global arrays")
    if train and backend is not None:
        blockers.append("the offload train step gathers on the host")
    if blockers:
        import warnings
        warnings.warn(
            f"wire_format = packed is unsupported on this path "
            f"({'; '.join(blockers)}); running padded-wide instead")
        return WireSpec()
    return spec


@dataclasses.dataclass
class WireBatch:
    """One encoded batch: the arrays that actually cross the wall plus
    the accounting both h2d counters need. ``batch`` stays attached for
    the step loop's bookkeeping (num_real, stream_pos, vocab_obs)."""
    batch: DeviceBatch
    args: Dict[str, Any]     # exactly the arrays to dispatch
    packed: bool
    L: int                   # static rectangle width (the unpack target)
    wire_bytes: int          # sum of args byte sizes (the real payload)
    logical_bytes: int       # the padded layout's byte size (what the
    # legacy wire would have shipped — the savings denominator)
    host_uniq: Optional[np.ndarray] = None  # offload score path only:
    # uniq_ids stay host-side for the backend gather, never dispatched


class WireEncoder:
    """The one device-bound batch encoder (fmlint R013 anchors here).

    ``pad_id`` is the MODEL's pad id (cfg.pad_id == vocabulary_size) —
    raw-ids batches mark padding cells with it directly; host-dedup
    batches mark padding via the uniq table's last slot, which the
    encoder derives per batch. Admit-mode batches must be remapped to
    physical rows BEFORE encoding (train's ensure_current and serve's
    flush both already order it that way).

    ``host_uniq=True`` (offload score path): uniq_ids are withheld from
    the dispatched args and surfaced on ``WireBatch.host_uniq`` for the
    backend's host-side gather.

    ``rect_fraction=True`` (the serving process): flat arrays bucket to
    the coarse rect-fraction ladder instead of the fine quarter-octave
    one, so the server's pre-compiled shape matrix stays bounded (see
    rect_fraction_rungs)."""

    def __init__(self, wire: WireSpec, pad_id: int,
                 host_uniq: bool = False, rect_fraction: bool = False):
        self.wire = wire
        self.pad_id = int(pad_id)
        self.host_uniq = bool(host_uniq)
        self.rect_fraction = bool(rect_fraction)

    # -- encode ----------------------------------------------------------
    def encode_train(self, batch: DeviceBatch) -> WireBatch:
        return self._encode(batch, train=True)

    def encode_score(self, batch: DeviceBatch) -> WireBatch:
        return self._encode(batch, train=False)

    def _padded_args(self, batch: DeviceBatch,
                     train: bool) -> Dict[str, Any]:
        # Delegate to the canonical layout (models/fm.batch_args) so a
        # DeviceBatch growing a new dispatched array can never leave
        # the padded wire shipping an incomplete dict. Local import:
        # fm.py is a downstream consumer of this module.
        from fast_tffm_tpu.models.fm import batch_args
        args = batch_args(batch)
        if not train:
            args.pop("labels"), args.pop("weights")
        return args

    def _encode(self, batch: DeviceBatch, train: bool) -> WireBatch:
        li = batch.local_idx
        B, L = li.shape
        # The padded layout's size is what the legacy wire would ship:
        # labels/weights ride only on the train wire, matching the
        # score path's historical arg set.
        logical = (li.nbytes + batch.vals.nbytes
                   + (batch.uniq_ids.nbytes
                      if batch.uniq_ids is not None else 0)
                   + (batch.fields.nbytes
                      if batch.fields is not None else 0)
                   + ((batch.labels.nbytes + batch.weights.nbytes)
                      if train else 0))
        if not self.wire.packed:
            args = self._padded_args(batch, train)
            return WireBatch(batch=batch, args=args, packed=False, L=L,
                             wire_bytes=logical, logical_bytes=logical)
        # Padding test: a cell is padding iff its TARGET ROW is the
        # dead pad row (pad_id == vocabulary_size — no real feature id
        # can reach it). Host-dedup batches must be tested through the
        # uniq table, not by slot index: the python builder parks
        # padding at slot U-1 but the C++ fast path parks it at slot 0
        # (both slots hold pad_id — the invariant is about rows, not
        # slot positions, and the on-device rebuild normalizes padding
        # to slot U-1, which is bit-identical math either way: padding
        # contributes exact 0.0 through the zeroed dead row).
        if batch.uniq_ids is None:
            mask = li != self.pad_id
            pad = self.pad_id
        else:
            mask = np.asarray(batch.uniq_ids)[li] != self.pad_id
            pad = len(batch.uniq_ids) - 1
        # Features are front-packed per row (make_device_batch scatters
        # cols 0..len-1), so row-major mask selection IS the per-example
        # contiguous CSR order the device unpack rebuilds from.
        lengths = mask.sum(axis=1).astype(np.int32)
        nnz = int(lengths.sum())
        P = (next(r for r in rect_fraction_rungs(B, L) if r >= nnz)
             if self.rect_fraction else flat_bucket(nnz))
        vdt = (NARROW_VALUE_DTYPE if self.wire.narrow else np.float32)
        flat_idx = np.full(P, pad, dtype=np.int32)
        flat_vals = np.zeros(P, dtype=vdt)
        flat_idx[:nnz] = li[mask]
        flat_vals[:nnz] = batch.vals[mask]
        args = {"lengths": lengths, "flat_idx": flat_idx,
                "flat_vals": flat_vals}
        if batch.fields is not None:
            ff = np.zeros(P, dtype=np.int32)
            ff[:nnz] = batch.fields[mask]
            args["flat_fields"] = ff
        host_uniq = None
        if self.host_uniq:
            # Offload score path: the uniq table stays host-side for
            # the backend gather; the packed rows program has no
            # uniq_ids parameter at all.
            host_uniq = batch.uniq_ids
        else:
            # None in raw-ids mode — the packed programs take it like
            # the padded ones do (an empty pytree leaf).
            args["uniq_ids"] = batch.uniq_ids
        if train:
            args["labels"] = batch.labels
            args["weights"] = (batch.weights.astype(vdt)
                               if self.wire.narrow else batch.weights)
        return WireBatch(batch=batch, args=args, packed=True, L=L,
                         wire_bytes=batch_payload_bytes(args),
                         logical_bytes=logical, host_uniq=host_uniq)

    # -- the depth-2 double buffer ---------------------------------------
    def device_put(self, wb: WireBatch) -> Dict[str, Any]:
        """Explicit async H2D of the encoded args — the double-buffered
        half of the wire layer. Dispatch is async, so by the time this
        runs for batch N, batch N-1's step is still executing on the
        compute stream; putting N's arrays here moves its transfer onto
        the copy stream CONCURRENT with that compute, instead of
        serializing at the head of N's step execution (the padded-era
        behavior, where the jit call transferred its numpy args
        inline). Single-device paths only — the mesh/lockstep paths
        have their own placement (shard_batch / global_batch)."""
        import jax
        from fast_tffm_tpu.obs.memory import LEDGER
        # Ledger (obs/memory.py): depth-2 window — this batch's bytes
        # on the copy stream plus the previous batch's still feeding
        # the executing step. wire_bytes is host metadata; an upsert
        # per put, no device interaction.
        LEDGER.register("wire_buffers", 2 * wb.wire_bytes)
        return jax.device_put(wb.args)


def unpack_rectangles(L: int, pad: int, lengths, flat_idx, flat_vals,
                      flat_fields=None):
    """Device-side inverse of the packed encoding: rebuild the
    ``[B, L]`` (local_idx, vals[, fields]) rectangles from flat CSR —
    BIT-identical to the host-built padded arrays (padding cells
    restored to exactly ``pad`` / 0.0 / 0). Runs inside the jitted
    step/score programs (models/fm.py), where the scatter is noise next
    to the transfer it replaced. All shapes static: B from ``lengths``,
    P from ``flat_idx``, ``L`` and ``pad`` are trace-time ints."""
    import jax.numpy as jnp
    lengths = lengths.astype(jnp.int32)
    B = lengths.shape[0]
    P = flat_idx.shape[0]
    ends = jnp.cumsum(lengths)
    starts = ends - lengths
    total = ends[-1]
    pos = jnp.arange(P, dtype=jnp.int32)
    # Row of each flat cell: count of example ends at or before it.
    row = jnp.searchsorted(ends, pos, side="right").astype(jnp.int32)
    valid = pos < total
    rowc = jnp.clip(row, 0, B - 1)
    col = jnp.clip(pos - starts[rowc], 0, L - 1)
    # Invalid (flat-padding) cells scatter to row B -> dropped; real
    # cells land exactly where make_device_batch put them.
    r = jnp.where(valid, rowc, B)
    li = jnp.full((B, L), pad, dtype=jnp.int32)
    li = li.at[r, col].set(flat_idx.astype(jnp.int32), mode="drop")
    vv = jnp.zeros((B, L), dtype=jnp.float32)
    vv = vv.at[r, col].set(flat_vals.astype(jnp.float32), mode="drop")
    ff = None
    if flat_fields is not None:
        ff = jnp.zeros((B, L), dtype=jnp.int32)
        ff = ff.at[r, col].set(flat_fields.astype(jnp.int32),
                               mode="drop")
    return li, vv, ff
