"""Test-support subpackage: deterministic fault injection for the data
plane (faults.py). Shipped inside the package (not under tests/) so
the ``tools/fmchaos`` CLI and external soak harnesses can drive the
same injectors the test suite pins."""
