"""Deterministic, seed-driven fault injection for the data plane.

The recovery paths this repo grew (bad-line policy, IO retry/backoff,
preemption save/resume — README "Fault tolerance") are exactly the
code that never runs on a healthy dev box, so they rot unless faults
are injectable on demand and REPRODUCIBLY: every injector here is
driven by an explicit seed (or an exact count/step), never wall-clock
randomness, so a failing chaos scenario replays bit-for-bit.

Injectors (all restore global state on exit):

- ``corrupt_corpus``      — write a corrupted copy of a clean libsvm
  file with a seeded fraction of lines mangled; returns the exact
  0-based line indices, so tests pin skip/quarantine counts to the
  injected truth.
- ``flaky_open``          — context manager: the first N ``open()``
  calls whose path matches a substring raise a transient ``OSError``
  (retryable class), exercising utils/retry.py end to end.
- ``preempt_after_steps`` — context manager: raises SIGTERM/SIGINT
  in-process after the Nth train step (hooked on ``StepTimer.tick``,
  the once-per-step bookkeeping call), so mid-epoch preemption lands
  at a deterministic step — no timers, no flakes.
- ``truncate_checkpoint`` — torn-write simulator: truncates one
  seeded-chosen array file inside the latest checkpoint step
  directory, for restore-error-path tests.
- ``wait_until``          — bounded condition poll for the
  compute-plane scenarios: the parent process delivers SIGKILL/SIGSTOP
  to a worker only once an observable milestone (a committed
  checkpoint step, a renewed heartbeat lease) proves the cluster is
  mid-lockstep — deterministic in WHAT it waits for, never a bare
  sleep.
- ``committed_steps``     — the milestone reader ``wait_until`` pairs
  with: committed checkpoint step numbers under ``<model_file>.ckpt``.

No jax import at module level: the injectors patch pure-Python seams.
"""

from __future__ import annotations

import builtins
import contextlib
import errno
import os
import random
import signal
import time
from typing import Callable, Iterator, List, Optional

# Corruption shapes that are malformed in EVERY parse mode (plain and
# hash_feature_id, FM and FFM): a non-float label, and a non-float
# feature value. (A corrupt feature ID would be legal under hashing.)
_CORRUPTIONS = (
    lambda line: "##bad_label## " + line.split(None, 1)[-1],
    lambda line: line.rstrip() + " 0:##bad_value##",
)


def corrupt_corpus(src: str, dst: str, fraction: float = 0.005,
                   seed: int = 0) -> List[int]:
    """Copy ``src`` to ``dst`` with ``max(1, round(n * fraction))``
    lines corrupted, picked and mangled by a ``seed``-driven RNG.
    Returns the sorted 0-based indices of the corrupted lines — the
    ground truth a skip/quarantine accounting test pins against."""
    with open(src, "r", encoding="utf-8") as fh:
        lines = fh.read().splitlines()
    rng = random.Random(f"corrupt/{seed}")
    n_bad = max(1, int(round(len(lines) * fraction)))
    idxs = sorted(rng.sample(range(len(lines)), n_bad))
    for k, i in enumerate(idxs):
        lines[i] = _CORRUPTIONS[k % len(_CORRUPTIONS)](lines[i])
    with open(dst, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")
    return idxs


@contextlib.contextmanager
def flaky_open(n_failures: int, match: str = "",
               use_errno: int = errno.EIO) -> Iterator[dict]:
    """Make the first ``n_failures`` ``open()`` calls on paths
    containing ``match`` raise a RETRYABLE OSError (default EIO — the
    classic transient networked-FS failure). ``match`` scopes the
    injection so unrelated opens (logs, metrics sink, checkpoints)
    pass through. Yields a state dict; ``state["failures"]`` counts
    injected failures (assert it afterwards to prove the fault
    actually fired)."""
    state = {"remaining": int(n_failures), "failures": 0}
    real_open = builtins.open

    def injected(file, *args, **kwargs):
        if state["remaining"] > 0:
            try:
                name = os.fspath(file)
            except TypeError:
                name = ""
            if not match or match in str(name):
                state["remaining"] -= 1
                state["failures"] += 1
                raise OSError(
                    use_errno,
                    f"injected transient open failure "
                    f"#{state['failures']}", str(name))
        return real_open(file, *args, **kwargs)

    builtins.open = injected
    try:
        yield state
    finally:
        builtins.open = real_open


@contextlib.contextmanager
def preempt_after_steps(n: int,
                        sig: int = signal.SIGTERM) -> Iterator[dict]:
    """Deliver ``sig`` to THIS process synchronously after the ``n``-th
    train step, by wrapping ``StepTimer.tick`` (the loop's
    once-per-step bookkeeping). ``signal.raise_signal`` on the main
    thread runs train()'s installed handler immediately, so the loop
    drains the preemption flag at the very next step boundary — the
    deterministic "mid-epoch SIGTERM scheduler". Yields a state dict
    (``state["fired"]``)."""
    from fast_tffm_tpu.utils.timing import StepTimer
    state = {"steps": 0, "fired": False}
    real_tick = StepTimer.tick

    def tick(self, n_examples):
        real_tick(self, n_examples)
        state["steps"] += 1
        if state["steps"] >= n and not state["fired"]:
            state["fired"] = True
            signal.raise_signal(sig)

    StepTimer.tick = tick
    try:
        yield state
    finally:
        StepTimer.tick = real_tick


def wait_until(predicate: Callable[[], bool], timeout: float,
               interval: float = 0.05,
               message: str = "condition") -> None:
    """Poll ``predicate`` until true or ``timeout`` seconds pass
    (AssertionError naming ``message`` on expiry). The chaos
    scenarios' trigger primitive: faults land at observable
    milestones, not at wall-clock guesses."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout:g}s waiting for "
                         f"{message}")


def committed_steps(model_file: str) -> List[int]:
    """Committed checkpoint step numbers for ``model_file`` — the
    milestone the multi-worker scenarios key fault delivery on (a
    committed step proves every worker is past bring-up and stepping
    in lockstep)."""
    from fast_tffm_tpu.checkpoint import list_step_dirs
    return list_step_dirs(os.path.abspath(model_file) + ".ckpt")


def truncate_checkpoint(model_file: str, seed: int = 0,
                        keep_bytes: int = 8,
                        step: Optional[int] = None) -> Optional[str]:
    """Simulate a torn checkpoint write: pick (seeded) one of the
    largest files under the LATEST step directory (or an explicit
    ``step``) of ``<model_file>.ckpt/`` and truncate it to
    ``keep_bytes``. Returns the truncated path, or None when no step
    directory exists. The save-time ``manifest-<step>.json`` sidecar is
    left untouched — exactly the torn-write shape ``ckpt_verify`` must
    catch (sizes on disk no longer match the manifest)."""
    directory = os.path.abspath(model_file) + ".ckpt"
    if not os.path.isdir(directory):
        return None
    steps = [d for d in os.listdir(directory) if d.isdigit()]
    if step is not None:
        steps = [d for d in steps if int(d) == step]
    if not steps:
        return None
    step_dir = os.path.join(directory, max(steps, key=int))
    candidates = []
    for root, _dirs, names in os.walk(step_dir):
        for name in names:
            p = os.path.join(root, name)
            candidates.append((os.path.getsize(p), p))
    if not candidates:
        return None
    candidates.sort(reverse=True)
    # Among the largest quartile (the array payloads — truncating a
    # tiny metadata json is a different, easier failure), pick one.
    top = candidates[:max(1, len(candidates) // 4)]
    _, victim = random.Random(f"trunc/{seed}").choice(top)
    with open(victim, "r+b") as fh:
        fh.truncate(keep_bytes)
    return victim
