"""Predict driver — the ``py/fm_predict.py`` equivalent (SURVEY.md §3.4).

Restores the latest checkpoint at the config's ``model_file``, streams the
predict files through parser + scorer, and writes one score per input
line, order-preserving — sigmoid-transformed for logistic loss, raw for
mse. ``score_path`` is treated as a directory; each input file ``f``
produces ``<score_path>/<basename(f)>.score``.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

import jax
import numpy as np

from fast_tffm_tpu.checkpoint import CheckpointState
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import (batch_iterator, expand_files,
                                         gil_bound_iteration, prefetch)
from fast_tffm_tpu.metrics import sigmoid
from fast_tffm_tpu.models.fm import (ModelSpec, batch_args,
                                     make_batch_scorer, ships_raw_batches)
from fast_tffm_tpu.obs.telemetry import (active, make_telemetry,
                                         pop_active, push_active)
from fast_tffm_tpu.obs.trace import span
from fast_tffm_tpu.utils.fetch import ChunkedFetcher
from fast_tffm_tpu.utils.logging import get_logger

# Output-order buffer depth buckets (batches retained between bulk
# fetches): powers of two up to 4x FETCH_CHUNK_BATCHES.
_DEPTH_BUCKETS = tuple(2 ** i for i in range(11))


class _ScoreWriter:
    """Ordered score-file writer on a small background thread, so the
    next file's parse/score/D2H overlaps the previous file's disk
    write instead of serializing behind it (the first bite of the
    predict-gap roadmap item). Submission order IS write order (one
    queue, one writer), the queue is bounded (at most 2 files' scores
    buffered), and ``close()`` in the caller's finally flushes
    everything and surfaces any deferred write error — a predict()
    return means every score file is on disk. Each write is a
    ``predict/write`` span on the ``fm-score-writer`` track in
    fmtrace."""

    def __init__(self, logger):
        import queue
        import threading
        self._logger = logger
        self._q: "queue.Queue" = queue.Queue(maxsize=2)
        self._sentinel = object()
        self._lock = threading.Lock()  # guards _error (worker writes,
        # submit/close read; fmlint R008)
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(target=self._run,
                                        name="fm-score-writer",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        from fast_tffm_tpu.obs.trace import span
        while True:
            job = self._q.get()
            if job is self._sentinel:
                return
            with self._lock:
                dead = self._error is not None
            if dead:
                # Drain-and-discard: the run is already doomed (the
                # error surfaces at the next submit()/close()); keep
                # unblocking producers, stop burning I/O on writes
                # that would land beside a failed one.
                continue
            out_path, vals = job
            try:
                with span("predict/write",
                          path=os.path.basename(out_path)):
                    with open(out_path, "w") as fh:
                        for v in vals:
                            fh.write(f"{v:.6f}\n")
                self._logger.info("wrote %d scores to %s", len(vals),
                                  out_path)
            except BaseException as e:  # surfaced at submit()/close()
                with self._lock:
                    if self._error is None:  # keep the FIRST failure
                        self._error = e

    def submit(self, out_path: str, vals: np.ndarray) -> None:
        with self._lock:
            err = self._error
        if err is not None:
            raise err
        self._q.put((out_path, vals))

    def close(self, raise_error: bool = True) -> None:
        if not self._closed:
            self._closed = True
            self._q.put(self._sentinel)
            self._thread.join()
        if raise_error:
            with self._lock:
                err = self._error
            if err is not None:
                raise err


def load_table(cfg: FmConfig, mesh=None) -> jax.Array:
    """Restore the table from the latest checkpoint.

    With a mesh: restored ROW-SHARDED in the [ckpt_rows, D] checkpoint
    layout — the full table never materializes on one device or host
    (BASELINE config #5 scale: 10^9 rows ~ 36 GB dense). Without: the
    logical [num_rows, D] table on the default device."""
    import jax.numpy as jnp
    from fast_tffm_tpu.train import checkpoint_template
    from fast_tffm_tpu.utils.retry import RetryPolicy
    ckpt = CheckpointState(cfg.model_file,
                           retry=RetryPolicy.from_config(cfg),
                           verify=getattr(cfg, "ckpt_verify", "size"))
    restored = ckpt.restore(template=checkpoint_template(cfg, mesh))
    ckpt.close()
    if restored is None:
        raise FileNotFoundError(
            f"no checkpoint found under {cfg.model_file}.ckpt "
            "(run training first)")
    from fast_tffm_tpu.train import check_restored_vocab
    check_restored_vocab(cfg, restored)
    if mesh is not None:
        return restored["table"]
    # Checkpoints store the 4096-aligned [ckpt_rows, D] layout; the
    # single-device scorer wants the logical table.
    return jnp.asarray(restored["table"][:cfg.num_rows], dtype=jnp.float32)


def predict_scores(cfg: FmConfig, table: jax.Array, files,
                   mesh=None, backend=None) -> np.ndarray:
    """Raw scores for every example in ``files``, in input order. With a
    mesh, the batch is data-sharded and scored against the row-sharded
    table in place (table shape [ckpt_rows, D]). With a lookup
    ``backend`` (lookup.HostOffloadLookup), rows are gathered host-side
    and only [U, D] blocks reach the device (``table`` is unused)."""
    spec = ModelSpec.from_config(cfg)
    score_fn = make_batch_scorer(spec, mesh=mesh, backend=backend)
    raw = ships_raw_batches(spec, mesh=mesh, backend=backend)
    # keep_empty: blank input lines become zero-feature examples so the
    # score file stays line-aligned with the input (SURVEY §3.4).
    # Chunked fetches (utils/fetch.py): per-batch syncs are ruinous over
    # a tunnelled link, whole-file buffering is unbounded.
    out: List[np.ndarray] = []
    # overlap=True: chunk N's D2H transfer rides a background thread
    # while this loop dispatches chunk N+1's scoring — without it the
    # sweep serializes on the fetch (measured: the single dominant cost
    # of predict_e2e on this link; BASELINE.md "Predict-path rate").
    fetcher = ChunkedFetcher(lambda s, num_real: out.append(s[:num_real]),
                             overlap=True)
    tel = active()
    # try/finally (ADVICE round 5): an exception mid-sweep must not
    # leave the overlap worker parked on queue.get forever with a
    # queued chunk of device score arrays pinned in HBM — close()
    # drains and joins the worker without masking the original error.
    try:
        for batch in prefetch(batch_iterator(cfg, files, training=False,
                                             epochs=1, keep_empty=True,
                                             raw_ids=raw),
                              depth=cfg.prefetch_depth,
                              gil_bound=gil_bound_iteration(
                                  cfg, keep_empty=True)):
            args = batch_args(batch)
            args.pop("labels"), args.pop("weights")
            fetcher.add(score_fn(table, args), batch.num_real)
            if tel is not None:
                tel.count("predict/batches")
                tel.count("predict/examples", batch.num_real)
                # Output-order buffer: device score arrays held back so
                # results land in input order — its depth is the D2H
                # backlog (BASELINE.md "Predict-path rate").
                tel.observe("predict/fetch_depth", fetcher.pending_depth,
                            bounds=_DEPTH_BUCKETS)
                # Watchdog beat: a scored batch is progress
                # (obs/health.py).
                tel.heartbeat()
        fetcher.flush()
    finally:
        fetcher.close()
    return (np.concatenate(out) if out
            else np.zeros(0, dtype=np.float32))


def predict(cfg: FmConfig, table: Optional[jax.Array] = None,
            job_name: Optional[str] = None,
            task_index: Optional[int] = None) -> List[str]:
    """Run batch prediction; returns the list of score files written.

    Multi-device hosts score through the mesh (row-sharded table +
    data-sharded batches — SURVEY.md §3.4's single restore+score stack,
    scaled the same way training is); a lone device gets the plain
    jitted scorer. ``dist_train worker <i>`` argv (mirroring the train
    CLI) joins a jax.distributed job: input is byte-range-sharded by
    process, scored in lockstep through the global mesh, and the chief
    merges per-process part files into the ordered score file (a shared
    ``score_path`` filesystem is assumed, as for checkpoints)."""
    logger = get_logger(log_file=cfg.log_file or None)
    if job_name is not None:
        from fast_tffm_tpu.parallel.distributed import init_from_cluster
        init_from_cluster(cfg, job_name, task_index or 0)
    # Run telemetry (obs/): created after cluster init so the process
    # index in the run metadata (and the per-worker shard suffix) is
    # real. The try/finally below is the sink's lifecycle guarantee —
    # a crash mid-sweep still flushes everything buffered.
    tel = make_telemetry(cfg, "predict")
    tel_prev = push_active(tel)
    # Compute-plane liveness (parallel/liveness.py): multi-process
    # predict is the same lockstep collective protocol as distributed
    # validation — a dead peer must raise a named WorkerLostError, not
    # park the survivors in the window allgather forever. No elastic
    # recovery here (predict is cheap to rerun); fail fast with the
    # diagnosis.
    lease = None
    guard_prev = None
    guard_installed = False
    if jax.process_count() > 1:
        from fast_tffm_tpu.parallel.liveness import (HeartbeatLease,
                                                     install_guard,
                                                     lease_dir)
        if cfg.heartbeat_seconds > 0:
            lease = HeartbeatLease(
                lease_dir(cfg), process_index=jax.process_index(),
                members=range(jax.process_count()),
                heartbeat_seconds=cfg.heartbeat_seconds).start()
            if tel is not None:
                tel.lease = lease
        guard_prev = install_guard(lease, cfg.collective_timeout_seconds)
        guard_installed = True
    try:
        written = _predict_body(cfg, table, logger)
        return written
    except BaseException as e:
        # Crash forensics (obs/health.py): traceback + recent-event
        # ring as the stream's last substantive event; the finally
        # still closes the sink so run_end terminates the stream.
        from fast_tffm_tpu.parallel.liveness import WorkerLostError
        if isinstance(e, WorkerLostError):
            # Fail fast with the diagnosis: drop buffered device
            # scalars (their producing collectives will never
            # complete) and retire the dead cluster's client so
            # interpreter exit isn't stalled by a shutdown barrier
            # that cannot succeed.
            if tel is not None:
                tel.sink.discard_scalars()
            from fast_tffm_tpu.parallel.distributed import (
                retire_distributed_client)
            retire_distributed_client()
        if tel is not None:
            try:
                tel.record_crash(e)
            except Exception:
                logger.exception("crash event emission failed")
        raise
    finally:
        if lease is not None:
            try:
                lease.stop()
            except Exception:
                logger.exception("heartbeat lease stop failed")
        if guard_installed:
            from fast_tffm_tpu.parallel.liveness import restore_guard
            restore_guard(guard_prev)
        if tel is not None:
            try:
                tel.close()
            except Exception:
                logger.exception("metrics sink close failed")
        pop_active(tel_prev)


def _predict_body(cfg: FmConfig, table, logger) -> List[str]:
    tel = active()
    if jax.process_count() > 1:
        if cfg.lookup == "host":
            raise ValueError("lookup = host predict is single-process")
        return _predict_multiprocess(cfg, table, logger)
    mesh = None
    backend = None
    if cfg.lookup == "host":
        # Offload predict (lookup.py seam): restore (or wrap a
        # caller-supplied table) into the best offload backend — pinned
        # accelerator-host memory where supported, local numpy else; the
        # device only ever sees per-batch [U, D] row blocks. Routing a
        # provided table to the device paths here would materialize the
        # offload-scale table in HBM — the exact OOM this mode avoids.
        from fast_tffm_tpu.lookup import make_score_backend
        backend = make_score_backend(cfg, table)
        table = None
        logger.info("offload predict [%s]: table [%d, %d] outside HBM",
                    type(backend).__name__, *backend.table.shape)
    elif jax.device_count() > 1:
        from fast_tffm_tpu.parallel.sharded import make_mesh, place_table
        try:
            mesh = make_mesh()
        except ValueError as e:
            # e.g. a non-power-of-two device count: score on one device
            # rather than refusing (the table must then fit it).
            logger.warning("mesh predict unavailable (%s); scoring on a "
                           "single device", e)
        if mesh is not None and cfg.batch_size % mesh.shape["data"]:
            logger.warning(
                "batch_size %d not divisible by the mesh data axis %d; "
                "scoring on a single device", cfg.batch_size,
                mesh.shape["data"])
            mesh = None
        if mesh is not None:
            logger.info("mesh predict: %s over %d devices",
                        dict(mesh.shape), jax.device_count())
            if table is not None and int(table.shape[0]) != cfg.ckpt_rows:
                table = place_table(cfg, mesh, table)
    if table is None and backend is None:
        table = load_table(cfg, mesh)
    os.makedirs(cfg.score_path, exist_ok=True)
    written = []
    # Writer thread (see _ScoreWriter): file N's disk write overlaps
    # file N+1's parse/score/D2H. The inner close() surfaces deferred
    # write errors on the clean path; the finally's close is the
    # idempotent no-mask flush for the error path.
    writer = _ScoreWriter(logger)
    try:
        for path in expand_files(cfg.predict_files):
            # fmlint: disable=R003 -- feeds the predict/seconds counter
            # and per-file rate gauge (always-on aggregates; the span
            # beside it is the timeline view)
            t0 = time.perf_counter()
            with span("predict/file", path=os.path.basename(path)):
                raw = predict_scores(cfg, table, [path], mesh=mesh,
                                     backend=backend)
            # fmlint: disable=R003 -- closes the predict/seconds sample
            dt = time.perf_counter() - t0
            vals = sigmoid(raw) if cfg.loss_type == "logistic" else raw
            out_path = os.path.join(cfg.score_path,
                                    os.path.basename(path) + ".score")
            writer.submit(out_path, vals)
            written.append(out_path)
            if tel is not None:
                rate = len(raw) / dt if dt > 0 else 0.0
                tel.count("predict/seconds", dt)
                tel.set("predict/examples_per_sec", rate)
                tel.sink.emit("predict_file",
                              {"path": path, "examples": len(raw),
                               "seconds": dt, "examples_per_sec": rate})
                # Per-file barrier: scores are already host-side here,
                # so the flush is pure file I/O.
                tel.barrier_flush(step=len(written))
        writer.close()
    finally:
        writer.close(raise_error=False)
    return written


def _predict_multiprocess(cfg: FmConfig, table, logger) -> List[str]:
    """Sharded predict: every process scores its byte-range input shard
    through the global-mesh score fn in lockstep (each call is a
    collective program — the filler-batch protocol from distributed
    validation keeps uneven shards from deadlocking), writes its ordered
    part file, and the chief concatenates parts in process order (byte
    ranges are contiguous: process i's lines all precede process
    i+1's)."""
    from jax.experimental import multihost_utils
    from fast_tffm_tpu.data.pipeline import (probe_uniq_bucket,
                                             require_bounded_examples)
    from fast_tffm_tpu.parallel.liveness import guarded_collective
    from fast_tffm_tpu.parallel.sharded import (lockstep_score_batches,
                                                make_mesh,
                                                make_sharded_score_fn)
    require_bounded_examples(cfg, "multi-process predict")
    mesh = make_mesh()
    if cfg.batch_size % mesh.shape["data"]:
        raise ValueError(
            f"batch_size {cfg.batch_size} must be divisible by the mesh "
            f"data axis {mesh.shape['data']} for multi-process predict")
    logger.info("multi-process predict: %s over %d devices, %d processes",
                dict(mesh.shape), jax.device_count(), jax.process_count())
    if table is None:
        table = load_table(cfg, mesh)
    spec = ModelSpec.from_config(cfg)
    score_fn = make_sharded_score_fn(spec, mesh)
    p, P = jax.process_index(), jax.process_count()
    os.makedirs(cfg.score_path, exist_ok=True)
    tel = active()
    written: List[str] = []
    for path in expand_files(cfg.predict_files):
        # fmlint: disable=R003 -- feeds the per-worker predict/seconds
        # counter (always-on aggregate)
        t0 = time.perf_counter()
        # Deterministic probe: every process reads the same bytes, so
        # all agree on U without a collective.
        ub = cfg.uniq_bucket or probe_uniq_bucket(cfg, [path])
        it = batch_iterator(cfg, [path], training=False, epochs=1,
                            keep_empty=True, shard_index=p, num_shards=P,
                            fixed_shape=True, uniq_bucket=ub)
        local: List[np.ndarray] = []
        with span("predict/file", path=os.path.basename(path)):
            for batch, scores in lockstep_score_batches(cfg, it, mesh,
                                                        score_fn, table,
                                                        ub):
                local.append(scores[:batch.num_real])
                if tel is not None:
                    tel.heartbeat()  # lockstep progress feeds the
                    # watchdog; a hung peer stalls the whole cluster
        raw = (np.concatenate(local) if local
               else np.zeros(0, dtype=np.float32))
        vals = sigmoid(raw) if cfg.loss_type == "logistic" else raw
        out_path = os.path.join(cfg.score_path,
                                os.path.basename(path) + ".score")
        part = f"{out_path}.part{p}"
        with open(part, "w") as fh:
            for v in vals:
                fh.write(f"{v:.6f}\n")
        tag = os.path.basename(path)
        guarded_collective(multihost_utils.sync_global_devices,
                           f"predict_parts_{tag}",
                           label="predict/parts_barrier")
        if p == 0:
            n = 0
            # Stream the merge in bounded chunks: reading a whole part
            # with fh.read() holds multi-GB strings on the chief for
            # billion-line predicts.
            with open(out_path, "wb") as out_fh:
                for i in range(P):
                    with open(f"{out_path}.part{i}", "rb") as fh:
                        while True:
                            chunk = fh.read(8 << 20)
                            if not chunk:
                                break
                            n += chunk.count(b"\n")
                            out_fh.write(chunk)
            logger.info("wrote %d scores to %s (merged %d parts)",
                        n, out_path, P)
        # Chief must finish reading every part before anyone deletes.
        guarded_collective(multihost_utils.sync_global_devices,
                           f"predict_merged_{tag}",
                           label="predict/merge_barrier")
        os.remove(part)
        written.append(out_path)
        if tel is not None:
            # Per-WORKER rate for this worker's shard; the merged view
            # (fmstat over all .p<i> shards) sums examples and seconds
            # across processes, keyed by process index in the metadata.
            # fmlint: disable=R003 -- closes the predict/seconds sample
            dt = time.perf_counter() - t0
            n_local = len(raw)
            tel.count("predict/seconds", dt)
            tel.count("predict/examples", n_local)
            tel.set("predict/examples_per_sec",
                    n_local / dt if dt > 0 else 0.0)
            tel.sink.emit("predict_file",
                          {"path": path, "examples": n_local,
                           "seconds": dt, "process_index": p})
            tel.barrier_flush(step=len(written))
    return written
