"""Predict driver — the ``py/fm_predict.py`` equivalent (SURVEY.md §3.4).

Restores the latest checkpoint at the config's ``model_file``, streams
the predict files through parser + scorer, and writes one score per
input line, order-preserving — sigmoid-transformed for logistic loss,
raw for mse. ``score_path`` is treated as a directory; each input file
``f`` produces ``<score_path>/<basename(f)>.score``.

Both drivers run ONE continuous batch stream across ALL predict files
(fast_tffm_tpu/scoring.py): file N's disk write, file N+1's D2H, file
N+2's scoring, and file N+3's parse all overlap — no per-file fetcher
drain, no per-file warmup, no per-file telemetry barrier (README
"Predict path"; the pre-refactor per-file loop was the 15x
predict-vs-train gap BENCH_r05 measured).
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

import jax
import numpy as np

from fast_tffm_tpu.checkpoint import CheckpointState
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import expand_files
from fast_tffm_tpu.metrics import sigmoid
from fast_tffm_tpu.obs.telemetry import (active, make_telemetry,
                                         pop_active, push_active)
from fast_tffm_tpu.obs.trace import span
from fast_tffm_tpu.scoring import ScoreWriter, score_sweep
from fast_tffm_tpu.utils.logging import get_logger


def load_table(cfg: FmConfig, mesh=None,
               step: Optional[int] = None,
               with_step: bool = False):
    """Restore the table from the latest checkpoint — or, with an
    explicit ``step``, those exact verified bytes (the serving
    process's hot-reload load, and the soak's per-step parity control;
    restore() verifies an explicit step and raises instead of walking
    past it).

    With a mesh: restored ROW-SHARDED in the [ckpt_rows, D] checkpoint
    layout — the full table never materializes on one device or host
    (BASELINE config #5 scale: 10^9 rows ~ 36 GB dense). Without: the
    logical [num_rows, D] table on the default device.

    ``with_step=True`` returns ``(table, step)`` — callers that must
    pair the table with its step's sidecars (the admit-mode vocab slot
    map) need to know which step the walk-back actually restored."""
    import jax.numpy as jnp
    from fast_tffm_tpu.train import checkpoint_template
    from fast_tffm_tpu.utils.retry import RetryPolicy
    ckpt = CheckpointState(cfg.model_file,
                           retry=RetryPolicy.from_config(cfg),
                           verify=getattr(cfg, "ckpt_verify", "size"))
    restored = ckpt.restore(step=step,
                            template=checkpoint_template(cfg, mesh))
    ckpt.close()
    if restored is None:
        raise FileNotFoundError(
            f"no checkpoint found under {cfg.model_file}.ckpt "
            "(run training first)")
    from fast_tffm_tpu.train import check_restored_vocab
    check_restored_vocab(cfg, restored)
    loaded_step = int(restored["step"])
    if mesh is not None:
        table = restored["table"]
    else:
        # Checkpoints store the 4096-aligned [ckpt_rows, D] layout;
        # the single-device scorer wants the logical table.
        table = jnp.asarray(restored["table"][:cfg.num_rows],
                            dtype=jnp.float32)
    return (table, loaded_step) if with_step else table


def predict_scores(cfg: FmConfig, table: jax.Array, files,
                   mesh=None, backend=None, vocab=None) -> np.ndarray:
    """Raw scores for every example in ``files``, in input order. With a
    mesh, the batch is data-sharded and scored against the row-sharded
    table in place (table shape [ckpt_rows, D]). With a lookup
    ``backend`` (lookup.HostOffloadLookup), rows are gathered host-side
    and only [U, D] blocks reach the device (``table`` is unused).

    A thin collector over scoring.score_sweep — the same continuous
    cross-file stream predict() writes files from, concatenated."""
    out: List[np.ndarray] = []
    score_sweep(cfg, table, files,
                on_file=lambda _path, vals: out.append(vals),
                mesh=mesh, backend=backend, vocab=vocab)
    return (np.concatenate(out) if out
            else np.zeros(0, dtype=np.float32))


def predict(cfg: FmConfig, table: Optional[jax.Array] = None,
            job_name: Optional[str] = None,
            task_index: Optional[int] = None) -> List[str]:
    """Run batch prediction; returns the list of score files written.

    Multi-device hosts score through the mesh (row-sharded table +
    data-sharded batches — SURVEY.md §3.4's single restore+score stack,
    scaled the same way training is); a lone device gets the plain
    jitted scorer. ``dist_train worker <i>`` argv (mirroring the train
    CLI) joins a jax.distributed job: input is byte-range-sharded by
    process, scored in lockstep through the global mesh, and the chief
    merges per-process part files into the ordered score file (a shared
    ``score_path`` filesystem is assumed, as for checkpoints)."""
    logger = get_logger(log_file=cfg.log_file or None)
    if job_name is not None:
        from fast_tffm_tpu.parallel.distributed import init_from_cluster
        init_from_cluster(cfg, job_name, task_index or 0)
    # Run telemetry (obs/): created after cluster init so the process
    # index in the run metadata (and the per-worker shard suffix) is
    # real. The try/finally below is the sink's lifecycle guarantee —
    # a crash mid-sweep still flushes everything buffered.
    tel = make_telemetry(cfg, "predict")
    tel_prev = push_active(tel)
    # Compute-plane liveness (parallel/liveness.py): multi-process
    # predict is the same lockstep collective protocol as distributed
    # validation — a dead peer must raise a named WorkerLostError, not
    # park the survivors in the window allgather forever. No elastic
    # recovery here (predict is cheap to rerun); fail fast with the
    # diagnosis.
    lease = None
    guard_prev = None
    guard_installed = False
    if jax.process_count() > 1:
        from fast_tffm_tpu.parallel.liveness import (HeartbeatLease,
                                                     install_guard,
                                                     lease_dir)
        if cfg.heartbeat_seconds > 0:
            lease = HeartbeatLease(
                lease_dir(cfg), process_index=jax.process_index(),
                members=range(jax.process_count()),
                heartbeat_seconds=cfg.heartbeat_seconds).start()
            if tel is not None:
                tel.lease = lease
        guard_prev = install_guard(lease, cfg.collective_timeout_seconds)
        guard_installed = True
    try:
        written = _predict_body(cfg, table, logger)
        return written
    except BaseException as e:
        # Crash forensics (obs/health.py): traceback + recent-event
        # ring as the stream's last substantive event; the finally
        # still closes the sink so run_end terminates the stream.
        from fast_tffm_tpu.parallel.liveness import WorkerLostError
        if isinstance(e, WorkerLostError):
            # Fail fast with the diagnosis: drop buffered device
            # scalars (their producing collectives will never
            # complete) and retire the dead cluster's client so
            # interpreter exit isn't stalled by a shutdown barrier
            # that cannot succeed.
            if tel is not None:
                tel.sink.discard_scalars()
            from fast_tffm_tpu.parallel.distributed import (
                retire_distributed_client)
            retire_distributed_client()
        if tel is not None:
            try:
                tel.record_crash(e)
            except Exception:
                logger.exception("crash event emission failed")
        raise
    finally:
        if lease is not None:
            try:
                lease.stop()
            except Exception:
                logger.exception("heartbeat lease stop failed")
        if guard_installed:
            from fast_tffm_tpu.parallel.liveness import restore_guard
            restore_guard(guard_prev)
        if tel is not None:
            try:
                tel.close()
            except Exception:
                logger.exception("metrics sink close failed")
        pop_active(tel_prev)


def _score_out_path(cfg: FmConfig, path: str) -> str:
    return os.path.join(cfg.score_path,
                        os.path.basename(path) + ".score")


def _predict_body(cfg: FmConfig, table, logger) -> List[str]:
    tel = active()
    if jax.process_count() > 1:
        if cfg.lookup == "host":
            raise ValueError("lookup = host predict is single-process")
        if getattr(cfg, "vocab_mode", "fixed") == "admit":
            raise ValueError(
                "vocab_mode = admit predict is single-process (the "
                "slot map is host state; see the train-side "
                "restriction)")
        return _predict_multiprocess(cfg, table, logger)
    mesh = None
    backend = None
    vocab = None
    admit = getattr(cfg, "vocab_mode", "fixed") == "admit"
    if admit and table is not None:
        raise ValueError(
            "vocab_mode = admit predict restores the (table, slot "
            "map, step) triple from the checkpoint together — a "
            "caller-held table has no slot map to pair with; pass "
            "table=None")
    if cfg.lookup == "host":
        # Offload predict (lookup.py seam): restore (or wrap a
        # caller-supplied table) into the best offload backend — pinned
        # accelerator-host memory where supported, local numpy else; the
        # device only ever sees per-batch [U, D] row blocks. Routing a
        # provided table to the device paths here would materialize the
        # offload-scale table in HBM — the exact OOM this mode avoids.
        from fast_tffm_tpu.lookup import make_score_backend
        backend = make_score_backend(cfg, table)
        table = None
        logger.info("offload predict [%s]: table [%d, %d] outside HBM",
                    type(backend).__name__, *backend.table.shape)
    elif jax.device_count() > 1:
        from fast_tffm_tpu.parallel.sharded import make_mesh, place_table
        try:
            mesh = make_mesh()
        except ValueError as e:
            # e.g. a non-power-of-two device count: score on one device
            # rather than refusing (the table must then fit it).
            logger.warning("mesh predict unavailable (%s); scoring on a "
                           "single device", e)
        if mesh is not None and cfg.batch_size % mesh.shape["data"]:
            logger.warning(
                "batch_size %d not divisible by the mesh data axis %d; "
                "scoring on a single device", cfg.batch_size,
                mesh.shape["data"])
            mesh = None
        if mesh is not None:
            logger.info("mesh predict: %s over %d devices",
                        dict(mesh.shape), jax.device_count())
            if table is not None and int(table.shape[0]) != cfg.ckpt_rows:
                table = place_table(cfg, mesh, table)
    vstep = None
    if backend is not None:
        vstep = int(getattr(backend, "step", -1))
    elif table is None:
        table, vstep = load_table(cfg, mesh, with_step=True)
    if table is not None:
        # Ledger (obs/memory.py): the sweep's resident table — .nbytes
        # is host metadata, no fetch. Upserted per sweep; the process-
        # global ledger carries it for the mem/* gauges and any OOM's
        # owner breakdown.
        from fast_tffm_tpu.obs.memory import LEDGER
        LEDGER.register("table", int(table.nbytes))
    if not admit:
        # The inverse loud-failure of the admit-without-sidecar raise
        # below: an admit-trained table scored through modulo ids
        # would gather arbitrary rows with zero errors.
        from fast_tffm_tpu.checkpoint import refuse_fixed_mode_admit_step
        refuse_fixed_mode_admit_step(
            cfg, os.path.abspath(cfg.model_file) + ".ckpt", vstep)
    if admit:
        # Pair the restored table with ITS step's slot map — the
        # sidecar rides checkpoints exactly like the watermark, so the
        # walk-back can never split the (table, slot map) pair.
        from fast_tffm_tpu.checkpoint import load_vocab_map
        vocab = load_vocab_map(
            cfg, os.path.abspath(cfg.model_file) + ".ckpt", vstep)
        logger.info("vocab admission map: %d live rows at step %d",
                    vocab.live_rows, vstep)
    os.makedirs(cfg.score_path, exist_ok=True)
    files = expand_files(cfg.predict_files)
    written: List[str] = []
    # Writer thread (see scoring.ScoreWriter): file N's disk write
    # overlaps file N+1's parse/score/D2H. The inner close() surfaces
    # deferred write errors on the clean path; the finally's close is
    # the idempotent no-mask flush for the error path.
    writer = ScoreWriter(logger)
    # fmlint: disable=R003 -- brackets the whole sweep for the
    # predict/seconds counter and rate gauge (always-on aggregates;
    # the predict/sweep span inside score_sweep is the timeline view)
    t0 = time.perf_counter()
    emitted = [0]  # cumulative examples cut so far (single-writer:
    # on_file runs on one thread at a time — score_sweep's contract)

    def on_file(path: str, raw: np.ndarray) -> None:
        # Runs on the fetch worker thread mid-sweep (score_sweep's
        # contract): the transform is vectorized numpy, the submit is
        # a bounded queue put, the telemetry emit is thread-safe —
        # nothing here stalls the device loop beyond backpressure.
        vals = sigmoid(raw) if cfg.loss_type == "logistic" else raw
        out_path = _score_out_path(cfg, path)
        writer.submit(out_path, vals)
        written.append(out_path)
        emitted[0] += len(raw)
        if tel is not None:
            # Per-file wall time no longer exists (files overlap — that
            # is the point), so seconds/rate report the sweep so far
            # at this file's cut.
            # fmlint: disable=R003 -- closes the sweep-rate sample
            dt = time.perf_counter() - t0
            tel.sink.emit("predict_file",
                          {"path": path, "examples": len(raw),
                           "seconds": dt,
                           "examples_per_sec":
                               emitted[0] / dt if dt > 0 else 0.0})

    try:
        n = score_sweep(cfg, table, files, on_file=on_file, mesh=mesh,
                        backend=backend, vocab=vocab)
        writer.close()
    finally:
        writer.close(raise_error=False)
        from fast_tffm_tpu.obs.memory import LEDGER
        LEDGER.release("table")
    # fmlint: disable=R003 -- closes the predict/seconds sample
    dt = time.perf_counter() - t0
    rate = n / dt if dt > 0 else 0.0
    if tel is not None:
        tel.count("predict/seconds", dt)
        tel.set("predict/examples_per_sec", rate)
        # One barrier for the sweep (scores are host-side; the flush
        # is pure file I/O) — the per-file barriers the old loop paid
        # serialized the stream once per file.
        tel.barrier_flush(step=len(written))
    logger.info("predict sweep: %d files, %d examples, %.0f examples/s",
                len(written), n, rate)
    return written


def _predict_multiprocess(cfg: FmConfig, table, logger) -> List[str]:
    """Sharded predict, one continuous stream: every process scores its
    byte-range shard of ALL files through the global-mesh score fn in
    lockstep (each call is a collective program — the filler-batch
    protocol from distributed validation keeps uneven shards from
    deadlocking), demuxes its ordered local scores into per-file part
    files through the bounded writer thread, and the CHIEF's background
    merge thread concatenates parts in process order as each file's
    markers land (byte ranges are contiguous: process i's lines all
    precede process i+1's) — so the merge of file N overlaps the
    scoring of file N+1. Three sweep-level barriers (stale-part scrub,
    parts done, merge done) replace the old two barriers per file."""
    from jax.experimental import multihost_utils
    from fast_tffm_tpu.data.pipeline import (FileMarks,
                                             batch_iterator,
                                             probe_uniq_bucket,
                                             require_bounded_examples)
    from fast_tffm_tpu.models.fm import ModelSpec
    from fast_tffm_tpu.parallel.liveness import guarded_collective
    from fast_tffm_tpu.parallel.sharded import (lockstep_score_batches,
                                                make_mesh,
                                                make_sharded_score_fn)
    from fast_tffm_tpu.scoring import (PartMerger, ScoreDemux,
                                       scrub_stale_parts)
    require_bounded_examples(cfg, "multi-process predict")
    mesh = make_mesh()
    if cfg.batch_size % mesh.shape["data"]:
        raise ValueError(
            f"batch_size {cfg.batch_size} must be divisible by the mesh "
            f"data axis {mesh.shape['data']} for multi-process predict")
    logger.info("multi-process predict: %s over %d devices, %d processes",
                dict(mesh.shape), jax.device_count(), jax.process_count())
    if table is None:
        table, vstep = load_table(cfg, mesh, with_step=True)
        # Same admit-trained-under-fixed loud failure as the
        # single-process path (admit itself is rejected before this
        # branch): the existence probe is deterministic on the shared
        # checkpoint dir, so every process raises uniformly — no
        # collective divergence.
        from fast_tffm_tpu.checkpoint import refuse_fixed_mode_admit_step
        refuse_fixed_mode_admit_step(
            cfg, os.path.abspath(cfg.model_file) + ".ckpt", vstep)
    spec = ModelSpec.from_config(cfg)
    score_fn = make_sharded_score_fn(spec, mesh)
    p, P = jax.process_index(), jax.process_count()
    os.makedirs(cfg.score_path, exist_ok=True)
    tel = active()
    files = expand_files(cfg.predict_files)
    if not files:
        # Only an empty predict_files tuple reaches here (a non-matching
        # glob stays a literal path and fails loudly at the probe's
        # open). expand_files is deterministic, so every process returns
        # uniformly — no collective divergence. The sweep-level probe
        # below would otherwise IndexError; the old per-file loop just
        # never entered.
        logger.warning("predict_files is empty; nothing to score")
        return []
    out_paths = [_score_out_path(cfg, f) for f in files]
    # ONE uniq-bucket decision per sweep (probe_uniq_bucket samples the
    # first/last/largest file — deterministic bytes, so every process
    # agrees without a collective). The old per-file probe re-read
    # every file's head/mid/tail before scoring it AND recompiled
    # nothing it couldn't have shared — the "double read" half of the
    # predict gap.
    ub = cfg.uniq_bucket or probe_uniq_bucket(cfg, files)
    marks = FileMarks()
    it = batch_iterator(cfg, files, training=False, epochs=1,
                        keep_empty=True, shard_index=p, num_shards=P,
                        fixed_shape=True, uniq_bucket=ub,
                        file_marks=marks)
    # Parts/markers left by a CRASHED prior sweep into the same
    # score_path would satisfy the merger's marker polls instantly and
    # merge the old run's scores as if fresh — the chief scrubs them
    # (any part index, markers included), and the barrier keeps every
    # worker's first fresh part behind the scrub.
    if p == 0:
        stale = scrub_stale_parts(out_paths)
        if stale:
            logger.warning(
                "removed %d stale part file(s) from a prior predict "
                "sweep into %s (first: %s)", len(stale),
                cfg.score_path, stale[0])
    guarded_collective(multihost_utils.sync_global_devices,
                       "predict_parts_clean",
                       label="predict/clean_barrier")
    writer = ScoreWriter(logger)
    merger = PartMerger(out_paths, P, logger) if p == 0 else None
    # fmlint: disable=R003 -- brackets the whole sweep for the
    # per-worker predict/seconds counter (always-on aggregate)
    t0 = time.perf_counter()
    n_local = 0

    def on_file(path: str, raw: np.ndarray) -> None:
        vals = sigmoid(raw) if cfg.loss_type == "logistic" else raw
        out_path = _score_out_path(cfg, path)
        part = f"{out_path}.part{p}"
        # The marker is created only after the part file is durably
        # written+closed — the chief's merge thread keys on it.
        writer.submit(part, vals, marker=f"{part}.done")
        if tel is not None:
            tel.count("predict/examples", len(raw))
            tel.sink.emit("predict_file",
                          {"path": path, "examples": len(raw),
                           "process_index": p})

    demux = ScoreDemux(marks, on_file)
    try:
        with span("predict/sweep", files=len(files)):
            for batch, local in lockstep_score_batches(cfg, it, mesh,
                                                       score_fn, table,
                                                       ub):
                demux.consume(local[:batch.num_real])
                n_local += batch.num_real
                if tel is not None:
                    tel.heartbeat()  # lockstep progress feeds the
                    # watchdog; a hung peer stalls the whole cluster
        demux.finalize()
        writer.close()  # every part + marker of this worker is on disk
        guarded_collective(multihost_utils.sync_global_devices,
                           "predict_parts_done",
                           label="predict/parts_barrier")
        if merger is not None:
            # All markers are durable past the barrier: the merge
            # thread finishes its remaining files promptly (bounded
            # per-marker grace; a missing marker raises by name).
            merger.finish()
        # Chief finished reading (and deleting) every part before
        # anyone returns and could rewrite/reuse the score dir.
        guarded_collective(multihost_utils.sync_global_devices,
                           "predict_merged",
                           label="predict/merge_barrier")
    finally:
        writer.close(raise_error=False)
        if merger is not None:
            merger.stop()
    if tel is not None:
        # Per-WORKER rate for this worker's shard; the merged view
        # (fmstat over all .p<i> shards) sums examples and seconds
        # across processes, keyed by process index in the metadata.
        # fmlint: disable=R003 -- closes the predict/seconds sample
        dt = time.perf_counter() - t0
        tel.count("predict/seconds", dt)
        tel.set("predict/examples_per_sec",
                n_local / dt if dt > 0 else 0.0)
        tel.barrier_flush(step=len(out_paths))
    return out_paths
