"""Predict driver — the ``py/fm_predict.py`` equivalent (SURVEY.md §3.4).

Restores the latest checkpoint at the config's ``model_file``, streams the
predict files through parser + scorer, and writes one score per input
line, order-preserving — sigmoid-transformed for logistic loss, raw for
mse. ``score_path`` is treated as a directory; each input file ``f``
produces ``<score_path>/<basename(f)>.score``.
"""

from __future__ import annotations

import os
from typing import List, Optional

import jax
import numpy as np

from fast_tffm_tpu.checkpoint import CheckpointState
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import (batch_iterator, expand_files,
                                         prefetch)
from fast_tffm_tpu.metrics import sigmoid
from fast_tffm_tpu.models.fm import ModelSpec, batch_args, make_score_fn
from fast_tffm_tpu.utils.logging import get_logger


def load_table(cfg: FmConfig) -> jax.Array:
    import jax.numpy as jnp
    from fast_tffm_tpu.train import checkpoint_template
    ckpt = CheckpointState(cfg.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    if restored is None:
        raise FileNotFoundError(
            f"no checkpoint found under {cfg.model_file}.ckpt "
            "(run training first)")
    return jnp.asarray(np.asarray(restored["table"]), dtype=jnp.float32)


def predict_scores(cfg: FmConfig, table: jax.Array,
                   files) -> np.ndarray:
    """Raw scores for every example in ``files``, in input order."""
    spec = ModelSpec.from_config(cfg)
    score_fn = make_score_fn(spec)
    out: List[np.ndarray] = []
    # keep_empty: blank input lines become zero-feature examples so the
    # score file stays line-aligned with the input (SURVEY §3.4).
    for batch in prefetch(batch_iterator(cfg, files, training=False,
                                         epochs=1, keep_empty=True)):
        args = batch_args(batch)
        args.pop("labels"), args.pop("weights")
        scores = np.asarray(score_fn(table, **args))
        out.append(scores[:batch.num_real])
    return (np.concatenate(out) if out
            else np.zeros(0, dtype=np.float32))


def predict(cfg: FmConfig, table: Optional[jax.Array] = None) -> List[str]:
    """Run batch prediction; returns the list of score files written."""
    logger = get_logger(log_file=cfg.log_file or None)
    if table is None:
        table = load_table(cfg)
    os.makedirs(cfg.score_path, exist_ok=True)
    written = []
    for path in expand_files(cfg.predict_files):
        raw = predict_scores(cfg, table, [path])
        vals = sigmoid(raw) if cfg.loss_type == "logistic" else raw
        out_path = os.path.join(cfg.score_path,
                                os.path.basename(path) + ".score")
        with open(out_path, "w") as fh:
            for v in vals:
                fh.write(f"{v:.6f}\n")
        logger.info("wrote %d scores to %s", len(vals), out_path)
        written.append(out_path)
    return written
