"""fast_tffm_tpu — a TPU-native factorization-machine framework.

A brand-new framework with the capabilities of ``douban/fast_tffm``
(reference layout: ``run_tffm.py``, ``py/fm_train.py``, ``py/fm_predict.py``,
``cc/fm_parser.cc``, ``cc/fm_scorer.cc``, ``cc/fm_grad.cc`` — see
``SURVEY.md`` §1–§3; the reference snapshot was unreadable this session, so
citations are upstream-path + SURVEY-section rather than file:line).

Where the reference pairs C++ TensorFlow custom ops with TF1's asynchronous
parameter-server runtime, this package is idiomatic JAX/XLA:

- ``data/``      host-side libsvm parsing (C++ + Python), hashing, bucketed
                 fixed-shape batching (the ``fm_parser`` equivalent).
- ``ops/``       the FM interaction math as XLA and Pallas kernels with a
                 custom VJP (the ``fm_scorer``/``fm_grad`` equivalents).
- ``models/``    model definitions (2nd-order FM, higher-order FM, FFM) and
                 a NumPy oracle used as ground truth in tests.
- ``parallel/``  device meshes, row-sharded embedding tables, synchronous
                 data-parallel training via ``shard_map`` + XLA collectives
                 (the PS/worker-runtime equivalent).
- ``utils/``     logging, timing, profiling helpers.
- ``train.py`` / ``predict.py`` — drivers (the ``fm_train.py`` /
                 ``fm_predict.py`` equivalents).
"""

__version__ = "0.1.0"

from fast_tffm_tpu.config import FmConfig, load_config  # noqa: F401
