"""Mesh-sharded training — the parameter-server replacement (SURVEY.md §7.5).

The reference scales by splitting the embedding table into
``vocabulary_block_num`` row blocks round-robined across TF1 parameter
servers, with workers gathering active rows and pushing sparse Adagrad
updates over gRPC, asynchronously (SURVEY §2 "Distributed backend", §3.2).

The TPU-native design here replaces all of that with SPMD over a
``jax.sharding.Mesh``:

- axes ``("data", "model")``: the batch is sharded over ``data``
  (data parallelism); the table and its Adagrad accumulator are
  **row-sharded over every device** (``P(("data", "model"))``) — the mesh
  *is* the parameter server, and FSDP-style row sharding means the table's
  memory scales with the slice, exactly like adding PS tasks.
- the per-step gather of the batch's unique rows and the scatter-add of
  their gradients cross shard boundaries; XLA/GSPMD inserts the
  collectives (all-gather of the small unique-id set, psum of gathered
  rows, sharded scatter) over ICI — no hand-written transport, per the
  scaling-book recipe (annotate shardings, let XLA place collectives).
- updates are **synchronous**: every step sees every gradient. This is a
  deliberate semantics upgrade over the reference's lock-free async
  (hogwild) PS updates — a documented divergence (SURVEY §7 hard part #2).

Tensor/pipeline/sequence/expert parallelism are structurally N/A for FMs
(no big dense ops, 2-layer-deep model, unordered feature bags, no MoE —
SURVEY §2 parallelism inventory); the two axes that exist for this model
family, batch-DP and table row sharding (model parallelism for an
embedding model), are both first-class here.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.models.fm import ModelSpec, score_body, train_step_body

# Table rows are sharded across *all* mesh devices — both axes — so table
# memory per chip shrinks linearly with slice size (the PS-scaling analogue).
ROW_SPEC = P(("data", "model"), None)


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              model_axis: int = 1) -> Mesh:
    """Build a ("data", "model") mesh over ``devices`` (default: all).

    ``model_axis`` splits devices between the two axes; with the default 1
    the mesh is pure data-parallel (table still row-sharded over all
    devices). Single device -> trivial 1x1 mesh, same code path.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if model_axis <= 0 or n % model_axis:
        raise ValueError(f"model_axis {model_axis} must divide {n} devices")
    # Power-of-two total so the 4096-aligned checkpoint row layout
    # (FmConfig.ckpt_rows) shards evenly; TPU slices are powers of two.
    if n & (n - 1) or n > 4096:
        raise ValueError(
            f"device count {n} must be a power of two <= 4096 so the "
            "4096-aligned table rows (FmConfig.ckpt_rows) shard evenly")
    n_data = n // model_axis
    # The pipeline's unique-id buckets are powers of two (>= 64), so the
    # data axis must be a power of two <= 64 for the U axis to shard
    # evenly; TPU slices are powers of two anyway.
    if n_data & (n_data - 1) or n_data > 64:
        raise ValueError(
            f"data axis size {n_data} must be a power of two <= 64 so the "
            "pipeline's power-of-two unique-id buckets shard evenly")
    # Multi-process: each data-axis row must stay within one process —
    # global_batch concatenates PER-PROCESS local batches along the data
    # axis (make_array_from_process_local_data), so a data row spanning
    # processes would pair different processes' data with one replicated
    # chunk and offset_local_idx into out-of-range unique slots:
    # silently corrupted gathers, not an error.
    if jax.process_count() > 1 and n_data % jax.process_count():
        raise ValueError(
            f"data axis size {n_data} must be a multiple of the process "
            f"count {jax.process_count()}: global_batch assembles one "
            "data-axis block per process")
    grid = np.asarray(devices).reshape(n_data, model_axis)
    return Mesh(grid, ("data", "model"))


def _require_host_dedup(spec: ModelSpec) -> None:
    """Mesh steps consume the host-side unique contract (uniq_ids with
    fixed buckets; global_batch offsets local_idx into the concatenated
    unique axis) — a raw-ids spec here would feed garbage indices.

    Design position, not a gap: on a single chip raw ids win because
    the only cost is H2D bytes and an on-chip unique (~3 us), while
    host dedup burns the scarce 1-core host. On a mesh the economics
    invert — the gather/scatter against the ROW-SHARDED table is
    cross-device traffic sized by the index vector, so deduping
    B*L raw slots down to U uniques host-side shrinks the all-to-all
    and the scatter-add by the batch's duplication factor, and the
    fixed-U lockstep protocol (multi-process global_batch) needs the
    static unique budget anyway. Shipping raw ids to the mesh would
    trade cheap distributed host CPU for scarce ICI bandwidth."""
    if spec.dedup == "device":
        raise ValueError(
            "dedup = device is for the plain single-device jit only; "
            "mesh steps require dedup = host. The shipped drivers only "
            "build a mesh when more than one device exists, where "
            "dedup = auto already resolves to host; when driving the "
            "mesh API directly on a one-device environment (where auto "
            "picks device), rebuild the spec with "
            "dataclasses.replace(spec, dedup='host')")


# kernel='pallas' on a mesh: GSPMD has no partitioning rule for a
# pallas_call custom call, so the step bodies wrap the kernel in
# shard_map over the data axis when given the mesh (models/fm._scores,
# ops/pallas_fm.fm_batch_scores_pallas) — each device runs the fused
# kernel on its batch shard, GSPMD keeps owning the gather/scatter
# collectives around it. The mesh is bound into the partial below.


def _layout(mesh: Mesh):
    """The one encoding of the sharding layout: (row, vec, mat, repl) =
    (table rows, per-example vectors, per-example matrices, replicated)."""
    return (NamedSharding(mesh, ROW_SPEC),
            NamedSharding(mesh, P("data")),
            NamedSharding(mesh, P("data", None)),
            NamedSharding(mesh, P()))


def _shardings(mesh: Mesh, with_fields: bool):
    row, vec, mat, repl = _layout(mesh)
    in_sh = [row, row, vec, vec, vec, mat, mat]
    if with_fields:
        in_sh.append(mat)
    out_sh = (row, row, repl, vec)
    return tuple(in_sh), out_sh


@functools.lru_cache(maxsize=None)
def make_sharded_train_step(spec: ModelSpec, mesh: Mesh,
                            with_fields: Optional[bool] = None):
    """The same step as models.fm.make_train_step, jitted with mesh
    shardings so GSPMD partitions it: batch over ``data``, table rows over
    the whole mesh, loss replicated. Cached per (spec, mesh)."""
    if with_fields is None:
        with_fields = spec.model_type == "ffm"
    _require_host_dedup(spec)
    in_sh, out_sh = _shardings(mesh, with_fields)
    fn = functools.partial(train_step_body, spec, mesh=mesh)
    jitted = jax.jit(fn, in_shardings=in_sh, out_shardings=out_sh,
                     donate_argnums=(0, 1))

    # pjit rejects kwargs when in_shardings is set; keep the kwargs-friendly
    # surface of make_train_step via a thin positional adapter.
    def step(table, acc, labels, weights, uniq_ids, local_idx, vals,
             fields=None):
        args = (table, acc, labels, weights, uniq_ids, local_idx, vals)
        if with_fields:
            args += (fields,)
        return jitted(*args)

    return step


@functools.lru_cache(maxsize=None)
def make_sharded_score_fn(spec: ModelSpec, mesh: Mesh,
                          with_fields: Optional[bool] = None):
    """Sharded inference: row-sharded table in, batch-sharded scores out."""
    if with_fields is None:
        with_fields = spec.model_type == "ffm"
    _require_host_dedup(spec)
    row, vec, mat, _ = _layout(mesh)
    in_sh = [row, vec, mat, mat] + ([mat] if with_fields else [])

    jitted = jax.jit(functools.partial(score_body, spec, mesh=mesh),
                     in_shardings=tuple(in_sh), out_shardings=vec)

    def score(table, uniq_ids, local_idx, vals, fields=None):
        args = (table, uniq_ids, local_idx, vals)
        if with_fields:
            args += (fields,)
        return jitted(*args)

    return score


def padded_num_rows(cfg: FmConfig, mesh: Mesh) -> int:
    """Table rows on the mesh == the checkpoint row layout
    (``cfg.ckpt_rows``, a fixed 4096 multiple): one shape for runtime,
    save, and restore means checkpoints round-trip row-sharded on any
    topology. The extra rows sit past ``pad_id`` so no id can ever
    gather or update them; exports slice them off via
    ``export_npz(..., vocabulary_size=...)``."""
    n = int(mesh.devices.size)
    rows = cfg.ckpt_rows
    assert rows % n == 0, (rows, n)  # make_mesh enforces pow2 <= 4096
    return rows


def init_sharded_state(cfg: FmConfig, mesh: Mesh, seed: int = 0
                       ) -> Tuple[jax.Array, jax.Array]:
    """Initialise (table, accumulator) directly sharded: jit with
    out_shardings makes every device materialise only its own row shard —
    a 10^9-row table never exists on one host (SURVEY §7 hard part #3).

    Row values match init_table() exactly for the first ``cfg.num_rows``
    rows (same key, same distribution; the pad tail is appended, not
    interleaved), so single-device and sharded runs are comparable.
    """
    row = NamedSharding(mesh, ROW_SPEC)
    n_rows = padded_num_rows(cfg, mesh)
    shape = (cfg.num_rows, cfg.row_dim)

    def init(key):
        t = jax.random.uniform(key, shape, dtype=jnp.float32,
                               minval=-cfg.init_value_range,
                               maxval=cfg.init_value_range)
        t = t.at[cfg.num_rows - 1:].set(0.0)
        pad = jnp.zeros((n_rows - cfg.num_rows, cfg.row_dim), jnp.float32)
        a = jnp.full((n_rows, cfg.row_dim), cfg.adagrad_init, jnp.float32)
        return jnp.concatenate([t, pad], axis=0), a

    return jax.jit(init, out_shardings=(row, row))(jax.random.PRNGKey(seed))


def place_table(cfg: FmConfig, mesh: Mesh, table) -> jax.Array:
    """Lift a host/logical table onto the mesh row-sharded, appending
    the dead pad tail up to the [ckpt_rows, D] runtime layout. The
    restore path doesn't need this (checkpoints restore sharded
    directly); it serves callers holding a dense table (tests, external
    .npz imports)."""
    row = NamedSharding(mesh, ROW_SPEC)
    n_pad = padded_num_rows(cfg, mesh) - int(np.shape(table)[0])

    def lift(t):
        pad = jnp.zeros((n_pad, cfg.row_dim), jnp.float32)
        return jnp.concatenate([t.astype(jnp.float32), pad], axis=0)

    if not isinstance(table, jax.Array):
        table = jnp.asarray(np.asarray(table), jnp.float32)
    return jax.jit(lift, out_shardings=row)(table)


def global_batch(mesh: Mesh, local_uniq_size: int, **arrays) -> dict:
    """Assemble per-process local batch arrays into global sharded arrays
    for multi-process SPMD training.

    Every process calls this with its own (identically-shaped, see
    pipeline ``fixed_shape``) local batch; the result is one global
    array per input whose global shape concatenates the process-local
    batches along dim 0, placed per the mesh's data-axis sharding.

    ``local_idx`` needs care: each process's values index its *local*
    unique-id block, so they are offset by ``process_index *
    local_uniq_size`` to index the concatenated global unique axis (each
    process's pad slot lands inside its own block, which still holds
    ``pad_id``, so padding semantics survive concatenation).

    Semantic note vs single-process: an id occurring on several
    processes occupies one unique slot per process, so its Adagrad
    accumulator gains sum-of-squared per-process grads (not the square
    of the summed grad) and its L2 reg is counted once per process.
    This matches per-row-touch semantics of the reference's PS (each
    worker pushed its own IndexedSlices update; SURVEY §3.2) and is the
    documented multi-host divergence, far smaller than the reference's
    async staleness.
    """
    p = jax.process_index()
    _, vec, mat, _ = _layout(mesh)
    out = {}
    for name, arr in arrays.items():
        if arr is None:
            continue
        arr = np.asarray(arr)
        if name == "local_idx":
            arr = offset_local_idx(arr, p, local_uniq_size)
        sh = vec if arr.ndim == 1 else mat
        out[name] = jax.make_array_from_process_local_data(sh, arr)
    return out


def offset_local_idx(local_idx: np.ndarray, process_index: int,
                     local_uniq_size: int) -> np.ndarray:
    """The multi-process unique-axis index math, factored out of
    global_batch so the driver's dryrun can simulate P logical processes'
    assembly through the REAL function (this offset is where the
    index bugs would live): process p's local_idx values index its own
    unique block, shifted into the concatenated global unique axis."""
    return np.asarray(local_idx) + np.int32(process_index
                                            * local_uniq_size)


def local_rows(global_arr: jax.Array) -> np.ndarray:
    """This process's rows of a ``P('data')``-sharded global dim-0 array
    (the output side of ``global_batch``): addressable shards ordered by
    index range and deduplicated — with ``model_axis > 1`` the vector is
    replicated along the model axis, so a process can hold several
    shards covering the SAME range; keeping one per range is required or
    the concat doubles the slice. Used by distributed validation and
    multi-process predict to recover the local batch's slice."""
    seen = set()
    pieces = []
    for s in sorted(global_arr.addressable_shards,
                    key=lambda s: s.index[0].start or 0):
        rng_key = (s.index[0].start, s.index[0].stop)
        if rng_key in seen:
            continue
        seen.add(rng_key)
        pieces.append(np.asarray(s.data))
    return np.concatenate(pieces)


# Batches agreed on per lockstep round: one flag allgather (a
# synchronizing host collective) covers this many score programs, and
# their device->host score fetches defer to the round's end so fetch i
# overlaps programs i+1.. still in flight. Device cost per round is
# WINDOW batches' args + [B_global] score vectors in flight (a few MB).
LOCKSTEP_WINDOW = 8


def lockstep_score_batches(cfg: FmConfig, it, mesh: Mesh, score_fn,
                           table, uniq_bucket: int,
                           max_batches: Optional[int] = None,
                           preempt=None):
    """Drive a per-process batch iterator through a mesh score fn in
    LOCKSTEP: every score call is a collective program, so a process
    whose shard ran dry (or hit ``max_batches`` real batches) feeds
    all-padding filler until every process is done. Yields
    ``(batch, local_scores)`` per local iterator batch — the single
    implementation of the deadlock-sensitive protocol shared by
    distributed validation and multi-process predict (a diverging copy
    here hangs a cluster, not a test).

    Round-5 windowing: processes agree once per LOCKSTEP_WINDOW batches
    (an allgather of per-process window fill) instead of once per batch
    — every round each process runs max(fills) collective programs,
    padding its own tail with fillers, so programs stay matched while
    the per-batch host-sync collective and the per-batch blocking score
    fetch both amortize across the window.

    ``preempt`` (zero-arg callable, may be None): a per-process
    preemption flag piggybacked on the fill allgather. A SIGTERM lands
    on ONE worker; without this the signalled worker alone would stop
    feeding collectives mid-sweep and desync the lockstep group — with
    it, every process sees the flag in the SAME gathered result and
    all stop together at the window boundary, before dispatching any
    of that window's programs (the sweep ends early; train()'s
    step-boundary save path then runs on every worker)."""
    import time as _time
    from jax.experimental import multihost_utils
    from fast_tffm_tpu.data.pipeline import empty_batch
    from fast_tffm_tpu.models.fm import batch_args
    from fast_tffm_tpu.obs.memory import LEDGER
    from fast_tffm_tpu.obs.telemetry import active
    from fast_tffm_tpu.obs.trace import anatomy_on, span
    from fast_tffm_tpu.parallel.liveness import guarded_collective
    tel = active()  # per-worker lockstep telemetry (obs/): each
    # process counts its own rounds/fillers/examples into its own
    # sink shard; fmstat merges the streams keyed by process index
    anat = anatomy_on()  # stamp window ids as span join keys
    wid = -1  # lockstep window id: every rank increments it in the
    # same barrier'd order (the window allgather IS the barrier), so
    # the same wid names the same window on every rank — the join key
    # fmtrace --anatomy aligns per-rank clocks on (obs/anatomy.py)
    wid_prev = -1  # the window whose deferred scores _drain fetches
    n_real = 0
    filler = None
    filler_gargs = None  # device assembly of the all-padding batch is
    # identical every filler step — ship it once, not once per step
    # (H2D is the documented bottleneck on a tunnelled chip)
    pending_prev: list = []  # previous window's dispatched scores,
    # fetched AFTER the next window is dispatched (see _drain below)

    def _drain(pending, fetch_wid=-1):
        """Window-deferred bulk fetch: every queued score vector of a
        PREVIOUS window materializes host-side here, after the next
        window's programs were already dispatched — so the D2H drain
        overlaps that window's device compute AND the following fill's
        host parse, instead of serializing between them (the cross-file
        predict sweep feeds one continuous stream through this loop;
        without the deferral every window boundary stalled on the
        fetch). One span for the whole drain. Guarded: fetching a
        score whose producing program can never complete (dead peer
        mid-window) blocks exactly like the dispatch would."""
        if not pending:
            return []
        ids = {"wid": fetch_wid} if (anat and fetch_wid >= 0) else {}
        t_fetch = _time.perf_counter()
        with span("lockstep/score_fetch", batches=len(pending), **ids):
            # collective=False: this is a LOCAL device wait (it runs
            # only when this rank's pending window is non-empty, a
            # per-rank count) — it rides the guard for the deadline,
            # not the protocol trace.
            out = guarded_collective(
                lambda: [(batch, local_rows(score))
                         for batch, score in pending],
                label="lockstep/score_fetch", collective=False)
        if tel is not None:
            tel.count("lockstep/fetch_seconds",
                      _time.perf_counter() - t_fetch)
        return out

    while True:
        window = []
        wid += 1
        ids = {"wid": wid} if anat else {}
        t_fill = _time.perf_counter()
        with span("lockstep/window_fill", **ids):
            while len(window) < LOCKSTEP_WINDOW:
                if max_batches and n_real + len(window) >= max_batches:
                    break
                b = next(it, None)
                if b is None:
                    break
                window.append(b)
        # The silent multi-worker wait: a peer still filling (or hung)
        # parks everyone here. The span makes the wait VISIBLE on the
        # timeline; the deadline guard (parallel/liveness.py) bounds
        # the wait — a dead peer raises WorkerLostError naming it
        # instead of parking the cluster forever.
        t_ag = _time.perf_counter()
        with span("lockstep/allgather", window=len(window), **ids):
            flags = guarded_collective(
                multihost_utils.process_allgather,
                np.asarray([len(window),
                            1 if (preempt is not None and preempt())
                            else 0]),
                label="lockstep/window_fill")
        flags = np.asarray(flags).reshape(-1, 2)
        if tel is not None:
            tel.count("lockstep/allgather_seconds",
                      _time.perf_counter() - t_ag)
        if flags[:, 1].any():
            # Coordinated preemption: every process computed the SAME
            # gathered flags, so all return here together — no program
            # of this window was dispatched, collectives stay matched.
            # The previous window's deferred scores drain first (local
            # device_get, no collective): they completed, so they are
            # yielded, not re-done after resume.
            for batch, local in _drain(pending_prev, wid_prev):
                yield batch, local
            if tel is not None:
                tel.count("lockstep/preempted_windows")
            LEDGER.release("lockstep_window")
            return
        rounds = int(flags[:, 0].max())
        if tel is not None:
            tel.heartbeat()  # a completed collective is progress
        if tel is not None and rounds:
            tel.count("lockstep/windows")
            # Collective programs this round == the window max across
            # workers; real + filler always sums to it, so the three
            # counters cross-check.
            tel.count("lockstep/programs", rounds)
            tel.count("lockstep/real_batches", len(window))
            # Filler programs this worker runs because a PEER's shard
            # is longer — the load-imbalance signal per worker.
            tel.count("lockstep/filler_batches", rounds - len(window))
            tel.count("lockstep/window_fill_seconds",
                      _time.perf_counter() - t_fill)
        if rounds == 0:
            # Every process ran dry in the same round: drain the last
            # deferred window and end the sweep.
            for batch, local in _drain(pending_prev, wid_prev):
                yield batch, local
            LEDGER.release("lockstep_window")
            return
        pending = []
        t_disp = _time.perf_counter()
        with span("lockstep/score_dispatch", batches=rounds, **ids):
            for i in range(rounds):
                if i < len(window):
                    batch = window[i]
                    args = batch_args(batch)
                    args.pop("labels"), args.pop("weights")
                    gargs = global_batch(mesh, len(batch.uniq_ids),
                                         **args)
                else:
                    if filler_gargs is None:
                        filler = empty_batch(cfg,
                                             uniq_bucket=uniq_bucket)
                        args = batch_args(filler)
                        args.pop("labels"), args.pop("weights")
                        filler_gargs = global_batch(
                            mesh, len(filler.uniq_ids), **args)
                    gargs = filler_gargs
                # Collective program dispatch under the deadline
                # guard: a dead peer parks the dispatch inside the
                # program's own collectives, out of reach of the
                # host-allgather guard above.
                score = guarded_collective(
                    score_fn, table,
                    label="lockstep/score_dispatch", **gargs)
                if i < len(window):
                    pending.append((batch, score))
        if tel is not None:
            tel.count("lockstep/dispatch_seconds",
                      _time.perf_counter() - t_disp)
        n_real += len(window)
        if tel is not None:
            tel.count("lockstep/examples",
                      sum(b.num_real for b in window))
        # Drain the PREVIOUS window (this window's programs are already
        # in flight, so its compute overlaps this D2H); this window's
        # scores stay queued on device until the next round — at most
        # one extra window of [B_global] f32 vectors held in HBM.
        fetched = _drain(pending_prev, wid_prev)
        pending_prev = pending
        wid_prev = wid
        # Ledger (obs/memory.py): the deferred window's [B_global]
        # score vectors held in HBM until the next round's drain —
        # .nbytes is host metadata, upserted once per window.
        LEDGER.register("lockstep_window",
                        sum(s.nbytes for _, s in pending))
        for batch, local in fetched:
            # This process's rows of the global [B_global] score vector
            # are exactly its local batch (global_batch concatenates
            # local batches in process order over process-contiguous
            # data-axis devices); local_rows dedups model-axis replicas.
            assert len(local) == len(batch.labels), (
                f"local score slice {len(local)} != local batch "
                f"{len(batch.labels)}")
            yield batch, local


def shard_batch(mesh: Mesh, **arrays) -> dict:
    """Place host batch arrays with their mesh shardings (keeps per-step
    host->device transfers going straight to the right shards)."""
    _, vec, mat, _ = _layout(mesh)
    n_data = mesh.shape["data"]
    out = {}
    for name, arr in arrays.items():
        if arr is None:
            continue
        if np.shape(arr)[0] % n_data:
            raise ValueError(
                f"batch array {name!r} dim 0 ({np.shape(arr)[0]}) must be "
                f"divisible by the mesh data axis ({n_data}); pick a "
                f"batch_size that is a multiple of it")
        sh = vec if np.ndim(arr) == 1 else mat
        out[name] = jax.device_put(arr, sh)
    return out
