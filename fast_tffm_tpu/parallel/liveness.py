"""Compute-plane liveness: heartbeat leases + collective deadline guards.

The lockstep SPMD port traded away the one robustness property the
reference's async PS design had by construction: a lost worker there
just stopped pulling batches, while here every step is a collective
program and one dead or wedged process parks every peer inside
``multihost_utils.process_allgather`` forever — the PR 2 watchdog can
dump the survivors' stacks but cannot say WHO died or unblock anyone.
This module closes that gap in two layers (train.py composes the third,
elastic recovery, on top):

- ``HeartbeatLease`` — each process periodically renews a tiny lease
  file in a shared rendezvous dir (``<model_file>.hb/``, the same
  shared-filesystem assumption checkpoints and metrics already make).
  Liveness means "the process is alive", not "it is making progress":
  the renewal runs on a daemon thread, so a worker blocked in a
  collective still renews — only a SIGKILLed, SIGSTOPped, or crashed
  worker goes stale. A daemon monitor tick emits ``health:
  worker_lost`` events naming the stale peer's process id and host.

- ``guarded_collective(fn, *args)`` — the deadline guard every blocking
  collective runs under (fmlint R006 enforces this at the host
  collective call sites; the lockstep step/score dispatches run under
  it too). A collective that RAISES (a SIGKILLed peer resets the
  transport within seconds) is converted to a distinct
  ``WorkerLostError`` naming the peers the lease table shows dead —
  the recovery entry point. A collective that BLOCKS (a SIGSTOPped
  peer keeps its sockets open) is watched by the lease monitor
  thread: past ``collective_timeout_seconds`` with stale peers it
  emits the named diagnosis, dumps all-thread stacks, and hard-exits
  ``EXIT_WORKER_LOST`` — a bounded, diagnosed failure instead of an
  indefinite hang (the blocked thread cannot be interrupted from
  Python, and dispatching jax programs from helper threads to buy a
  timeout is memory-unsafe in practice).

Everything here is host-only and clock-injectable: staleness math and
the guard's decision logic run under fake clocks in tests, no real
multi-process spawn needed.
"""

from __future__ import annotations

import dataclasses
import faulthandler
import json
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# A lease is stale once it is this many heartbeat intervals old: one
# interval of ordinary scheduling jitter, one of shared-FS lag, and the
# rest margin — a live-but-slow worker must not read as dead (a false
# "lost" verdict shrinks a healthy cluster), while a dead one must go
# stale well inside any sane collective_timeout_seconds.
STALE_FACTOR = 4.0

# Elastic reform: after the live set and the announced set first agree,
# membership must hold still for this long before survivors commit to
# it — absorbs the skew between survivors' guard expiries.
REFORM_SETTLE_SECONDS = 1.0

# Elastic GROW rendezvous files (all in the same ``<model_file>.hb/``
# dir as the worker leases — one shared-FS assumption, one sweep):
#   join-<stamp>-<pid>   a replacement process's join-request lease
#                        (renewed like a worker lease; lease ORDER is
#                        the filename sort, the deterministic
#                        tie-break when joiners race open slots)
#   grow-<g>.json        the incumbent chief's admission plan for
#                        cluster generation g (which ticket gets which
#                        worker slot) — what a joiner polls for
#   commit-<g>.json      the chief's FINAL membership for generation g,
#                        written once the settle window resolves; every
#                        party (incumbent or joiner) adopts it verbatim
#                        so nobody can disagree about num_processes
JOIN_PREFIX = "join-"
GROW_PLAN_PREFIX = "grow-"
COMMIT_PREFIX = "commit-"


class WorkerLostError(RuntimeError):
    """A blocking collective expired (or failed) and the liveness table
    names dead peers — the compute-plane analogue of BadInputError.
    ``lost`` carries the stale peers' lease info for the elastic
    recovery path; empty when the deadline fired with every peer still
    heartbeating (a genuine timeout, not a death)."""

    def __init__(self, message: str, lost: Sequence["PeerInfo"] = ()):
        super().__init__(message)
        self.lost: Tuple["PeerInfo", ...] = tuple(lost)


@dataclasses.dataclass(frozen=True)
class PeerInfo:
    """One row of the liveness table."""
    process_index: int
    host: str = "?"
    pid: int = -1
    age_seconds: Optional[float] = None  # None = lease never written

    def describe(self) -> str:
        age = ("no lease on disk" if self.age_seconds is None
               else f"last heartbeat {self.age_seconds:.1f}s ago")
        return f"process {self.process_index} ({self.host}, {age})"


class HeartbeatLease:
    """One process's lease in the shared rendezvous dir, plus the read
    side of every peer's.

    ``renew()`` atomically rewrites ``worker-<i>.hb`` with a wall-clock
    timestamp (``clock`` injectable; wall time because staleness is a
    CROSS-process comparison — the writer's stamp against the reader's
    now). ``start()`` runs renew on a daemon thread every
    ``heartbeat_seconds`` and monitors peers between renewals, emitting
    one ``health: worker_lost`` per peer per staleness episode.
    ``members`` is the current expected membership (original process
    indices) — elastic reform shrinks it so departed workers stop
    being reported."""

    def __init__(self, directory: str, process_index: int,
                 members: Sequence[int], heartbeat_seconds: float = 5.0,
                 host: Optional[str] = None, pid: Optional[int] = None,
                 stale_after: Optional[float] = None,
                 clock: Callable[[], float] = time.time):
        self.directory = directory
        self.process_index = int(process_index)
        self.members: Tuple[int, ...] = tuple(sorted(members))
        self.heartbeat_seconds = float(heartbeat_seconds)
        self.stale_after = (float(stale_after) if stale_after is not None
                            else STALE_FACTOR * self.heartbeat_seconds)
        self.host = host if host is not None else socket.gethostname()
        self.pid = int(pid if pid is not None else os.getpid())
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reported_lost: set = set()  # one event per episode
        os.makedirs(self.directory, exist_ok=True)

    # -- write side ------------------------------------------------------
    def lease_path(self, process_index: int) -> str:
        return os.path.join(self.directory,
                            f"worker-{process_index}.hb")

    def renew(self) -> None:
        """Atomic lease rewrite; never raises into the renew loop — a
        transient shared-FS error must cost one missed beat, not the
        whole liveness layer (the stale margin absorbs it)."""
        path = self.lease_path(self.process_index)
        tmp = f"{path}.tmp.{self.pid}"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"process_index": self.process_index,
                           "host": self.host, "pid": self.pid,
                           "time": self._clock()}, fh)
            os.replace(tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass

    # -- read side -------------------------------------------------------
    def read(self, process_index: int) -> Optional[Dict]:
        """A peer's raw lease record, or None (missing/torn/garbled —
        all read as 'never heard from', the safe direction)."""
        try:
            with open(self.lease_path(process_index),
                      encoding="utf-8") as fh:
                rec = json.load(fh)
            float(rec["time"])
            return rec
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def peer_info(self, process_index: int,
                  now: Optional[float] = None) -> PeerInfo:
        rec = self.read(process_index)
        if rec is None:
            return PeerInfo(process_index)
        now = self._clock() if now is None else now
        return PeerInfo(process_index,
                        host=str(rec.get("host", "?")),
                        pid=int(rec.get("pid", -1)),
                        age_seconds=max(0.0, now - float(rec["time"])))

    def age(self) -> Optional[float]:
        """Seconds since OUR lease last reached disk (the fmstat
        worker-table row); None before the first renewal lands."""
        return self.peer_info(self.process_index).age_seconds

    def stale_peers(self, now: Optional[float] = None) -> List[PeerInfo]:
        """Members (excluding self) whose lease is older than
        ``stale_after`` or missing entirely — the diagnosis the
        deadline guard names."""
        now = self._clock() if now is None else now
        out = []
        for p in self.members:
            if p == self.process_index:
                continue
            info = self.peer_info(p, now=now)
            if info.age_seconds is None or info.age_seconds > self.stale_after:
                out.append(info)
        return out

    def live_members(self, now: Optional[float] = None) -> List[int]:
        """Members with a fresh lease (self included — our own renew
        thread keeps ours fresh). The elastic reform's membership
        source."""
        now = self._clock() if now is None else now
        stale = {i.process_index for i in self.stale_peers(now=now)}
        return [p for p in self.members if p not in stale]

    def fresh(self, process_index: int,
              now: Optional[float] = None) -> bool:
        """Whether ``worker-<i>.hb`` is on disk and within the
        staleness threshold — membership-agnostic (the grow rendezvous
        asks about JOINER slots before they are members)."""
        if process_index == self.process_index:
            return True
        info = self.peer_info(process_index, now=now)
        return (info.age_seconds is not None
                and info.age_seconds <= self.stale_after)

    # -- elastic reform rendezvous --------------------------------------
    def announce_reform(self, generation: int) -> None:
        """Publish that this process is ready to reform into cluster
        generation ``generation`` (idempotent; per-generation files so
        a later reform can't read an earlier round's announcements)."""
        path = os.path.join(self.directory,
                            f"reform-{int(generation)}"
                            f"-{self.process_index}")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(f"{self.host} {self.pid} {self._clock():.3f}\n")

    def reform_members(self, generation: int) -> List[int]:
        """Original process indices that announced ``generation``."""
        prefix = f"reform-{int(generation)}-"
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        out = []
        for n in names:
            if n.startswith(prefix):
                try:
                    out.append(int(n[len(prefix):]))
                except ValueError:
                    pass
        return sorted(out)

    # -- renew/monitor thread -------------------------------------------
    def check_peers(self) -> List[PeerInfo]:
        """One monitor tick: emit ``health: worker_lost`` for every
        member newly gone stale (one event per staleness episode; a
        peer whose lease resumes re-arms). Returns the newly-lost
        peers. Called from the daemon loop; tests call it directly
        under a fake clock."""
        if self.read(self.process_index) is None:
            # Our OWN lease — renewed this very tick — is unreadable:
            # the rendezvous dir itself is transiently broken (NFS
            # blip, permissions flip), not the peers. Reporting every
            # peer lost off an unreadable dir would be a mass false
            # positive; skip the tick, staleness re-evaluates next
            # interval.
            return []
        stale = self.stale_peers()
        stale_ids = {i.process_index for i in stale}
        fresh = [i for i in stale
                 if i.process_index not in self._reported_lost]
        # fmlint: disable=R008 -- single-writer by design: episode
        # dedup state is touched only by check_peers(), which runs on
        # the one heartbeat-lease monitor thread (tests call it
        # directly with the thread stopped); no other thread reads it
        self._reported_lost &= stale_ids  # recovered peers re-arm
        for info in fresh:
            # fmlint: disable=R008 -- same monitor-thread-only state
            self._reported_lost.add(info.process_index)
            _emit_worker_lost([info], label="heartbeat_monitor")
        return fresh

    def start(self) -> "HeartbeatLease":
        if self._thread is None and self.heartbeat_seconds > 0:
            self.renew()  # lease exists before anyone can look for it

            def loop():
                while not self._stop.wait(self.heartbeat_seconds):
                    self.renew()
                    try:
                        self.check_peers()
                        check_deadline()  # collective deadline
                        # sentinel: the blocked main thread cannot
                        # time itself out (see guard module comment)
                    except Exception:  # noqa: BLE001 - the monitor
                        # must outlive a bad tick; staleness is
                        # re-evaluated every interval anyway
                        pass
            self._thread = threading.Thread(target=loop,
                                            name="heartbeat-lease",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self, remove: bool = True) -> None:
        """Stop renewing; ``remove`` drops our lease file — and sweeps
        any STALE lease left behind by retired/dead members — so a
        clean exit doesn't leave a lease dir full of ghosts for the
        next run (or a joiner scanning for a live cluster) to read. A
        fresh peer lease is never touched: staleness is the same
        threshold the liveness verdicts use."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
        if remove:
            try:
                os.remove(self.lease_path(self.process_index))
            except OSError:
                pass
            try:
                names = os.listdir(self.directory)
            except OSError:
                return
            now = self._clock()
            for n in names:
                if not (n.startswith("worker-") and n.endswith(".hb")):
                    continue
                try:
                    idx = int(n[len("worker-"):-len(".hb")])
                except ValueError:
                    continue
                if idx == self.process_index:
                    continue
                info = self.peer_info(idx, now=now)
                if (info.age_seconds is None
                        or info.age_seconds > self.stale_after):
                    try:
                        os.remove(os.path.join(self.directory, n))
                    except OSError:
                        pass


def lease_dir(cfg) -> str:
    """The rendezvous dir for a run: ``<model_file>.hb/`` — a sibling
    of the checkpoint dir, on the same shared filesystem."""
    return os.path.abspath(cfg.model_file) + ".hb"


# --- elastic GROW: join tickets + admission plans ------------------------
#
# Shrink's mechanisms (generation-bumped reform announcements, live-
# lease filtering, the settle window) run here in the opposite
# direction: a replacement process publishes a JOIN TICKET in the
# rendezvous dir, the running cluster notices it at a safe barrier
# (epoch boundary / publish settle — train.py owns the trigger), the
# chief writes an admission PLAN assigning the ticket a free worker
# slot, and both sides rendezvous through the same per-generation
# announce files into a reformed cluster that includes the newcomer.
# The failure half is first-class: a joiner that dies mid-rendezvous
# is filtered by its lease going stale inside the settle window and
# the reform COMMITS without it; a joiner announcing into a
# generation it was never planned into is refused loudly; joiners
# racing fewer open slots resolve deterministically by ticket order.


class JoinTicket:
    """A replacement process's join-request lease.

    ``join-<stamp>-<pid>`` in the rendezvous dir, renewed on a daemon
    thread exactly like a worker lease — a joiner that dies stops
    renewing, so the cluster's admission scan (``pending_join_tickets``)
    never plans a slot for a ghost. The zero-padded monotonic stamp
    makes filename sort the deterministic admission order."""

    def __init__(self, directory: str, heartbeat_seconds: float = 5.0,
                 host: Optional[str] = None, pid: Optional[int] = None,
                 clock: Callable[[], float] = time.time,
                 name: Optional[str] = None):
        self.directory = directory
        self.heartbeat_seconds = float(heartbeat_seconds)
        self.host = host if host is not None else socket.gethostname()
        self.pid = int(pid if pid is not None else os.getpid())
        self._clock = clock
        self.name = name or (f"{JOIN_PREFIX}"
                             f"{int(self._clock() * 1e3):016d}"
                             f"-{self.pid}")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(self.directory, exist_ok=True)

    @property
    def path(self) -> str:
        return os.path.join(self.directory, self.name)

    def renew(self) -> None:
        """Same atomic-rewrite / swallow-OSError contract as
        HeartbeatLease.renew — one missed beat, never a crash."""
        tmp = f"{self.path}.tmp.{self.pid}"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"host": self.host, "pid": self.pid,
                           "time": self._clock()}, fh)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass

    def start(self) -> "JoinTicket":
        if self._thread is None and self.heartbeat_seconds > 0:
            self.renew()

            def loop():
                while not self._stop.wait(self.heartbeat_seconds):
                    self.renew()
            self._thread = threading.Thread(target=loop,
                                            name="join-ticket",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self, remove: bool = True) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
        if remove:
            try:
                os.remove(self.path)
            except OSError:
                pass


def pending_join_tickets(directory: str, stale_after: float,
                         now: Optional[float] = None) -> List[str]:
    """FRESH join-ticket names in deterministic (filename-sorted)
    order — the cluster's admission scan. A stale or garbled ticket is
    a dead joiner: never planned for, swept with the generation
    litter. Unreadable dir reads as 'nobody waiting' (the safe
    direction: admission is an optimization, never a liveness
    dependency)."""
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return []
    now = time.time() if now is None else now
    out = []
    for n in names:
        if not n.startswith(JOIN_PREFIX) or ".tmp." in n:
            continue
        try:
            with open(os.path.join(directory, n),
                      encoding="utf-8") as fh:
                rec = json.load(fh)
            age = now - float(rec["time"])
        except (OSError, ValueError, KeyError, TypeError):
            continue
        if age <= stale_after:  # clock skew (age < 0) reads fresh
            out.append(n)
    return out


def plan_grow(generation: int, members: Sequence[int], capacity: int,
              tickets: Sequence[str]) -> Optional[Dict]:
    """The chief's admission decision at a safe barrier: assign fresh
    join tickets to free ORIGINAL worker slots (the dead workers'
    indices — re-using them keeps ``worker_hosts`` slot semantics and
    the fmstat per-worker rows stable), hottest ticket first by
    filename order. None when there is nothing to do. Deterministic
    and pure — the multi-worker trigger broadcasts the chief's plan,
    and two joiners racing one open slot resolve by ticket order, the
    loser staying pending for the next opening."""
    free = sorted(set(range(int(capacity))) - {int(m) for m in members})
    tickets = sorted(tickets)
    if not free or not tickets:
        return None
    return {
        "generation": int(generation),
        "incumbents": sorted(int(m) for m in members),
        "joiners": {t: s for t, s in zip(tickets, free)},
    }


def _atomic_write_json(path: str, obj) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(obj, fh)
    os.replace(tmp, path)


def grow_plan_path(directory: str, generation: int) -> str:
    return os.path.join(directory,
                        f"{GROW_PLAN_PREFIX}{int(generation)}.json")


def commit_path(directory: str, generation: int) -> str:
    return os.path.join(directory,
                        f"{COMMIT_PREFIX}{int(generation)}.json")


def write_grow_plan(directory: str, plan: Dict) -> str:
    path = grow_plan_path(directory, plan["generation"])
    _atomic_write_json(path, plan)
    return path


def write_commit(directory: str, generation: int,
                 members: Sequence[int]) -> str:
    path = commit_path(directory, generation)
    _atomic_write_json(path, {"generation": int(generation),
                              "members": [int(m) for m in members]})
    return path


def _read_json(path: str) -> Optional[Dict]:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def read_grow_plan(directory: str, generation: int) -> Optional[Dict]:
    plan = _read_json(grow_plan_path(directory, generation))
    if (not isinstance(plan, dict) or "incumbents" not in plan
            or not isinstance(plan.get("joiners"), dict)):
        return None
    return plan


def read_commit(directory: str,
                generation: int) -> Optional[List[int]]:
    rec = _read_json(commit_path(directory, generation))
    if not isinstance(rec, dict) or "members" not in rec:
        return None
    try:
        return sorted(int(m) for m in rec["members"])
    except (TypeError, ValueError):
        return None


def grow_plan_for(directory: str, ticket_name: str,
                  min_generation: int = 0) -> Optional[Dict]:
    """The newest admission plan naming ``ticket_name``, ignoring
    generations below ``min_generation`` (a refused joiner bumps the
    floor so a stale plan — litter from a superseded round — is never
    acted on twice). What the joiner's wait loop polls."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    gens = []
    for n in names:
        if n.startswith(GROW_PLAN_PREFIX) and n.endswith(".json"):
            try:
                gens.append(int(n[len(GROW_PLAN_PREFIX):-len(".json")]))
            except ValueError:
                pass
    for g in sorted(gens, reverse=True):
        if g < min_generation:
            break
        plan = read_grow_plan(directory, g)
        if plan is not None and ticket_name in plan["joiners"]:
            return plan
    return None


def grow_rendezvous_step(lease: HeartbeatLease, plan: Dict,
                         now_monotonic: float,
                         join_deadline: float) -> Optional[List[int]]:
    """One tick of the incumbent chief's grow settle loop: the final
    membership once it is decidable, else None (keep polling).

    Committable once every incumbent has announced the plan's
    generation AND the join settle window (``join_deadline``) has
    fully elapsed — the window is never cut short, even with every
    planned joiner already announced, because staleness is the ONLY
    death signal and it lags a death by the staleness threshold: a
    joiner that announced and died a breath later must be visibly
    stale by commit time (``join_settle_seconds`` is floored at the
    staleness window for exactly this). At the deadline each planned
    slot is in (announced with a FRESH worker lease) or out (missing
    or stale: it died mid-rendezvous, and must never wedge the
    incumbents). Clock-injectable through the lease; tests drive it
    directly."""
    g = int(plan["generation"])
    announced = set(lease.reform_members(g))
    incumbents = [int(i) for i in plan["incumbents"]]
    if not set(incumbents) <= announced:
        return None
    optional = sorted(int(s) for s in plan["joiners"].values())
    if optional and now_monotonic < join_deadline:
        return None
    joined = [s for s in optional
              if s in announced and lease.fresh(s)]
    return sorted(set(incumbents) | set(joined))


def unexpected_announcers(lease: HeartbeatLease,
                          plan: Dict) -> List[int]:
    """Announce files for the plan's generation from slots the plan
    never assigned — a joiner acting on a stale plan, or a slot
    collision. The reform ignores them for membership; the caller
    refuses them LOUDLY (``health: join_refused``) so the operator
    sees the turned-away process instead of wondering why it idles."""
    g = int(plan["generation"])
    expected = ({int(i) for i in plan["incumbents"]}
                | {int(s) for s in plan["joiners"].values()})
    return sorted(set(lease.reform_members(g)) - expected)


def sweep_lease_dir(directory: str, generation: int,
                    members: Sequence[int],
                    join_stale_after: float = 0.0,
                    now: Optional[float] = None) -> int:
    """Reform-completion litter sweep: per-generation announce files,
    plans, and commits of SUPERSEDED generations, lease files of
    processes no longer in the membership, and dead (stale/garbled)
    join tickets — a long-lived elastic stream must not grow the
    rendezvous dir forever. Current-generation files and fresh join
    tickets (joiners still waiting for a future opening) survive.
    Returns the number of files removed; never raises."""
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    keep = {int(m) for m in members}
    fresh_tickets = (set(pending_join_tickets(directory,
                                              join_stale_after,
                                              now=now))
                     if join_stale_after > 0 else set())
    removed = 0
    for n in names:
        drop = False
        if ".tmp." in n:
            drop = True
        elif n.startswith("reform-"):
            try:
                drop = int(n.split("-")[1]) < int(generation)
            except (IndexError, ValueError):
                drop = True
        elif (n.startswith(GROW_PLAN_PREFIX)
              or n.startswith(COMMIT_PREFIX)) and n.endswith(".json"):
            prefix = (GROW_PLAN_PREFIX if n.startswith(GROW_PLAN_PREFIX)
                      else COMMIT_PREFIX)
            try:
                drop = int(n[len(prefix):-len(".json")]) < int(generation)
            except ValueError:
                drop = True
        elif n.startswith("worker-") and n.endswith(".hb"):
            try:
                drop = int(n[len("worker-"):-len(".hb")]) not in keep
            except ValueError:
                drop = True
        elif n.startswith(JOIN_PREFIX):
            drop = n not in fresh_tickets
        if drop:
            try:
                os.remove(os.path.join(directory, n))
                removed += 1
            except OSError:
                pass
    return removed


def emit_join_refused(generation: int, slot, reason: str) -> None:
    """Loud refusal of a joiner the rendezvous turned away (stale
    generation announce, commit that excluded it): a ``health:
    join_refused`` event + counter on the active stream, flushed — the
    refused process idles away outside the cluster, so the evidence
    must not wait for a barrier. No-op without an active run."""
    from fast_tffm_tpu.obs.telemetry import active
    tel = active()
    if tel is None:
        return
    tel.count("cluster/joins_refused")
    tel.sink.emit("health", {
        "status": "join_refused",
        "generation": int(generation),
        "slot": int(slot) if slot is not None else -1,
        "reason": str(reason)[:200],
    })
    tel.sink.flush()


# --- the guard -----------------------------------------------------------
#
# Two failure shapes, two mechanisms, ONE caller surface
# (guarded_collective):
#
# - A DEAD peer (SIGKILL, crash, node loss) resets the transport, so
#   the collective RAISES on the calling thread within seconds (gloo:
#   "Connection closed by peer"); the guard converts that to a
#   WorkerLostError naming the stale lease holders. Inline — the
#   calling thread keeps control, so elastic recovery can proceed.
#
# - A WEDGED peer (SIGSTOP, livelock) keeps its sockets open: the
#   collective blocks INSIDE a C-level wait that Python cannot
#   interrupt — no thread trick changes that, and dispatching jax
#   programs from a helper thread to get a timeout is memory-unsafe in
#   practice (observed heap corruption under the gloo CPU client). So
#   the deadline is enforced by the lease's MONITOR thread instead:
#   guarded_collective marks the call in-flight, and when the same
#   call is still in flight past ``collective_timeout_seconds`` WITH
#   stale peers on the table, the monitor emits the worker_lost
#   diagnosis naming them, dumps stacks, and hard-exits with
#   EXIT_WORKER_LOST — a diagnosed, bounded failure instead of an
#   indefinite hang (a blocked main thread cannot run recovery code,
#   so in-process shrink is only possible for the dead-peer shape; the
#   supervisor restart plus the bounded bring-up retry owns the
#   wedged shape).

# Distinctive exit status for the monitor's escalation path: the
# process was executed, the diagnosis is in the log/stream, and the
# supervisor can tell "worker lost" from an ordinary crash.
EXIT_WORKER_LOST = 86


@dataclasses.dataclass
class _GuardState:
    lease: Optional[HeartbeatLease]
    timeout_seconds: float
    # (label, started_monotonic) of the collective currently blocking
    # the calling thread; None between collectives. Tuple assignment —
    # atomic under the GIL, read by the monitor thread.
    in_flight: Optional[Tuple[str, float]] = None
    # Monotonic time a guarded collective last COMPLETED (or the guard
    # was armed). The lockstep protocol runs a guarded collective
    # every step/window, so "none completed within the deadline"
    # catches hangs that land in UNGUARDED sync points too — with
    # async dispatch, a dead peer can surface as a block inside a
    # device_put or result unpack rather than inside the wrapped call.
    last_progress: float = 0.0
    # Escalation hook (the monitor's hang verdict); tests inject a
    # recorder instead of killing the test process.
    escalate: Callable[[str], None] = None  # type: ignore[assignment]
    warned_slow: bool = False


_GUARD: Optional[_GuardState] = None


def install_guard(lease: Optional[HeartbeatLease],
                  timeout_seconds: float,
                  escalate: Optional[Callable[[str], None]] = None
                  ) -> Optional[_GuardState]:
    """Arm guarded_collective() for this process (train/predict call
    this once the cluster is up). Returns the previous state for
    ``restore_guard`` — the same push/pop shape as telemetry's
    active()."""
    global _GUARD
    prev = _GUARD
    _GUARD = _GuardState(lease=lease,
                         timeout_seconds=float(timeout_seconds),
                         last_progress=time.monotonic(),
                         escalate=escalate or _default_escalate)
    return prev


def restore_guard(prev: Optional[_GuardState]) -> None:
    global _GUARD
    _GUARD = prev


def current_guard() -> Optional[_GuardState]:
    return _GUARD


# --- protocol tracing (the fmlint R014 runtime oracle) ----------------

_PROTOCOL_TRACE: Optional[bool] = None  # enable_protocol_trace override
_PROTOCOL_ENV: Optional[bool] = None    # cached FM_PROTOCOL_TRACE parse
_PROTOCOL_SEQ = 0
# Collectives post from the driver loop only (fmlint R015 proves it),
# but the trace helpers must stay thread-clean anyway so a caller that
# ever moves onto a thread trips R015 alone, not a cascade of R008s
# over this module's state.
_PROTOCOL_LOCK = threading.Lock()


def protocol_trace_enabled() -> bool:
    """Whether every guarded collective should also emit a
    ``collective`` telemetry event (sequence number + label + op).
    Three switches, in precedence order: an explicit
    ``enable_protocol_trace()`` call, the ``FM_PROTOCOL_TRACE`` env
    fallback (same-named [Train] knob, fmlint R009), and the active
    run's ``protocol_trace`` config knob. The per-rank event streams
    are the ground truth ``fmtrace --collectives`` diffs against the
    static protocol automaton — identical sequences on every rank, or
    the first mismatching pair names the deadlock."""
    if _PROTOCOL_TRACE is not None:
        return _PROTOCOL_TRACE
    global _PROTOCOL_ENV
    if _PROTOCOL_ENV is None:
        with _PROTOCOL_LOCK:
            raw = os.environ.get("FM_PROTOCOL_TRACE", "")
            _PROTOCOL_ENV = raw.strip().lower() not in ("", "0", "false",
                                                        "no")
    if _PROTOCOL_ENV:
        return True
    from fast_tffm_tpu.obs.telemetry import active
    tel = active()
    return tel is not None and getattr(tel, "protocol_trace", False)


def enable_protocol_trace(on: bool = True) -> None:
    global _PROTOCOL_TRACE
    _PROTOCOL_TRACE = bool(on)


def _trace_protocol_op(label: str, fn: Callable) -> None:
    """Emit one ``collective`` event BEFORE the op posts, so a hung
    collective still shows the attempted label as the stream's last
    entry. Tracing must never kill a run — a sink failure is
    swallowed."""
    global _PROTOCOL_SEQ
    try:
        from fast_tffm_tpu.obs.telemetry import active
        tel = active()
        if tel is None:
            return
        with _PROTOCOL_LOCK:
            _PROTOCOL_SEQ += 1
            seq = _PROTOCOL_SEQ
        tel.sink.emit("collective", {
            "seq": seq, "label": label,
            "op": getattr(fn, "__name__", type(fn).__name__)})
    except Exception:
        pass


def guarded_collective(fn: Callable, *args, label: str = "collective",
                       collective: bool = True, **kwargs):
    """Run a blocking collective under the process's deadline guard —
    a HOST collective (process_allgather, broadcast, sync) or the
    dispatch/fetch of a collective XLA program (the lockstep step and
    score calls: on a dead cluster those block inside the program's
    collectives exactly like a host allgather). With no guard
    installed (single-process, or the knob off) this is a plain call —
    zero behavior change. Armed:

    - the call runs INLINE, marked in-flight for the monitor thread's
      deadline check (see module comment above);
    - a raise is re-raised, EXCEPT when the lease table shows dead
      peers (a killed peer's transport reset surfaces as an opaque
      RuntimeError/ValueError) — then a ``WorkerLostError`` naming
      them, with the original error as ``__cause__``, after emitting
      the ``health: worker_lost`` diagnosis;
    - a call still blocked past ``collective_timeout_seconds`` with
      stale peers is escalated by the monitor thread: diagnosis event,
      stack dump, and a hard exit with ``EXIT_WORKER_LOST``.
    """
    if collective and protocol_trace_enabled():
        # collective=False marks a guarded wrap that is NOT a
        # collective program (the lockstep score fetch is a local D2H
        # wait that runs a different number of times per rank when a
        # window drains empty) — tracing it would make every healthy
        # run look divergent under fmtrace --collectives.
        _trace_protocol_op(label, fn)
    state = _GUARD
    if state is None:
        return fn(*args, **kwargs)
    state.in_flight = (label, time.monotonic())
    try:
        return fn(*args, **kwargs)
    except WorkerLostError:
        raise
    except Exception as e:
        _convert_if_peers_lost(state.lease, label, e)
        raise
    finally:
        state.in_flight = None
        state.last_progress = time.monotonic()
        state.warned_slow = False


def check_deadline(state: Optional[_GuardState] = None,
                   now: Optional[float] = None) -> Optional[str]:
    """One monitor tick of the collective deadline (called from the
    lease's daemon loop; tests call it directly): when the in-flight
    collective has exceeded ``collective_timeout_seconds``:

    - stale peers on the lease table -> emit the ``health:
      worker_lost`` diagnosis naming them, dump stacks, and invoke the
      escalation hook (default: log a WorkerLostError-formatted
      CRITICAL line and ``os._exit(EXIT_WORKER_LOST)``) — the blocked
      thread can never raise, so a diagnosed bounded exit is the only
      alternative to hanging forever;
    - nobody stale -> a one-shot ``health: collective_slow`` warning
      (a slow save/compile/storage stall must not kill a healthy
      cluster).

    Returns "escalated", "slow", or None for tests."""
    state = state if state is not None else _GUARD
    if state is None or state.timeout_seconds <= 0:
        return None
    now = time.monotonic() if now is None else now
    snap = state.in_flight
    if snap is not None:
        label, started = snap
        waited = now - started
    else:
        # No guarded call in flight, but none has COMPLETED within the
        # deadline either: with async dispatch a dead peer can park
        # the thread in an unguarded sync point (a device_put against
        # a full queue, a result unpack) — the lockstep cadence of
        # guarded collectives makes their absence the hang signal.
        label = "no guarded collective completing"
        waited = now - state.last_progress
    if waited <= state.timeout_seconds:
        return None
    lease = state.lease
    lost = lease.stale_peers() if lease is not None else []
    if not lost:
        if not state.warned_slow:
            state.warned_slow = True
            _emit_collective_slow(label, waited, state.timeout_seconds)
        return "slow"
    _emit_worker_lost(lost, label=label,
                      timeout_seconds=state.timeout_seconds)
    _dump_stacks(label)
    who = "; ".join(i.describe() for i in lost)
    message = (f"WorkerLostError: '{label}' exceeded "
               f"collective_timeout_seconds="
               f"{state.timeout_seconds:g}s; peers that stopped "
               f"heartbeating: {who}. The blocked thread cannot be "
               f"unblocked from Python; exiting {EXIT_WORKER_LOST} "
               "with the diagnosis on the telemetry stream.")
    state.escalate(message)
    return "escalated"


def _default_escalate(message: str) -> None:
    import logging
    logging.getLogger("fast_tffm_tpu").critical(message)
    from fast_tffm_tpu.obs.telemetry import active
    tel = active()
    if tel is not None:
        try:
            tel.sink.flush()
        except Exception:  # noqa: BLE001 - nothing left to do with a
            pass           # broken sink on the way out
    os._exit(EXIT_WORKER_LOST)


def _await_staleness(lease: Optional[HeartbeatLease]
                     ) -> List[PeerInfo]:
    """Stale peers per the lease table, polling briefly: the guard's
    deadline and a peer's lease crossing the staleness threshold are
    independent clocks — give a freshly-dead peer up to one staleness
    window to go visibly stale before concluding nobody died."""
    if lease is None:
        return []
    deadline = time.monotonic() + lease.stale_after + lease.heartbeat_seconds
    while True:
        stale = lease.stale_peers()
        if stale or time.monotonic() >= deadline:
            return stale
        time.sleep(min(0.05, max(lease.heartbeat_seconds / 4, 0.01)))


# Error text that smells like the TRANSPORT failing (what a dead
# peer's reset looks like through gloo/grpc/XLA), as opposed to a
# semantic error (shape mismatch, OOM) the collective raised on its
# own. Only transport-shaped errors are worth waiting a full staleness
# window for — a genuine bug must re-raise promptly, not sit out a
# ~25s grace poll on every worker.
_TRANSPORT_ERROR_MARKERS = (
    "connection", "unavailable", "socket", "gloo", "transport",
    "deadline", "aborted", "cancelled", "coordination", "heartbeat",
    "peer", "barrier",
)


def _looks_like_transport_error(cause: BaseException) -> bool:
    text = f"{type(cause).__name__}: {cause}".lower()
    return any(m in text for m in _TRANSPORT_ERROR_MARKERS)


def _convert_if_peers_lost(lease: Optional[HeartbeatLease], label: str,
                           cause: BaseException) -> None:
    """Raise WorkerLostError (from ``cause``) when the lease table
    blames a dead peer for a failed collective; return otherwise (the
    caller re-raises the original). A transport-shaped error gets the
    full staleness grace (a SIGKILLed peer's reset arrives long before
    its lease crosses the threshold); any other error gets ONE
    immediate lease check and re-raises without delay."""
    if lease is not None and not _looks_like_transport_error(cause):
        lost = lease.stale_peers()
        if not lost:
            return
    else:
        lost = _await_staleness(lease)
    if not lost:
        return
    _emit_worker_lost(lost, label=label, error=f"{type(cause).__name__}: "
                      f"{str(cause)[:200]}")
    who = "; ".join(i.describe() for i in lost)
    raise WorkerLostError(
        f"collective '{label}' failed and the liveness table names "
        f"dead peers: {who}", lost=lost) from cause


def _emit_worker_lost(lost: Sequence[PeerInfo], label: str,
                      timeout_seconds: Optional[float] = None,
                      error: Optional[str] = None) -> None:
    from fast_tffm_tpu.obs.health import emit_worker_lost
    emit_worker_lost(lost, label=label, timeout_seconds=timeout_seconds,
                     error=error)


def _emit_collective_slow(label: str, waited: float,
                          timeout_seconds: float) -> None:
    """One-shot warning event: the collective exceeded its deadline
    but EVERY peer is still heartbeating — a wedged-or-slow cluster,
    not a shrunken one; never a reason to kill a healthy job."""
    from fast_tffm_tpu.obs.telemetry import active
    tel = active()
    if tel is None:
        return
    tel.sink.emit("health", {
        "status": "collective_slow",
        "label": str(label),
        "waited_seconds": round(float(waited), 3),
        "timeout_seconds": float(timeout_seconds),
    })
    tel.sink.flush()


def _dump_stacks(label: str) -> None:
    """All-thread stacks beside the metrics file (same sidecar the
    stall watchdog uses) — the 'where was everyone parked' answer for
    the expired collective. Best-effort: no active telemetry, no
    dump."""
    from fast_tffm_tpu.obs.telemetry import active
    tel = active()
    if tel is None:
        return
    try:
        path = tel.sink.path + ".stacks"
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(f"\n==== collective '{label}' deadline expired at "
                     f"{time.time():.3f} ====\n")
            fh.flush()
            faulthandler.dump_traceback(file=fh, all_threads=True)
    except Exception:  # noqa: BLE001 - forensics must never mask the
        # WorkerLostError about to be raised
        pass
