"""Multi-process bring-up — the TF1 ``ClusterSpec``/``Server`` equivalent.

The reference builds a gRPC cluster from the config's ``[Cluster]``
``ps_hosts``/``worker_hosts`` and runs async PS training (SURVEY.md §3.2/
§3.3). The TPU-native replacement: every worker is a ``jax.distributed``
process in ONE synchronous SPMD job; XLA collectives over ICI/DCN replace
gRPC parameter traffic; there are no ps roles — the table is row-sharded
across the global mesh (parallel/sharded.py), so the mesh *is* the
parameter server.

CLI surface parity: ``run_tffm.py train cfg dist_train worker <i>``
maps worker i onto jax.distributed process i, with ``worker_hosts[0]``
doubling as the coordinator (the analogue of the reference's chief
worker). ``ps`` roles are accepted and explained away (run_tffm.py):
a job that listed N ps hosts simply doesn't start them.
"""

from __future__ import annotations

import os
import time
from typing import Callable, List, Optional, Sequence, Tuple

from fast_tffm_tpu.config import FmConfig

# Per-attempt cap on the coordinator handshake: the total budget
# (cluster_connect_timeout_seconds) is spent in bounded slices with a
# short breather between them, so one wedged TCP connect can't eat the
# whole budget and the worker's log shows it is still trying.
CONNECT_ATTEMPT_CAP_SECONDS = 60.0
CONNECT_RETRY_SLEEP_SECONDS = 2.0


def coordinator_address(cfg: FmConfig, generation: int = 0,
                        hosts: Optional[Sequence[str]] = None) -> str:
    """worker_hosts[0] with its port shifted up by 1000: the reference's
    worker port serves TF gRPC; the jax.distributed coordinator needs its
    own listening port, derived deterministically so every process
    computes the same address from the shared config.

    ``generation`` (elastic recovery) bumps the port once per cluster
    reform: the previous generation's coordinator socket may still sit
    in TIME_WAIT — or belong to the dead worker — and every survivor
    derives the same bumped address without a side channel. ``hosts``
    overrides the config's worker list (the reform passes the
    SURVIVING hosts; the new chief is the first of them)."""
    host = (hosts if hosts is not None else cfg.worker_hosts)[0]
    if ":" in host:
        name, port = host.rsplit(":", 1)
        return f"{name}:{int(port) + 1000 + int(generation)}"
    return f"{host}:{8476 + int(generation)}"


def _emit_bringup_failed(address: str, process_id: int, attempts: int,
                         timeout_seconds: float,
                         last_error: Exception) -> None:
    """``health: cluster_bringup_failed`` on the active telemetry
    stream, flushed before the caller raises: the exception alone is
    invisible to fmstat post-mortems — an operator reading the stream
    of a job that never formed must see WHICH process gave up on WHICH
    coordinator. No-op without an active run."""
    from fast_tffm_tpu.obs.telemetry import active
    tel = active()
    if tel is None:
        return
    try:
        tel.count("cluster/bringup_failures")
        tel.sink.emit("health", {
            "status": "cluster_bringup_failed",
            "coordinator": address,
            "process_index": int(process_id),
            "attempts": int(attempts),
            "timeout_seconds": float(timeout_seconds),
            "error": f"{type(last_error).__name__}: "
                     f"{str(last_error)[:300]}",
        })
        tel.sink.flush()
    except Exception:  # noqa: BLE001 - forensics must never mask the
        # actionable bring-up error about to be raised
        pass


def initialize_with_retry(initialize: Callable[..., None], address: str,
                          num_processes: int, process_id: int,
                          timeout_seconds: float,
                          sleep: Callable[[float], None] = time.sleep,
                          clock: Callable[[], float] = time.monotonic
                          ) -> int:
    """Drive ``initialize`` (jax.distributed.initialize-shaped) in a
    bounded retry loop until it succeeds or ``timeout_seconds`` of
    total budget is spent, then raise naming the coordinator address
    and which process failed to join — the un-hardened call hangs
    workers forever on a coordinator that is still booting (the common
    staggered bring-up) or never coming (the failure an operator must
    see, not infer from silence). Each attempt gets jax's own
    ``initialization_timeout`` capped at CONNECT_ATTEMPT_CAP_SECONDS
    and at the remaining budget. ``sleep``/``clock`` are injectable so
    tests pin the budget math without real waits. Returns the number
    of attempts made (for logging/tests)."""
    deadline = clock() + timeout_seconds
    attempts = 0
    last_error: Exception = None  # type: ignore[assignment]
    while True:
        remaining = deadline - clock()
        if remaining <= 0:
            _emit_bringup_failed(address, process_id, attempts,
                                 timeout_seconds, last_error)
            raise RuntimeError(
                f"process {process_id} failed to join the "
                f"jax.distributed cluster: coordinator {address} did "
                f"not accept the connection within "
                f"cluster_connect_timeout_seconds={timeout_seconds:g}s "
                f"({attempts} attempt(s)). Is the coordinator process "
                "(worker 0) up, and its port (worker_hosts[0] port + "
                f"1000) reachable from this host? Last error: "
                f"{last_error}") from last_error
        attempts += 1
        try:
            initialize(coordinator_address=address,
                       num_processes=num_processes,
                       process_id=process_id,
                       initialization_timeout=max(1, int(min(
                           remaining, CONNECT_ATTEMPT_CAP_SECONDS))))
            return attempts
        except Exception as e:  # jax surfaces an unreachable
            # coordinator as RuntimeError (grpc DEADLINE_EXCEEDED /
            # UNAVAILABLE) — class varies by jax version, so retry on
            # any failure while budget remains; a genuinely fatal
            # misconfiguration exhausts the budget and raises with the
            # last underlying error attached.
            last_error = e
            if clock() + CONNECT_RETRY_SLEEP_SECONDS >= deadline:
                # No room for another attempt: fall through to the
                # deadline raise on the next loop iteration.
                sleep(max(0.0, deadline - clock()))
            else:
                sleep(CONNECT_RETRY_SLEEP_SECONDS)


def init_from_cluster(cfg: FmConfig, job_name: str,
                      task_index: int) -> Tuple[int, int]:
    """Join the SPMD job as process ``task_index`` of the cluster in the
    config. Returns (data_shard_index, num_shards) for the input
    pipeline (each worker reads a disjoint line shard, the analogue of
    the reference's per-worker file shards; SURVEY §3.2)."""
    if job_name != "worker":
        raise ValueError(f"unsupported job_name {job_name!r}; only "
                         "'worker' exists in the TPU rebuild (ps roles "
                         "are handled at the CLI)")
    hosts = cfg.worker_hosts
    # Validate BEFORE the single-host early return: a launcher started
    # with an out-of-range index against a 1-host config would
    # otherwise be silently accepted as shard 0 of 1 and race the real
    # worker's checkpoint writes instead of erroring like any
    # multi-host config does.
    if not 0 <= task_index < max(len(hosts), 1):
        raise ValueError(f"task_index {task_index} out of range for "
                         f"{len(hosts)} worker_hosts")
    if len(hosts) <= 1:
        return 0, 1
    _join_cluster(cfg, address=coordinator_address(cfg),
                  num_processes=len(hosts), process_id=task_index)
    return task_index, len(hosts)


def _liveness_owns_death_detection(cfg: FmConfig) -> bool:
    """jax's own death detection (abort every survivor ~100s after any
    task death) is replaced ONLY when the heartbeat-lease layer is on
    to do the job instead — with ``heartbeat_seconds = 0`` there is no
    monitor thread to enforce the collective deadline, and disabling
    both layers would make a dead peer an UNBOUNDED hang (strictly
    worse than the historical abort)."""
    return getattr(cfg, "heartbeat_seconds", 0) > 0


def _join_cluster(cfg: FmConfig, address: str, num_processes: int,
                  process_id: int) -> None:
    """Clear any pre-existing backends, assert the platform/collectives
    config, and join the jax.distributed job at ``address`` as process
    ``process_id`` of ``num_processes`` — shared by the initial
    bring-up and the elastic reform (which must rebuild the exact same
    client state against a different membership)."""
    import os

    import jax
    import jax.extend.backend
    # Backends may already exist (this environment's sitecustomize
    # resolves them at interpreter startup): distributed state and
    # collectives config only apply at client creation, so clear first.
    jax.extend.backend.clear_backends()
    # Re-assert the operator's platform choice: the sitecustomize layer
    # can override the JAX_PLATFORMS env var at import time, which would
    # make every worker race for the same tunnelled TPU chip instead of
    # forming the requested (e.g. CPU smoke) cluster.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    # CPU processes need an explicit collectives backend to federate into
    # one device namespace (TPU slices federate natively over ICI/DCN;
    # this setting only affects the CPU client, e.g. the localhost
    # smoke-cluster test, SURVEY §4).
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    def _initialize(**kw):
        try:
            if _liveness_owns_death_detection(cfg):
                _initialize_resilient(**kw)
            else:
                jax.distributed.initialize(**kw)
        except Exception:
            # A failed connect leaves the half-built client in
            # jax.distributed's global state (the client is registered
            # BEFORE connect()), and a bare re-initialize would then
            # raise 'should only be called once' instead of retrying.
            # Tear the partial state down so the next attempt is clean.
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            raise

    initialize_with_retry(
        _initialize,
        address=address,
        num_processes=num_processes,
        process_id=process_id,
        timeout_seconds=getattr(cfg, "cluster_connect_timeout_seconds",
                                300.0))
    if jax.process_count() != num_processes:
        raise RuntimeError(
            "jax.distributed did not federate the cluster: expected "
            f"{num_processes} processes, got {jax.process_count()}")


# jax's own death detection is DISABLED at bring-up (heartbeat budget
# pushed out ~3 years): its only response to a dead task is a
# LOG(FATAL) that ABORTS every surviving process ~100s after the loss
# — the exact opposite of this module's job. The liveness layer
# (parallel/liveness.py: sub-10s lease staleness, named diagnosis,
# elastic recovery) replaces it; transport-level failures still
# surface organically as collective errors, which the deadline guard
# converts.
_DISABLED_HEARTBEAT_KWARGS = dict(
    service_heartbeat_interval_seconds=100_000_000,
    service_max_missing_heartbeats=1_000,
    client_heartbeat_interval_seconds=100_000_000,
    client_max_missing_heartbeats=1_000,
)


def _initialize_resilient(coordinator_address: str, num_processes: int,
                          process_id: int,
                          initialization_timeout: int = 300) -> None:
    """jax.distributed.initialize with survivable failure semantics:
    identical global-state wiring (the public function forwards to
    this same ``global_state.initialize``), but with the runtime's
    die-with-the-first-casualty heartbeat detection pushed out of the
    picture (see ``_DISABLED_HEARTBEAT_KWARGS``). Falls back to the
    plain public call on signature drift — the cluster still works
    there, only the abort-on-peer-death default returns."""
    import jax
    from jax._src import distributed as _dist
    try:
        _dist.global_state.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id,
            initialization_timeout=initialization_timeout,
            **_DISABLED_HEARTBEAT_KWARGS)
    except TypeError:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id,
            initialization_timeout=initialization_timeout)


# Strong references to retired runtime clients/services: their gRPC
# threads may still be parked on a dead peer, and a destructor-driven
# shutdown from GC could block or abort mid-recovery. One entry per
# lost-worker incident — a deliberate, bounded leak.
_RETIRED: List[Tuple] = []


def has_retired_clients() -> bool:
    """True when this process retired a dead cluster's runtime client
    (elastic recovery or fail-fast). The CLI checks this to exit via
    ``os._exit`` after sinks close: interpreter teardown would destroy
    the retired service, whose call cancellation trips the retired
    client's error-poll handler — a LOG(FATAL) abort AFTER a perfectly
    clean run. All durable state (checkpoint, metrics, logs, exports)
    is closed by then; skipping C++ teardown of already-dead cluster
    plumbing is the correct exit."""
    return bool(_RETIRED)


def retire_distributed_client() -> None:
    """Drop the jax.distributed client/service WITHOUT the shutdown
    handshake. A clean ``shutdown()`` runs the coordination service's
    Shutdown barrier, which by definition cannot complete while a
    registered peer is dead — it stalls for its full timeout and then
    (with jaxlib's default callback) aborts the process. After a
    WorkerLostError the old cluster is unrecoverable anyway: keep the
    objects alive (no destructor side effects), reset the global
    state so a reform (or a lone-survivor fallback to single-process)
    can rebuild from scratch, and restore the local-backend config."""
    import jax
    import jax.extend.backend
    from jax._src import distributed as _dist
    state = _dist.global_state
    _RETIRED.append((state.client, state.service,
                     getattr(state, "preemption_sync_manager", None)))
    _dist.global_state = type(state)()
    # The gloo CPU-collectives setting outlives the client it needs: a
    # lone survivor rebuilding its LOCAL backend would fail inside
    # make_gloo_tcp_collectives(distributed_client=None). Reset to the
    # default; _join_cluster re-asserts gloo when a shrunken
    # multi-process cluster actually reforms.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "none")
    except Exception:
        pass
    try:
        jax.extend.backend.clear_backends()
    except Exception:
        pass


def reform_shrunken_cluster(cfg: FmConfig, lease, generation: int,
                            logger=None) -> Tuple[int, int, List[int]]:
    """Rebuild the SPMD job from the surviving membership after a
    WorkerLostError (elastic = shrink):

    1. retire the old distributed client (no shutdown handshake — see
       ``retire_distributed_client``);
    2. announce readiness for cluster generation ``generation`` in the
       heartbeat rendezvous dir and wait until every LIVE lease holder
       has announced and the set holds still for a settle window —
       survivors' guard deadlines expire at slightly different times,
       so membership is only committed once it stops changing;
    3. re-rank: survivors sorted by ORIGINAL process index; the first
       survivor's host becomes the new coordinator at a
       generation-bumped port; ``initialize_with_retry`` forms the
       shrunken job (a lone survivor skips jax.distributed entirely
       and simply continues single-process).

    Returns ``(new_shard_index, num_shards, members)`` — the members
    list holds the survivors' original indices, which is also the new
    input-shard order, so the lost worker's byte ranges redistribute
    across everyone at the next epoch pass. The lease's expected
    membership is shrunk in place so departed workers stop being
    reported lost forever after."""
    from fast_tffm_tpu.parallel.liveness import REFORM_SETTLE_SECONDS
    log = logger or _silent_logger()
    retire_distributed_client()
    lease.announce_reform(generation)
    budget = getattr(cfg, "cluster_connect_timeout_seconds", 300.0)
    deadline = time.monotonic() + budget
    members: List[int] = []
    stable_since: Optional[float] = None
    while True:
        live = set(lease.live_members())
        announced = set(lease.reform_members(generation))
        agreed = sorted(live & announced)
        now = time.monotonic()
        if agreed and live <= announced:
            if agreed != members:
                members, stable_since = agreed, now
            elif (stable_since is not None
                  and now - stable_since >= REFORM_SETTLE_SECONDS):
                break
        else:
            members, stable_since = agreed, None
        if now >= deadline:
            raise RuntimeError(
                f"elastic reform generation {generation} did not "
                f"converge within cluster_connect_timeout_seconds="
                f"{budget:g}s: live={sorted(live)} "
                f"announced={sorted(announced)}")
        time.sleep(min(0.1, max(lease.heartbeat_seconds / 4, 0.02)))
    if lease.process_index not in members:
        raise RuntimeError(
            f"elastic reform generation {generation}: this process "
            f"({lease.process_index}) lost its own lease; members="
            f"{members}")
    lease.members = tuple(members)
    rank = members.index(lease.process_index)
    log.info("elastic reform generation %d: survivors %s, this process "
             "re-ranks %d -> %d of %d", generation, members,
             lease.process_index, rank, len(members))
    if len(members) > 1:
        hosts = [cfg.worker_hosts[m] for m in members]
        _join_cluster(cfg,
                      address=coordinator_address(cfg, generation,
                                                  hosts=hosts),
                      num_processes=len(members), process_id=rank)
    if rank == 0:
        # Reform-completion litter sweep (chief only): superseded
        # generations' announce/plan/commit files and departed
        # members' leases must not accumulate over a long elastic
        # stream's reforms.
        from fast_tffm_tpu.parallel.liveness import sweep_lease_dir
        sweep_lease_dir(lease.directory, generation, members,
                        join_stale_after=lease.stale_after)
    return rank, len(members), members


def reform_grown_cluster(cfg: FmConfig, lease, generation: int,
                         plan: dict, logger=None
                         ) -> Tuple[int, int, List[int], int]:
    """Rebuild the SPMD job with replacement worker(s) admitted
    (``elastic = grow``) — the inverse of ``reform_shrunken_cluster``,
    through the same per-generation rendezvous files:

    1. retire the (healthy) distributed client when one exists — the
       reformed job needs a fresh client against the bumped
       generation's coordinator either way, and retire is the one
       teardown that can never stall on a handshake;
    2. announce readiness for ``generation`` and poll
       ``grow_rendezvous_step``: incumbents are mandatory, planned
       joiners optional — a joiner whose worker lease never turns up
       fresh inside ``join_settle_seconds`` died mid-rendezvous and
       the reform proceeds WITHOUT it (never wedging the incumbents);
       announcers the plan never assigned are refused loudly;
    3. the chief commits the final membership (``commit-<g>.json``);
       every party adopts it verbatim, so nobody can disagree about
       ``num_processes``; then form the job at the generation-bumped
       coordinator port.

    A joiner that dies AFTER the commit but before its connect lands
    surfaces as the bring-up retry exhausting its budget; the
    incumbents then fall back to a shrink-style reform at the NEXT
    generation, which the now-stale joiner drops out of — a bounded
    detour, not a wedge. Returns ``(rank, num_shards, members,
    generation)`` — the FINAL generation, which the fallback bumps
    past the plan's: the caller must adopt it, or the next reform
    would reuse an already-consumed generation (and its coordinator
    port, still held by the retired service)."""
    from fast_tffm_tpu.parallel import liveness as lv
    log = logger or _silent_logger()
    import jax
    if jax.process_count() > 1:
        retire_distributed_client()
    lease.announce_reform(generation)
    budget = getattr(cfg, "cluster_connect_timeout_seconds", 300.0)
    settle = getattr(cfg, "join_settle_seconds", 5.0)
    deadline = time.monotonic() + budget
    join_deadline = time.monotonic() + max(
        settle, lease.stale_after + lease.heartbeat_seconds)
    incumbents = [int(i) for i in plan["incumbents"]]
    chief = lease.process_index == min(incumbents)
    refused: set = set()
    while True:
        now = time.monotonic()
        members = lv.read_commit(lease.directory, generation)
        if members is not None:
            break
        for slot in lv.unexpected_announcers(lease, plan):
            if slot not in refused:
                refused.add(slot)
                log.warning(
                    "grow generation %d: refusing announce from slot "
                    "%d — not in the admission plan (stale generation "
                    "or slot collision)", generation, slot)
                if chief:
                    # Chief-only like the other job-global health
                    # events: every incumbent sees the same announce
                    # file, and per-worker shard counters merge by
                    # SUM — one turned-away process must count once,
                    # not once per incumbent.
                    lv.emit_join_refused(generation, slot,
                                         "announced a generation it "
                                         "was never planned into")
        if chief:
            members = lv.grow_rendezvous_step(lease, plan, now,
                                              join_deadline)
            if members is not None:
                dropped = sorted(
                    set(int(s) for s in plan["joiners"].values())
                    - set(members))
                if dropped:
                    log.warning(
                        "grow generation %d: planned joiner slot(s) "
                        "%s never rendezvoused inside the settle "
                        "window (died mid-rendezvous?); reforming "
                        "without them", generation, dropped)
                lv.write_commit(lease.directory, generation, members)
                break
        if now >= deadline:
            raise RuntimeError(
                f"elastic grow generation {generation} did not "
                f"converge within cluster_connect_timeout_seconds="
                f"{budget:g}s: announced="
                f"{lease.reform_members(generation)} plan={plan}")
        time.sleep(min(0.1, max(lease.heartbeat_seconds / 4, 0.02)))
    if lease.process_index not in members:
        raise RuntimeError(
            f"elastic grow generation {generation}: this incumbent "
            f"({lease.process_index}) is missing from the committed "
            f"membership {members}")
    lease.members = tuple(members)
    rank = members.index(lease.process_index)
    joined = sorted(set(members) - set(incumbents))
    log.info("elastic grow generation %d: members %s (admitted %s), "
             "this process re-ranks %d -> %d of %d", generation,
             members, joined or "nobody", lease.process_index, rank,
             len(members))
    if len(members) > 1:
        hosts = [cfg.worker_hosts[m] for m in members]
        try:
            _join_cluster(cfg,
                          address=coordinator_address(cfg, generation,
                                                      hosts=hosts),
                          num_processes=len(members), process_id=rank)
        except RuntimeError:
            stale_joiners = [s for s in joined if not lease.fresh(s)]
            if not stale_joiners:
                raise
            # The committed joiner died between commit and connect:
            # fall back to a shrink-style reform at the next
            # generation — live-lease filtering drops it there.
            log.warning(
                "grow generation %d bring-up failed with committed "
                "joiner(s) %s now stale; falling back to a shrink "
                "reform at generation %d", generation, stale_joiners,
                generation + 1)
            rank, n, members = reform_shrunken_cluster(
                cfg, lease, generation + 1, logger)
            return rank, n, members, generation + 1
    if rank == 0:
        from fast_tffm_tpu.parallel.liveness import sweep_lease_dir
        sweep_lease_dir(lease.directory, generation, members,
                        join_stale_after=lease.stale_after)
    return rank, len(members), members, generation


def join_rendezvous(cfg: FmConfig, logger=None
                    ) -> Tuple[object, int, int, List[int], int, int]:
    """The replacement process's half of elastic GROW
    (``run_tffm.py train <cfg> --join``): publish a join ticket in the
    rendezvous dir, wait for a running cluster's admission plan, then
    come up through the SAME generation-bumped rendezvous the
    incumbents use. Returns ``(lease, rank, num_shards, members,
    generation, slot)`` — from there the elastic driver treats this
    process exactly like any other member (verified checkpoint
    restore, chief-broadcast watermark/vocab, shard re-balance all
    happen in the session it enters).

    Bounded: ``join_timeout_seconds`` (default: the cluster-connect
    budget) caps the wait for an offer; a commit that EXCLUDES this
    joiner (it lost a slot race, or announced too late) is refused
    loudly and the wait resumes for the next opening until the budget
    runs out."""
    from fast_tffm_tpu.parallel import liveness as lv
    log = logger or _silent_logger()
    directory = lv.lease_dir(cfg)
    os.makedirs(directory, exist_ok=True)
    hb = getattr(cfg, "heartbeat_seconds", 5.0)
    ticket = lv.JoinTicket(directory, heartbeat_seconds=hb).start()
    budget = (getattr(cfg, "join_timeout_seconds", 0.0)
              or getattr(cfg, "cluster_connect_timeout_seconds", 300.0))
    deadline = time.monotonic() + budget
    poll = min(1.0, max(hb / 4, 0.05))
    min_generation = 0
    lease = None
    log.info("join: ticket %s published in %s; waiting for a running "
             "cluster's admission plan (budget %gs)", ticket.name,
             directory, budget)
    try:
        while True:
            plan = lv.grow_plan_for(directory, ticket.name,
                                    min_generation=min_generation)
            if plan is not None:
                g = int(plan["generation"])
                slot = int(plan["joiners"][ticket.name])
                committed = lv.read_commit(directory, g)
                if committed is not None and slot not in committed:
                    # Stale plan: that generation already closed
                    # without us. Refuse it loudly and only consider
                    # NEWER offers from here on.
                    log.warning(
                        "join: generation %d committed without this "
                        "joiner (stale plan); waiting for a fresh "
                        "offer", g)
                    min_generation = g + 1
                    plan = None
            if plan is not None:
                hint = sorted({int(i) for i in plan["incumbents"]}
                              | {int(s)
                                 for s in plan["joiners"].values()})
                lease = lv.HeartbeatLease(
                    directory, process_index=slot, members=hint,
                    heartbeat_seconds=hb).start()
                lease.announce_reform(g)
                log.info("join: announced for cluster generation %d "
                         "as worker slot %d", g, slot)
                while True:
                    committed = lv.read_commit(directory, g)
                    if committed is not None:
                        break
                    if time.monotonic() >= deadline:
                        raise RuntimeError(
                            f"join: generation {g} never committed "
                            f"within join budget {budget:g}s (did the "
                            "incumbents die mid-rendezvous?)")
                    time.sleep(poll)
                if slot not in committed:
                    log.warning(
                        "join: commit for generation %d excludes this "
                        "joiner (slot race lost / announce too late); "
                        "re-queueing for the next opening", g)
                    lv.emit_join_refused(g, slot,
                                         "commit excluded this joiner")
                    lease.stop()
                    lease = None
                    min_generation = g + 1
                    continue
                members = committed
                lease.members = tuple(members)
                rank = members.index(slot)
                if len(members) > 1:
                    hosts = [cfg.worker_hosts[m] for m in members]
                    _join_cluster(
                        cfg,
                        address=coordinator_address(cfg, g,
                                                    hosts=hosts),
                        num_processes=len(members), process_id=rank)
                log.info("join: admitted into generation %d as rank "
                         "%d of %d (worker slot %d)", g, rank,
                         len(members), slot)
                return lease, rank, len(members), members, g, slot
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"join: no running cluster admitted this process "
                    f"within {budget:g}s — is a trainer with elastic "
                    f"= grow running against "
                    f"{getattr(cfg, 'model_file', '?')}, with a free "
                    "worker slot, and reaching its next safe barrier "
                    "(epoch boundary / publish settle)?")
            time.sleep(poll)
    except BaseException:
        if lease is not None:
            try:
                lease.stop()
            except Exception:
                pass
        raise
    finally:
        ticket.stop(remove=True)


def _silent_logger():
    from fast_tffm_tpu.utils.logging import get_logger
    return get_logger()
