"""Multi-process bring-up — the TF1 ``ClusterSpec``/``Server`` equivalent.

The reference builds a gRPC cluster from the config's ``[Cluster]``
``ps_hosts``/``worker_hosts`` and runs async PS training (SURVEY.md §3.2/
§3.3). The TPU-native replacement: every worker is a ``jax.distributed``
process in ONE synchronous SPMD job; XLA collectives over ICI/DCN replace
gRPC parameter traffic; there are no ps roles — the table is row-sharded
across the global mesh (parallel/sharded.py), so the mesh *is* the
parameter server.

CLI surface parity: ``run_tffm.py train cfg dist_train worker <i>``
maps worker i onto jax.distributed process i, with ``worker_hosts[0]``
doubling as the coordinator (the analogue of the reference's chief
worker). ``ps`` roles are accepted and explained away (run_tffm.py):
a job that listed N ps hosts simply doesn't start them.
"""

from __future__ import annotations

from typing import Tuple

from fast_tffm_tpu.config import FmConfig


def coordinator_address(cfg: FmConfig) -> str:
    """worker_hosts[0] with its port shifted up by 1000: the reference's
    worker port serves TF gRPC; the jax.distributed coordinator needs its
    own listening port, derived deterministically so every process
    computes the same address from the shared config."""
    host = cfg.worker_hosts[0]
    if ":" in host:
        name, port = host.rsplit(":", 1)
        return f"{name}:{int(port) + 1000}"
    return f"{host}:8476"


def init_from_cluster(cfg: FmConfig, job_name: str,
                      task_index: int) -> Tuple[int, int]:
    """Join the SPMD job as process ``task_index`` of the cluster in the
    config. Returns (data_shard_index, num_shards) for the input
    pipeline (each worker reads a disjoint line shard, the analogue of
    the reference's per-worker file shards; SURVEY §3.2)."""
    if job_name != "worker":
        raise ValueError(f"unsupported job_name {job_name!r}; only "
                         "'worker' exists in the TPU rebuild (ps roles "
                         "are handled at the CLI)")
    hosts = cfg.worker_hosts
    # Validate BEFORE the single-host early return: a launcher started
    # with an out-of-range index against a 1-host config would
    # otherwise be silently accepted as shard 0 of 1 and race the real
    # worker's checkpoint writes instead of erroring like any
    # multi-host config does.
    if not 0 <= task_index < max(len(hosts), 1):
        raise ValueError(f"task_index {task_index} out of range for "
                         f"{len(hosts)} worker_hosts")
    if len(hosts) <= 1:
        return 0, 1
    import os

    import jax
    import jax.extend.backend
    # Backends may already exist (this environment's sitecustomize
    # resolves them at interpreter startup): distributed state and
    # collectives config only apply at client creation, so clear first.
    jax.extend.backend.clear_backends()
    # Re-assert the operator's platform choice: the sitecustomize layer
    # can override the JAX_PLATFORMS env var at import time, which would
    # make every worker race for the same tunnelled TPU chip instead of
    # forming the requested (e.g. CPU smoke) cluster.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    # CPU processes need an explicit collectives backend to federate into
    # one device namespace (TPU slices federate natively over ICI/DCN;
    # this setting only affects the CPU client, e.g. the localhost
    # smoke-cluster test, SURVEY §4).
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_address(cfg),
        num_processes=len(hosts),
        process_id=task_index)
    if jax.process_count() != len(hosts):
        raise RuntimeError(
            "jax.distributed did not federate the cluster: expected "
            f"{len(hosts)} processes, got {jax.process_count()}")
    return task_index, len(hosts)
