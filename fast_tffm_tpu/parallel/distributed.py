"""Multi-process bring-up — the TF1 ``ClusterSpec``/``Server`` equivalent.

The reference builds a gRPC cluster from the config's ``[Cluster]``
``ps_hosts``/``worker_hosts`` and runs async PS training (SURVEY.md §3.2/
§3.3). The TPU-native replacement is ``jax.distributed.initialize``: every
worker is a JAX process in one synchronous SPMD job; XLA collectives over
ICI/DCN replace gRPC parameter traffic; there are no ps roles (the table
is row-sharded across the mesh, parallel/sharded.py).
"""

from __future__ import annotations

from typing import Tuple

from fast_tffm_tpu.config import FmConfig


def init_from_cluster(cfg: FmConfig, job_name: str,
                      task_index: int) -> Tuple[int, int]:
    """Map the reference's ``dist_train worker <i>`` identity onto a
    jax.distributed process. Returns (data_shard_index, num_shards) for
    the input pipeline. Worker 0's host doubles as the coordinator (the
    analogue of the reference's chief worker; SURVEY §3.2)."""
    if job_name != "worker":
        raise ValueError(f"unsupported job_name {job_name!r}; only "
                         "'worker' exists in the TPU rebuild")
    hosts = cfg.worker_hosts
    if len(hosts) <= 1:
        return 0, 1
    if not 0 <= task_index < len(hosts):
        raise ValueError(f"task_index {task_index} out of range for "
                         f"{len(hosts)} worker_hosts")
    # Gradient/table synchronization across processes rides the sharded
    # train step (parallel/sharded.py) under a global mesh; until the
    # train driver wires that in for multi-process runs, refusing is
    # strictly better than N silently-independent replicas racing on one
    # checkpoint directory.
    raise NotImplementedError(
        "multi-process dist_train is not wired up yet: single-process "
        "multi-device training (one host of a TPU slice) is supported via "
        "the sharded train step; run one process or shard files manually")
