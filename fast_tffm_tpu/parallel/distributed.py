"""Multi-process bring-up — the TF1 ``ClusterSpec``/``Server`` equivalent.

The reference builds a gRPC cluster from the config's ``[Cluster]``
``ps_hosts``/``worker_hosts`` and runs async PS training (SURVEY.md §3.2/
§3.3). The TPU-native replacement: every worker is a ``jax.distributed``
process in ONE synchronous SPMD job; XLA collectives over ICI/DCN replace
gRPC parameter traffic; there are no ps roles — the table is row-sharded
across the global mesh (parallel/sharded.py), so the mesh *is* the
parameter server.

CLI surface parity: ``run_tffm.py train cfg dist_train worker <i>``
maps worker i onto jax.distributed process i, with ``worker_hosts[0]``
doubling as the coordinator (the analogue of the reference's chief
worker). ``ps`` roles are accepted and explained away (run_tffm.py):
a job that listed N ps hosts simply doesn't start them.
"""

from __future__ import annotations

import time
from typing import Callable, Tuple

from fast_tffm_tpu.config import FmConfig

# Per-attempt cap on the coordinator handshake: the total budget
# (cluster_connect_timeout_seconds) is spent in bounded slices with a
# short breather between them, so one wedged TCP connect can't eat the
# whole budget and the worker's log shows it is still trying.
CONNECT_ATTEMPT_CAP_SECONDS = 60.0
CONNECT_RETRY_SLEEP_SECONDS = 2.0


def coordinator_address(cfg: FmConfig) -> str:
    """worker_hosts[0] with its port shifted up by 1000: the reference's
    worker port serves TF gRPC; the jax.distributed coordinator needs its
    own listening port, derived deterministically so every process
    computes the same address from the shared config."""
    host = cfg.worker_hosts[0]
    if ":" in host:
        name, port = host.rsplit(":", 1)
        return f"{name}:{int(port) + 1000}"
    return f"{host}:8476"


def initialize_with_retry(initialize: Callable[..., None], address: str,
                          num_processes: int, process_id: int,
                          timeout_seconds: float,
                          sleep: Callable[[float], None] = time.sleep,
                          clock: Callable[[], float] = time.monotonic
                          ) -> int:
    """Drive ``initialize`` (jax.distributed.initialize-shaped) in a
    bounded retry loop until it succeeds or ``timeout_seconds`` of
    total budget is spent, then raise naming the coordinator address
    and which process failed to join — the un-hardened call hangs
    workers forever on a coordinator that is still booting (the common
    staggered bring-up) or never coming (the failure an operator must
    see, not infer from silence). Each attempt gets jax's own
    ``initialization_timeout`` capped at CONNECT_ATTEMPT_CAP_SECONDS
    and at the remaining budget. ``sleep``/``clock`` are injectable so
    tests pin the budget math without real waits. Returns the number
    of attempts made (for logging/tests)."""
    deadline = clock() + timeout_seconds
    attempts = 0
    last_error: Exception = None  # type: ignore[assignment]
    while True:
        remaining = deadline - clock()
        if remaining <= 0:
            raise RuntimeError(
                f"process {process_id} failed to join the "
                f"jax.distributed cluster: coordinator {address} did "
                f"not accept the connection within "
                f"cluster_connect_timeout_seconds={timeout_seconds:g}s "
                f"({attempts} attempt(s)). Is the coordinator process "
                "(worker 0) up, and its port (worker_hosts[0] port + "
                f"1000) reachable from this host? Last error: "
                f"{last_error}") from last_error
        attempts += 1
        try:
            initialize(coordinator_address=address,
                       num_processes=num_processes,
                       process_id=process_id,
                       initialization_timeout=max(1, int(min(
                           remaining, CONNECT_ATTEMPT_CAP_SECONDS))))
            return attempts
        except Exception as e:  # jax surfaces an unreachable
            # coordinator as RuntimeError (grpc DEADLINE_EXCEEDED /
            # UNAVAILABLE) — class varies by jax version, so retry on
            # any failure while budget remains; a genuinely fatal
            # misconfiguration exhausts the budget and raises with the
            # last underlying error attached.
            last_error = e
            if clock() + CONNECT_RETRY_SLEEP_SECONDS >= deadline:
                # No room for another attempt: fall through to the
                # deadline raise on the next loop iteration.
                sleep(max(0.0, deadline - clock()))
            else:
                sleep(CONNECT_RETRY_SLEEP_SECONDS)


def init_from_cluster(cfg: FmConfig, job_name: str,
                      task_index: int) -> Tuple[int, int]:
    """Join the SPMD job as process ``task_index`` of the cluster in the
    config. Returns (data_shard_index, num_shards) for the input
    pipeline (each worker reads a disjoint line shard, the analogue of
    the reference's per-worker file shards; SURVEY §3.2)."""
    if job_name != "worker":
        raise ValueError(f"unsupported job_name {job_name!r}; only "
                         "'worker' exists in the TPU rebuild (ps roles "
                         "are handled at the CLI)")
    hosts = cfg.worker_hosts
    # Validate BEFORE the single-host early return: a launcher started
    # with an out-of-range index against a 1-host config would
    # otherwise be silently accepted as shard 0 of 1 and race the real
    # worker's checkpoint writes instead of erroring like any
    # multi-host config does.
    if not 0 <= task_index < max(len(hosts), 1):
        raise ValueError(f"task_index {task_index} out of range for "
                         f"{len(hosts)} worker_hosts")
    if len(hosts) <= 1:
        return 0, 1
    import os

    import jax
    import jax.extend.backend
    # Backends may already exist (this environment's sitecustomize
    # resolves them at interpreter startup): distributed state and
    # collectives config only apply at client creation, so clear first.
    jax.extend.backend.clear_backends()
    # Re-assert the operator's platform choice: the sitecustomize layer
    # can override the JAX_PLATFORMS env var at import time, which would
    # make every worker race for the same tunnelled TPU chip instead of
    # forming the requested (e.g. CPU smoke) cluster.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    # CPU processes need an explicit collectives backend to federate into
    # one device namespace (TPU slices federate natively over ICI/DCN;
    # this setting only affects the CPU client, e.g. the localhost
    # smoke-cluster test, SURVEY §4).
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    def _initialize(**kw):
        try:
            jax.distributed.initialize(**kw)
        except Exception:
            # A failed connect leaves the half-built client in
            # jax.distributed's global state (the client is registered
            # BEFORE connect()), and a bare re-initialize would then
            # raise 'should only be called once' instead of retrying.
            # Tear the partial state down so the next attempt is clean.
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            raise

    initialize_with_retry(
        _initialize,
        address=coordinator_address(cfg),
        num_processes=len(hosts),
        process_id=task_index,
        timeout_seconds=getattr(cfg, "cluster_connect_timeout_seconds",
                                300.0))
    if jax.process_count() != len(hosts):
        raise RuntimeError(
            "jax.distributed did not federate the cluster: expected "
            f"{len(hosts)} processes, got {jax.process_count()}")
    return task_index, len(hosts)
