"""Host input pipeline: text files -> fixed-shape device batches.

Replaces the reference's TF queue-runner pipeline (filename queue ->
TextLineReader.read_up_to -> shuffle queue; SURVEY.md §2 "Input pipeline",
§3.1) with an epoch-aware Python iterator that emits **static-shape**
batches XLA can compile once per bucket:

- per-example feature counts are padded to a bucket ladder (``L``),
- the batch's **unique** feature ids are computed on the host (the
  reference does ``tf.unique`` in-graph; SURVEY §3.1) and padded to their
  own ladder (``U``), so the device gathers ``U`` table rows instead of
  ``B*L`` and gradient scatter-adds are already deduplicated,
- short final batches are padded with zero-weight dummy examples.

Padding invariants (relied on by ops/ and tests):
- ``uniq_ids`` padding slots hold ``pad_id == vocabulary_size`` (a dead
  extra table row); the last slot is always padding.
- ``local_idx`` padding points at that last slot and ``vals`` padding is
  0.0, so padded positions contribute exactly zero to scores and grads.
- dummy examples have weight 0.0 and no features.
"""

from __future__ import annotations

import dataclasses
import glob as globlib
import random
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.parser import ParsedBlock


@dataclasses.dataclass
class DeviceBatch:
    """One fixed-shape batch. Shapes: B examples, L feature slots per
    example, U unique-row slots."""
    labels: np.ndarray       # f32 [B]
    weights: np.ndarray      # f32 [B]; 0.0 marks padded dummy examples
    uniq_ids: np.ndarray     # i32 [U]; padded with pad_id, last slot pad
    local_idx: np.ndarray    # i32 [B, L]; indexes uniq_ids; pad -> U-1
    vals: np.ndarray         # f32 [B, L]; 0.0 padding
    fields: Optional[np.ndarray] = None  # i32 [B, L]; 0 padding (FFM)
    num_real: int = 0        # examples that are not padding

    @property
    def shape_key(self) -> Tuple[int, int, int, bool]:
        return (len(self.labels), self.local_idx.shape[1],
                len(self.uniq_ids), self.fields is not None)


def expand_files(patterns: Sequence[str]) -> List[str]:
    """File list with glob expansion, order-stable (reference configs list
    globs/comma lists; SURVEY Appendix A)."""
    out: List[str] = []
    for p in patterns:
        hits = sorted(globlib.glob(p))
        if hits:
            out.extend(hits)
        else:
            out.append(p)  # let open() raise -> loud failure on missing file
    return out


def _ladder_fit(n: int, ladder: Sequence[int]) -> int:
    for b in ladder:
        if n <= b:
            return b
    # beyond the configured ladder: next power of two, so arbitrarily long
    # examples still get a (rarely recompiled) static bucket
    b = ladder[-1]
    while b < n:
        b *= 2
    return b


def _uniq_ladder(batch_size: int, max_l: int) -> List[int]:
    """Power-of-two ladder for the unique-row bucket; the top rung is the
    first power of two > B*L (so a padding slot exists even when every id
    is distinct). All rungs stay powers of two because mesh-sharded runs
    split the U axis across devices (parallel/sharded.py) and explicit
    shardings need divisible dims."""
    cap = batch_size * max_l + 1
    out, b = [], 64
    while b < cap:
        out.append(b)
        b *= 2
    out.append(b)
    return out


def make_device_batch(block: ParsedBlock, cfg: FmConfig,
                      weights: Optional[np.ndarray] = None,
                      batch_size: Optional[int] = None,
                      fixed_shape: bool = False) -> DeviceBatch:
    """CSR block -> fixed-shape DeviceBatch (pad + host-side unique).

    ``fixed_shape`` pins L and U to their ladder maxima instead of
    fitting this batch — required in multi-process SPMD, where every
    process must assemble identically-shaped global arrays every step
    (a process whose local batch picked a smaller bucket would deadlock
    the collective program).
    """
    B = batch_size or cfg.batch_size
    n_real = block.batch_size
    if n_real > B:
        raise ValueError(f"block of {n_real} examples exceeds batch_size {B}")
    sizes = block.sizes
    max_l = int(sizes.max()) if n_real else 1
    ladder = cfg.bucket_ladder
    L = ladder[-1] if fixed_shape else _ladder_fit(max(max_l, 1), ladder)
    if max_l > L:
        raise ValueError(f"example with {max_l} features exceeds the fixed "
                         f"bucket {L}; raise bucket_ladder or "
                         "max_features_per_example")

    # Host-side unique (replaces the reference's in-graph tf.unique).
    try:
        from fast_tffm_tpu.data.cparser import dedup_ids_fast
        uniq, inverse = dedup_ids_fast(block.ids)
    except RuntimeError:  # C++ extension unavailable
        uniq, inverse = np.unique(block.ids, return_inverse=True)
    uladder = _uniq_ladder(B, L)
    U = uladder[-1] if fixed_shape else _ladder_fit(len(uniq) + 1, uladder)

    uniq_ids = np.full(U, cfg.pad_id, dtype=np.int32)
    uniq_ids[:len(uniq)] = uniq
    pad_slot = U - 1  # always a pad_id slot by construction

    local_idx = np.full((B, L), pad_slot, dtype=np.int32)
    vals = np.zeros((B, L), dtype=np.float32)
    fields = (np.zeros((B, L), dtype=np.int32)
              if block.fields is not None else None)
    if n_real:
        # Vectorized CSR -> padded scatter (this runs per step on the hot
        # host path; a per-example Python loop here dominates step time).
        ex_sizes = np.diff(block.poses[:n_real + 1])
        rows = np.repeat(np.arange(n_real), ex_sizes)
        cols = np.arange(len(rows)) - np.repeat(block.poses[:n_real],
                                                ex_sizes)
        local_idx[rows, cols] = inverse
        vals[rows, cols] = block.vals
        if fields is not None:
            fields[rows, cols] = block.fields

    labels = np.zeros(B, dtype=np.float32)
    labels[:n_real] = block.labels
    w = np.zeros(B, dtype=np.float32)
    if weights is not None:
        w[:n_real] = np.asarray(weights, dtype=np.float32)[:n_real]
    else:
        w[:n_real] = 1.0
    return DeviceBatch(labels=labels, weights=w, uniq_ids=uniq_ids,
                       local_idx=local_idx, vals=vals, fields=fields,
                       num_real=n_real)


def _iter_lines(files: Sequence[str], weight_files: Sequence[str],
                shard_index: int, num_shards: int,
                keep_empty: bool = False) -> Iterator[Tuple[str, float]]:
    """Yield (line, weight) pairs, sharded by global line index so N
    data-parallel processes see disjoint examples (the reference shards by
    giving workers disjoint file lists; index-sharding also balances a
    single big file)."""
    wf = list(weight_files) if weight_files else [None] * len(files)
    if weight_files and len(weight_files) != len(files):
        raise ValueError("weight_files must parallel train_files "
                         f"({len(weight_files)} vs {len(files)})")
    idx = 0
    for path, wpath in zip(files, wf):
        wfh = open(wpath) if wpath else None
        try:
            with open(path) as fh:
                for line in fh:
                    wline = wfh.readline() if wfh else ""
                    if not line.strip() and not keep_empty:
                        continue
                    if idx % num_shards == shard_index:
                        yield line, float(wline) if wline.strip() else 1.0
                    idx += 1
        finally:
            if wfh:
                wfh.close()


def _fast_batch_iterator(cfg: FmConfig, bb, files: List[str], B: int,
                         n_epochs: int, shuffle: bool,
                         seed: Optional[int],
                         fixed_shape: bool) -> Iterator[DeviceBatch]:
    """Chunked C++ fast path: raw file bytes stream straight into the
    C++ BatchBuilder (parse + hash + dedup + padded scatter in one native
    pass); Python never touches individual lines.

    Shuffle here is a window-of-batches pick plus a within-batch row
    permutation — the same mixing radius as the reference's bounded
    shuffle queue of ``queue_size`` lines (SURVEY §2 "Input pipeline"),
    expressed at batch granularity. Exact reservoir-per-line semantics
    remain on the generic path (weight files / FFM / sharded input / the
    Python parser force it).
    """
    L_cap = bb.L
    pyrng = random.Random(cfg.seed if seed is None else seed)
    nprng = np.random.default_rng(pyrng.getrandbits(64))
    window: List[DeviceBatch] = []
    window_cap = max(2, cfg.queue_size // B) if shuffle else 1

    def emit(n, labels, uniq, li, vals, max_nnz) -> DeviceBatch:
        L = (L_cap if fixed_shape
             else _ladder_fit(max(max_nnz, 1), cfg.bucket_ladder))
        if L < L_cap:
            li = np.ascontiguousarray(li[:, :L])
            vals = np.ascontiguousarray(vals[:, :L])
        uladder = _uniq_ladder(B, L)
        U = uladder[-1] if fixed_shape else _ladder_fit(len(uniq) + 1,
                                                        uladder)
        uniq_ids = np.full(U, cfg.pad_id, dtype=np.int32)
        uniq_ids[:len(uniq)] = uniq  # slot 0 already pad_id (C++ layout)
        weights = np.zeros(B, np.float32)
        weights[:n] = 1.0
        labels[n:] = 0.0  # C++ buffer may hold stale labels past n
        if shuffle and n > 1:
            # Permute only the real rows: consumers rely on the padding
            # block staying at the tail ([:num_real] slicing).
            perm = np.concatenate([nprng.permutation(n),
                                   np.arange(n, B)])
            labels, weights = labels[perm], weights[perm]
            li, vals = li[perm], vals[perm]
        return DeviceBatch(labels=labels, weights=weights,
                           uniq_ids=uniq_ids, local_idx=li, vals=vals,
                           fields=None, num_real=n)

    def drain(batch: DeviceBatch) -> Iterator[DeviceBatch]:
        if shuffle:
            window.append(batch)
            if len(window) >= window_cap:
                yield window.pop(pyrng.randrange(len(window)))
        else:
            yield batch

    for _ in range(n_epochs):
        for path in files:
            with open(path, "rb") as fh:
                tail = b""
                while True:
                    chunk = fh.read(4 << 20)
                    if not chunk:
                        if not tail:
                            break
                        # final line missing its newline
                        data, tail = tail + b"\n", b""
                    else:
                        data, tail = (tail + chunk if tail else chunk), b""
                    off = 0
                    while True:
                        full, consumed = bb.feed(data, off)
                        off += consumed
                        if not full:
                            break
                        yield from drain(emit(*bb.finish()))
                    tail = data[off:]
                    if not chunk:
                        break
        n, labels, uniq, li, vals, max_nnz = bb.finish()
        if n:  # short final batch of the epoch
            yield from drain(emit(n, labels, uniq, li, vals, max_nnz))
        while window:
            yield window.pop(pyrng.randrange(len(window)))


def batch_iterator(cfg: FmConfig, files: Sequence[str],
                   training: bool = True,
                   weight_files: Sequence[str] = (),
                   shard_index: int = 0, num_shards: int = 1,
                   epochs: Optional[int] = None,
                   batch_size: Optional[int] = None,
                   seed: Optional[int] = None,
                   keep_empty: bool = False,
                   fixed_shape: bool = False) -> Iterator[DeviceBatch]:
    """Epoch/shuffle/batch loop over text files.

    Shuffling is a bounded reservoir of ``cfg.queue_size`` lines, the same
    memory/coverage contract as the reference's shuffle queue (SURVEY §2
    "Input pipeline"); deterministic given ``seed``.
    """
    from fast_tffm_tpu.data.parser import parse_lines
    from fast_tffm_tpu.data.cparser import parse_lines_fast

    files = expand_files(files)
    B = batch_size or cfg.batch_size
    n_epochs = epochs if epochs is not None else (cfg.epoch_num if training
                                                  else 1)
    rng = random.Random(cfg.seed if seed is None else seed)
    do_shuffle = training and cfg.shuffle

    # Chunked C++ fast path (see _fast_batch_iterator): applies whenever
    # no feature needs per-line Python handling. Requires a hard
    # per-example cap (the builder writes fixed-stride rows);
    # max_features_per_example = 0 means "unlimited" and stays generic.
    if (num_shards == 1 and not keep_empty and not weight_files
            and cfg.model_type != "ffm"
            and cfg.max_features_per_example > 0):
        try:
            from fast_tffm_tpu.data.cparser import BatchBuilder
            # A ladder value (power of two past the top), so batches with
            # max_features_per_example > ladder[-1] land in the same
            # extended pow2 buckets the generic path compiles for.
            L_cap = _ladder_fit(
                max(cfg.bucket_ladder[-1], cfg.max_features_per_example),
                cfg.bucket_ladder)
            bb = BatchBuilder(B, L_cap, cfg.vocabulary_size,
                              hash_feature_id=cfg.hash_feature_id,
                              max_features_per_example=(
                                  cfg.max_features_per_example))
        except RuntimeError:
            bb = None  # C++ extension unavailable -> generic path
        if bb is not None:
            yield from _fast_batch_iterator(cfg, bb, files, B, n_epochs,
                                            do_shuffle, seed, fixed_shape)
            return
    # keep_empty needs blank lines to become zero-feature examples; only
    # the Python parser implements that.
    parse = (None if cfg.model_type == "ffm" or keep_empty
             else parse_lines_fast)

    for _ in range(n_epochs):
        pending: List[Tuple[str, float]] = []
        buf: List[Tuple[str, float]] = []

        def flush_batches(done: bool):
            while len(pending) >= B or (done and pending):
                chunk = pending[:B]
                del pending[:B]
                lines = [c[0] for c in chunk]
                w = np.array([c[1] for c in chunk], dtype=np.float32)
                block = _parse_block(lines, cfg, parse, keep_empty)
                yield make_device_batch(block, cfg, weights=w, batch_size=B,
                                        fixed_shape=fixed_shape)

        for item in _iter_lines(files, weight_files if training else (),
                                shard_index, num_shards,
                                keep_empty=keep_empty):
            if do_shuffle:
                buf.append(item)
                if len(buf) >= max(cfg.queue_size, B):
                    j = rng.randrange(len(buf))
                    buf[j], buf[-1] = buf[-1], buf[j]
                    pending.append(buf.pop())
            else:
                pending.append(item)
            yield from flush_batches(False)
        if do_shuffle and buf:
            rng.shuffle(buf)
            pending.extend(buf)
        yield from flush_batches(True)


def empty_batch(cfg: FmConfig, batch_size: Optional[int] = None
                ) -> DeviceBatch:
    """An all-padding batch (num_real=0, zero weights): the SPMD filler a
    data-exhausted process feeds while peers finish their shards — every
    term it contributes to loss/grad/reg is exactly zero by the padding
    invariants above."""
    fields = (np.zeros(0, np.int32) if cfg.model_type == "ffm" else None)
    block = ParsedBlock(labels=np.zeros(0, np.float32),
                        poses=np.zeros(1, np.int32),
                        ids=np.zeros(0, np.int32),
                        vals=np.zeros(0, np.float32), fields=fields)
    return make_device_batch(block, cfg, batch_size=batch_size,
                             fixed_shape=True)


def prefetch(iterator: Iterator[DeviceBatch],
             depth: int = 2) -> Iterator[DeviceBatch]:
    """Run ``iterator`` in a background thread, ``depth`` batches ahead.

    The reference overlaps input with compute via TF queue-runner threads
    (SURVEY §2 "Input pipeline"); here one host thread prepares the next
    batches while the device runs the current step. The C++ parser and
    numpy release the GIL, so the overlap is real — given a spare core.

    On a single-core host this is pure loss (measured 4x slower: the
    worker thread contends with jax dispatch for the one core, and the
    serial loop already overlaps device compute because dispatch is
    async), so it degrades to a passthrough there.
    """
    import os
    try:
        n_cpus = len(os.sched_getaffinity(0))  # cgroup/cpuset-aware
    except AttributeError:
        n_cpus = os.cpu_count() or 1
    if n_cpus <= 1:
        yield from iterator
        return

    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
    sentinel = object()
    stop = threading.Event()
    errbox: List[BaseException] = []

    def worker():
        try:
            for item in iterator:
                # Bounded put + stop checks so an abandoned consumer
                # (step raised, caller broke out) can't strand this
                # thread blocked forever holding file handles/batches.
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # re-raised on the consumer side
            errbox.append(e)
        finally:
            # Same bounded-put dance: a live consumer must get the
            # sentinel, a gone one (stop set) must not block us.
            while not stop.is_set():
                try:
                    q.put(sentinel, timeout=0.1)
                    break
                except queue.Full:
                    continue

    threading.Thread(target=worker, daemon=True).start()
    try:
        while True:
            item = q.get()
            if item is sentinel:
                if errbox:
                    raise errbox[0]
                return
            yield item
    finally:
        stop.set()


def _parse_block(lines: Sequence[str], cfg: FmConfig, fast_parse,
                 keep_empty: bool = False) -> ParsedBlock:
    from fast_tffm_tpu.data.parser import parse_lines
    field_aware = cfg.model_type == "ffm"
    if fast_parse is not None:
        try:
            return fast_parse(
                lines, cfg.vocabulary_size,
                hash_feature_id=cfg.hash_feature_id,
                max_features_per_example=cfg.max_features_per_example)
        except (OSError, RuntimeError):
            pass  # C++ extension unavailable -> Python fallback
    return parse_lines(
        lines, cfg.vocabulary_size, hash_feature_id=cfg.hash_feature_id,
        field_aware=field_aware, field_num=cfg.field_num,
        max_features_per_example=cfg.max_features_per_example,
        keep_empty=keep_empty)
