"""Host input pipeline: text files -> fixed-shape device batches.

Replaces the reference's TF queue-runner pipeline (filename queue ->
TextLineReader.read_up_to -> shuffle queue; SURVEY.md §2 "Input pipeline",
§3.1) with an epoch-aware Python iterator that emits **static-shape**
batches XLA can compile once per bucket:

- per-example feature counts are padded to a bucket ladder (``L``),
- the batch's **unique** feature ids are computed on the host (the
  reference does ``tf.unique`` in-graph; SURVEY §3.1) and padded to their
  own ladder (``U``), so the device gathers ``U`` table rows instead of
  ``B*L`` and gradient scatter-adds are already deduplicated,
- short final batches are padded with zero-weight dummy examples.

Padding invariants (relied on by ops/ and tests):
- ``uniq_ids`` padding slots hold ``pad_id == vocabulary_size`` (a dead
  extra table row); the last slot is always padding.
- ``local_idx`` padding points at that last slot and ``vals`` padding is
  0.0, so padded positions contribute exactly zero to scores and grads.
- dummy examples have weight 0.0 and no features.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import glob as globlib
import os
import random
import re
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.badlines import BadLineTracker
from fast_tffm_tpu.data.parser import (WHITESPACE, ParsedBlock,
                                       ParseError)
from fast_tffm_tpu.utils.retry import (RetryPolicy, open_with_retry,
                                       retry_io)


class UniqOverflow(ValueError):
    """A batch's unique-id count exceeds the fixed unique bucket; the
    caller must spill (emit a prefix of the batch and requeue the rest)."""


@dataclasses.dataclass
class SpillStats:
    """Spill observability for fixed-U (multi-process) input: when a
    batch's unique ids exceed ``uniq_bucket`` it closes early with fewer
    real examples — correct but throughput-degrading, and invisible
    without these counters (a dense tail the startup probe missed would
    otherwise silently collapse effective batch size). Pass one to
    batch_iterator and read it after the epoch; train() logs it.
    """
    batches: int = 0            # batches emitted
    spilled_batches: int = 0    # closed early on the unique-row budget
    real_examples: int = 0      # non-padding examples emitted
    capacity: int = 0           # batches * batch_size
    max_uniq: int = 0           # densest batch's unique-row count — the
    # shrink branch of train.adapt_uniq_bucket halves an oversized
    # bucket only when the whole epoch's densest batch fits the halved
    # budget with headroom (a mean would hide the one batch that spills)

    def count(self, num_real: int, batch_size: int,
              spilled: bool, num_uniq: int = 0) -> None:
        self.batches += 1
        self.spilled_batches += int(spilled)
        self.real_examples += num_real
        self.capacity += batch_size
        self.max_uniq = max(self.max_uniq, num_uniq)
        if spilled:
            # Spill visibility also reaches the run's metrics stream
            # (obs/): this is the single counting point for fixed-U
            # spills, so the JSONL and the epoch log line can't drift.
            from fast_tffm_tpu.obs.telemetry import active
            tel = active()
            if tel is not None:
                tel.count("pipeline/spilled_batches")

    @property
    def spill_fraction(self) -> float:
        return self.spilled_batches / self.batches if self.batches else 0.0

    @property
    def fill_fraction(self) -> float:
        return (self.real_examples / self.capacity if self.capacity
                else 1.0)

    def describe(self) -> str:
        return (f"{self.batches} batches, {self.real_examples} examples "
                f"(fill {self.fill_fraction:.1%}), "
                f"{self.spilled_batches} spilled "
                f"({self.spill_fraction:.1%})")


# Above this spilled-batch fraction the pipeline is visibly degraded by
# an undersized uniq_bucket and train() warns with the fix.
SPILL_WARN_FRACTION = 0.1


def require_bounded_examples(cfg: FmConfig, context: str) -> None:
    """Fixed-shape (multi-process) modes cap L at the ladder top; an
    over-long example caught lazily mid-run would kill one worker
    between collectives and hang its peers, so refuse up front.
    max_features_per_example = 0 means "unlimited", which can never be
    honored under a fixed L."""
    if not (0 < cfg.max_features_per_example <= cfg.bucket_ladder[-1]):
        raise ValueError(
            f"{context} needs 0 < max_features_per_example "
            f"({cfg.max_features_per_example}) <= bucket_ladder max "
            f"({cfg.bucket_ladder[-1]}) so over-long examples are "
            "truncated up front instead of faulting one worker mid-run")


def effective_L_cap(cfg: FmConfig) -> int:
    """The fixed-shape per-example feature bucket: the ladder value (a
    power of two extended past the top if needed) covering
    max_features_per_example. One definition shared by the fast-path
    builder and probe_uniq_bucket — the two MUST agree or multi-process
    shapes desynchronize across the probe/build boundary."""
    return _ladder_fit(
        max(cfg.bucket_ladder[-1], cfg.max_features_per_example),
        cfg.bucket_ladder)


@dataclasses.dataclass
class DeviceBatch:
    """One fixed-shape batch. Shapes: B examples, L feature slots per
    example, U unique-row slots.

    Raw-ids mode (``dedup = device``): ``uniq_ids`` is None and
    ``local_idx`` holds RAW feature ids (pad cells = pad_id); the jitted
    step runs the unique pass on device (models/fm._device_dedup)."""
    labels: np.ndarray       # f32 [B]
    weights: np.ndarray      # f32 [B]; 0.0 marks padded dummy examples
    uniq_ids: Optional[np.ndarray]  # i32 [U]; pad_id padding; None = raw
    local_idx: np.ndarray    # i32 [B, L]; indexes uniq_ids (or raw ids)
    vals: np.ndarray         # f32 [B, L]; 0.0 padding
    fields: Optional[np.ndarray] = None  # i32 [B, L]; 0 padding (FFM)
    num_real: int = 0        # examples that are not padding
    # Streaming run mode only (data/stream.py): the durable stream
    # position AFTER this batch's lines — a watermark payload dict the
    # train loop adopts once the batch has actually been stepped, so
    # checkpoints record exactly what was trained (prefetched-but-
    # unstepped batches must not advance the stream position). None
    # everywhere outside stream mode.
    stream_pos: Optional[dict] = None
    # vocab_mode = admit only (vocab/table.py): the batch's distinct
    # HASHED ids, attached by the remap seam — the train loop feeds
    # them to the admission sketch only once the batch is STEPPED
    # (the stream_pos adopt-on-step rule, applied to admission state
    # so it round-trips checkpoints exactly-once). None otherwise.
    vocab_obs: Optional[np.ndarray] = None
    # Admit mode only: the slot-map generation the remap ran under and
    # the retained hash-space originals (references, not copies) — the
    # train loop's ensure_current redoes a remap whose map a barrier
    # moved while the batch sat prefetched (vocab/table.py).
    vocab_gen: Optional[int] = None
    vocab_src: Optional[tuple] = None

    @property
    def shape_key(self) -> Tuple[int, int, int, bool]:
        return (len(self.labels), self.local_idx.shape[1],
                len(self.uniq_ids) if self.uniq_ids is not None else 0,
                self.fields is not None)


class FileMarks:
    """Per-file example-offset ledger for a single-pass keep_empty sweep
    — the cross-file streaming scorer's demux map (scoring.py).

    The pipeline appends ``(path, examples_before)`` as each file STARTS
    feeding; under ``keep_empty`` every line is exactly one example, so
    file i's examples span ``[starts[i], starts[i+1])`` of the emitted
    example stream (the last file ends at the sweep total). The
    load-bearing ordering invariant, kept by every pipeline path: a
    file's entry is appended BEFORE any batch containing that file's
    first example is yielded — so by the time the consumer holds enough
    ordered scores to cut file i, entry i+1 (if any) already exists.
    The scanner-ahead parallel plane appends entries EARLIER than the
    serial path would; earlier is always safe, later never happens.

    Thread-safe: the producing side runs on the prefetch/scanner
    thread, the reading side on the fetch worker — both under one
    lock. Requires ``keep_empty`` (blank lines are examples), a single
    epoch, and no shuffle; batch_iterator enforces all three."""

    def __init__(self):
        self._lock = threading.Lock()
        self._starts: List[Tuple[str, int]] = []

    def start_file(self, path: str, examples_before: int) -> None:
        with self._lock:
            self._starts.append((path, int(examples_before)))

    def snapshot(self) -> List[Tuple[str, int]]:
        with self._lock:
            return list(self._starts)


def expand_files(patterns: Sequence[str]) -> List[str]:
    """File list with glob expansion, order-stable (reference configs list
    globs/comma lists; SURVEY Appendix A)."""
    out: List[str] = []
    for p in patterns:
        hits = sorted(globlib.glob(p))
        if hits:
            out.extend(hits)
        else:
            out.append(p)  # let open() raise -> loud failure on missing file
    return out


def expand_paired_files(patterns: Sequence[str],
                        sidecar_patterns: Sequence[str]
                        ) -> Tuple[List[str], List[str]]:
    """Expand a data-file pattern list and its line-parallel sidecar
    pattern list TOGETHER, one pattern pair at a time.

    A purely positional zip of the two fully-expanded lists can pair
    sidecars to the WRONG files while passing a total-length check —
    e.g. two data patterns against one sidecar pattern whose hit count
    happens to match (ADVICE round 5). Pairing per pattern (both sides
    sort within a pattern, as expand_files does) makes parallel naming
    schemes like ``day*.txt`` / ``day*.weights`` line up by
    construction, and any per-pattern count mismatch fails loudly with
    the offending pair named."""
    if len(sidecar_patterns) != len(patterns):
        raise ValueError(
            f"sidecar pattern list must pair 1:1 with its data pattern "
            f"list ({len(sidecar_patterns)} sidecar patterns vs "
            f"{len(patterns)} data patterns); write one sidecar "
            "pattern per data pattern")
    files: List[str] = []
    sidecars: List[str] = []
    for dp, sp in zip(patterns, sidecar_patterns):
        d = expand_files([dp])
        s = expand_files([sp])
        if len(d) != len(s):
            raise ValueError(
                f"sidecar pattern pair expands to mismatched counts: "
                f"{dp!r} -> {len(d)} data files but {sp!r} -> {len(s)} "
                "sidecars; every data file needs exactly one sidecar")
        files.extend(d)
        sidecars.extend(s)
    return files, sidecars


def _ladder_fit(n: int, ladder: Sequence[int]) -> int:
    for b in ladder:
        if n <= b:
            return b
    # beyond the configured ladder: next power of two, so arbitrarily long
    # examples still get a (rarely recompiled) static bucket
    b = ladder[-1]
    while b < n:
        b *= 2
    return b


def _uniq_ladder(batch_size: int, max_l: int) -> List[int]:
    """Power-of-two ladder for the unique-row bucket; the top rung is the
    first power of two > B*L (so a padding slot exists even when every id
    is distinct). All rungs stay powers of two because mesh-sharded runs
    split the U axis across devices (parallel/sharded.py) and explicit
    shardings need divisible dims."""
    cap = batch_size * max_l + 1
    out, b = [], 64
    while b < cap:
        out.append(b)
        b *= 2
    out.append(b)
    return out


def make_device_batch(block: ParsedBlock, cfg: FmConfig,
                      weights: Optional[np.ndarray] = None,
                      batch_size: Optional[int] = None,
                      fixed_shape: bool = False,
                      uniq_bucket: int = 0,
                      raw_ids: bool = False) -> DeviceBatch:
    """CSR block -> fixed-shape DeviceBatch (pad + host-side unique).

    ``fixed_shape`` pins L and U instead of fitting this batch —
    required in multi-process SPMD, where every process must assemble
    identically-shaped global arrays every step (a process whose local
    batch picked a smaller bucket would deadlock the collective
    program). ``uniq_bucket`` (fixed_shape only) pins U to a measured
    density bound instead of the worst-case ladder top — raising
    UniqOverflow when the block genuinely exceeds it (spill protocol).

    ``raw_ids`` (dedup=device mode, incompatible with fixed_shape):
    skip the host unique pass entirely — local_idx holds raw ids,
    uniq_ids is None, the device runs the unique.
    """
    B = batch_size or cfg.batch_size
    n_real = block.batch_size
    if n_real > B:
        raise ValueError(f"block of {n_real} examples exceeds batch_size {B}")
    if raw_ids and fixed_shape:
        raise ValueError("raw_ids (dedup=device) has no fixed-U protocol; "
                         "multi-process mode needs dedup=host")
    sizes = block.sizes
    max_l = int(sizes.max()) if n_real else 1
    ladder = cfg.bucket_ladder
    L = ladder[-1] if fixed_shape else _ladder_fit(max(max_l, 1), ladder)
    if max_l > L:
        raise ValueError(f"example with {max_l} features exceeds the fixed "
                         f"bucket {L}; raise bucket_ladder or "
                         "max_features_per_example")

    if raw_ids:
        uniq_ids, inverse, pad_slot = None, block.ids, cfg.pad_id
    else:
        # Host-side unique (replaces the reference's in-graph tf.unique).
        try:
            from fast_tffm_tpu.data.cparser import dedup_ids_fast
            uniq, inverse = dedup_ids_fast(block.ids)
        except RuntimeError:  # C++ extension unavailable
            uniq, inverse = np.unique(block.ids, return_inverse=True)
        uladder = _uniq_ladder(B, L)
        if fixed_shape:
            U = uniq_bucket or uladder[-1]
            if len(uniq) + 1 > U:
                raise UniqOverflow(
                    f"{len(uniq)} unique ids exceed the fixed unique "
                    f"bucket {U} (one slot is reserved for padding)")
        else:
            U = _ladder_fit(len(uniq) + 1, uladder)

        uniq_ids = np.full(U, cfg.pad_id, dtype=np.int32)
        uniq_ids[:len(uniq)] = uniq
        pad_slot = U - 1  # always a pad_id slot by construction

    local_idx = np.full((B, L), pad_slot, dtype=np.int32)
    vals = np.zeros((B, L), dtype=np.float32)
    fields = (np.zeros((B, L), dtype=np.int32)
              if block.fields is not None else None)
    if n_real:
        # Vectorized CSR -> padded scatter (this runs per step on the hot
        # host path; a per-example Python loop here dominates step time).
        ex_sizes = np.diff(block.poses[:n_real + 1])
        rows = np.repeat(np.arange(n_real), ex_sizes)
        cols = np.arange(len(rows)) - np.repeat(block.poses[:n_real],
                                                ex_sizes)
        local_idx[rows, cols] = inverse
        vals[rows, cols] = block.vals
        if fields is not None:
            fields[rows, cols] = block.fields

    labels = np.zeros(B, dtype=np.float32)
    labels[:n_real] = block.labels
    w = np.zeros(B, dtype=np.float32)
    if weights is not None:
        w[:n_real] = np.asarray(weights, dtype=np.float32)[:n_real]
    else:
        w[:n_real] = 1.0
    return DeviceBatch(labels=labels, weights=w, uniq_ids=uniq_ids,
                       local_idx=local_idx, vals=vals, fields=fields,
                       num_real=n_real)


def epoch_file_order(files: List[str], shuffle: bool, seed: int,
                     epoch: int) -> List[str]:
    """Per-epoch file visit order: shuffled when shuffling is on (the
    reference's filename queue shuffles file order each epoch — SURVEY
    §2 "Input pipeline"; the bounded line/batch shuffle alone never
    mixes ACROSS files, so time-ordered multi-file datasets would feed
    whole files in sequence forever).

    Drawn from a DEDICATED per-(seed, epoch) Random — never the stream
    rng:
    that rng advances at a shard-data-dependent rate (shuffle window
    draws per emitted batch), so sharing it would give different
    processes different file orders by epoch 2 and break multi-process
    lockstep."""
    if not shuffle or len(files) < 2:
        return files
    out = list(files)
    random.Random(f"{seed}/{epoch}").shuffle(out)
    return out


def shard_byte_range(path: str, shard_index: int,
                     num_shards: int) -> Tuple[int, int]:
    """This shard's byte range of ``path``: worker i owns every line
    whose FIRST byte falls in [size*i/N, size*(i+1)/N). Each worker
    reads only ~1/N of every file (the reference sharded whole files
    across workers; byte ranges additionally balance one big file)."""
    size = os.path.getsize(path)
    return (size * shard_index // num_shards,
            size * (shard_index + 1) // num_shards)


def _iter_owned_chunks(path: str, start: int, end: int,
                       chunk_bytes: int = 4 << 20,
                       retry: Optional[RetryPolicy] = None
                       ) -> Iterator[bytes]:
    """Yield byte chunks that together contain exactly the lines owned
    by byte range [start, end) of ``path``.

    Ownership is by line start (the Hadoop-split convention): the line
    straddling ``start`` belongs to the previous range (skipped by
    scanning from start-1 to the first newline — adjacent ranges agree
    on that newline, so every line is owned exactly once); the line
    straddling ``end`` is read to completion. Only the final chunk at
    EOF may lack a trailing newline.

    ``retry`` wraps the open and each chunk read in the transient-IO
    retry loop (utils/retry.py) — a flaky networked filesystem costs
    backoff, not the run. Retry is at CHUNK granularity, and every
    attempt seeks back to the chunk's start offset first: a partial
    buffered read ADVANCES the underlying position before raising, so
    a naive in-place retry would silently resume past the lost bytes
    (truncated/merged lines — wrong training data, the worst failure
    mode this module exists to prevent).
    """
    fh = (open(path, "rb") if retry is None else
          open_with_retry(path, "rb", policy=retry, op="data_open"))

    def read(n: int) -> bytes:
        if retry is None:
            return fh.read(n)
        pos0 = fh.tell()

        def attempt() -> bytes:
            fh.seek(pos0)
            return fh.read(n)
        return retry_io(attempt, policy=retry, op="data_read")

    with fh:
        pos = start
        if start > 0:
            fh.seek(start - 1)
            while True:  # skip to the byte after the first newline
                b = read(chunk_bytes)
                if not b:
                    return  # EOF before any owned line
                i = b.find(b"\n")
                if i >= 0:
                    pos = fh.tell() - len(b) + i + 1
                    fh.seek(pos)
                    break
        if pos >= end:
            return  # first owned line starts past the range
        while True:
            b = read(chunk_bytes)
            if not b:
                return
            if pos + len(b) >= end:
                # The ownership boundary falls in this chunk: emit
                # through the first newline at absolute offset >= end-1
                # (the last owned line's terminator) and stop.
                cut = b.find(b"\n", max(end - 1 - pos, 0))
                if cut >= 0:
                    yield b[:cut + 1]
                    return
                # straddling line continues past this chunk: keep going
            yield b
            pos += len(b)


def _iter_range_lines(path: str, start: int, end: int,
                      retry: Optional[RetryPolicy] = None
                      ) -> Iterator[str]:
    """Decoded lines owned by byte range [start, end) of ``path``
    (ownership rules of _iter_owned_chunks). Splits on newlines BEFORE
    decoding so a multibyte UTF-8 character straddling a chunk boundary
    survives intact — the one implementation of the tail-carry split
    shared by _iter_lines and probe_uniq_bucket (the C++ fast path
    consumes raw bytes and never forms lines in Python)."""
    tail = b""
    for chunk in _iter_owned_chunks(path, start, end, retry=retry):
        parts = (tail + chunk if tail else chunk).split(b"\n")
        tail = parts.pop()
        for raw in parts:
            yield raw.decode("utf-8")
    if tail:  # final owned line missing its newline
        yield tail.decode("utf-8")


def _owned_start_line_index(path: str, start: int,
                            retry: Optional[RetryPolicy] = None) -> int:
    """Global line index of the first line OWNED by a byte range
    beginning at ``start`` (ownership rules of _iter_owned_chunks) == the
    newline count in [0, s) where s is that line's byte offset. A pure
    memchr-speed scan (~GB/s) — it aligns line-parallel sidecar files
    (weight_files) with a byte-range data shard without parsing.

    Memoized per file VERSION: train() builds a fresh iterator per
    epoch and this value is constant per (path, start) given the
    byte-range sharding's standing assumption that input files don't
    change mid-run — but the cache is module-level, so a long-lived
    process (pytest session, REPL) that rewrites the same path between
    runs must not be served the old file's count; size+mtime_ns+inode
    in the key invalidates rewrites (inode catches the common
    regenerate-then-rename) short of an in-place same-size rewrite
    inside one mtime clock tick, which no stat-based key can see."""
    st = os.stat(path)
    return _owned_start_line_index_for(path, start, st.st_size,
                                       st.st_mtime_ns, st.st_ino,
                                       retry)


@functools.lru_cache(maxsize=512)
def _owned_start_line_index_for(path: str, start: int, _size: int,
                                _mtime_ns: int, _ino: int,
                                retry: Optional[RetryPolicy] = None
                                ) -> int:
    if start <= 0:
        return 0
    n = 0
    # RetryPolicy is a frozen (hashable) dataclass, so it rides the
    # memo key; the scan is a pure prefix read, safe to re-drive whole.
    with (open(path, "rb") if retry is None else
          open_with_retry(path, "rb", policy=retry,
                          op="sidecar_align")) as fh:
        # Newlines strictly before `start - 1`, then resolve the
        # boundary: the newline at/after start-1 terminates the previous
        # owner's line, so the first owned line is one past it.
        remaining = start - 1
        while remaining > 0:
            b = fh.read(min(4 << 20, remaining))
            if not b:
                return n
            n += b.count(b"\n")
            remaining -= len(b)
        while True:
            b = fh.read(4 << 20)
            if not b:
                return n  # EOF before a newline: range owns nothing more
            i = b.find(b"\n")
            if i >= 0:
                return n + 1
            # keep scanning: the straddling line continues


def _iter_lines(files: Sequence[str], weight_files: Sequence[str],
                shard_index: int, num_shards: int,
                keep_empty: bool = False,
                retry: Optional[RetryPolicy] = None,
                file_marks: Optional[FileMarks] = None
                ) -> Iterator[Tuple[str, float, Tuple[str, int, int,
                                                      int]]]:
    """Yield (line, weight, source) triples for this shard, where
    ``source = (path, rel_lineno, shard_index, num_shards)`` is the
    line's provenance: ``rel_lineno`` is 1-based within the shard's
    owned byte range, resolved to an absolute file line number only on
    the error path (_resolve_source — the newline scan is lazy, so
    clean runs never pay it).

    Sharding is per-file byte ranges (shard_byte_range): each worker
    PARSES only its ~1/N of the bytes. Weight files are line-parallel to
    data files, so the weighted path aligns them by counting the data
    shard's starting line index (_owned_start_line_index — a newline
    scan, not a parse) and skipping that many weight lines; weight files
    are ~20x smaller than their data, so each worker streaming its own
    prefix of the weight file is cheap. (Until round 4 this path
    index-modulo-sharded over a FULL read of the data — N workers each
    reading and parsing every byte.)"""
    if weight_files:
        if len(weight_files) != len(files):
            raise ValueError(
                "weight sidecar list must pair 1:1 with its data files "
                f"after glob expansion ({len(weight_files)} sidecars vs "
                f"{len(files)} files)")
        for path, wpath in zip(files, weight_files):
            start, end = shard_byte_range(path, shard_index, num_shards)
            n_skip = _owned_start_line_index(path, start, retry)
            wfh = (open(wpath) if retry is None else
                   open_with_retry(wpath, policy=retry,
                                   op="sidecar_open"))
            with wfh:
                # Weight files are LINE-PARALLEL sidecars; a missing or
                # blank weight line means the pairing is broken
                # (truncated copy, corrupted file) and every example
                # from there on would silently train with the wrong
                # weight — fail loudly instead of substituting 1.0.
                for i in range(n_skip):
                    if not wfh.readline():
                        raise ValueError(
                            f"weight file {wpath} is shorter than its "
                            f"data file {path}: ended at line {i} while "
                            f"skipping to this shard's start ({n_skip})")
                lineno = n_skip
                rel = 0
                for line in _iter_range_lines(path, start, end,
                                              retry=retry):
                    wline = wfh.readline()
                    lineno += 1
                    rel += 1
                    if not wline:
                        raise ValueError(
                            f"weight file {wpath} is shorter than its "
                            f"data file {path}: no weight for data "
                            f"line {lineno}")
                    if not line.strip(WHITESPACE) and not keep_empty:
                        continue
                    try:
                        # fmlint: disable=R001 -- parses a weight-file
                        # TEXT line; no device value exists here
                        w = float(wline)
                    except ValueError:
                        raise ValueError(
                            f"bad weight {wline.strip()!r} at {wpath} "
                            f"line {lineno}") from None
                    yield line, w, (path, rel, shard_index, num_shards)
        return
    yielded = 0
    for path in files:
        if file_marks is not None:
            # keep_empty sweeps yield one example per owned line, so
            # the yielded count IS the example offset (batch_iterator
            # rejects file_marks without keep_empty).
            file_marks.start_file(path, yielded)
        start, end = shard_byte_range(path, shard_index, num_shards)
        rel = 0
        for line in _iter_range_lines(path, start, end, retry=retry):
            rel += 1
            # strip() pinned to the libsvm separator set: a line holding
            # only \x1c would read as blank here (skipped) but as a
            # parse-error line on the C++ fast path otherwise.
            if line.strip(WHITESPACE) or keep_empty:
                yielded += 1
                yield line, 1.0, (path, rel, shard_index, num_shards)


# Both parser paths prefix errors "line <block-relative-index>: ...";
# the pipeline layers the real provenance (file, absolute lineno,
# shard) on top, so a bad line in a 40-file glob is findable.
_LINE_MSG = re.compile(r"^line (\d+): (.*)$", re.S)


def _source_lineno(src: Tuple[str, int, int, int]) -> Tuple[str, int]:
    """(path, absolute 1-based file lineno) for a provenance tuple —
    what the quarantine record carries. The newline scan resolving the
    shard's starting line is memoized and error/bad-line-path-only;
    falls back to the shard-relative lineno when the file went
    unreadable underneath us."""
    path, rel, si, ns = src
    try:
        start, _ = shard_byte_range(path, si, ns)
        return path, _owned_start_line_index(path, start) + rel
    except OSError:
        return path, rel


def _resolve_source(src: Tuple[str, int, int, int]) -> str:
    """Human-findable rendering of a provenance tuple (_source_lineno's
    absolute lineno, plus the shard byte range when sharded)."""
    path, rel, si, ns = src
    _, abs_ln = _source_lineno(src)
    if ns <= 1:
        return f"{path} line {abs_ln}"
    try:
        start, end = shard_byte_range(path, si, ns)
    except OSError:
        return f"{path} line {abs_ln} (of shard {si}/{ns})"
    return f"{path} line {abs_ln}, shard {si}/{ns} (bytes {start}-{end})"


def _strip_line_prefix(msg: str) -> str:
    m = _LINE_MSG.match(msg)
    return m.group(2) if m else msg


def _attach_block_source(e: ParseError,
                         provenance: Sequence[Tuple[str, int, int, int]]
                         ) -> ParseError:
    """Rewrite a block-relative ParseError ("line 3: bad label ...")
    with the failing line's file/lineno/shard provenance."""
    m = _LINE_MSG.match(str(e))
    if not m:
        return e
    i = int(m.group(1))
    if i >= len(provenance):
        return e
    return ParseError(f"{_resolve_source(provenance[i])}: {m.group(2)}")


def _host_cpus() -> int:
    """Usable host cores, cgroup/cpuset-aware — the ONE counting rule
    behind the auto host_threads resolution, the per-worker feed-thread
    decision, and prefetch's GIL-bound passthrough gate (three callers
    that must never disagree about what 'the host has N cores'
    means)."""
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:
        return os.cpu_count() or 1


def resolve_host_threads(cfg: FmConfig) -> int:
    """The parallel data plane's CONFIGURED batch-build worker count:
    ``host_threads`` as set, or — 0 (auto) — min(4, host cores). 1
    keeps the serial path, byte-for-byte the pre-parallel behavior.
    Whether a given input actually fans out additionally depends on
    routing (C++ availability, weight sidecars, ...): use
    ``host_parallel_workers`` for the honest per-input answer."""
    n = int(getattr(cfg, "host_threads", 0))
    if n > 0:
        return n
    return max(1, min(4, _host_cpus()))


def host_parallel_workers(cfg: FmConfig, weight_files: Sequence[str] = (),
                          keep_empty: bool = False,
                          fixed_shape: bool = False) -> int:
    """The worker count the data plane will ACTUALLY use for these
    inputs — resolve_host_threads when a parallel route exists (the
    C++ fast path, or the tolerant generic path minus its serial-only
    features), else 1. This is the SAME predicate _batch_iterator_impl
    routes on, shared so train's startup log (and any other reporter)
    can never claim a fan-out the pipeline won't perform."""
    workers = resolve_host_threads(cfg)
    if workers <= 1:
        return 1
    from fast_tffm_tpu.data import cparser
    if not cparser.available():
        return 1
    if _fast_path_eligible(cfg, weight_files):
        return workers
    if (getattr(cfg, "bad_line_policy", "error") != "error"
            and not weight_files and not fixed_shape):
        # Tolerant generic plane. keep_empty rides it too since the C++
        # block parser grew the blank-line-preserving mode (ABI 7):
        # chunk composition stays line-deterministic — under keep_empty
        # a bad line becomes a zero-feature example instead of
        # dropping, so boundaries can't shift at all — and the parse
        # is the GIL-releasing C++ pass, so fanning it out is real
        # parallelism (the old Python-parser route made keep_empty
        # serial by routing; that was the shape predict's quarantine
        # sweeps ran single-threaded).
        return workers
    return 1


def _worker_feed_threads(workers: int, spill_capable: bool) -> int:
    """Feed parse threads per pool-worker builder. Spill-capable mode
    (fixed U) REQUIRES the serial feed: the rewind protocol needs the
    byte-exact consumed offset of a budget close, which the threaded
    feed's pending queue hides. Otherwise give each worker 2 feed
    threads when the host has cores to spare — the pool supplies the
    main fan-out, this only shortens a single group's critical path."""
    if spill_capable:
        return 1
    return 2 if _host_cpus() >= 2 * workers else 1


def _make_builder(cfg: FmConfig, B: int, raw_ids: bool, keep_empty: bool,
                  fixed_shape: bool, uniq_bucket: int,
                  num_threads: int = 0):
    """The ONE BatchBuilder construction, shared by the serial fast
    path and the parallel plane's per-worker builders — a knob threaded
    into one and missed in the other would silently fork the batch
    contract between host_threads settings. Raises RuntimeError when
    the C++ extension is unavailable (callers fall back generic)."""
    from fast_tffm_tpu.data.cparser import BatchBuilder
    # A ladder value (power of two past the top), so batches with
    # max_features_per_example > ladder[-1] land in the same extended
    # pow2 buckets the generic path compiles for.
    L_cap = effective_L_cap(cfg)
    return BatchBuilder(B, L_cap, cfg.vocabulary_size,
                        hash_feature_id=cfg.hash_feature_id,
                        field_aware=cfg.model_type == "ffm",
                        field_num=cfg.field_num,
                        raw_ids=raw_ids, keep_empty=keep_empty,
                        max_features_per_example=(
                            cfg.max_features_per_example),
                        max_uniq=(uniq_bucket if fixed_shape else 0),
                        num_threads=num_threads)


class _BatchEmitter:
    """Builder-output tuple -> DeviceBatch, plus the window-shuffle
    drain: ONE implementation shared by the serial fast path and the
    parallel ring coordinator. The host_threads=1 vs >1 bit-identical
    parity guarantee rests on this being the same object — same rng
    construction, same draw order per emitted batch, same window
    bookkeeping — fed batches in the same stream order."""

    def __init__(self, cfg: FmConfig, B: int, L_cap: int,
                 fixed_shape: bool, uniq_bucket: int, shuffle: bool,
                 seed: Optional[int], stats: Optional[SpillStats]):
        self.cfg = cfg
        self.B = B
        self.L_cap = L_cap
        self.fixed_shape = fixed_shape
        self.uniq_bucket = uniq_bucket
        self.shuffle = shuffle
        self.stats = stats
        self.pyrng = random.Random(cfg.seed if seed is None else seed)
        self.nprng = np.random.default_rng(self.pyrng.getrandbits(64))
        self.window: List[DeviceBatch] = []
        self.window_cap = (max(2, cfg.queue_size // B) if shuffle
                           else 1)

    def emit_drain(self, out, spilled: bool) -> Iterator[DeviceBatch]:
        """Emit one builder finish() tuple and drain through the
        bounded shuffle window (a passthrough when shuffle is off)."""
        batch = self._emit(*out, spilled=spilled)
        if self.shuffle:
            self.window.append(batch)
            if len(self.window) >= self.window_cap:
                yield self.window.pop(
                    self.pyrng.randrange(len(self.window)))
        else:
            yield batch

    def flush_window(self) -> Iterator[DeviceBatch]:
        while self.window:
            yield self.window.pop(
                self.pyrng.randrange(len(self.window)))

    def _emit(self, n, labels, uniq, li, vals, fields, max_nnz,
              spilled: bool = False) -> DeviceBatch:
        cfg, B = self.cfg, self.B
        if self.stats is not None:
            self.stats.count(n, B, spilled,
                             num_uniq=_num_uniq(uniq, cfg.pad_id))
        L = (self.L_cap if self.fixed_shape
             else _ladder_fit(max(max_nnz, 1), cfg.bucket_ladder))
        if L < self.L_cap:
            li = np.ascontiguousarray(li[:, :L])
            vals = np.ascontiguousarray(vals[:, :L])
            if fields is not None:
                fields = np.ascontiguousarray(fields[:, :L])
        if uniq is None:  # raw-ids mode: li holds raw ids, no unique set
            uniq_ids = None
        else:
            if self.fixed_shape and self.uniq_bucket:
                U = self.uniq_bucket  # builder guarantees len(uniq) <= U
            else:
                uladder = _uniq_ladder(B, L)
                # The builder's uniq already CONTAINS the reserved pad
                # slot (index 0), unlike the generic path's real-ids-only
                # set — fitting len+1 here would double-reserve and
                # inflate U to the next rung exactly at boundaries
                # (2x gather/scatter width, and a fast-vs-generic shape
                # divergence that defeats compile-cache reuse).
                U = (uladder[-1] if self.fixed_shape
                     else _ladder_fit(len(uniq), uladder))
            uniq_ids = np.full(U, cfg.pad_id, dtype=np.int32)
            uniq_ids[:len(uniq)] = uniq  # slot 0 already pad_id (C++)
        weights = np.zeros(B, np.float32)
        weights[:n] = 1.0
        labels[n:] = 0.0  # C++ buffer may hold stale labels past n
        if self.shuffle and n > 1:
            # Permute only the real rows: consumers rely on the padding
            # block staying at the tail ([:num_real] slicing).
            perm = np.concatenate([self.nprng.permutation(n),
                                   np.arange(n, B)])
            labels, weights = labels[perm], weights[perm]
            li, vals = li[perm], vals[perm]
            if fields is not None:
                fields = fields[perm]
        return DeviceBatch(labels=labels, weights=weights,
                           uniq_ids=uniq_ids, local_idx=li, vals=vals,
                           fields=fields, num_real=n)


class _BuildRing:
    """Bounded ORDERED ring between a pool of batch-build workers and
    the consuming iterator — the fan-out/fan-in seam of the parallel
    host data plane. ``submit(payload)`` assigns the next sequence
    number; workers pull tasks FIFO, build outside the lock, and post
    results keyed by sequence; ``wait(seq)`` hands the consumer exactly
    the in-order stream. ``invalidate_after(seq)`` implements the
    spill-rewind protocol: a generation bump discards every queued task
    and completed-but-unconsumed result past ``seq``, and in-flight
    stale work discards itself at post time (its captured generation no
    longer matches) — speculative batches are dropped, never emitted.

    Thread-safety: every shared mutation (task deque, result map,
    generation, liveness counts) holds ``self._lock``; the condition
    variable rides the same lock (fmlint R008 checks these
    thread-reachable writes). Worker-local build state (the per-worker
    BatchBuilder) lives in objects created inside each worker thread
    and never shared. Workers are daemon threads named ``fm-build-<i>``
    so their telemetry spans render as per-worker tracks in fmtrace;
    ``close()`` stops and joins them (bounded), so an aborted run never
    leaks the pool."""

    def __init__(self, workers: int, depth: int, work,
                 make_state=None):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._tasks: collections.deque = collections.deque()
        self._results: Dict[int, tuple] = {}
        self._gen = 0
        self._next_seq = 0
        self._stop = False
        self._pool_error: Optional[BaseException] = None
        self._alive = 0
        self._started = 0
        self._work = work
        self._make_state = make_state
        self.depth = max(int(depth), 2)
        self.workers = int(workers)
        self._threads: List[threading.Thread] = []
        for i in range(self.workers):
            t = threading.Thread(target=self._worker_main,
                                 name=f"fm-build-{i}", daemon=True)
            self._threads.append(t)
            t.start()

    def submit(self, payload) -> int:
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._tasks.append((self._gen, seq, payload))
            self._cv.notify_all()
            return seq

    def has(self, seq: int) -> bool:
        with self._lock:
            return seq in self._results

    def occupancy(self) -> int:
        """Completed-but-unconsumed results parked in the ring — the
        occupancy gauge (full ring = consumer-bound, empty = builders
        can't keep up)."""
        with self._lock:
            return len(self._results)

    def wait(self, seq: int) -> tuple:
        """Block until ``seq``'s result is ready and take it:
        ("ok", value) or ("error", exception). Raises instead when the
        pool itself is unusable (a worker's state factory failed, or
        every worker exited) — the consumer must never park forever on
        a ring nobody will fill."""
        with self._lock:
            while True:
                res = self._results.pop(seq, None)
                if res is not None:
                    return res
                if self._pool_error is not None:
                    raise self._pool_error
                if self._started >= self.workers and self._alive == 0:
                    raise RuntimeError(
                        "all batch-build workers exited; the host "
                        "data plane cannot make progress")
                self._cv.wait()

    def invalidate_after(self, seq: int) -> None:
        with self._lock:
            self._gen += 1
            self._tasks.clear()
            self._results = {s: r for s, r in self._results.items()
                             if s <= seq}

    def close(self) -> None:
        with self._lock:
            self._stop = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)

    def _worker_main(self) -> None:
        from fast_tffm_tpu.obs.telemetry import active
        from fast_tffm_tpu.obs.trace import span
        import time as _time
        try:
            state = (self._make_state()
                     if self._make_state is not None else None)
        except BaseException as e:  # builder creation failed: poison
            with self._lock:
                self._started += 1
                self._pool_error = e
                self._cv.notify_all()
            return
        with self._lock:
            self._started += 1
            self._alive += 1
        try:
            while True:
                with self._lock:
                    while not self._tasks and not self._stop:
                        self._cv.wait()
                    if self._stop:
                        return
                    gen, seq, payload = self._tasks.popleft()
                tel = active()
                try:
                    if tel is None:
                        res = ("ok", self._work(state, payload))
                    else:
                        # fmlint: disable=R003 -- feeds the pipeline/
                        # worker_build_seconds counter (per-worker
                        # aggregate; the build_worker span beside it is
                        # the timeline view)
                        t0 = _time.perf_counter()
                        with span("pipeline/build_worker"):
                            res = ("ok", self._work(state, payload))
                        # fmlint: disable=R003 -- closes the sample
                        tel.count("pipeline/worker_build_seconds",
                                  _time.perf_counter() - t0)
                except BaseException as e:  # delivered at wait(seq)
                    res = ("error", e)
                with self._lock:
                    if gen == self._gen:
                        self._results[seq] = res
                        self._cv.notify_all()
        finally:
            with self._lock:
                self._alive -= 1
                self._cv.notify_all()


class _Group:
    """One dispatched line group: the raw bytes of exactly one batch's
    worth of example-producing lines (newline-terminated), plus its
    stream provenance — the count of stream lines before it and inside
    it (for error rebasing and spill rewind)."""

    __slots__ = ("blob", "line_start", "lines")

    def __init__(self, blob: bytes, line_start: int, lines: int):
        self.blob = blob
        self.line_start = line_start
        self.lines = lines


class _GroupScanner:
    """Cuts the shard's byte stream into per-batch line groups for the
    parallel fast plane — the deterministic interleave the pool fans
    out over.

    Group invariant: every non-final group holds exactly B
    example-producing lines by the BUILDER'S OWN counting rule
    (cparser.scan_examples shares the C++ blank-line table), is
    newline-terminated, and never splits a line — so feeding it to a
    fresh-state builder yields exactly the batch the serial builder
    would emit at that stream position. The scan is memchr-speed C++;
    Python here only slices blobs and walks 4 MB chunks, so the
    coordinator thread stays far faster than the parse it feeds.

    ``file_spans`` and the consumed-line counter mirror the serial
    path's error-provenance map (_attach_stream_source); ``pushback``
    is the spill-rewind entry: unconsumed bytes return to the stream
    head and the line counter rewinds with them, so re-cut groups get
    the same line numbers they would have had serially."""

    def __init__(self, files: Sequence[str], shard_index: int,
                 num_shards: int, B: int, keep_empty: bool,
                 retry: Optional[RetryPolicy],
                 file_marks: Optional[FileMarks] = None):
        self._files = list(files)
        self._fi = 0
        self._chunks: Optional[Iterator[bytes]] = None
        self._buf = b""
        self._pos = 0
        self._B = B
        self._keep_empty = keep_empty
        self._retry = retry
        self._si, self._ns = shard_index, num_shards
        self._file_marks = file_marks
        self.lines = 0  # stream lines consumed into groups so far
        self.file_spans: List[Tuple[int, str, int, int]] = []

    def pushback(self, blob: bytes, line_start: int) -> None:
        self._buf = blob + self._buf[self._pos:]
        self._pos = 0
        self.lines = line_start

    def next_group(self) -> Optional[_Group]:
        from fast_tffm_tpu.data.cparser import scan_examples
        while True:
            found, consumed, nlines = scan_examples(
                self._buf, self._B, self._keep_empty, offset=self._pos)
            if found >= self._B:
                return self._cut(consumed, nlines)
            chunk = self._next_chunk()
            if chunk is None:
                if found:
                    g = self._cut(consumed, nlines)
                else:
                    g = None
                # Trailing blank lines (never example-producing) are
                # dropped — the serial path feeds them to the builder,
                # which skips them with no observable effect.
                self._buf = b""
                self._pos = 0
                return g
            self._buf = self._buf[self._pos:] + chunk
            self._pos = 0

    def _cut(self, consumed: int, nlines: int) -> _Group:
        g = _Group(self._buf[self._pos:self._pos + consumed],
                   self.lines, nlines)
        self._pos += consumed
        self.lines += nlines
        return g

    def _next_chunk(self) -> Optional[bytes]:
        while True:
            if self._chunks is not None:
                chunk = next(self._chunks, None)
                if chunk is not None:
                    return chunk
                self._chunks = None
                # File exhausted: terminate a newline-less final line
                # so its group cuts exactly where the serial path's
                # `feed(tail + b"\n")` would.
                tail = self._buf[self._pos:]
                if tail and not tail.endswith(b"\n"):
                    return b"\n"
            if self._fi >= len(self._files):
                return None
            path = self._files[self._fi]
            self._fi += 1
            start, end = shard_byte_range(path, self._si, self._ns)
            # Lines before this file = lines already consumed into
            # groups + complete lines still buffered (all from earlier
            # files; a newline-less tail was terminated above) — the
            # serial path's fed_lines at the same stream point.
            base = self.lines + self._buf.count(b"\n", self._pos)
            self.file_spans.append((base, path, start, end))
            if self._file_marks is not None:
                # base counts every stream line before this file; under
                # keep_empty (the only file_marks mode) lines ARE
                # examples, and a spill rewind re-counts to the same
                # values — the recorded base never moves.
                self._file_marks.start_file(path, base)
            self._chunks = _iter_owned_chunks(path, start, end,
                                              retry=self._retry)


class _FastWorkerState:
    """Per-worker build state: ONE BatchBuilder owned by one pool
    thread (the per-worker builder ownership the C++ concurrency
    contract requires), plus a mirror of its internal line counter for
    rebasing builder-relative error linenos onto the stream. Created
    inside the worker thread and never shared."""

    def __init__(self, make_builder):
        self._make_builder = make_builder
        self.bb = make_builder()
        self.fed = 0  # lines consumed by self.bb since creation

    def reset(self) -> None:
        # After a parse error the builder holds a half-built batch and
        # an unrecoverable line counter; a fresh builder restores both
        # invariants (the old handle frees via __del__).
        self.bb = self._make_builder()
        self.fed = 0


def _fast_group_work(state: _FastWorkerState, group: _Group):
    """Build ONE group (one batch's worth of lines) on a pool worker.
    Returns ``(finish_tuple, bytes_consumed)``; ``consumed <
    len(blob)`` IS the spill signal — the builder closed the batch
    early on the unique budget and left the offending line unconsumed,
    so the coordinator must rewind. ParseErrors rebase from
    builder-relative to stream-relative line numbers HERE, where the
    group's line offset is known; the coordinator then attaches file
    provenance exactly like the serial path."""
    bb = state.bb
    fed_before = state.fed
    try:
        _full, consumed = bb.feed(group.blob, 0)
        out = bb.finish()
    except ParseError as e:
        state.reset()
        m = _LINE_MSG.match(str(e))
        if m:
            k = int(m.group(1)) - fed_before
            raise ParseError(
                f"line {group.line_start + k}: {m.group(2)}") from None
        raise
    state.fed += (group.lines if consumed >= len(group.blob)
                  else group.blob[:consumed].count(b"\n"))
    return out, consumed


def _parallel_fast_batch_iterator(cfg: FmConfig, files: List[str],
                                  B: int, n_epochs: int, shuffle: bool,
                                  seed: Optional[int],
                                  fixed_shape: bool, shard_index: int,
                                  num_shards: int, uniq_bucket: int,
                                  stats: Optional[SpillStats],
                                  raw_ids: bool, keep_empty: bool,
                                  workers: int,
                                  file_marks: Optional[FileMarks] = None
                                  ) -> Iterator[DeviceBatch]:
    """Parallel host data plane, fast path: parse+hash+dedup+pack fans
    out across ``workers`` pool threads — each owning its own C++
    BatchBuilder — over a deterministic per-batch interleave of the
    shard's line groups; finished batches re-serialize through a
    bounded ordered ring (_BuildRing) that the existing prefetch() H2D
    stage drains.

    Parity guarantee (pinned by tests/test_parallel_pipeline.py): the
    emitted batch stream is BIT-IDENTICAL to ``host_threads = 1`` for
    the same config/seed. The load-bearing pieces:

    - groups are cut at example boundaries by the builder's own
      counting rule (_GroupScanner), so group k's lines are exactly
      serial batch k's lines;
    - each group meets a fresh-state builder (finish() resets; the C++
      library clears row buffers per batch), so batch arrays cannot
      depend on which worker built them or what it built before;
    - batches re-serialize in group order, and all shuffle-window/rng
      work happens in the shared _BatchEmitter on the consuming side —
      same rng, same draw order as serial;
    - a unique-budget spill (fixed-U mode) invalidates every in-flight
      group past it and re-cuts from the spilled line — the serial
      stream's requeue replayed at group granularity; speculative work
      is discarded, never emitted (spills cost a little wasted build,
      never correctness, mirroring the spill protocol's own contract).
    """
    from fast_tffm_tpu.obs.telemetry import active
    spill_capable = bool(fixed_shape and uniq_bucket)
    feed_threads = _worker_feed_threads(workers, spill_capable)
    make_builder = functools.partial(_make_builder, cfg, B, raw_ids,
                                     keep_empty, fixed_shape,
                                     uniq_bucket, feed_threads)
    emitter = _BatchEmitter(cfg, B, effective_L_cap(cfg), fixed_shape,
                            uniq_bucket, shuffle, seed, stats)
    retry = RetryPolicy.from_config(cfg)
    file_seed = cfg.seed if seed is None else seed
    ring = _BuildRing(workers, depth=2 * workers,
                      work=_fast_group_work,
                      make_state=lambda: _FastWorkerState(make_builder))
    tel = active()
    if tel is not None:
        tel.set("pipeline/host_threads", workers)
    try:
        for epoch in range(n_epochs):
            scanner = _GroupScanner(
                epoch_file_order(files, shuffle, file_seed, epoch),
                shard_index, num_shards, B, keep_empty, retry,
                file_marks=file_marks)
            inflight: Dict[int, _Group] = {}
            order: collections.deque = collections.deque()
            scan_done = False
            while True:
                while not scan_done and len(inflight) < ring.depth:
                    g = scanner.next_group()
                    if g is None:
                        scan_done = True
                        break
                    s = ring.submit(g)
                    inflight[s] = g
                    order.append(s)
                if not order:
                    break
                s = order.popleft()
                g = inflight.pop(s)
                kind, payload = ring.wait(s)
                if tel is not None:
                    tel.set("pipeline/ring_occupancy",
                            ring.occupancy())
                if kind == "error":
                    if isinstance(payload, ParseError):
                        raise _attach_stream_source(
                            payload, scanner.file_spans,
                            num_shards) from None
                    raise payload
                out, consumed = payload
                spilled = consumed < len(g.blob)
                yield from emitter.emit_drain(out, spilled)
                if spilled:
                    # Rewind: the unconsumed tail of this group plus
                    # every in-flight group after it returns to the
                    # scanner, which re-cuts from the spilled line —
                    # exactly the lines the serial builder would open
                    # the next batch with.
                    lines_used = g.blob[:consumed].count(b"\n")
                    leftover = g.blob[consumed:] + b"".join(
                        inflight[t].blob for t in order)
                    ring.invalidate_after(s)
                    inflight.clear()
                    order.clear()
                    scanner.pushback(leftover,
                                     g.line_start + lines_used)
                    scan_done = False
            yield from emitter.flush_window()
    finally:
        ring.close()


def _fast_batch_iterator(cfg: FmConfig, bb, files: List[str], B: int,
                         n_epochs: int, shuffle: bool,
                         seed: Optional[int], fixed_shape: bool,
                         shard_index: int = 0, num_shards: int = 1,
                         uniq_bucket: int = 0,
                         stats: Optional[SpillStats] = None,
                         file_marks: Optional[FileMarks] = None
                         ) -> Iterator[DeviceBatch]:
    """Chunked C++ fast path: raw file bytes stream straight into the
    C++ BatchBuilder (parse + hash + dedup + padded scatter in one native
    pass); Python never touches individual lines. Sharded input reads
    only this worker's byte ranges (shard_byte_range) — N workers read
    each byte once, not N times.

    Shuffle here is a window-of-batches pick plus a within-batch row
    permutation — the same mixing radius as the reference's bounded
    shuffle queue of ``queue_size`` lines (SURVEY §2 "Input pipeline"),
    expressed at batch granularity. Exact reservoir-per-line semantics
    remain on the generic path (weight files or an unavailable C++
    extension force it; FFM and keep_empty both ride this fast path —
    field-aware tokens and blank-line examples are builder modes).

    With ``uniq_bucket`` (fixed_shape multi-process mode) the builder
    caps each batch's unique rows; a too-dense batch closes early with
    n < B real examples (the spill protocol) and shapes stay constant.

    Emission (stats counting, window shuffle, per-batch row
    permutation) is the shared _BatchEmitter — the same code the
    parallel plane's ring coordinator runs, which is what makes
    ``host_threads`` a pure throughput knob (bit-identical streams).
    """
    emitter = _BatchEmitter(cfg, B, bb.L, fixed_shape, uniq_bucket,
                            shuffle, seed, stats)

    tail = b""
    fed_lines = 0       # complete lines fed to the builder so far —
    # mirrors the C++ builder's internal lineno (it counts every fed
    # line; a spilled line is re-fed but counted once on both sides)
    file_spans: List[Tuple[int, str, int, int]] = []  # (lines_before,
    # path, start, end) per file fed — the provenance map builder
    # "line N" errors resolve against (threaded feeds DEFER errors, so
    # one can surface while a later file is being fed)

    def feed_all(data: bytes) -> Iterator[DeviceBatch]:
        nonlocal tail, fed_lines
        fed_lines += data.count(b"\n")  # complete lines get consumed
        off = 0
        while True:
            full, consumed = bb.feed(data, off)
            off += consumed
            if not full:
                break
            out = bb.finish()
            # The builder returns "full" either at B examples or when a
            # line would blow the unique budget — the latter closes the
            # batch short (the spill being counted).
            yield from emitter.emit_drain(out, spilled=out[0] < B)
        tail = data[off:]  # unconsumed partial line, re-fed next chunk

    retry = RetryPolicy.from_config(cfg)
    file_seed = cfg.seed if seed is None else seed
    try:
        for epoch in range(n_epochs):
            for path in epoch_file_order(files, shuffle, file_seed,
                                         epoch):
                start, end = shard_byte_range(path, shard_index,
                                              num_shards)
                tail = b""
                file_spans.append((fed_lines, path, start, end))
                if file_marks is not None:
                    # fed_lines at file start == examples before it
                    # (keep_empty: every line is an example; batches
                    # holding this file's lines are yielded only from
                    # feeds AFTER this append).
                    file_marks.start_file(path, fed_lines)
                for chunk in _iter_owned_chunks(path, start, end,
                                                retry=retry):
                    yield from feed_all(tail + chunk if tail else chunk)
                if tail:  # final owned line missing its newline
                    yield from feed_all(tail + b"\n")
            out = bb.finish()
            if out[0]:  # short final batch of the epoch
                yield from emitter.emit_drain(out, spilled=False)
            yield from emitter.flush_window()
    except ParseError as e:
        raise _attach_stream_source(e, file_spans, num_shards) from None


def _attach_stream_source(e: ParseError,
                          file_spans: Sequence[Tuple[int, str, int,
                                                     int]],
                          num_shards: int) -> ParseError:
    """Rewrite a builder-stream ParseError ("line N: ..." where N
    counts every line fed to the builder since its creation) with the
    owning file's path and the absolute file line number. The span map
    is searched rather than assuming the current file: the threaded
    builder defers a parse error until batch consumption reaches it,
    which can be while a LATER file is feeding."""
    m = _LINE_MSG.match(str(e))
    if not m or not file_spans:
        return e
    n = int(m.group(1))
    owner = file_spans[0]
    for span_rec in file_spans:
        if span_rec[0] < n:
            owner = span_rec
        else:
            break
    base, path, start, end = owner
    try:
        abs_ln = _owned_start_line_index(path, start) + (n - base)
    except OSError:
        return ParseError(f"{path}: {e}")
    note = (f", shard bytes {start}-{end}" if num_shards > 1 else "")
    return ParseError(f"{path} line {abs_ln}{note}: {m.group(2)}")


def _num_uniq(uniq_ids, pad_id: int) -> int:
    """Real unique-row count of a host-deduped uniq array (pad_id slots
    are fill; no real feature id can equal it). 0 for raw-ids (None).
    The ONE counting rule for both pipeline paths — the shrink decision
    in train.adapt_uniq_bucket compares their stats directly."""
    if uniq_ids is None:
        return 0
    return int((uniq_ids != pad_id).sum())


def _batch_num_uniq(batch: DeviceBatch, cfg: FmConfig) -> int:
    return _num_uniq(batch.uniq_ids, cfg.pad_id)


def batch_iterator(cfg: FmConfig, files: Sequence[str],
                   training: bool = True,
                   weight_files: Sequence[str] = (),
                   shard_index: int = 0, num_shards: int = 1,
                   epochs: Optional[int] = None,
                   batch_size: Optional[int] = None,
                   seed: Optional[int] = None,
                   keep_empty: bool = False,
                   fixed_shape: bool = False,
                   uniq_bucket: int = 0,
                   stats: Optional[SpillStats] = None,
                   raw_ids: bool = False,
                   bad_lines: Optional[BadLineTracker] = None,
                   file_marks: Optional[FileMarks] = None,
                   vocab=None
                   ) -> Iterator[DeviceBatch]:
    """Epoch/shuffle/batch loop over text files (see _batch_iterator_impl
    for the full contract). This wrapper is the pipeline's telemetry
    seam: with a run's metrics active (obs/), each built batch feeds
    the pipeline counters (examples, padding waste, dedup inputs) and
    a build-seconds histogram — timed HERE, on the producing side, so
    under prefetch it measures actual build cost on the worker thread,
    not consumer stall. Inactive (the default), batches pass straight
    through.

    ``vocab`` (a vocab.VocabMap/VocabRuntime; vocab_mode = admit) is
    ALSO seamed here: the inner iterator builds batches in the hashed
    id space (``vocab.build_cfg`` — same config, vocabulary_size
    swapped for the 2^30 hash space, so every parser/builder below
    mods into it), and every emitted batch is remapped to physical
    rows before anything downstream — telemetry included — sees it.
    None (the default, and always for vocab_mode = fixed) is
    bit-identical to the historical pipeline."""
    from fast_tffm_tpu.obs.telemetry import active
    it = _batch_iterator_impl(cfg if vocab is None
                              else vocab.build_cfg(cfg), files,
                              training=training,
                              weight_files=weight_files,
                              shard_index=shard_index,
                              num_shards=num_shards, epochs=epochs,
                              batch_size=batch_size, seed=seed,
                              keep_empty=keep_empty,
                              fixed_shape=fixed_shape,
                              uniq_bucket=uniq_bucket, stats=stats,
                              raw_ids=raw_ids, bad_lines=bad_lines,
                              file_marks=file_marks)
    tel = active()
    if tel is None:
        if vocab is None:
            yield from it
        else:
            for batch in it:
                yield vocab.remap(batch)
        return
    import time as _time
    from fast_tffm_tpu.obs.trace import span
    pad_id = cfg.pad_id
    while True:
        # fmlint: disable=R003 -- feeds the pipeline/batch_build_seconds
        # histogram (always-on aggregate); the span beside it is the
        # timeline view and is a no-op unless the run traces
        t0 = _time.perf_counter()
        # span (obs/trace): the same interval, as a timeline event on
        # the producing (prefetch) thread's track.
        with span("pipeline/build"):
            batch = next(it, None)
        if batch is None:
            return
        if vocab is not None:
            # Remap INSIDE the build bracket (it is build cost) and
            # before pipeline_batch: the padding-waste counter must
            # see the physical pad_id the remap writes.
            batch = vocab.remap(batch)
        # fmlint: disable=R003 -- closes the build-seconds sample
        tel.pipeline_batch(batch, pad_id,
                           build_seconds=_time.perf_counter() - t0)
        yield batch


def _batch_iterator_impl(cfg: FmConfig, files: Sequence[str],
                         training: bool = True,
                         weight_files: Sequence[str] = (),
                         shard_index: int = 0, num_shards: int = 1,
                         epochs: Optional[int] = None,
                         batch_size: Optional[int] = None,
                         seed: Optional[int] = None,
                         keep_empty: bool = False,
                         fixed_shape: bool = False,
                         uniq_bucket: int = 0,
                         stats: Optional[SpillStats] = None,
                         raw_ids: bool = False,
                         bad_lines: Optional[BadLineTracker] = None,
                         file_marks: Optional[FileMarks] = None
                         ) -> Iterator[DeviceBatch]:
    """Epoch/shuffle/batch loop over text files.

    Shuffling is a bounded reservoir of ``cfg.queue_size`` lines, the same
    memory/coverage contract as the reference's shuffle queue (SURVEY §2
    "Input pipeline"); deterministic given ``seed``.

    ``uniq_bucket`` (fixed_shape mode): fixed unique-row count per batch
    — see probe_uniq_bucket. Overfull batches spill: they close early
    with fewer real examples and the remainder opens the next batch.

    ``raw_ids`` (dedup=device): skip the host unique pass; batches carry
    raw ids in local_idx and uniq_ids=None (models/fm dedups on device).

    ``bad_lines``: the run-scoped BadLineTracker when the caller owns
    one (train passes a single tracker through every epoch so the
    bad-fraction breaker and the quarantine dedupe see the whole run);
    with a tolerant ``cfg.bad_line_policy`` and no caller tracker, one
    is created per iteration (evaluate/predict). Tolerant policies
    ride the generic path — the streaming C++ builder stays
    all-or-nothing (_fast_path_eligible) and per-line failures are
    reported through the block-level salvage parse
    (cparser.parse_lines_salvage).
    """
    from fast_tffm_tpu.data.parser import parse_lines
    from fast_tffm_tpu.data.cparser import parse_lines_fast

    if weight_files:
        # Sidecars expand PER PATTERN PAIR (expand_paired_files): a flat
        # post-expansion zip can silently pair weights to the wrong
        # files when multiple patterns are in play; the per-pair count
        # check fails loudly instead (ADVICE round 5). The count check
        # in _iter_lines still catches sets drifting between expansion
        # and open.
        files, weight_files = expand_paired_files(files, weight_files)
    else:
        files = expand_files(files)
        weight_files = ()
    B = batch_size or cfg.batch_size
    n_epochs = epochs if epochs is not None else (cfg.epoch_num if training
                                                  else 1)
    rng = random.Random(cfg.seed if seed is None else seed)
    do_shuffle = training and cfg.shuffle
    uniq_bucket = uniq_bucket or cfg.uniq_bucket
    if raw_ids and fixed_shape:
        raise ValueError("raw_ids (dedup=device) has no fixed-U protocol; "
                         "multi-process mode needs dedup=host")
    if file_marks is not None:
        # The ledger maps example offsets to files; that mapping only
        # exists for a single in-order keep_empty pass (one example per
        # line, no reordering, no re-reads).
        if not keep_empty or do_shuffle or n_epochs != 1 or weight_files:
            raise ValueError(
                "file_marks requires keep_empty=True, a single epoch, "
                "no shuffle, and no weight sidecars (the per-file "
                "example-offset ledger is only meaningful for an "
                "in-order one-example-per-line pass)")

    # Chunked C++ fast path (see _fast_batch_iterator): applies whenever
    # no feature needs per-line Python handling — including sharded
    # multi-process input (byte ranges), field-aware FFM tokens, and
    # keep_empty line alignment (predict). With host_threads > 1 the
    # same path fans out across the parallel data plane's worker pool
    # (bit-identical stream; README "Data plane"). The routing
    # predicate is host_parallel_workers — the SAME one train's
    # startup log reports, so the log can't claim a fan-out this
    # function won't perform.
    workers = host_parallel_workers(cfg, weight_files, keep_empty,
                                    fixed_shape)
    if _fast_path_eligible(cfg, weight_files):
        if workers > 1:
            yield from _parallel_fast_batch_iterator(
                cfg, files, B, n_epochs, do_shuffle, seed, fixed_shape,
                shard_index, num_shards, uniq_bucket, stats, raw_ids,
                keep_empty, workers, file_marks=file_marks)
            return
        try:
            bb = _make_builder(cfg, B, raw_ids, keep_empty, fixed_shape,
                               uniq_bucket)
        except RuntimeError:
            bb = None  # C++ extension unavailable -> generic path
        if bb is not None:
            yield from _fast_batch_iterator(cfg, bb, files, B, n_epochs,
                                            do_shuffle, seed, fixed_shape,
                                            shard_index, num_shards,
                                            uniq_bucket, stats=stats,
                                            file_marks=file_marks)
            return
    # Blank-line-preserving parse rides the C++ block parser too since
    # ABI 7 (keep_empty mode); _parse_block threads the flag through.
    parse = parse_lines_fast
    retry = RetryPolicy.from_config(cfg)
    tracker = bad_lines
    own_tracker = False
    if tracker is None:
        tracker = BadLineTracker.from_config(cfg)
        own_tracker = tracker is not None

    def parse_chunk(chunk, precounted: int = 0):
        """One pending chunk -> (surviving chunk, block, weights).

        Error policy: a ParseError propagates with the failing line's
        file/lineno/shard provenance attached. Tolerant policies: bad
        lines are recorded in the tracker (which may raise the
        max_bad_fraction breaker) and dropped from the chunk — except
        under keep_empty, where the parser already replaced them with
        zero-feature examples so predict's line alignment holds.

        ``precounted``: the first this-many chunk items already passed
        through the tracker on an earlier pass (a UniqOverflow spill
        requeues its tail at the front of pending) — they must not
        count or record a second time, or spills would inflate the
        totals and break the skip-count-equals-injected contract."""
        lines = [c[0] for c in chunk]
        if tracker is None:
            try:
                block = _parse_block(lines, cfg, parse, keep_empty)
            except ParseError as e:
                raise _attach_block_source(
                    e, [c[2] for c in chunk]) from None
        else:
            bads: List[Tuple[int, str, str]] = []
            block = _salvage_block(lines, cfg, keep_empty, bads)
            fresh_bads = [b for b in bads if b[0] >= precounted]
            tracker.count_ok(len(lines) - precounted
                             - len(fresh_bads))
            if fresh_bads:
                for i, raw, msg in fresh_bads:
                    path, abs_ln = _source_lineno(chunk[i][2])
                    tracker.record(path, abs_ln, raw,
                                   _strip_line_prefix(msg))
            if bads and not keep_empty:
                badset = {i for i, _, _ in bads}
                chunk = [c for i, c in enumerate(chunk)
                         if i not in badset]
        w = np.array([c[1] for c in chunk], dtype=np.float32)
        return chunk, block, w

    # Generic-path fan-out (tolerant bad-line policies): chunk
    # composition is line-order-deterministic — a bad line drops from
    # the parsed BLOCK, never shifts the B-line chunk boundaries — and
    # with fixed_shape off no UniqOverflow can reorder the stream, so
    # each chunk's parse+build is an independent task farmed to the
    # pool and re-serialized in submit order (same bounded ordered
    # ring as the fast plane). The run-scoped LOCKED tracker is shared
    # by every worker, so the max_bad_fraction breaker and the
    # quarantine (file, lineno) dedupe stay global; only the ORDER of
    # quarantine records may interleave across workers — the set is
    # identical, pinned by the parity tests. keep_empty rides the pool
    # too (ABI 7: the C++ parser preserves blanks, and a bad line
    # becomes a zero-feature example — boundaries can't shift at all);
    # weighted and fixed-shape inputs stay serial (GIL-bound pairing
    # and the spill-requeue's sequential composition).
    pool: Optional[_BuildRing] = None
    pool_order: collections.deque = collections.deque()
    if tracker is not None and workers > 1:
        # workers > 1 already folds in the route conditions (C++
        # available, no weights/fixed_shape; keep_empty allowed since
        # ABI 7) via host_parallel_workers above.
        def _pool_work(_state, payload):
            raw_chunk, precounted = payload
            chunk, block, w = parse_chunk(raw_chunk,
                                          precounted=precounted)
            if block.batch_size == 0:
                return None  # every line of the chunk was bad
            return make_device_batch(block, cfg, weights=w,
                                     batch_size=B,
                                     fixed_shape=fixed_shape,
                                     uniq_bucket=uniq_bucket,
                                     raw_ids=raw_ids)
        pool = _BuildRing(workers, depth=2 * workers,
                          work=_pool_work)
        from fast_tffm_tpu.obs.telemetry import active as _active
        _tel = _active()
        if _tel is not None:
            _tel.set("pipeline/host_threads", workers)

    def pool_drain(limit: int) -> Iterator[DeviceBatch]:
        """Yield completed pool batches in submit order: every
        already-finished head eagerly, plus (blocking) enough to keep
        the in-flight count within ``limit`` (0 = drain everything)."""
        from fast_tffm_tpu.obs.telemetry import active as _active
        tel = _active()
        while pool_order and (len(pool_order) > limit
                              or pool.has(pool_order[0])):
            s = pool_order.popleft()
            kind, val = pool.wait(s)
            if tel is not None:
                tel.set("pipeline/ring_occupancy", pool.occupancy())
            if kind == "error":
                raise val
            if val is None:
                continue  # all-bad chunk: nothing to emit
            if stats is not None:
                stats.count(val.num_real, B, False,
                            num_uniq=_batch_num_uniq(val, cfg))
            yield val

    file_seed = cfg.seed if seed is None else seed
    try:
        for epoch in range(n_epochs):
            pending: List[Tuple[str, float, tuple]] = []
            buf: List[Tuple[str, float, tuple]] = []
            # How many FRONT items of `pending` already passed through
            # the tracker (spill-requeued tails); see parse_chunk.
            requeue_counted = [0]

            def flush_batches(done: bool):
                while len(pending) >= B or (done and pending):
                    raw_chunk = pending[:B]
                    del pending[:B]
                    k = min(requeue_counted[0], len(raw_chunk))
                    requeue_counted[0] -= k
                    if pool is not None:
                        pool_order.append(pool.submit((raw_chunk, k)))
                        yield from pool_drain(pool.depth)
                        continue
                    chunk, block, w = parse_chunk(raw_chunk,
                                                  precounted=k)
                    if tracker is not None and block.batch_size == 0:
                        continue  # every line of the chunk was bad
                    try:
                        out = make_device_batch(block, cfg, weights=w,
                                                batch_size=B,
                                                fixed_shape=fixed_shape,
                                                uniq_bucket=uniq_bucket,
                                                raw_ids=raw_ids)
                        if stats is not None:
                            stats.count(out.num_real, B, False,
                                        num_uniq=_batch_num_uniq(out,
                                                                 cfg))
                        yield out
                    except UniqOverflow:
                        # Spill: emit the longest example prefix that
                        # fits the unique budget; the tail reopens the
                        # queue.
                        m = _uniq_prefix_examples(block, uniq_bucket)
                        if m == 0:
                            raise ValueError(
                                "single example exceeds uniq_bucket "
                                f"{uniq_bucket}; raise it (or set 0 "
                                "for auto)")
                        pending[0:0] = chunk[m:]
                        if tracker is not None:
                            # The requeued tail is already tracked; it
                            # must not count/record again next pass.
                            requeue_counted[0] += len(chunk) - m
                        # Re-parse of already-validated survivors: no
                        # tracker (they were counted once above).
                        head = _parse_block([c[0] for c in chunk[:m]],
                                            cfg, parse, keep_empty,
                                            salvage=tracker is not None)
                        out = make_device_batch(head, cfg,
                                                weights=w[:m],
                                                batch_size=B,
                                                fixed_shape=fixed_shape,
                                                uniq_bucket=uniq_bucket)
                        if stats is not None:
                            stats.count(out.num_real, B, True,
                                        num_uniq=_batch_num_uniq(out,
                                                                 cfg))
                        yield out

            for item in _iter_lines(
                    epoch_file_order(files,
                                     do_shuffle and not weight_files,
                                     file_seed, epoch),
                    weight_files,
                    shard_index, num_shards, keep_empty=keep_empty,
                    retry=retry, file_marks=file_marks):
                if do_shuffle:
                    buf.append(item)
                    if len(buf) >= max(cfg.queue_size, B):
                        j = rng.randrange(len(buf))
                        buf[j], buf[-1] = buf[-1], buf[j]
                        pending.append(buf.pop())
                else:
                    pending.append(item)
                yield from flush_batches(False)
            if do_shuffle and buf:
                rng.shuffle(buf)
                pending.extend(buf)
            yield from flush_batches(True)
            if pool is not None:  # epoch barrier: ring fully drained
                yield from pool_drain(0)
    finally:
        if pool is not None:
            pool.close()
        if own_tracker:
            tracker.close()


def _uniq_prefix_examples(block: ParsedBlock, uniq_bucket: int) -> int:
    """Largest count of leading examples whose id union fits the unique
    bucket (one slot reserved for padding) — the generic-path spill
    split point."""
    if block.batch_size == 0:
        return 0
    _, first_pos = np.unique(block.ids, return_index=True)
    # Example index owning each first occurrence -> uniques per example.
    ex = np.searchsorted(block.poses, first_pos, side="right") - 1
    cum = np.cumsum(np.bincount(ex, minlength=block.batch_size))
    return int(np.searchsorted(cum, uniq_bucket - 1, side="right"))


def probe_uniq_bucket(cfg: FmConfig, files: Sequence[str],
                      batch_size: Optional[int] = None) -> int:
    """Pick the fixed unique-row bucket for multi-process training by
    measuring the data instead of assuming the worst case (the ladder
    top is next_pow2(B*L) — ~50x a realistic Criteo batch's uniques).

    Parses one batch each from the head, middle, and tail of the FIRST,
    LAST, and LARGEST files (day-partitioned datasets whose later files
    are denser would defeat a first-file-only probe) — every process
    reads the same bytes, so all agree without a collective — and
    returns the next power of two >= 2x the max measured unique count
    (>= 64, > the per-example cap, <= the ladder top). Densities the
    probe still missed are absorbed by the spill protocol, costing
    throughput, never correctness — counted by SpillStats, warned at
    epoch end, and recovered by train()'s epoch-boundary bucket raise.
    """
    B = batch_size or cfg.batch_size
    files = expand_files(files)
    top = _uniq_ladder(B, effective_L_cap(cfg))[-1]
    retry = RetryPolicy.from_config(cfg)
    from fast_tffm_tpu.data.cparser import parse_lines_fast
    parse = parse_lines_fast
    # Tolerant bad-line policies must not die in the PROBE on a line
    # the training sweep would skip: the probe's density estimate
    # simply ignores bad lines (they are recorded/counted later, when
    # the real iterators scan them).
    tolerant = getattr(cfg, "bad_line_policy", "error") != "error"

    cand = sorted({files[0], files[-1],
                   max(files, key=os.path.getsize)})
    u_max = 0
    got_lines = False
    for path in cand:
        size = retry_io(os.path.getsize, path, policy=retry,
                        op="probe_stat")
        for start in sorted({0, size // 3, 2 * size // 3}):
            lines: List[str] = []
            for line in _iter_range_lines(path, start, size,
                                          retry=retry):
                if line.strip(WHITESPACE):
                    lines.append(line)
                if len(lines) >= B:
                    break
            if not lines:
                continue
            got_lines = True
            try:
                block = _parse_block(lines[:B], cfg, parse,
                                     salvage=tolerant)
            except ParseError as e:
                raise ParseError(f"{path} (uniq-bucket probe near "
                                 f"byte {start}): "
                                 f"{_strip_line_prefix(str(e))}"
                                 ) from None
            u_max = max(u_max, len(np.unique(block.ids)))
    if not got_lines:
        return min(1 << 10, top)
    b = 64
    while b < 2 * (u_max + 2) or b <= cfg.max_features_per_example:
        b *= 2
    return min(b, top)


def uniq_bucket_top(cfg: FmConfig, batch_size: Optional[int] = None) -> int:
    """The worst-case unique bucket (ladder top) — the ceiling for
    train()'s epoch-boundary adaptive raise."""
    return _uniq_ladder(batch_size or cfg.batch_size,
                        effective_L_cap(cfg))[-1]


def empty_batch(cfg: FmConfig, batch_size: Optional[int] = None,
                uniq_bucket: int = 0) -> DeviceBatch:
    """An all-padding batch (num_real=0, zero weights): the SPMD filler a
    data-exhausted process feeds while peers finish their shards — every
    term it contributes to loss/grad/reg is exactly zero by the padding
    invariants above. ``uniq_bucket`` must match the live batches'."""
    fields = (np.zeros(0, np.int32) if cfg.model_type == "ffm" else None)
    block = ParsedBlock(labels=np.zeros(0, np.float32),
                        poses=np.zeros(1, np.int32),
                        ids=np.zeros(0, np.int32),
                        vals=np.zeros(0, np.float32), fields=fields)
    return make_device_batch(block, cfg, batch_size=batch_size,
                             fixed_shape=True,
                             uniq_bucket=uniq_bucket or cfg.uniq_bucket)


def _fast_path_eligible(cfg: FmConfig,
                        weight_files: Sequence[str]) -> bool:
    """The ONE gate for the chunked C++ fast path: no per-line Python
    handling (weight sidecars pair weights to lines in Python), a
    hard per-example cap (the builder writes fixed-stride rows;
    max_features_per_example = 0 means "unlimited" and stays generic),
    and the strict bad-line policy — the streaming builder is
    all-or-nothing on a parse error by design (its batch state is not
    recoverable mid-line), so skip/quarantine tolerance lives on the
    generic path, whose blocks still parse through the C++ block
    parser with a per-line Python salvage retry only for a FAILING
    block (cparser.parse_lines_salvage).
    batch_iterator's path selection and gil_bound_iteration's
    GIL-contention answer must agree, so both call here — a hand-copied
    predicate drifting between them would silently thread a GIL-bound
    iterator (or passthrough a releasing one)."""
    return (not weight_files and cfg.max_features_per_example > 0
            and getattr(cfg, "bad_line_policy", "error") == "error")


def gil_bound_iteration(cfg: FmConfig, weight_files: Sequence[str] = (),
                        keep_empty: bool = False) -> bool:
    """Whether batch_iterator's iteration for these inputs is dominated
    by GIL-holding Python work — the SAME path selection
    batch_iterator makes (_fast_path_eligible), exposed so prefetch
    callers can gate the worker thread on it. That happens when the
    C++ extension is unavailable, on the generic keep_empty shapes
    (their block parse is C++ since ABI 7, but the per-line Python
    iteration of _iter_lines still holds the GIL), and on the WEIGHTED
    path: its block parse is C++ (GIL released) but the per-line weight
    pairing (readline/float/strip and a Python yield per line) holds
    the GIL — threading it on a single core is the contention class
    the gate exists to passthrough."""
    from fast_tffm_tpu.data import cparser
    if not cparser.available():
        return True
    if weight_files:
        return True
    if getattr(cfg, "bad_line_policy", "error") != "error":
        # Tolerant policies ride the generic path: C++ block parse
        # (GIL released) but per-line Python iteration holds the GIL —
        # the weighted path's contention class.
        return True
    return (not _fast_path_eligible(cfg, weight_files)) and keep_empty


def prefetch(iterator: Iterator[DeviceBatch], depth: int = 2,
             gil_bound: bool = False) -> Iterator[DeviceBatch]:
    """Run ``iterator`` in a background thread, ``depth`` batches ahead.

    The reference overlaps input with compute via TF queue-runner threads
    (SURVEY §2 "Input pipeline"); here one host thread prepares the next
    batches while the device runs the current step. The C++ parser,
    numpy, and the device-transfer waits all release the GIL, so the
    overlap is real even on a single-core host: the builder thread runs
    while the consumer waits on H2D (measured on the 1-core tunnelled
    chip, round 4: threaded 825-857k ex/s vs serial 447-790k at bench
    shapes, and never slower across dedup modes).

    ``gil_bound`` (see gil_bound_iteration): the iterator parses in pure
    Python and would CONTEND with jax dispatch on a single core
    (measured 4x slower in round 2, when Python was the only parser) —
    that combination keeps the passthrough.
    """
    if gil_bound:
        if _host_cpus() <= 1:
            yield from iterator
            return

    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
    sentinel = object()
    stop = threading.Event()
    errbox: List[BaseException] = []

    def worker():
        try:
            for item in iterator:
                # Bounded put + stop checks so an abandoned consumer
                # (step raised, caller broke out) can't strand this
                # thread blocked forever holding file handles/batches.
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # re-raised on the consumer side
            errbox.append(e)
        finally:
            # Same bounded-put dance: a live consumer must get the
            # sentinel, a gone one (stop set) must not block us.
            while not stop.is_set():
                try:
                    q.put(sentinel, timeout=0.1)
                    break
                except queue.Full:
                    continue

    # Named thread: span events from the pipeline carry the thread name
    # as their Perfetto track (tools/fmtrace).
    threading.Thread(target=worker, name="prefetch", daemon=True).start()
    ledgered = False
    try:
        while True:
            item = q.get()
            if item is sentinel:
                if errbox:
                    raise errbox[0]
                return
            if not ledgered:
                # Ledger (obs/memory.py): the prefetch window's
                # standing footprint — queue depth + the in-hand batch,
                # sized from the first batch (bucketed shapes keep
                # later ones comparable). Host-resident numpy until the
                # wire layer places it (host=True: gauged, excluded
                # from the device live total). Once, not per batch —
                # this is the hottest host loop in the tree.
                ledgered = True
                nb = 0
                for v in getattr(item, "__dict__", {}).values():
                    nb += getattr(v, "nbytes", 0)
                if nb:
                    from fast_tffm_tpu.obs.memory import LEDGER
                    LEDGER.register("prefetch_batches",
                                    (max(depth, 1) + 1) * nb,
                                    host=True)
            yield item
    finally:
        stop.set()
        from fast_tffm_tpu.obs.memory import LEDGER
        LEDGER.release("prefetch_batches")


def _salvage_block(lines: Sequence[str], cfg: FmConfig,
                   keep_empty: bool,
                   bads: List[Tuple[int, str, str]]) -> ParsedBlock:
    """The ONE cfg -> parse_lines_salvage plumbing (tolerant block
    parse; cparser). Every tolerant call site goes through here so a
    future parser knob can't be threaded into one site and missed in
    another."""
    from fast_tffm_tpu.data.cparser import parse_lines_salvage
    return parse_lines_salvage(
        lines, cfg.vocabulary_size,
        hash_feature_id=cfg.hash_feature_id,
        field_aware=cfg.model_type == "ffm", field_num=cfg.field_num,
        max_features_per_example=cfg.max_features_per_example,
        keep_empty=keep_empty, bad_lines=bads)


def _parse_block(lines: Sequence[str], cfg: FmConfig, fast_parse,
                 keep_empty: bool = False,
                 salvage: bool = False) -> ParsedBlock:
    from fast_tffm_tpu.data.parser import parse_lines
    field_aware = cfg.model_type == "ffm"
    if salvage:
        # Tolerant re-parse (the generic path's spill split re-parses
        # survivor lines whose bad neighbors were already recorded):
        # bad lines drop silently instead of raising.
        return _salvage_block(lines, cfg, keep_empty, [])
    if fast_parse is not None:
        try:
            return fast_parse(
                lines, cfg.vocabulary_size,
                hash_feature_id=cfg.hash_feature_id,
                field_aware=field_aware, field_num=cfg.field_num,
                max_features_per_example=cfg.max_features_per_example,
                keep_empty=keep_empty)
        except (OSError, RuntimeError):
            pass  # C++ extension unavailable -> Python fallback
    return parse_lines(
        lines, cfg.vocabulary_size, hash_feature_id=cfg.hash_feature_id,
        field_aware=field_aware, field_num=cfg.field_num,
        max_features_per_example=cfg.max_features_per_example,
        keep_empty=keep_empty)
