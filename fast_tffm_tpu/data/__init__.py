from fast_tffm_tpu.data.hashing import murmur64, hash_feature  # noqa: F401
from fast_tffm_tpu.data.parser import ParsedBlock, parse_lines  # noqa: F401
