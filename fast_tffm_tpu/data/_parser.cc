// C++ libsvm line parser — the throughput path of the fm_parser contract.
//
// The reference implements batch text->CSR parsing as a multithreaded C++
// TensorFlow custom op (upstream cc/fm_parser.cc; SURVEY.md §2). This is
// the same job as a dependency-free shared object driven through ctypes
// (fast_tffm_tpu/data/cparser.py): a newline-separated blob of
//     <label> <fid>[:<fval>] ...
// lines in, CSR arrays out. Semantics must match the Python parser
// (fast_tffm_tpu/data/parser.py) bit-for-bit — including MurmurHash64A
// feature hashing — and golden tests (tests/test_cparser.py) enforce it.
//
// Parallelism: lines are sliced into contiguous ranges, one thread per
// range parsing into private buffers, stitched in order afterwards, so
// output ordering is identical to single-threaded parsing.

#include <cctype>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// MurmurHash64A (Austin Appleby, public domain), seed 0 — must match
// fast_tffm_tpu/data/hashing.py (golden tests pin both).
uint64_t murmur64(const char* key, size_t len, uint64_t seed) {
  const uint64_t m = 0xc6a4a7935bd1e995ULL;
  const int r = 47;
  uint64_t h = seed ^ (len * m);
  const unsigned char* data = reinterpret_cast<const unsigned char*>(key);
  const size_t nblocks = len / 8;
  for (size_t i = 0; i < nblocks; i++) {
    uint64_t k;
    std::memcpy(&k, data + i * 8, 8);
    k *= m;
    k ^= k >> r;
    k *= m;
    h ^= k;
    h *= m;
  }
  const unsigned char* tail = data + nblocks * 8;
  uint64_t t = 0;
  switch (len & 7) {
    case 7: t ^= uint64_t(tail[6]) << 48; [[fallthrough]];
    case 6: t ^= uint64_t(tail[5]) << 40; [[fallthrough]];
    case 5: t ^= uint64_t(tail[4]) << 32; [[fallthrough]];
    case 4: t ^= uint64_t(tail[3]) << 24; [[fallthrough]];
    case 3: t ^= uint64_t(tail[2]) << 16; [[fallthrough]];
    case 2: t ^= uint64_t(tail[1]) << 8; [[fallthrough]];
    case 1:
      t ^= uint64_t(tail[0]);
      h ^= t;
      h *= m;
  }
  h ^= h >> r;
  h *= m;
  h ^= h >> r;
  return h;
}

struct ShardOut {
  std::vector<float> labels;
  std::vector<int32_t> sizes;  // per-example nnz
  std::vector<int32_t> ids;
  std::vector<float> vals;
  bool failed = false;
  std::string error;
};

inline bool is_ws(char c) {
  return c == ' ' || c == '\t' || c == '\r' || c == '\v' || c == '\f';
}

// Parse one whitespace-delimited token as float; matches Python float()
// on normal numeric data. Returns false on garbage/empty.
inline bool parse_float(const char* begin, const char* end, float* out) {
  if (begin == end) return false;
  // strtof needs NUL-terminated input; tokens are short, copy to stack.
  char buf[64];
  size_t n = size_t(end - begin);
  if (n >= sizeof(buf)) return false;
  std::memcpy(buf, begin, n);
  buf[n] = '\0';
  char* endp = nullptr;
  errno = 0;
  float v = std::strtof(buf, &endp);
  if (endp != buf + n || errno == ERANGE) return false;
  *out = v;
  return true;
}

inline bool parse_int(const char* begin, const char* end, int64_t* out) {
  if (begin == end) return false;
  char buf[32];
  size_t n = size_t(end - begin);
  if (n >= sizeof(buf)) return false;
  std::memcpy(buf, begin, n);
  buf[n] = '\0';
  char* endp = nullptr;
  errno = 0;
  long long v = std::strtoll(buf, &endp, 10);
  if (endp != buf + n || errno == ERANGE) return false;
  *out = v;
  return true;
}

void fail(ShardOut* out, int64_t lineno, const std::string& msg) {
  out->failed = true;
  out->error = "line " + std::to_string(lineno) + ": " + msg;
}

// Parse lines [begin, end) of the blob (byte offsets of line starts are
// implicit: we scan). `first_lineno` is for error messages only.
void parse_range(const char* blob, const char* end, int64_t first_lineno,
                 int64_t vocab, bool hash_ids, int max_feats,
                 ShardOut* out) {
  const char* p = blob;
  int64_t lineno = first_lineno;
  while (p < end) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', size_t(end - p)));
    if (line_end == nullptr) line_end = end;
    const char* q = p;
    // skip leading whitespace; blank lines are dropped (training path;
    // keep_empty goes through the Python parser)
    while (q < line_end && is_ws(*q)) q++;
    if (q == line_end) {
      p = line_end + 1;
      lineno++;
      continue;
    }
    // label token
    const char* tok_end = q;
    while (tok_end < line_end && !is_ws(*tok_end)) tok_end++;
    float label;
    if (!parse_float(q, tok_end, &label)) {
      return fail(out, lineno,
                  "bad label '" + std::string(q, tok_end) + "'");
    }
    out->labels.push_back(label);
    int32_t n_feats = 0;
    q = tok_end;
    while (true) {
      while (q < line_end && is_ws(*q)) q++;
      if (q >= line_end) break;
      tok_end = q;
      const char* colon = nullptr;
      bool extra_colon = false;
      while (tok_end < line_end && !is_ws(*tok_end)) {
        if (*tok_end == ':') {
          if (colon != nullptr) extra_colon = true;
          else colon = tok_end;
        }
        tok_end++;
      }
      if (max_feats > 0 && n_feats >= max_feats) {
        // Python breaks out at the cap without validating the tail of
        // the line; skipping (not erroring) matches that.
        q = tok_end;
        continue;
      }
      if (extra_colon) {
        return fail(out, lineno,
                    "bad token '" + std::string(q, tok_end) +
                        "' (want fid[:val])");
      }
      const char* fid_end = colon ? colon : tok_end;
      int32_t row;
      if (hash_ids) {
        row = int32_t(murmur64(q, size_t(fid_end - q), 0) %
                      uint64_t(vocab));
      } else {
        int64_t fid;
        if (!parse_int(q, fid_end, &fid)) {
          return fail(out, lineno,
                      "non-integer feature id '" +
                          std::string(q, fid_end) +
                          "' (set hash_feature_id = True for string ids)");
        }
        if (fid < 0 || fid >= vocab) {
          return fail(out, lineno,
                      "feature id " + std::to_string(fid) +
                          " out of range [0, " + std::to_string(vocab) +
                          ")");
        }
        row = int32_t(fid);
      }
      float val = 1.0f;
      if (colon != nullptr &&
          !parse_float(colon + 1, tok_end, &val)) {
        return fail(out, lineno,
                    "bad value '" + std::string(colon + 1, tok_end) + "'");
      }
      out->ids.push_back(row);
      out->vals.push_back(val);
      n_feats++;
      q = tok_end;
    }
    out->sizes.push_back(n_feats);
    p = line_end + 1;
    lineno++;
  }
}

}  // namespace

extern "C" {

// Returns 0 on success. Outputs:
//   labels[n_examples], poses[n_examples+1], ids[nnz], vals[nnz]
// Caller allocates: labels/poses sized for the line count, ids/vals for
// the worst-case token count (cparser.py sizes them from the blob).
int fm_parse_block(const char* blob, int64_t blob_len, int64_t vocab,
                   int hash_ids, int max_feats, int num_threads,
                   int64_t* n_examples_out, int64_t* nnz_out,
                   float* labels_out, int32_t* poses_out, int32_t* ids_out,
                   float* vals_out, char* err_out, int64_t err_cap) {
  if (vocab <= 0) {
    std::snprintf(err_out, size_t(err_cap), "vocabulary_size must be > 0");
    return 1;
  }
  int T = num_threads > 0
              ? num_threads
              : int(std::min(8u, std::thread::hardware_concurrency()));
  if (T < 1) T = 1;
  if (blob_len < (64 << 10)) T = 1;  // small blocks: threading overhead

  // Slice the blob into T ranges on line boundaries.
  std::vector<const char*> starts{blob};
  const char* end = blob + blob_len;
  for (int t = 1; t < T; t++) {
    const char* target = blob + blob_len * t / T;
    if (target <= starts.back()) {
      continue;
    }
    const char* nl = static_cast<const char*>(
        std::memchr(target, '\n', size_t(end - target)));
    const char* start = nl ? nl + 1 : end;
    if (start > starts.back()) starts.push_back(start);
  }
  starts.push_back(end);
  int shards = int(starts.size()) - 1;

  // Line numbers per shard for error messages: count newlines up front.
  std::vector<int64_t> first_lineno(size_t(shards), 0);
  for (int s = 1; s < shards; s++) {
    int64_t count = 0;
    for (const char* c = starts[s - 1]; c < starts[s]; c++) {
      if (*c == '\n') count++;
    }
    first_lineno[size_t(s)] = first_lineno[size_t(s - 1)] + count;
  }

  std::vector<ShardOut> outs(static_cast<size_t>(shards));
  std::vector<std::thread> threads;
  for (int s = 0; s < shards; s++) {
    threads.emplace_back(parse_range, starts[size_t(s)],
                         starts[size_t(s) + 1], first_lineno[size_t(s)],
                         vocab, hash_ids != 0, max_feats, &outs[size_t(s)]);
  }
  for (auto& th : threads) th.join();

  for (const auto& o : outs) {
    if (o.failed) {
      std::snprintf(err_out, size_t(err_cap), "%s", o.error.c_str());
      return 1;
    }
  }

  // Stitch in order.
  int64_t b = 0, z = 0;
  poses_out[0] = 0;
  for (const auto& o : outs) {
    std::memcpy(labels_out + b, o.labels.data(),
                o.labels.size() * sizeof(float));
    std::memcpy(ids_out + z, o.ids.data(), o.ids.size() * sizeof(int32_t));
    std::memcpy(vals_out + z, o.vals.data(), o.vals.size() * sizeof(float));
    for (size_t e = 0; e < o.sizes.size(); e++) {
      poses_out[b + int64_t(e) + 1] =
          poses_out[b + int64_t(e)] + o.sizes[e];
    }
    b += int64_t(o.labels.size());
    z += int64_t(o.ids.size());
  }
  *n_examples_out = b;
  *nnz_out = z;
  return 0;
}

}  // extern "C"
