// C++ libsvm line parser — the throughput path of the fm_parser contract.
//
// The reference implements batch text->CSR parsing as a multithreaded C++
// TensorFlow custom op (upstream cc/fm_parser.cc; SURVEY.md §2). This is
// the same job as a dependency-free shared object driven through ctypes
// (fast_tffm_tpu/data/cparser.py): a newline-separated blob of
//     <label> <fid>[:<fval>] ...            (FM)
//     <label> <field>:<fid>[:<fval>] ...    (FFM, field_aware mode)
// lines in, CSR arrays out. Semantics must match the Python parser
// (fast_tffm_tpu/data/parser.py) bit-for-bit — including MurmurHash64A
// feature hashing — and golden tests (tests/test_cparser.py) enforce it.
//
// Parallelism: lines are sliced into contiguous ranges, one thread per
// range parsing into private buffers, stitched in order afterwards, so
// output ordering is identical to single-threaded parsing.

#include <cctype>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// MurmurHash64A (Austin Appleby, public domain), seed 0 — must match
// fast_tffm_tpu/data/hashing.py (golden tests pin both).
uint64_t murmur64(const char* key, size_t len, uint64_t seed) {
  const uint64_t m = 0xc6a4a7935bd1e995ULL;
  const int r = 47;
  uint64_t h = seed ^ (len * m);
  const unsigned char* data = reinterpret_cast<const unsigned char*>(key);
  const size_t nblocks = len / 8;
  for (size_t i = 0; i < nblocks; i++) {
    uint64_t k;
    std::memcpy(&k, data + i * 8, 8);
    k *= m;
    k ^= k >> r;
    k *= m;
    h ^= k;
    h *= m;
  }
  const unsigned char* tail = data + nblocks * 8;
  uint64_t t = 0;
  switch (len & 7) {
    case 7: t ^= uint64_t(tail[6]) << 48; [[fallthrough]];
    case 6: t ^= uint64_t(tail[5]) << 40; [[fallthrough]];
    case 5: t ^= uint64_t(tail[4]) << 32; [[fallthrough]];
    case 4: t ^= uint64_t(tail[3]) << 24; [[fallthrough]];
    case 3: t ^= uint64_t(tail[2]) << 16; [[fallthrough]];
    case 2: t ^= uint64_t(tail[1]) << 8; [[fallthrough]];
    case 1:
      t ^= uint64_t(tail[0]);
      h ^= t;
      h *= m;
  }
  h ^= h >> r;
  h *= m;
  h ^= h >> r;
  return h;
}

struct ShardOut {
  std::vector<float> labels;
  std::vector<int32_t> sizes;  // per-example nnz
  std::vector<int32_t> ids;
  std::vector<float> vals;
  std::vector<int32_t> fields;   // field-aware (FFM) mode only
  std::vector<int64_t> linenos;  // per-example line number (filled only
                                 // when keep_linenos; base = caller's
                                 // first_lineno convention)
  int64_t lines_scanned = 0;  // lines walked by parse_range (left 0 on
                              // a parse failure; callers fall back)
  bool failed = false;
  // Error site, kept as (lineno, message) instead of preformatted text
  // so parse_threaded can rebase shard-relative linenos after the join
  // (shards must not pre-scan for absolute offsets — see there).
  int64_t error_lineno = 0;
  std::string error_msg;
};

// Byte class table for the separator test: one L1-resident load beats
// the 5-way compare chain in the token-scan loops (measured 1.4x on a
// scan-only microbench; the full-parse effect is a few percent, inside
// this environment's ambient noise — kept because the scan loops are
// the host throughput ceiling and the semantics are byte-identical).
// Set bytes: \t \v \f \r and space. parser.WHITESPACE is this set PLUS
// \n (Python strips whole decoded lines); here \n must stay 0 — the
// C++ paths split on it as the LINE terminator first, and marking it a
// token separator would silently merge lines.
static const uint8_t kWsTable[256] = {
    0, 0, 0, 0, 0, 0, 0, 0, 0, 1 /*\t*/, 0 /*\n*/, 1 /*\v*/, 1 /*\f*/,
    1 /*\r*/, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
    1 /*space*/};

inline bool is_ws(char c) {
  return kWsTable[static_cast<unsigned char>(c)] != 0;
}

// Slow-path float parse via strtod + float cast. Double-then-float
// rounding matches the Python parser's float(token) -> np.float32 exactly
// (strtof's direct-to-float rounding can differ in double-rounding
// corners, so the double route is the parity-correct one).
//
// Lexical grammar is pinned to PYTHON's float() (the golden-parity
// contract), which is narrower than strtod's: no hex floats ("0x10"),
// no "nan(chars)" payloads — only decimal literals and the inf/infinity/
// nan words. Overflow reads as +-inf like Python (strtod flags ERANGE);
// underflow reads as a denormal/0 like Python (ERANGE ignored there).
bool parse_float_slow(const char* begin, const char* end, float* out) {
  char buf[64];
  size_t n = size_t(end - begin);
  if (n >= sizeof(buf) || n == 0) return false;
  bool word_ok = false;  // [+-]?(inf|infinity|nan), case-insensitive
  {
    const char* p = begin;
    if (*p == '+' || *p == '-') p++;
    char low[16];
    size_t m = size_t(end - p);
    if (m > 0 && m < sizeof(low)) {
      for (size_t i = 0; i < m; i++) {
        low[i] = char(std::tolower((unsigned char)p[i]));
      }
      low[m] = '\0';
      word_ok = !std::strcmp(low, "inf") || !std::strcmp(low, "infinity") ||
                !std::strcmp(low, "nan");
    }
  }
  if (!word_ok) {
    for (const char* p = begin; p < end; p++) {
      char c = *p;
      if (!((c >= '0' && c <= '9') || c == '.' || c == '+' || c == '-' ||
            c == 'e' || c == 'E')) {
        return false;  // hex floats, nan payloads, garbage
      }
    }
  }
  std::memcpy(buf, begin, n);
  buf[n] = '\0';
  char* endp = nullptr;
  errno = 0;
  double v = std::strtod(buf, &endp);
  if (endp != buf + n) return false;
  *out = float(v);
  return true;
}

// Exact powers of ten for the simple-decimal fast paths: mantissa /
// 10^frac is one correctly-rounded double op (mantissa exact in 2^53,
// powers exact up to 1e22), equal to Python's float(token).
static const double kPow10[23] = {
    1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,  1e8,  1e9,  1e10,
    1e11, 1e12, 1e13, 1e14, 1e15, 1e16, 1e17, 1e18, 1e19, 1e20, 1e21,
    1e22};

// Parse one whitespace-delimited token as float; matches Python float()
// -> float32 on all inputs. Returns false on garbage/empty.
//
// Fast path: plain decimals (the overwhelming case in libsvm data,
// "1.374", "0.83", "1") with <= 15 digits and <= 22 fractional digits
// (see kPow10). strtod/strtof dominate parse time otherwise
// (~100ns/token, 40 tokens/line at Criteo shapes).
inline bool parse_float(const char* begin, const char* end, float* out) {
  if (begin == end) return false;
  const char* p = begin;
  bool neg = false;
  if (*p == '+' || *p == '-') {
    neg = (*p == '-');
    p++;
  }
  uint64_t mant = 0;
  int digits = 0, frac = 0;
  bool any = false, dot = false, simple = true;
  for (; p < end; p++) {
    char c = *p;
    if (c >= '0' && c <= '9') {
      any = true;
      if (digits < 15) {
        mant = mant * 10 + uint64_t(c - '0');
        if (mant) digits++;  // leading zeros are free
        if (dot) frac++;
      } else {
        simple = false;
        break;
      }
    } else if (c == '.' && !dot) {
      dot = true;
    } else {
      simple = false;  // exponent / inf / nan / garbage -> slow path
      break;
    }
  }
  if (simple && any && frac <= 22) {
    double v = double(mant) / kPow10[frac];
    *out = float(neg ? -v : v);
    return true;
  }
  return parse_float_slow(begin, end, out);
}

// 0 = parsed; 1 = not integer syntax; 2 = integer syntax but > 18
// significant digits (magnitude beyond any vocab/field range — callers
// must report it as OUT OF RANGE, not non-integer, to match Python's
// arbitrary-precision int() + range check).
inline int parse_int_status(const char* begin, const char* end,
                            int64_t* out) {
  if (begin == end) return 1;
  const char* p = begin;
  bool neg = false;
  if (*p == '+' || *p == '-') {
    neg = (*p == '-');
    p++;
  }
  if (p == end) return 1;
  uint64_t v = 0;
  int digits = 0;
  bool over = false;
  for (; p < end; p++) {
    char c = *p;
    if (c < '0' || c > '9') return 1;
    if (!over) {
      v = v * 10 + uint64_t(c - '0');
      // Significant digits only: zero-padded ids ("000...05") must
      // parse like Python int(). 18 significant digits can't overflow.
      if (v && ++digits > 18) over = true;
    }
  }
  if (over) return 2;
  *out = neg ? -int64_t(v) : int64_t(v);
  return 0;
}

// Python-int repr of an integer-syntax token span: sign only when
// negative and nonzero, leading zeros stripped — what Python's
// f"{int(s)}" renders in range-error messages, valid for spans that
// overflowed int64 too.
inline std::string canon_int(const char* begin, const char* end) {
  const char* p = begin;
  bool neg = false;
  if (p < end && (*p == '+' || *p == '-')) {
    neg = (*p == '-');
    p++;
  }
  while (p < end && *p == '0') p++;
  if (p == end) return "0";
  return (neg ? "-" : "") + std::string(p, end);
}

void fail(ShardOut* out, int64_t lineno, const std::string& msg) {
  out->failed = true;
  out->error_lineno = lineno;
  out->error_msg = msg;
}

// "line N: msg" — the one rendering of a shard's error site.
std::string shard_error(const ShardOut& o) {
  return "line " + std::to_string(o.error_lineno) + ": " + o.error_msg;
}

// One feature token parsed. FM: `fid[:val]`; field-aware (FFM):
// `field:fid[:val]`. Mirrors parser.py's tok.split(":") handling
// exactly, including error wording (golden tests pin output parity).
struct Token {
  int32_t row;
  int32_t field;  // field-aware only
  float val;
};

// Single-pass fast path for the dominant token shapes in every parse
// mode: `<int fid>[:<simple decimal>]` (FM), the same with a hashed
// string fid (any non-ws, non-colon bytes), and the field-aware
// `<int field>:<fid>[:<simple decimal>]` (FFM, hashed or not). Parses
// WHILE scanning — the general path walks the token bytes twice
// (scan_token for structure, then parse_int/parse_float/murmur over
// the same ranges), and this loop is the host throughput ceiling.
// Returns 1 with (*tok_end_out, *t) filled on success; 0 for ANYTHING
// unusual (sign, exponent, surplus colon, out-of-range field/id,
// empty id, overlong) — the caller then runs the general scan+parse
// path, which owns all error semantics, so the two paths cannot
// disagree on what's accepted (golden + property tests pin that).
inline int try_fast_token(const char* q, const char* line_end,
                          int64_t vocab, bool hash_ids, bool field_aware,
                          int64_t field_num, const char** tok_end_out,
                          Token* t) {
  const char* p = q;
  if (field_aware) {
    uint64_t fld = 0;
    int fdigs = 0;
    while (p < line_end) {
      const char c = *p;
      if (c < '0' || c > '9') break;
      fld = fld * 10 + uint64_t(c - '0');
      if (fld && ++fdigs > 9) return 0;  // overlong field: general path
      p++;
    }
    // Needs digits then ':' (sign, string field, bare token: fall back)
    if (p == q || p >= line_end || *p != ':') return 0;
    if (fld >= uint64_t(field_num)) return 0;  // general path raises
    t->field = int32_t(fld);
    p++;
  } else {
    t->field = 0;
  }
  if (hash_ids) {
    const char* id0 = p;
    while (p < line_end && !is_ws(*p) && *p != ':') p++;
    if (p == id0) return 0;  // empty id: general path owns acceptance
    t->row = int32_t(murmur64(id0, size_t(p - id0), 0) % uint64_t(vocab));
  } else {
    const char* id0 = p;
    uint64_t fid = 0;
    int digs = 0;
    while (p < line_end) {
      const char c = *p;
      if (c < '0' || c > '9') break;
      fid = fid * 10 + uint64_t(c - '0');
      if (fid && ++digs > 18) return 0;
      p++;
    }
    if (p == id0) return 0;  // no digits (sign, string id, ...)
    if (fid >= uint64_t(vocab)) return 0;  // general path raises
    t->row = int32_t(fid);
  }
  if (p >= line_end || is_ws(*p)) {
    t->val = 1.0f;
  } else if (*p == ':') {
    p++;
    uint64_t mant = 0;
    int vdigs = 0, frac = 0;
    bool dot = false, any = false;
    while (p < line_end) {
      const char c = *p;
      if (c >= '0' && c <= '9') {
        any = true;
        if (vdigs >= 15) return 0;
        mant = mant * 10 + uint64_t(c - '0');
        if (mant) vdigs++;
        if (dot) frac++;
      } else if (c == '.' && !dot) {
        dot = true;
      } else {
        break;
      }
      p++;
    }
    if (p < line_end && !is_ws(*p)) return 0;  // exponent, ':', garbage
    if (!any || frac > 22) return 0;
    t->val = float(double(mant) / kPow10[frac]);
  } else {
    return 0;  // id runs into non-digit, non-colon, non-ws bytes
  }
  *tok_end_out = p;
  return 1;
}

// Scan one whitespace-delimited token, recording its first two colons
// and whether more exist — one pass shared with token-boundary
// detection (the parse loops are the host throughput ceiling; the
// bytes must not be walked twice).
inline const char* scan_token(const char* q, const char* line_end,
                              const char** c1, const char** c2,
                              bool* extra) {
  *c1 = *c2 = nullptr;
  *extra = false;
  const char* s = q;
  while (s < line_end && !is_ws(*s)) {
    if (*s == ':') {
      if (*c1 == nullptr) *c1 = s;
      else if (*c2 == nullptr) *c2 = s;
      else *extra = true;
    }
    s++;
  }
  return s;  // tok_end
}

// Returns 0 ok, 1 parse error (message in *err). c1/c2/extra come from
// scan_token over [q, tok_end).
inline int parse_token(const char* q, const char* tok_end,
                       const char* c1, const char* c2, bool extra,
                       int64_t vocab, bool hash_ids, bool field_aware,
                       int64_t field_num, Token* t, std::string* err) {
  const char* fid_begin = q;
  const char* fid_end;
  const char* val_begin = nullptr;  // null = default 1.0
  if (field_aware) {
    if (c1 == nullptr || extra) {
      *err = "bad ffm token '" + std::string(q, tok_end) +
             "' (want field:fid[:val])";
      return 1;
    }
    int64_t fld = 0;
    const int fst = parse_int_status(q, c1, &fld);
    if (fst == 1) {
      *err = "bad field '" + std::string(q, c1) + "'";
      return 1;
    }
    if (fst == 2 || fld < 0 || fld >= field_num) {
      *err = "field " + canon_int(q, c1) + " out of range [0, " +
             std::to_string(field_num) + ")";
      return 1;
    }
    t->field = int32_t(fld);
    fid_begin = c1 + 1;
    fid_end = c2 ? c2 : tok_end;
    if (c2) val_begin = c2 + 1;
  } else {
    if (c2 != nullptr || extra) {
      *err = "bad token '" + std::string(q, tok_end) + "' (want fid[:val])";
      return 1;
    }
    t->field = 0;
    fid_end = c1 ? c1 : tok_end;
    if (c1) val_begin = c1 + 1;
  }
  if (hash_ids) {
    t->row = int32_t(murmur64(fid_begin, size_t(fid_end - fid_begin), 0) %
                     uint64_t(vocab));
  } else {
    int64_t fid = 0;
    const int st = parse_int_status(fid_begin, fid_end, &fid);
    if (st == 1) {
      *err = "non-integer feature id '" + std::string(fid_begin, fid_end) +
             "' (set hash_feature_id = True for string ids)";
      return 1;
    }
    if (st == 2 || fid < 0 || fid >= vocab) {
      *err = "feature id " + canon_int(fid_begin, fid_end) +
             " out of range [0, " + std::to_string(vocab) + ")";
      return 1;
    }
    t->row = int32_t(fid);
  }
  t->val = 1.0f;
  if (val_begin != nullptr && !parse_float(val_begin, tok_end, &t->val)) {
    *err = "bad value '" + std::string(val_begin, tok_end) + "'";
    return 1;
  }
  return 0;
}

// Parse lines [begin, end) of the blob (byte offsets of line starts are
// implicit: we scan). `first_lineno` seeds the per-example line numbers
// (and error messages). `keep_empty` turns blank lines into
// zero-feature label-0 examples (the BatchBuilder's predict-alignment
// mode); otherwise blanks are dropped. `keep_linenos` fills the
// per-example linenos vector — only the streaming-builder feed reads
// it, and this loop is the host throughput ceiling, so the block-parse
// path must not pay the per-example push.
void parse_range(const char* blob, const char* end, int64_t first_lineno,
                 int64_t vocab, bool hash_ids, bool field_aware,
                 int64_t field_num, int max_feats, bool keep_empty,
                 bool keep_linenos, ShardOut* out) {
  const char* p = blob;
  int64_t lineno = first_lineno;
  while (p < end) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', size_t(end - p)));
    if (line_end == nullptr) line_end = end;
    const char* q = p;
    while (q < line_end && is_ws(*q)) q++;
    if (q == line_end) {
      if (keep_empty) {
        out->labels.push_back(0.0f);
        out->sizes.push_back(0);
        if (keep_linenos) out->linenos.push_back(lineno);
      }
      p = line_end + 1;
      lineno++;
      continue;
    }
    // label token
    const char* tok_end = q;
    while (tok_end < line_end && !is_ws(*tok_end)) tok_end++;
    float label;
    if (!parse_float(q, tok_end, &label)) {
      return fail(out, lineno,
                  "bad label '" + std::string(q, tok_end) + "'");
    }
    out->labels.push_back(label);
    int32_t n_feats = 0;
    q = tok_end;
    while (true) {
      while (q < line_end && is_ws(*q)) q++;
      if (q >= line_end) break;
      Token t;
      if (max_feats > 0 && n_feats >= max_feats) {
        // Python breaks out at the cap without validating the tail of
        // the line; skipping (not erroring) matches that. Only the
        // token boundary matters here, not its structure.
        while (q < line_end && !is_ws(*q)) q++;
        continue;
      }
      if (!try_fast_token(q, line_end, vocab, hash_ids, field_aware,
                          field_num, &tok_end, &t)) {
        const char* c1;
        const char* c2;
        bool extra;
        tok_end = scan_token(q, line_end, &c1, &c2, &extra);
        std::string err;
        if (parse_token(q, tok_end, c1, c2, extra, vocab, hash_ids,
                        field_aware, field_num, &t, &err)) {
          return fail(out, lineno, err);
        }
      }
      out->ids.push_back(t.row);
      out->vals.push_back(t.val);
      if (field_aware) out->fields.push_back(t.field);
      n_feats++;
      q = tok_end;
    }
    out->sizes.push_back(n_feats);
    if (keep_linenos) out->linenos.push_back(lineno);
    p = line_end + 1;
    lineno++;
  }
  out->lines_scanned = lineno - first_lineno;
}

// Slice [blob, end) into <= T line-aligned ranges and parse them on T
// threads. Returns the shard outputs in order. Shared by fm_parse_block
// and the threaded BatchBuilder feed path.
std::vector<ShardOut> parse_threaded(const char* blob, const char* end,
                                     int64_t first_lineno, int T,
                                     int64_t vocab, bool hash_ids,
                                     bool field_aware, int64_t field_num,
                                     int max_feats, bool keep_empty,
                                     bool keep_linenos) {
  const int64_t blob_len = end - blob;
  std::vector<const char*> starts{blob};
  for (int t = 1; t < T; t++) {
    const char* target = blob + blob_len * t / T;
    if (target <= starts.back()) continue;
    const char* nl = static_cast<const char*>(
        std::memchr(target, '\n', size_t(end - target)));
    const char* start = nl ? nl + 1 : end;
    if (start > starts.back()) starts.push_back(start);
  }
  starts.push_back(end);
  int shards = int(starts.size()) - 1;

  std::vector<ShardOut> outs(static_cast<size_t>(shards));
  if (shards == 1) {
    parse_range(starts[0], starts[1], first_lineno, vocab, hash_ids,
                field_aware, field_num, max_feats, keep_empty,
                keep_linenos, &outs[0]);
    return outs;
  }
  // Shards past the first parse with RELATIVE linenos (base 0) and are
  // rebased after the join from the earlier shards' lines_scanned —
  // the alternative (pre-scanning [starts[0], starts[N-1]) for
  // newlines to seed absolute offsets) is a serial O(blob) walk on the
  // calling thread before any parse thread starts, an Amdahl cap on
  // exactly the loop this parallelism exists to speed up.
  std::vector<std::thread> threads;
  for (int s = 0; s < shards; s++) {
    threads.emplace_back(parse_range, starts[size_t(s)],
                         starts[size_t(s) + 1],
                         s == 0 ? first_lineno : 0, vocab,
                         hash_ids, field_aware, field_num, max_feats,
                         keep_empty, keep_linenos, &outs[size_t(s)]);
  }
  for (auto& th : threads) th.join();
  // Rebase: shard s's absolute base = first_lineno + lines before it.
  // A failed shard's lines_scanned is 0/partial, but every shard after
  // the first failure is dropped by both consumers (stitch and feed
  // break at the failed shard), so their linenos never surface.
  int64_t base = outs[0].lines_scanned;  // shard 0 is already absolute
  bool dead = outs[0].failed;
  for (int s = 1; s < shards && !dead; s++) {
    ShardOut& o = outs[size_t(s)];
    const int64_t delta = first_lineno + base;
    for (int64_t& ln : o.linenos) ln += delta;
    if (o.failed) {
      o.error_lineno += delta;
      dead = true;
    }
    base += o.lines_scanned;
  }
  return outs;
}

}  // namespace

extern "C" {

// Bumped whenever any exported signature changes. cparser.py refuses a
// .so reporting a different version: the mtime/symbol checks alone
// cannot catch a stale binary whose symbols still exist but whose
// argument layouts moved (silent data corruption, not a load error).
// History: 1 = initial; 2 = field-aware (FFM) params + fields buffers;
// 3 = raw_ids builder mode (dedup=device); 4 = keep_empty builder mode
// (blank line -> zero-feature example; the predict path's line
// alignment); 5 = fm_bb_new num_threads param (threaded streaming
// feed: parallel parse into a pending queue + serial drain); 6 =
// fm_scan_examples (example-boundary scanner for the parallel host
// data plane's per-batch line groups); 7 = fm_parse_block keep_empty
// param (block-parse path for the predict alignment mode — until this
// the BLOCK parser had no blank-line-preserving mode, so every
// tolerant/weighted keep_empty input fell back to the Python parser
// and the tolerant keep_empty shape routed serial).
int64_t fm_abi_version() { return 7; }

// Scan complete lines of [blob, blob+blob_len) until `n_target` lines
// that PRODUCE AN EXAMPLE have been seen. The counting rule must equal
// the BatchBuilder's exactly (is_ws over the same table): a line whose
// bytes are all separator whitespace is blank — skipped by the builder
// unless keep_empty, where every line becomes an example. Returns the
// count found (<= n_target); *consumed_out = bytes through the LAST
// counted line's newline (trailing blanks stay unconsumed — they
// belong to the next group); *lines_out = total lines (blanks
// included) inside those consumed bytes. A trailing partial line is
// never consumed. This is the parallel data plane's group cutter
// (data/pipeline._GroupScanner): memchr-speed, so the coordinator can
// slice per-batch groups without Python ever touching lines.
int64_t fm_scan_examples(const char* blob, int64_t blob_len,
                         int64_t n_target, int keep_empty,
                         int64_t* consumed_out, int64_t* lines_out) {
  const char* p = blob;
  const char* end = blob + blob_len;
  int64_t found = 0, lines = 0;
  int64_t mark = 0, mark_lines = 0;  // end of the last COUNTED line
  while (p < end && found < n_target) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', size_t(end - p)));
    if (nl == nullptr) break;  // partial line: next chunk's problem
    lines++;
    bool counting = keep_empty != 0;
    if (!counting) {
      const char* q = p;
      while (q < nl && is_ws(*q)) q++;
      counting = q != nl;
    }
    if (counting) {
      found++;
      mark = (nl + 1) - blob;
      mark_lines = lines;
    }
    p = nl + 1;
  }
  *consumed_out = mark;
  *lines_out = mark_lines;
  return found;
}

// The auto ("num_threads = 0") parse-thread count, exported so Python
// reports the value this library actually uses instead of re-deriving
// the formula (which would drift silently).
int fm_auto_threads() {
  int T = int(std::min(8u, std::thread::hardware_concurrency()));
  return T < 1 ? 1 : T;
}

// Returns 0 on success. Outputs:
//   labels[n_examples], poses[n_examples+1], ids[nnz], vals[nnz]
//   (+ fields[nnz] when field_aware — FFM `field:fid[:val]` tokens)
// Caller allocates: labels/poses sized for the line count, ids/vals/
// fields for the worst-case token count (cparser.py sizes them from the
// blob). fields_out may be null when !field_aware. `keep_empty` turns
// blank lines into zero-feature label-0 examples (the predict path's
// one-score-per-input-line alignment), same rule as the BatchBuilder.
int fm_parse_block(const char* blob, int64_t blob_len, int64_t vocab,
                   int hash_ids, int field_aware, int64_t field_num,
                   int max_feats, int keep_empty, int num_threads,
                   int64_t* n_examples_out, int64_t* nnz_out,
                   float* labels_out, int32_t* poses_out, int32_t* ids_out,
                   float* vals_out, int32_t* fields_out, char* err_out,
                   int64_t err_cap) {
  if (vocab <= 0) {
    std::snprintf(err_out, size_t(err_cap), "vocabulary_size must be > 0");
    return 1;
  }
  int T = num_threads > 0 ? num_threads : fm_auto_threads();
  if (T < 1) T = 1;
  if (blob_len < (64 << 10)) T = 1;  // small blocks: threading overhead

  std::vector<ShardOut> outs = parse_threaded(
      blob, blob + blob_len, 0, T, vocab, hash_ids != 0, field_aware != 0,
      field_num, max_feats, keep_empty != 0,
      /*keep_linenos=*/false);

  for (const auto& o : outs) {
    if (o.failed) {
      std::snprintf(err_out, size_t(err_cap), "%s",
                    shard_error(o).c_str());
      return 1;
    }
  }

  // Stitch in order.
  int64_t b = 0, z = 0;
  poses_out[0] = 0;
  for (const auto& o : outs) {
    std::memcpy(labels_out + b, o.labels.data(),
                o.labels.size() * sizeof(float));
    std::memcpy(ids_out + z, o.ids.data(), o.ids.size() * sizeof(int32_t));
    std::memcpy(vals_out + z, o.vals.data(), o.vals.size() * sizeof(float));
    if (field_aware != 0 && fields_out != nullptr) {
      std::memcpy(fields_out + z, o.fields.data(),
                  o.fields.size() * sizeof(int32_t));
    }
    for (size_t e = 0; e < o.sizes.size(); e++) {
      poses_out[b + int64_t(e) + 1] =
          poses_out[b + int64_t(e)] + o.sizes[e];
    }
    b += int64_t(o.labels.size());
    z += int64_t(o.ids.size());
  }
  *n_examples_out = b;
  *nnz_out = z;
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Batch builder: raw byte chunks -> one fully padded device batch in a
// single pass (parse + hash + dedup + padded scatter). This is the hot
// host path for throughput training (bench.py): it replaces the Python
// per-line iteration, the str join/encode, np.unique and the fancy-index
// scatter of the generic path. Resumable across feed() calls so the
// caller can stream arbitrary chunk sizes; the dedup hash map is stamped
// per batch (no per-batch clears).
//
// Padding convention: unique slot 0 is RESERVED for pad_id (== vocab);
// real uniques start at slot 1, and padded local_idx cells are 0. (The
// generic Python path pads at slot U-1; both satisfy the documented
// invariant "padding cells point at a slot holding pad_id".)
// ---------------------------------------------------------------------------

struct BatchBuilder {
  int64_t B, L, vocab;
  bool hash_ids;
  bool field_aware = false;  // FFM `field:fid[:val]` tokens
  bool raw_ids = false;      // dedup=device: li holds raw ids, no dedup
  bool keep_empty = false;   // blank line -> zero-feature example
  int64_t field_num = 0;
  int max_feats;
  int64_t max_uniq;  // 0 = unlimited; else batch closes BEFORE exceeding
  int T = 1;         // feed parse threads (1 = the serial in-line path)
  std::vector<float> labels;    // [B]
  std::vector<int32_t> uniq;    // [B*L + 1]
  std::vector<int32_t> li;      // [B*L], default 0 (pad slot)
  std::vector<float> vals;      // [B*L], default 0
  std::vector<int32_t> fields;  // [B*L] (field_aware only), default 0
  std::vector<int32_t> slot;    // dedup table -> slot index
  std::vector<uint32_t> stamp;  // dedup table stamping
  std::vector<uint32_t> line_slots;  // hash slots inserted by current line
  uint32_t cur_stamp = 0;
  uint32_t mask = 0;
  int64_t n_ex = 0;
  int32_t n_uniq = 1;  // slot 0 = pad
  int32_t max_nnz = 0;
  int64_t lineno = 0;
  std::string error;
  // Threaded feed (T > 1): each fed chunk's complete lines are parsed
  // by T threads into this pending CSR queue (the expensive tokenize/
  // float-parse/hash phase); a cheap serial drain then does the
  // order-dependent work (dedup slots, padded scatter, uniq-budget
  // spill). A parse error is DEFERRED: examples before it drain
  // normally and the error surfaces only when consumption reaches it —
  // the exact observable behavior of the serial path.
  std::vector<float> p_labels;
  std::vector<int32_t> p_sizes;
  std::vector<int64_t> p_linenos;
  std::vector<int32_t> p_ids;
  std::vector<float> p_vals;
  std::vector<int32_t> p_fields;
  size_t p_cursor = 0;      // next pending example
  size_t p_nnz = 0;         // its flat ids/vals offset
  bool p_failed = false;
  std::string p_error;
};

namespace {

void bb_reset(BatchBuilder* bb) {
  bb->n_ex = 0;
  bb->n_uniq = 1;
  bb->max_nnz = 0;
  bb->cur_stamp++;
  // Raw mode: padding cells hold the raw pad id (== vocab, the dead
  // row) — there is no "pad slot 0" indirection without a unique table.
  std::fill(bb->li.begin(), bb->li.end(),
            bb->raw_ids ? int32_t(bb->vocab) : 0);
  std::memset(bb->vals.data(), 0, size_t(bb->B * bb->L) * sizeof(float));
  if (bb->field_aware) {
    std::memset(bb->fields.data(), 0,
                size_t(bb->B * bb->L) * sizeof(int32_t));
  }
}

inline int32_t bb_slot(BatchBuilder* bb, int32_t key) {
  uint32_t h = (uint32_t(key) * 2654435761u) & bb->mask;
  for (;;) {
    if (bb->stamp[h] != bb->cur_stamp) {
      bb->stamp[h] = bb->cur_stamp;
      bb->slot[h] = bb->n_uniq;
      bb->uniq[size_t(bb->n_uniq)] = key;
      bb->line_slots.push_back(h);  // for per-line rollback (uniq cap)
      return bb->n_uniq++;
    }
    if (bb->uniq[size_t(bb->slot[h])] == key) return bb->slot[h];
    h = (h + 1) & bb->mask;
  }
}

// Undo the current line's unique insertions. Un-stamping (stamp 0 never
// equals cur_stamp >= 1) is probe-chain-safe: a committed key's probe
// path to its slot runs over slots that were already occupied at its
// insertion time, and the rolled-back slots were all claimed later, so
// they can't sit on any committed path.
inline void bb_rollback_line(BatchBuilder* bb, int32_t saved_uniq) {
  for (uint32_t h : bb->line_slots) bb->stamp[h] = 0;
  bb->n_uniq = saved_uniq;
}

// The unique-budget close-out, shared by the serial feed and the
// threaded drain so the spill protocol (rollback + row scrub + the
// budget error message) has exactly one implementation. Returns 1 when
// the batch closes early (spill — the example stays unconsumed), -1
// when the batch is empty so the example can never fit (error).
inline int bb_budget_close(BatchBuilder* bb, int32_t* irow, float* vrow,
                           int32_t* frow, int32_t nf, int32_t saved_uniq,
                           int64_t lineno, char* err_out,
                           int64_t err_cap) {
  bb_rollback_line(bb, saved_uniq);
  std::memset(irow, 0, size_t(nf) * sizeof(int32_t));
  std::memset(vrow, 0, size_t(nf) * sizeof(float));
  if (frow != nullptr) std::memset(frow, 0, size_t(nf) * sizeof(int32_t));
  if (bb->n_ex == 0) {
    std::snprintf(err_out, size_t(err_cap),
                  "line %lld: single example exceeds the unique-row "
                  "budget %lld; raise uniq_bucket",
                  (long long)lineno, (long long)bb->max_uniq);
    return -1;
  }
  return 1;
}

// Drain pending (threaded-parse) examples into the batch. Returns 1
// when the batch is full or closed early on the unique budget, 0 when
// pending is exhausted with room left, -1 when consumption reaches a
// deferred parse error (message to err_out).
int bb_drain(BatchBuilder* bb, char* err_out, int64_t err_cap) {
  while (bb->n_ex < bb->B) {
    if (bb->p_cursor >= bb->p_sizes.size()) {
      if (bb->p_failed) {
        std::snprintf(err_out, size_t(err_cap), "%s",
                      bb->p_error.c_str());
        return -1;
      }
      return 0;
    }
    const size_t e = bb->p_cursor;
    const int32_t nf = bb->p_sizes[e];
    const int32_t* ids = bb->p_ids.data() + bb->p_nnz;
    const float* vals = bb->p_vals.data() + bb->p_nnz;
    const int32_t* flds =
        bb->field_aware ? bb->p_fields.data() + bb->p_nnz : nullptr;
    float* vrow = bb->vals.data() + bb->n_ex * bb->L;
    int32_t* irow = bb->li.data() + bb->n_ex * bb->L;
    int32_t* frow =
        bb->field_aware ? bb->fields.data() + bb->n_ex * bb->L : nullptr;
    bb->line_slots.clear();
    const int32_t saved_uniq = bb->n_uniq;
    for (int32_t j = 0; j < nf; j++) {
      irow[j] = bb->raw_ids ? ids[j] : bb_slot(bb, ids[j]);
      vrow[j] = vals[j];
      if (frow != nullptr) frow[j] = flds[j];
    }
    if (bb->max_uniq != 0 && bb->n_uniq > bb->max_uniq) {
      return bb_budget_close(bb, irow, vrow, frow, nf, saved_uniq,
                             bb->p_linenos[e], err_out, err_cap);
    }
    bb->labels[size_t(bb->n_ex)] = bb->p_labels[e];
    if (nf > bb->max_nnz) bb->max_nnz = nf;
    bb->n_ex++;
    bb->p_cursor++;
    bb->p_nnz += size_t(nf);
  }
  return 1;
}

// Threaded feed: parse every complete line of the chunk in parallel
// into pending, then drain. Consumes up to the last newline regardless
// of where the batch fills (excess examples wait in pending; deferred
// errors wait for their turn).
int bb_feed_threaded(BatchBuilder* bb, const char* blob, int64_t blob_len,
                     int64_t* consumed_out, char* err_out,
                     int64_t err_cap) {
  *consumed_out = 0;
  int rc = bb_drain(bb, err_out, err_cap);
  if (rc != 0) return rc;  // full from pending alone, or deferred error
  const char* end0 = blob + blob_len;
  // Last complete line: search the final newline from the back.
  const char* last_nl = nullptr;
  for (const char* c = end0 - 1; c >= blob; c--) {
    if (*c == '\n') {
      last_nl = c;
      break;
    }
  }
  if (last_nl == nullptr) return 0;  // no complete line: need more bytes
  const char* end = last_nl + 1;

  bb->p_labels.clear();
  bb->p_sizes.clear();
  bb->p_linenos.clear();
  bb->p_ids.clear();
  bb->p_vals.clear();
  bb->p_fields.clear();
  bb->p_cursor = 0;
  bb->p_nnz = 0;
  bb->p_failed = false;

  // Small feeds (EOF tails, tiny files) don't amortize thread spawns —
  // the same 64 KB cutoff fm_parse_block uses.
  const int T = (end - blob) < (64 << 10) ? 1 : bb->T;
  std::vector<ShardOut> outs = parse_threaded(
      blob, end, bb->lineno + 1, T, bb->vocab, bb->hash_ids,
      bb->field_aware, bb->field_num, bb->max_feats, bb->keep_empty,
      /*keep_linenos=*/true);
  // parse_range already walked every line; reuse its per-shard counts
  // instead of rescanning the chunk's bytes for newlines ([blob, end)
  // is newline-terminated, so lines == newlines). A failed shard
  // leaves lines_scanned partial — fall back to the byte scan there to
  // keep bb->lineno's post-error value unchanged (the stream is dead
  // after the error reaches the consumer, but parity is free).
  bool any_failed = false;
  for (const auto& o : outs) any_failed |= o.failed;
  if (any_failed) {
    for (const char* c = blob; c < end; c++) {
      if (*c == '\n') bb->lineno++;
    }
  } else {
    for (const auto& o : outs) bb->lineno += o.lines_scanned;
  }
  for (const auto& o : outs) {
    // A failed shard still contributes the examples it completed
    // before the error (labels may hold one half-parsed extra entry;
    // sizes is the count of COMPLETE examples).
    const size_t n_ok = o.sizes.size();
    int64_t nnz_ok = 0;
    for (size_t i = 0; i < n_ok; i++) nnz_ok += o.sizes[i];
    bb->p_labels.insert(bb->p_labels.end(), o.labels.begin(),
                        o.labels.begin() + std::ptrdiff_t(n_ok));
    bb->p_sizes.insert(bb->p_sizes.end(), o.sizes.begin(), o.sizes.end());
    bb->p_linenos.insert(bb->p_linenos.end(), o.linenos.begin(),
                         o.linenos.end());
    bb->p_ids.insert(bb->p_ids.end(), o.ids.begin(),
                     o.ids.begin() + std::ptrdiff_t(nnz_ok));
    bb->p_vals.insert(bb->p_vals.end(), o.vals.begin(),
                      o.vals.begin() + std::ptrdiff_t(nnz_ok));
    if (bb->field_aware) {
      bb->p_fields.insert(bb->p_fields.end(), o.fields.begin(),
                          o.fields.begin() + std::ptrdiff_t(nnz_ok));
    }
    if (o.failed) {
      bb->p_failed = true;
      bb->p_error = shard_error(o);
      break;  // later shards' examples come after the error: dropped
    }
  }
  *consumed_out = end - blob;
  return bb_drain(bb, err_out, err_cap);
}

}  // namespace

extern "C" {

void* fm_bb_new(int64_t B, int64_t L, int64_t vocab, int hash_ids,
                int field_aware, int64_t field_num, int raw_ids,
                int keep_empty, int max_feats, int64_t max_uniq,
                int num_threads) {
  if (B <= 0 || L <= 0 || vocab <= 0) return nullptr;
  if (field_aware != 0 && field_num <= 0) return nullptr;
  // raw_ids skips dedup entirely; the fixed-U spill protocol needs the
  // dedup table, so the two are mutually exclusive.
  if (raw_ids != 0 && max_uniq != 0) return nullptr;
  auto* bb = new BatchBuilder();
  bb->B = B;
  bb->L = L;
  bb->vocab = vocab;
  bb->hash_ids = hash_ids != 0;
  bb->field_aware = field_aware != 0;
  bb->raw_ids = raw_ids != 0;
  bb->keep_empty = keep_empty != 0;
  bb->field_num = field_num;
  bb->max_feats = (max_feats > 0 && max_feats < L) ? max_feats : int(L);
  // A single line adds <= max_feats uniques (+ the pad slot), so the cap
  // must exceed that or one line could never fit in an empty batch.
  if (max_uniq != 0 && max_uniq <= bb->max_feats) {
    delete bb;
    return nullptr;
  }
  bb->max_uniq = max_uniq;
  // Thread count for the feed parse phase (0 = auto). T == 1 keeps the
  // original single-pass loop — on a 1-core host the phase-split would
  // only add buffer traffic.
  const int T = num_threads > 0 ? num_threads : fm_auto_threads();
  bb->T = T < 1 ? 1 : T;
  bb->labels.resize(size_t(B));
  bb->uniq.resize(size_t(B * L + 1));
  bb->uniq[0] = int32_t(vocab);  // pad slot
  bb->li.assign(size_t(B * L), bb->raw_ids ? int32_t(vocab) : 0);
  bb->vals.assign(size_t(B * L), 0.0f);
  if (bb->field_aware) bb->fields.assign(size_t(B * L), 0);
  size_t cap = 16;
  while (cap < size_t(B * L) * 2) cap <<= 1;
  bb->mask = uint32_t(cap - 1);
  bb->slot.resize(cap);
  bb->stamp.assign(cap, 0);
  bb->cur_stamp = 1;
  return bb;
}

void fm_bb_free(void* h) { delete static_cast<BatchBuilder*>(h); }

// Parse lines from blob until the batch has B examples or the blob's
// complete lines are exhausted. Only whole lines (ending in '\n') are
// consumed — the caller carries the tail bytes into its next chunk.
// Returns 1 when the batch is full, 0 for "feed me more", -1 on parse
// error (message in err_out).
int fm_bb_feed(void* h, const char* blob, int64_t blob_len,
               int64_t* consumed_out, char* err_out, int64_t err_cap) {
  auto* bb = static_cast<BatchBuilder*>(h);
  if (bb->T > 1) {
    return bb_feed_threaded(bb, blob, blob_len, consumed_out, err_out,
                            err_cap);
  }
  const char* p = blob;
  const char* end = blob + blob_len;
  while (bb->n_ex < bb->B) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', size_t(end - p)));
    if (line_end == nullptr) break;  // partial line: leave for next chunk
    const char* q = p;
    bb->lineno++;
    while (q < line_end && is_ws(*q)) q++;
    if (q == line_end) {
      if (bb->keep_empty) {
        // Blank line -> zero-feature example, label 0 (predict owes one
        // score per input line; the row buffers are already pad/zero).
        bb->labels[size_t(bb->n_ex)] = 0.0f;
        bb->n_ex++;
      }
      p = line_end + 1;
      continue;
    }
    const char* tok_end = q;
    while (tok_end < line_end && !is_ws(*tok_end)) tok_end++;
    float label;
    if (!parse_float(q, tok_end, &label)) {
      std::snprintf(err_out, size_t(err_cap), "line %lld: bad label '%.*s'",
                    (long long)bb->lineno, int(tok_end - q), q);
      return -1;
    }
    float* vrow = bb->vals.data() + bb->n_ex * bb->L;
    int32_t* irow = bb->li.data() + bb->n_ex * bb->L;
    int32_t* frow = bb->field_aware
                        ? bb->fields.data() + bb->n_ex * bb->L
                        : nullptr;
    int n_feats = 0;
    bb->line_slots.clear();
    const int32_t saved_uniq = bb->n_uniq;
    q = tok_end;
    while (true) {
      while (q < line_end && is_ws(*q)) q++;
      if (q >= line_end) break;
      Token t;
      if (n_feats >= bb->max_feats) {  // cap: skip tail like Python
        while (q < line_end && !is_ws(*q)) q++;  // boundary only
        continue;
      }
      if (!try_fast_token(q, line_end, bb->vocab, bb->hash_ids,
                          bb->field_aware, bb->field_num, &tok_end,
                          &t)) {
        const char* c1;
        const char* c2;
        bool extra;
        tok_end = scan_token(q, line_end, &c1, &c2, &extra);
        std::string terr;
        if (parse_token(q, tok_end, c1, c2, extra, bb->vocab,
                        bb->hash_ids, bb->field_aware, bb->field_num, &t,
                        &terr)) {
          std::snprintf(err_out, size_t(err_cap), "line %lld: %s",
                        (long long)bb->lineno, terr.c_str());
          return -1;
        }
      }
      irow[n_feats] = bb->raw_ids ? t.row : bb_slot(bb, t.row);
      vrow[n_feats] = t.val;
      if (frow != nullptr) frow[n_feats] = t.field;
      n_feats++;
      q = tok_end;
    }
    if (bb->max_uniq != 0 && bb->n_uniq > bb->max_uniq) {
      // This line would push the batch past its unique-row budget:
      // roll it back, close the batch early (spill protocol — the line
      // is left unconsumed and opens the next batch). fm_bb_new
      // guarantees a single line always fits an empty batch.
      const int64_t spill_lineno = bb->lineno;
      bb->lineno--;  // will be re-fed
      const int rc = bb_budget_close(bb, irow, vrow, frow, n_feats,
                                     saved_uniq, spill_lineno, err_out,
                                     err_cap);
      if (rc < 0) return -1;
      *consumed_out = p - blob;
      return 1;
    }
    bb->labels[size_t(bb->n_ex)] = label;
    if (n_feats > bb->max_nnz) bb->max_nnz = n_feats;
    bb->n_ex++;
    p = line_end + 1;
  }
  *consumed_out = p - blob;
  return bb->n_ex >= bb->B ? 1 : 0;
}

// Copy the accumulated batch out and reset for the next one.
// labels_out[B], uniq_out[n_uniq] (slot 0 = pad_id), li_out[B*L],
// vals_out[B*L], fields_out[B*L] (field_aware builders only; may be
// null otherwise). Returns n_examples (0 if the batch is empty).
int64_t fm_bb_finish(void* h, float* labels_out, int32_t* uniq_out,
                     int32_t* li_out, float* vals_out, int32_t* fields_out,
                     int64_t* n_uniq_out, int64_t* max_nnz_out) {
  auto* bb = static_cast<BatchBuilder*>(h);
  const int64_t n = bb->n_ex;
  std::memcpy(labels_out, bb->labels.data(), size_t(n) * sizeof(float));
  std::memcpy(uniq_out, bb->uniq.data(),
              size_t(bb->n_uniq) * sizeof(int32_t));
  std::memcpy(li_out, bb->li.data(), size_t(bb->B * bb->L) * sizeof(int32_t));
  std::memcpy(vals_out, bb->vals.data(),
              size_t(bb->B * bb->L) * sizeof(float));
  if (bb->field_aware && fields_out != nullptr) {
    std::memcpy(fields_out, bb->fields.data(),
                size_t(bb->B * bb->L) * sizeof(int32_t));
  }
  *n_uniq_out = bb->n_uniq;
  *max_nnz_out = bb->max_nnz;
  bb_reset(bb);
  return n;
}

}  // extern "C"

extern "C" {

// First-occurrence-order unique + inverse over a batch's feature ids —
// the hot host-side replacement for np.unique(return_inverse=True), which
// is sort-based and dominates batch-build time at Criteo shapes (~320k
// ids -> ~14ms; this open-addressing pass is ~3ms). Order of uniq_out is
// insertion order, which downstream code treats as opaque.
// uniq_out/inverse_out are caller-allocated (nnz and nnz slots).
// Returns the number of unique ids.
int64_t fm_dedup_ids(const int32_t* ids, int64_t nnz, int32_t* uniq_out,
                     int32_t* inverse_out) {
  if (nnz <= 0) return 0;
  size_t cap = 16;
  while (cap < size_t(nnz) * 2) cap <<= 1;
  const uint32_t mask = uint32_t(cap - 1);
  std::vector<int32_t> slot(cap, -1);  // -> index into uniq_out
  int32_t n_uniq = 0;
  for (int64_t i = 0; i < nnz; i++) {
    const int32_t key = ids[i];
    uint32_t h = (uint32_t(key) * 2654435761u) & mask;
    for (;;) {
      const int32_t s = slot[h];
      if (s < 0) {
        slot[h] = n_uniq;
        uniq_out[n_uniq] = key;
        inverse_out[i] = n_uniq;
        n_uniq++;
        break;
      }
      if (uniq_out[s] == key) {
        inverse_out[i] = s;
        break;
      }
      h = (h + 1) & mask;
    }
  }
  return n_uniq;
}

}  // extern "C"
