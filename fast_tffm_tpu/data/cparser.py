"""ctypes loader for the C++ parser extension (``_parser.cc``).

The reference keeps line parsing in a C++ TF op because at target
throughput (SURVEY.md §7 hard part #4: ~280k lines/s/host-group) Python
string handling is the bottleneck. Here the same role is played by a plain
shared object built from ``_parser.cc`` with g++ on first use (no TF/pybind
dependency; see SURVEY §7 layer 2). ``parse_lines_fast`` matches
``parser.parse_lines``'s contract bit-for-bit (golden tests enforce it).

If the extension cannot be built/loaded, callers fall back to the Python
parser (pipeline._parse_block).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

from fast_tffm_tpu.data.parser import ParsedBlock, ParseError


def _tel():
    """The active run telemetry (obs/), or None. Parser-level counters
    (lines parsed, parse errors, bytes fed) live HERE — the one layer
    that sees every line regardless of which pipeline path consumed it.
    Lazy import: this module must stay importable without obs/ costs
    when telemetry is off."""
    from fast_tffm_tpu.obs.telemetry import active
    return active()

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "_parser.cc")
_SO = os.path.join(_HERE, "_parser.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_error: Optional[str] = None


def _build() -> None:
    # Build to a temp name and os.replace: atomic for concurrent
    # processes, and never rewrites a live mmap in place. NOTE this does
    # NOT make a same-path retry dlopen see the new library — glibc
    # dedups by pathname before stat'ing the inode — which is why
    # _load's ABI-mismatch retry opens the rebuilt file through a
    # one-off path.
    tmp = f"{_SO}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
           "-pthread", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _SO)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


# Must equal fm_abi_version() in _parser.cc. Bump both together whenever
# an exported signature changes.
_ABI_VERSION = 7


def _open_checked(path: Optional[str] = None) -> Optional[ctypes.CDLL]:
    """dlopen the .so and verify every symbol exists AND the compiled-in
    ABI version matches this wrapper. Returns None when the binary is
    stale — wrong version OR missing symbols (a pre-versioning .so has
    no fm_abi_version at all) — so the caller can rebuild once."""
    lib = ctypes.CDLL(path or _SO)
    try:
        lib.fm_abi_version
        lib.fm_auto_threads
        lib.fm_parse_block
        lib.fm_dedup_ids
        lib.fm_scan_examples
        lib.fm_bb_new
        lib.fm_bb_feed
        lib.fm_bb_finish
        lib.fm_bb_free
    except AttributeError:
        return None  # stale binary predating a symbol: rebuildable
    lib.fm_abi_version.restype = ctypes.c_int64
    lib.fm_abi_version.argtypes = []
    if lib.fm_abi_version() != _ABI_VERSION:
        return None
    return lib


def _load() -> ctypes.CDLL:
    global _lib, _load_error
    with _lock:
        if _lib is not None:
            return _lib
        if _load_error is not None:
            raise RuntimeError(_load_error)
        try:
            if not os.path.exists(_SO) or (
                    os.path.exists(_SRC)
                    and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
                if not os.path.exists(_SRC):
                    raise FileNotFoundError(_SRC)
                _build()
            lib = _open_checked()
            if lib is None:
                # Stale binary (ABI drift or missing symbols) with
                # source present: rebuild once and retry (an
                # mtime-preserving deploy can leave a stale .so "newer"
                # than the source; mtime/symbol checks alone can't catch
                # changed argument layouts — silent corruption).
                if not os.path.exists(_SRC):
                    raise RuntimeError(
                        f"{_SO} is a stale ABI and no source is present "
                        "to rebuild")
                _build()
                # dlopen dedups by PATHNAME before inode: re-opening _SO
                # would hand back the stale mapping we just probed. Open
                # the rebuilt library through a one-off path instead
                # (the mapping survives the unlink).
                import shutil
                reload_path = f"{_SO}.reload.{os.getpid()}"
                shutil.copy2(_SO, reload_path)
                try:
                    lib = _open_checked(reload_path)
                finally:
                    os.unlink(reload_path)
                if lib is None:
                    raise RuntimeError(
                        f"{_SO} is still a stale ABI after rebuild")
        except (OSError, FileNotFoundError, AttributeError,
                subprocess.CalledProcessError, RuntimeError) as e:
            _load_error = f"C++ parser unavailable: {e}"
            raise RuntimeError(_load_error)
        lib.fm_auto_threads.restype = ctypes.c_int
        lib.fm_auto_threads.argtypes = []
        lib.fm_parse_block.restype = ctypes.c_int
        lib.fm_parse_block.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,              # buffer, length
            ctypes.c_int64, ctypes.c_int,                 # vocab, hash flag
            ctypes.c_int, ctypes.c_int64,                 # field flag, count
            ctypes.c_int,                                 # max feats/example
            ctypes.c_int,                                 # keep_empty
            ctypes.c_int,                                 # num threads
            ctypes.POINTER(ctypes.c_int64),               # out: n_examples
            ctypes.POINTER(ctypes.c_int64),               # out: nnz
            np.ctypeslib.ndpointer(np.float32),           # labels buf
            np.ctypeslib.ndpointer(np.int32),             # poses buf
            np.ctypeslib.ndpointer(np.int32),             # ids buf
            np.ctypeslib.ndpointer(np.float32),           # vals buf
            np.ctypeslib.ndpointer(np.int32),             # fields buf
            ctypes.c_char_p, ctypes.c_int64,              # err buf, err cap
        ]
        lib.fm_dedup_ids.restype = ctypes.c_int64
        lib.fm_dedup_ids.argtypes = [
            np.ctypeslib.ndpointer(np.int32), ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32),             # uniq out
            np.ctypeslib.ndpointer(np.int32),             # inverse out
        ]
        lib.fm_scan_examples.restype = ctypes.c_int64
        lib.fm_scan_examples.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,              # blob, length
            ctypes.c_int64, ctypes.c_int,                 # n_target, keep
            ctypes.POINTER(ctypes.c_int64),               # out: consumed
            ctypes.POINTER(ctypes.c_int64)]               # out: lines
        lib.fm_bb_new.restype = ctypes.c_void_p
        lib.fm_bb_new.argtypes = [ctypes.c_int64, ctypes.c_int64,
                                  ctypes.c_int64, ctypes.c_int,
                                  ctypes.c_int, ctypes.c_int64,  # field flag, count
                                  ctypes.c_int,                  # raw_ids
                                  ctypes.c_int,                  # keep_empty
                                  ctypes.c_int, ctypes.c_int64,
                                  ctypes.c_int]                  # num_threads
        lib.fm_bb_free.argtypes = [ctypes.c_void_p]
        lib.fm_bb_feed.restype = ctypes.c_int
        lib.fm_bb_feed.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p, ctypes.c_int64]
        lib.fm_bb_finish.restype = ctypes.c_int64
        lib.fm_bb_finish.argtypes = [
            ctypes.c_void_p,
            np.ctypeslib.ndpointer(np.float32),           # labels
            np.ctypeslib.ndpointer(np.int32),             # uniq
            np.ctypeslib.ndpointer(np.int32),             # local_idx
            np.ctypeslib.ndpointer(np.float32),           # vals
            np.ctypeslib.ndpointer(np.int32),             # fields
            ctypes.POINTER(ctypes.c_int64),               # n_uniq
            ctypes.POINTER(ctypes.c_int64)]               # max_nnz
        _lib = lib
        return lib


def available() -> bool:
    try:
        _load()
        return True
    except RuntimeError:
        return False


def auto_threads() -> int:
    """The parse-thread count a ``num_threads=0`` builder actually uses
    — read from the library (fm_auto_threads) so reporting can't drift
    from the C++ rule. 1 when the extension is unavailable (the generic
    Python path is single-threaded)."""
    try:
        return int(_load().fm_auto_threads())
    except RuntimeError:
        return 1


def parse_lines_fast(lines: Sequence[str], vocabulary_size: int,
                     hash_feature_id: bool = False,
                     field_aware: bool = False, field_num: int = 0,
                     max_features_per_example: int = 0,
                     keep_empty: bool = False,
                     num_threads: int = 0) -> ParsedBlock:
    """C++-accelerated ``parse_lines`` (FM and field-aware FFM formats).
    ``keep_empty`` preserves blank lines as zero-feature label-0
    examples (the predict path's line alignment), matching the Python
    parser bit-for-bit. Raises RuntimeError when the extension is
    unusable, ParseError on malformed input."""
    lib = _load()
    # The output buffers below are sized from len(lines), but the C++
    # side splits the joined blob on '\n' — an EMBEDDED newline in one
    # input string would make it emit more examples than allocated
    # (heap overflow, reproduced as a SIGSEGV). The Python parser
    # treats '\n' inside a line as plain token whitespace (str.split),
    # so mapping it to ' ' preserves bit-for-bit parity while keeping
    # the example count equal to len(lines).
    lines = [ln.replace("\n", " ") if "\n" in ln else ln for ln in lines]
    blob = "\n".join(lines).encode("utf-8")
    if keep_empty and lines:
        # Terminate the final line: "a\nb".split('\n') drops no line in
        # C++, but a trailing EMPTY line ("a\n".join ending in "") is
        # invisible to the newline walk — and under keep_empty every
        # input line owes an example. Harmless otherwise, but only
        # keep_empty NEEDS it, so the strict path's blob stays
        # byte-identical to what it always fed.
        blob += b"\n"
    n_lines = len(lines)
    # Worst-case token count bounds the output buffers: a feature token is
    # at least 2 bytes ("i "), a line at least 2 ("0\n").
    max_nnz = max(len(blob) // 2 + 1, 1)
    labels = np.empty(n_lines, dtype=np.float32)
    poses = np.empty(n_lines + 1, dtype=np.int32)
    ids = np.empty(max_nnz, dtype=np.int32)
    vals = np.empty(max_nnz, dtype=np.float32)
    fields = np.empty(max_nnz if field_aware else 1, dtype=np.int32)
    n_ex = ctypes.c_int64(0)
    nnz = ctypes.c_int64(0)
    errbuf = ctypes.create_string_buffer(512)
    rc = lib.fm_parse_block(
        blob, len(blob), vocabulary_size, int(hash_feature_id),
        int(field_aware), field_num,
        max_features_per_example, int(keep_empty), num_threads,
        ctypes.byref(n_ex), ctypes.byref(nnz),
        labels, poses, ids, vals, fields, errbuf, len(errbuf))
    tel = _tel()
    if rc != 0:
        if tel is not None:
            tel.count("pipeline/parse_errors")
        raise ParseError(errbuf.value.decode("utf-8", "replace"))
    if tel is not None:
        tel.count("pipeline/lines_parsed", len(lines))
    b = n_ex.value
    z = nnz.value
    return ParsedBlock(labels=labels[:b].copy(), poses=poses[:b + 1].copy(),
                       ids=ids[:z].copy(), vals=vals[:z].copy(),
                       fields=fields[:z].copy() if field_aware else None)


def scan_examples(data: bytes, n_target: int, keep_empty: bool = False,
                  offset: int = 0) -> "tuple[int, int, int]":
    """Count example-producing lines in the COMPLETE lines of
    ``data[offset:]`` up to ``n_target``, without parsing: returns
    ``(found, bytes_consumed, lines_consumed)`` where ``bytes_consumed``
    ends at the last counted line's newline (relative to ``offset``)
    and ``lines_consumed`` includes the blank lines inside that span.
    The counting rule is the BatchBuilder's own (C++ ``is_ws``), so the
    parallel data plane's group cutter and the builder can never
    disagree about which lines fill a batch. Raises RuntimeError when
    the extension is unusable. Zero-copy via pointer arithmetic, like
    BatchBuilder.feed."""
    lib = _load()
    base = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value
    consumed = ctypes.c_int64(0)
    nlines = ctypes.c_int64(0)
    found = lib.fm_scan_examples(ctypes.c_void_p((base or 0) + offset),
                                 len(data) - offset, n_target,
                                 int(keep_empty), ctypes.byref(consumed),
                                 ctypes.byref(nlines))
    return int(found), int(consumed.value), int(nlines.value)


def parse_lines_salvage(lines: Sequence[str], vocabulary_size: int,
                        hash_feature_id: bool = False,
                        field_aware: bool = False, field_num: int = 0,
                        max_features_per_example: int = 0,
                        keep_empty: bool = False,
                        bad_lines: Optional[list] = None) -> ParsedBlock:
    """Tolerant block parse — the per-line failure surface of
    ``bad_line_policy = skip|quarantine`` over the C++ fast path.

    The C++ block parser is all-or-nothing by design (its threads
    abort the failing shard; per-line bookkeeping would slow the
    clean-corpus hot path that is 99.99%+ of production bytes). So
    tolerance is layered: the block goes through the C++ parser first,
    and only a FAILING block is retried through the Python parser's
    per-line tolerant mode, which identifies every bad line (recorded
    into ``bad_lines`` as ``(index, raw, message)``) and returns the
    block minus those lines. Clean blocks pay zero extra cost; a block
    with a bad line pays one Python re-parse of that block only.

    ``keep_empty`` rides the same layering since ABI 7 (fm_parse_block
    grew the blank-line-preserving mode): a clean keep_empty block is
    one C++ pass, and under ``keep_empty`` the Python retry replaces a
    bad line with a zero-feature example instead of dropping it, so
    predict's one-score-per-input-line alignment survives corruption.

    Pool-safe: every buffer here is per-call, the C++ block parser
    holds no global state, and the telemetry counters go through the
    locked registry — the parallel data plane calls this concurrently
    from its build workers (one bad block's Python retry runs on the
    worker that hit it, not a shared salvage structure).
    """
    if bad_lines is None:
        bad_lines = []
    try:
        return parse_lines_fast(
            lines, vocabulary_size,
            hash_feature_id=hash_feature_id,
            field_aware=field_aware, field_num=field_num,
            max_features_per_example=max_features_per_example,
            keep_empty=keep_empty)
    except (OSError, RuntimeError):
        pass  # C++ extension unavailable -> Python handles it all
    except ParseError:
        pass  # failing block -> tolerant Python retry below
    from fast_tffm_tpu.data.parser import parse_lines
    return parse_lines(
        lines, vocabulary_size, hash_feature_id=hash_feature_id,
        field_aware=field_aware, field_num=field_num,
        max_features_per_example=max_features_per_example,
        keep_empty=keep_empty, bad_lines=bad_lines)


class BatchBuilder:
    """Streaming raw-bytes -> padded-batch builder (C++ `fm_bb_*`).

    ``feed(chunk)`` consumes whole lines until the batch holds B
    examples, returning True when full (unconsumed tail bytes of the
    chunk must be re-fed). ``finish()`` returns the padded arrays —
    labels [B], uniq [n_uniq] with slot 0 = pad_id, local_idx [B, L],
    vals [B, L] — and resets for the next batch. One parse pass does
    parse + hash + dedup + padded scatter; there is no per-line Python.

    Concurrency contract (the parallel host data plane relies on it):
    the C++ library keeps ALL state per handle — distinct builders on
    distinct threads never share anything, so a pool of workers each
    OWNING one builder is safe, and every ctypes call releases the GIL
    for its duration. A single handle is NOT internally locked; one
    builder must stay owned by one thread at a time.
    """

    def __init__(self, batch_size: int, max_cols: int,
                 vocabulary_size: int, hash_feature_id: bool = False,
                 field_aware: bool = False, field_num: int = 0,
                 raw_ids: bool = False, keep_empty: bool = False,
                 max_features_per_example: int = 0, max_uniq: int = 0,
                 num_threads: int = 0):
        """``max_uniq`` > 0 caps the batch's unique-row count (incl. the
        pad slot): a line that would exceed it closes the batch early
        (spill) and opens the next one — the fixed-U protocol for
        multi-process SPMD. Must exceed the per-example feature cap.
        ``field_aware`` parses FFM ``field:fid[:val]`` tokens and makes
        ``finish()`` return a fields array. ``raw_ids`` (dedup=device)
        skips the dedup pass: local_idx holds raw feature ids (pad cells
        = vocabulary_size) and finish() returns uniq=None; incompatible
        with max_uniq. ``keep_empty`` turns blank lines into
        zero-feature examples (label 0) — the predict path's
        one-score-per-input-line alignment. ``num_threads`` sets the
        feed parse-thread count (0 = auto: min(8, cores)); with more
        than one thread each fed chunk is parsed in parallel and
        drained serially, with byte-identical outputs."""
        self._lib = _load()
        self.B, self.L = batch_size, max_cols
        self.field_aware = field_aware
        self.raw_ids = raw_ids
        self._h = self._lib.fm_bb_new(batch_size, max_cols,
                                      vocabulary_size,
                                      int(hash_feature_id),
                                      int(field_aware), field_num,
                                      int(raw_ids), int(keep_empty),
                                      max_features_per_example,
                                      max_uniq, num_threads)
        if not self._h:
            # ValueError, not RuntimeError: the extension IS available,
            # the arguments are wrong — callers must not read this as
            # "no C++, use the slow path" and silently degrade.
            raise ValueError("fm_bb_new rejected its arguments (bad "
                             "sizes, or max_uniq <= max feature count "
                             "per example)")
        self._err = ctypes.create_string_buffer(512)

    def feed(self, chunk: bytes, offset: int = 0) -> "tuple[bool, int]":
        """Feed ``chunk[offset:]`` (zero-copy via pointer arithmetic —
        the caller re-feeds from a moving offset after each full batch).
        Returns (batch_full, bytes_consumed)."""
        base = ctypes.cast(ctypes.c_char_p(chunk), ctypes.c_void_p).value
        consumed = ctypes.c_int64(0)
        rc = self._lib.fm_bb_feed(self._h,
                                  ctypes.c_void_p((base or 0) + offset),
                                  len(chunk) - offset,
                                  ctypes.byref(consumed), self._err,
                                  len(self._err))
        if rc < 0:
            tel = _tel()
            if tel is not None:
                tel.count("pipeline/parse_errors")
            raise ParseError(self._err.value.decode("utf-8", "replace"))
        tel = _tel()
        if tel is not None:
            # The streaming builder never forms Python lines; bytes fed
            # is its honest parse-volume counter (lines land in
            # pipeline/examples via the batch wrapper).
            tel.count("pipeline/bytes_fed", consumed.value)
        return rc == 1, consumed.value

    def finish(self):
        """-> (n_examples, labels[B], uniq[n_uniq], local_idx[B,L],
        vals[B,L], fields[B,L]-or-None, max_nnz); resets the builder."""
        labels = np.empty(self.B, np.float32)
        uniq = np.empty(self.B * self.L + 1, np.int32)
        li = np.empty((self.B, self.L), np.int32)
        vals = np.empty((self.B, self.L), np.float32)
        fields = np.empty((self.B, self.L) if self.field_aware else (1, 1),
                          np.int32)
        n_uniq = ctypes.c_int64(0)
        max_nnz = ctypes.c_int64(0)
        n = self._lib.fm_bb_finish(self._h, labels, uniq, li, vals, fields,
                                   ctypes.byref(n_uniq),
                                   ctypes.byref(max_nnz))
        return (int(n), labels,
                None if self.raw_ids else uniq[:n_uniq.value].copy(),
                li, vals,
                fields if self.field_aware else None, int(max_nnz.value))

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.fm_bb_free(h)
            self._h = None


def dedup_ids_fast(ids: np.ndarray):
    """First-occurrence unique + inverse (np.unique(return_inverse=True)
    contract minus sortedness, which callers treat as opaque). ~5x faster
    than the sort-based np.unique on batch-sized id arrays. Raises
    RuntimeError when the extension is unusable."""
    lib = _load()
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    n = len(ids)
    if n == 0:
        return ids[:0], np.zeros(0, dtype=np.int32)
    uniq = np.empty(n, dtype=np.int32)
    inverse = np.empty(n, dtype=np.int32)
    n_uniq = lib.fm_dedup_ids(ids, n, uniq, inverse)
    return uniq[:n_uniq].copy(), inverse
