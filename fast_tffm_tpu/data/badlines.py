"""Bad-line policy bookkeeping: skip/quarantine accounting, the
rate-limited ``health: bad_input`` events, and the ``max_bad_fraction``
circuit breaker (README "Fault tolerance").

The production corpora fast_tffm served (SURVEY §5) are huge, messy,
and regenerated daily — a single malformed line must not abort a
multi-hour run (``bad_line_policy = skip|quarantine``), but silent
corpus rot must not train a garbage model either, so the breaker
aborts with the worst offending file named once the bad fraction
crosses the configured ceiling.

One ``BadLineTracker`` instance follows one run's pipeline (train
passes a single tracker through every epoch's iterator; evaluate/
predict auto-create their own), so the fraction, the per-file
attribution, and the quarantine dedupe all see the whole run:

- every skipped line counts ``pipeline/bad_lines`` in the metrics
  stream and bumps the per-file tally;
- ``health: bad_input`` events are rate-limited on a power-of-two
  schedule (the 1st, 2nd, 4th, 8th, ... bad line emits) — visibility
  without letting a 1%-corrupt terabyte corpus write millions of
  events;
- ``quarantine`` appends one JSON line per offending input line —
  ``{"file", "lineno", "error", "raw"}`` — to the quarantine sidecar
  (``<metrics_file>.quarantine``, or ``<model_file>.quarantine`` when
  metrics are off), deduplicated by (file, lineno) so a multi-epoch
  run records each bad line once;
- the breaker trips when ``bad / total > max_bad_fraction`` AND at
  least ``MIN_BAD_LINES_TO_TRIP`` lines are bad (one early bad line
  in a small sample must not abort a run the fraction would forgive
  over the full corpus).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional, Set, Tuple

# Absolute floor before the fraction breaker may trip: the fraction
# estimate over fewer bad lines than this is too noisy to abort on.
MIN_BAD_LINES_TO_TRIP = 8


class BadInputError(ValueError):
    """The max_bad_fraction circuit breaker: too much of the corpus is
    malformed for skip/quarantine to be safe."""


def quarantine_path(cfg) -> str:
    """Where this process quarantines offending lines: beside the
    metrics stream when one exists, beside the model file otherwise.
    BOTH branches carry the per-process shard suffix (the metrics path
    already has it; the model-file fallback adds its own), so P
    concurrent writers of a multi-process run never interleave in one
    file."""
    from fast_tffm_tpu.obs.telemetry import resolve_metrics_path
    base = resolve_metrics_path(cfg)
    if base is None:
        base = getattr(cfg, "model_file", "./fm_model")
        import jax
        p = jax.process_index()
        if p:
            base = f"{base}.p{p}"
    return base + ".quarantine"


class BadLineTracker:
    """Accounting for one run's bad-line policy; see module docstring.

    ``record()`` raises BadInputError when the breaker trips — the
    pipeline lets it propagate, aborting the run with the worst file
    named. ``count_ok(n)`` feeds the denominator."""

    def __init__(self, policy: str, max_bad_fraction: float,
                 quarantine_file: Optional[str] = None):
        if policy not in ("skip", "quarantine"):
            raise ValueError(
                f"BadLineTracker is for tolerant policies, got "
                f"{policy!r}")
        self.policy = policy
        self.max_bad_fraction = float(max_bad_fraction)
        self.quarantine_file = quarantine_file
        self.total = 0          # lines scanned (good + bad)
        self.bad = 0            # lines skipped
        self.by_file: Dict[str, int] = {}
        self._next_emit = 1     # power-of-two health-event schedule
        self._quarantined: Set[Tuple[str, int]] = set()
        self._q_fh = None
        self._breaker: Optional[BadInputError] = None
        # The tracker is run-scoped and fed from prefetch PRODUCER
        # threads AND the parallel data plane's build workers (several
        # concurrent recorders per run is now the normal case, not the
        # brief-overlap exception), so the counters, the quarantine
        # handle, and the breaker all serialize here.
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, cfg) -> Optional["BadLineTracker"]:
        """A tracker per the config's policy, or None for ``error``
        (the zero-cost default path)."""
        policy = getattr(cfg, "bad_line_policy", "error")
        if policy == "error":
            return None
        return cls(policy, getattr(cfg, "max_bad_fraction", 0.01),
                   quarantine_file=(quarantine_path(cfg)
                                    if policy == "quarantine" else None))

    # -- accounting ------------------------------------------------------
    def count_ok(self, n: int) -> None:
        with self._lock:
            self.total += n

    def record(self, path: str, lineno: int, raw: str,
               error: str) -> None:
        """One bad line skipped: count, attribute, maybe emit a health
        event, maybe quarantine, check the breaker (which raises)."""
        from fast_tffm_tpu.obs.telemetry import active
        tel = active()
        with self._lock:
            if self._breaker is not None:
                # The breaker TRIPS ONCE: under the parallel data
                # plane several workers can cross the threshold
                # near-simultaneously, and each must surface the SAME
                # stored diagnosis (same worst file, same counts) —
                # not re-count lines past the abort or mint competing
                # error texts.
                raise self._breaker
            self.total += 1
            self.bad += 1
            self.by_file[path] = self.by_file.get(path, 0) + 1
            if tel is not None:
                tel.count("pipeline/bad_lines")
                if self.bad >= self._next_emit:
                    while self._next_emit <= self.bad:
                        self._next_emit *= 2
                    tel.sink.emit("health", {
                        "status": "bad_input",
                        "policy": self.policy,
                        "bad_lines": self.bad,
                        "total_lines": self.total,
                        "file": path,
                        "lineno": lineno,
                        "error": error[:200],
                    })
            if (self.quarantine_file is not None
                    and (path, lineno) not in self._quarantined):
                self._quarantined.add((path, lineno))
                if self._q_fh is None:
                    d = os.path.dirname(os.path.abspath(
                        self.quarantine_file))
                    os.makedirs(d, exist_ok=True)
                    self._q_fh = open(self.quarantine_file, "a",
                                      encoding="utf-8")
                self._q_fh.write(json.dumps(
                    {"file": path, "lineno": lineno, "error": error,
                     "raw": raw}) + "\n")
                self._q_fh.flush()  # must survive a later crash
            self._check_breaker()

    def _check_breaker(self) -> None:
        # Caller holds the lock (BadInputError propagates out of the
        # `with`, releasing it).
        if (self.bad >= MIN_BAD_LINES_TO_TRIP and self.total
                and self.bad / self.total > self.max_bad_fraction):
            worst, n_worst = max(self.by_file.items(),
                                 key=lambda kv: kv[1])
            self._breaker = BadInputError(
                f"aborting: {self.bad} of {self.total} input lines "
                f"({self.bad / self.total:.2%}) are malformed, over "
                f"the max_bad_fraction ceiling "
                f"({self.max_bad_fraction:.2%}); worst file: {worst} "
                f"({n_worst} bad lines). The corpus looks corrupt — "
                "fix the data (see the quarantine file if "
                "bad_line_policy = quarantine) or raise "
                "max_bad_fraction if this corruption level is "
                "expected.")
            raise self._breaker

    def describe(self) -> str:
        frac = self.bad / self.total if self.total else 0.0
        return (f"{self.bad} bad line(s) of {self.total} scanned "
                f"({frac:.3%}) under policy {self.policy}"
                + (f"; quarantined to {self.quarantine_file}"
                   if self.quarantine_file else ""))

    def close(self) -> None:
        with self._lock:
            if self._q_fh is not None:
                self._q_fh.close()
                self._q_fh = None
