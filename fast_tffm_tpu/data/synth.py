"""Faithfully synthesized Criteo-Kaggle-like CTR data with known ground
truth.

BASELINE config #1 names the Criteo-Kaggle 1M-row libsvm sample and the
tracked metric is "examples/sec/chip + test-AUC", but no real dataset
ships in this environment (SURVEY.md §0: no network). This module
synthesizes data with the distributional properties that make Criteo
hard — and, unlike the real thing, a KNOWN generative model, so measured
AUC can be compared against an independent oracle trained on the same
draws (tests/test_criteo_like.py, tools/criteo_bench.py):

- 26 categorical fields with mixed vocabulary sizes (tens to ~100k) and
  Zipf-skewed id frequencies (head ids dominate, a long rare tail);
- 13 numeric fields, log-normal counts written as ``I<j>:<log1p value>``;
- labels ~ Bernoulli(sigmoid(logit)) where the logit is a real FM-style
  model: per-id main effects + low-rank pairwise interactions between
  selected field pairs + linear numeric effects. The positive rate is
  CTR-like but seed-dependent (the head ids' drawn effects shift the
  mean logit; observed ~6-25% across seeds) — callers that need a
  specific rate must check write_dataset's returned metadata;
- tokens are strings (``C<f>=v<id>``), exercising the murmur hashing
  path mod a 2^20 space with realistic collision rates.

Everything is drawn from one seeded Generator, so train/test splits and
reruns are deterministic.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Tuple

import numpy as np

# 26 categorical fields, vocab sizes spanning the Criteo spread (a few
# categories to ~100k); indices are the C14-C39-style fields.
CAT_VOCABS: Tuple[int, ...] = (
    40, 500, 90000, 30000, 200, 15, 10000, 400, 3, 25000,
    4000, 80000, 3000, 25, 8000, 60000, 10, 4000, 1500, 4,
    50000, 12, 14, 30000, 60, 20000)
NUM_FIELDS = 13          # numeric I1..I13
ZIPF_A = 1.35            # id popularity skew
PAIR_RANK = 4            # latent dim of ground-truth pair interactions
N_PAIRS = 30             # interacting field pairs


@dataclasses.dataclass
class GroundTruth:
    """The generative model: enough to recompute any example's logit."""
    main: List[np.ndarray]          # per field: [vocab_f] effects
    pair_u: dict                    # (f, g) -> ([vocab_f, R], [vocab_g, R])
    num_w: np.ndarray               # [NUM_FIELDS] numeric coefficients
    bias: float


def make_ground_truth(seed: int = 0) -> GroundTruth:
    rng = np.random.default_rng(seed)
    main = [rng.normal(0.0, 0.45, size=v) for v in CAT_VOCABS]
    pairs = {}
    n_fields = len(CAT_VOCABS)
    chosen = set()
    while len(chosen) < N_PAIRS:
        f, g = sorted(rng.choice(n_fields, size=2, replace=False))
        chosen.add((int(f), int(g)))
    for f, g in chosen:
        pairs[(f, g)] = (
            rng.normal(0.0, 0.35, size=(CAT_VOCABS[f], PAIR_RANK)),
            rng.normal(0.0, 0.35, size=(CAT_VOCABS[g], PAIR_RANK)))
    num_w = rng.normal(0.0, 0.25, size=NUM_FIELDS)
    # Centers the logit in CTR territory; the realized positive rate
    # still moves with the seed's head-id effect draws (see module doc).
    return GroundTruth(main=main, pair_u=pairs, num_w=num_w, bias=-1.9)


def _draw_ids(rng: np.random.Generator, n: int) -> np.ndarray:
    """[n, 26] Zipf-skewed categorical ids (head-heavy, long tail)."""
    cols = []
    for v in CAT_VOCABS:
        z = rng.zipf(ZIPF_A, size=n)
        cols.append((z - 1) % v)
    return np.stack(cols, axis=1)


def logits_for(gt: GroundTruth, cat_ids: np.ndarray,
               num_z: np.ndarray) -> np.ndarray:
    """Ground-truth logit for drawn examples ([n, 26] ids, [n, 13]
    transformed numerics)."""
    logit = np.full(len(cat_ids), gt.bias)
    for f in range(len(CAT_VOCABS)):
        logit += gt.main[f][cat_ids[:, f]]
    for (f, g), (u, v) in gt.pair_u.items():
        logit += np.einsum("nr,nr->n", u[cat_ids[:, f]], v[cat_ids[:, g]])
    logit += num_z @ gt.num_w
    return logit


def generate(n: int, seed: int, gt: GroundTruth
             ) -> Tuple[List[str], np.ndarray, np.ndarray]:
    """n libsvm lines + the labels + the true logits (for headroom
    measurement: AUC of the true logit is the Bayes ceiling)."""
    rng = np.random.default_rng(seed)
    cat_ids = _draw_ids(rng, n)
    counts = rng.lognormal(mean=1.0, sigma=1.2, size=(n, NUM_FIELDS))
    num_z = np.round(np.log1p(counts), 3)
    logit = logits_for(gt, cat_ids, num_z)
    labels = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.int32)
    # ~8% of numeric fields are missing (dropped token), like Criteo
    miss = rng.random((n, NUM_FIELDS)) < 0.08
    lines = []
    for i in range(n):
        parts = [str(labels[i])]
        parts += [f"I{j}:{num_z[i, j]}" for j in range(NUM_FIELDS)
                  if not miss[i, j]]
        parts += [f"C{f}=v{cat_ids[i, f]}" for f in range(len(CAT_VOCABS))]
        lines.append(" ".join(parts))
    # Headroom ceiling = the OBSERVED-information logit: the dropped
    # numeric tokens contributed to the label-generating logit but are
    # absent from the written files, so a ceiling computed from the
    # full logit would overstate what any model trained on the files
    # can reach (part of the gap would be irreducible information
    # loss, not trainer underperformance). Labels keep the full logit —
    # the data itself is byte-identical to before.
    obs_logit = logit - np.where(miss, num_z, 0.0) @ gt.num_w
    return lines, labels, obs_logit


def write_dataset(path_train: str, path_test: str, n_train: int,
                  n_test: int, seed: int = 0) -> dict:
    """Write train/test files; returns metadata incl. the Bayes-ceiling
    AUC of the true logits on the test split."""
    from fast_tffm_tpu.metrics import exact_auc
    gt = make_ground_truth(seed)
    train_lines, train_y, _ = generate(n_train, seed + 1, gt)
    test_lines, test_y, test_logit = generate(n_test, seed + 2, gt)
    with open(path_train, "w") as fh:
        fh.write("\n".join(train_lines) + "\n")
    with open(path_test, "w") as fh:
        fh.write("\n".join(test_lines) + "\n")
    return {
        "n_train": n_train, "n_test": n_test,
        "positive_rate_train": float(train_y.mean()),
        "positive_rate_test": float(test_y.mean()),
        "bayes_auc": exact_auc(test_logit, test_y),
    }


# ---------------------------------------------------------------------------
# Independent NumPy SGD-FM oracle: hand-derived gradients, numpy-only
# training loop. Shares ONLY the parsed CSR arrays with the framework
# (parser parity is separately golden-tested); the model, backward pass,
# and update rule are written from the math in SURVEY §3.5, not from
# models/fm.py, so agreement is evidence, not tautology.
# ---------------------------------------------------------------------------


def _pad_batches(blocks, L: int, pad_id: int
                 ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Padded slots point at the dead row ``pad_id`` (== vocab, the
    documented invariant): id 0 is a live hashed row and must not
    collect padding's reg/accumulator updates."""
    for block in blocks:
        n = block.batch_size
        ids = np.full((n, L), pad_id, np.int64)
        x = np.zeros((n, L), np.float64)
        sizes = block.sizes
        rows = np.repeat(np.arange(n), sizes)
        cols = np.arange(len(rows)) - np.repeat(block.poses[:-1], sizes)
        ids[rows, cols] = block.ids
        x[rows, cols] = block.vals
        yield ids, x, block.labels.astype(np.float64)


def _fm_forward(z: np.ndarray, order: int):
    """Interaction value per (example, factor dim) and its dz gradient.

    order 2: e2 = (e1² - p2)/2,            d e2/dz_l = e1 - z_l
    order 3: adds e3 = (e1³ - 3·e1·p2 + 2·p3)/6,
             d e3/dz_l = e2 − z_l·(e1 − z_l)   (the ANOVA identity:
             the degree-3 kernel's partial is the degree-2 kernel over
             the OTHER slots) — matching ops/interaction._anova_terms'
             "degrees 2..order" definition.
    Returns (inter [B, k], dz [B, L, k])."""
    e1 = z.sum(axis=1)                                  # [B, k]
    p2 = np.square(z).sum(axis=1)
    e2 = 0.5 * (np.square(e1) - p2)
    inter = e2.copy()
    dz = e1[:, None, :] - z                             # [B, L, k]
    if order == 3:
        p3 = (z ** 3).sum(axis=1)
        inter += (e1 ** 3 - 3.0 * e1 * p2 + 2.0 * p3) / 6.0
        dz = dz + (e2[:, None, :] - z * (e1[:, None, :] - z))
    elif order != 2:
        raise ValueError(f"oracle supports order 2 or 3, got {order}")
    return inter, dz


def numpy_fm_train_predict(train_blocks, test_blocks, vocab: int, k: int,
                           lr: float, epochs: int, factor_lambda: float,
                           bias_lambda: float, init_range: float = 0.01,
                           adagrad_init: float = 0.1, seed: int = 7,
                           L: int = 48, order: int = 2) -> np.ndarray:
    """Train an order-2 (or order-3 ANOVA, BASELINE config #4) FM with
    minibatch Adagrad in pure NumPy and return raw test scores. Padded
    id slots point at the dead row ``vocab`` with x=0. Backward (per
    example, g = dloss/dscore):
        dw[l] = g x_l ;  dv[l, f] = g x_l · (d inter_f / d z_{l,f})
    with the interaction/gradient pair in _fm_forward.
    """
    rng = np.random.default_rng(seed)
    W = rng.uniform(-init_range, init_range, size=(vocab + 1, k + 1))
    W[-1] = 0.0
    acc = np.full((vocab + 1, k + 1), adagrad_init)

    for _ in range(epochs):
        for ids, x, y in _pad_batches(train_blocks, L, vocab):
            B = len(y)
            rows = W[ids]                                   # [B, L, k+1]
            v, w = rows[..., :k], rows[..., k]
            z = v * x[..., None]                            # [B, L, k]
            inter, dz = _fm_forward(z, order)
            score = (w * x).sum(axis=1) + inter.sum(axis=1)
            p = 1.0 / (1.0 + np.exp(-score))
            g = (p - y) / B                                 # [B]
            dv = g[:, None, None] * x[..., None] * dz
            dw = g[:, None] * x
            grad = np.concatenate([dv, dw[..., None]], axis=2)
            # Sparse accumulation onto the batch's unique rows (the
            # vocab-sized dense buffer would dominate at 2^22 rows),
            # plus batch-active L2 on those rows (SURVEY §3.5).
            uniq, inv = np.unique(ids, return_inverse=True)
            grows = np.zeros((len(uniq), k + 1))
            np.add.at(grows, inv.ravel(), grad.reshape(-1, k + 1))
            grows[:, :k] += 2.0 * factor_lambda * W[uniq, :k]
            grows[:, k] += 2.0 * bias_lambda * W[uniq, k]
            acc[uniq] += np.square(grows)
            W[uniq] -= lr * grows / np.sqrt(acc[uniq])
            W[-1] = 0.0  # dead pad row stays dead

    scores = []
    for ids, x, _ in _pad_batches(test_blocks, L, vocab):
        rows = W[ids]
        v, w = rows[..., :k], rows[..., k]
        z = v * x[..., None]
        inter, _ = _fm_forward(z, order)
        scores.append((w * x).sum(axis=1) + inter.sum(axis=1))
    return np.concatenate(scores)


# ---------------------------------------------------------------------------
# Field-aware (FFM) twin: Avazu-like data with a KNOWN field-aware
# generative model, plus an independent NumPy FFM-SGD oracle — the
# config-#3 analogue of the FM pair above. One categorical id per field
# per example (Avazu's shape), ids offset into disjoint per-field ranges
# of one vocabulary space (the framework's single-table FFM layout).
# ---------------------------------------------------------------------------

FFM_FIELDS: Tuple[int, ...] = (40, 3000, 25000, 15, 400, 9000, 3,
                               1200, 60000, 25, 5000, 150)
# Cumulative per-field offsets keep ids disjoint in ONE compact vocab
# (Σ field vocabs ~104k rows) instead of fixed power-of-two strides
# whose table would be ~87% dead rows — the framework and the oracle
# both size their tables from ffm_vocab_size().
FFM_FIELD_OFFSETS: Tuple[int, ...] = tuple(
    int(x) for x in np.concatenate([[0], np.cumsum(FFM_FIELDS)[:-1]]))
FFM_PAIR_RANK = 3
FFM_N_PAIRS = 20


def ffm_vocab_size() -> int:
    return int(sum(FFM_FIELDS))


def _make_ffm_truth(seed: int):
    rng = np.random.default_rng(seed)
    F = len(FFM_FIELDS)
    main = [rng.normal(0.0, 0.4, size=v) for v in FFM_FIELDS]
    chosen = set()
    while len(chosen) < FFM_N_PAIRS:
        f, g = sorted(rng.choice(F, size=2, replace=False))
        chosen.add((int(f), int(g)))
    pairs = {(f, g): (rng.normal(0.0, 0.4, size=(FFM_FIELDS[f],
                                                 FFM_PAIR_RANK)),
                      rng.normal(0.0, 0.4, size=(FFM_FIELDS[g],
                                                 FFM_PAIR_RANK)))
             for f, g in chosen}
    return main, pairs


def _ffm_generate(n: int, seed: int, truth):
    main, pairs = truth
    rng = np.random.default_rng(seed)
    F = len(FFM_FIELDS)
    ids = np.stack([(rng.zipf(ZIPF_A, size=n) - 1) % v
                    for v in FFM_FIELDS], axis=1)       # [n, F]
    logit = np.full(n, -1.2)
    for f in range(F):
        logit += main[f][ids[:, f]]
    for (f, g), (u, v) in pairs.items():
        logit += np.einsum("nr,nr->n", u[ids[:, f]], v[ids[:, g]])
    labels = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(
        np.int32)
    lines = [" ".join([str(labels[i])]
                      + [f"{f}:{FFM_FIELD_OFFSETS[f] + ids[i, f]}"
                         for f in range(F)])
             for i in range(n)]
    return lines, labels, logit, ids


def write_ffm_dataset(path_train: str, path_test: str, n_train: int,
                      n_test: int, seed: int = 0) -> dict:
    """Write field-aware train/test files (`f:id` tokens, one id per
    field); returns metadata incl. the Bayes-ceiling AUC."""
    from fast_tffm_tpu.metrics import exact_auc
    truth = _make_ffm_truth(seed)
    train_lines, train_y, _, _ = _ffm_generate(n_train, seed + 1, truth)
    test_lines, test_y, test_logit, _ = _ffm_generate(n_test, seed + 2,
                                                      truth)
    with open(path_train, "w") as fh:
        fh.write("\n".join(train_lines) + "\n")
    with open(path_test, "w") as fh:
        fh.write("\n".join(test_lines) + "\n")
    return {"n_train": n_train, "n_test": n_test,
            "positive_rate_train": float(train_y.mean()),
            "positive_rate_test": float(test_y.mean()),
            "bayes_auc": exact_auc(test_logit, test_y)}


def parse_ffm_file(path: str, batch_size: int):
    """[B, F] global-id batches + labels, parsed directly from `f:id`
    lines — the oracle's OWN reader (independence from the framework's
    parser; golden parity for that parser is tested separately)."""
    F = len(FFM_FIELDS)
    batches = []
    ids_buf, y_buf = [], []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            toks = line.split()
            if not toks:
                continue
            y_buf.append(float(toks[0]))
            row = np.full(F, -1, np.int64)  # -1 = field unseen: a
            # truncated or duplicated line must fail loudly here, not
            # silently train the oracle on different data than the
            # framework parser sees (which would void parity)
            for t in toks[1:]:
                f, i = t.split(":")
                f = int(f)
                if row[f] >= 0:
                    raise ValueError(
                        f"{path}:{lineno}: field {f} appears twice")
                row[f] = int(i)
            if (row < 0).any():
                raise ValueError(
                    f"{path}:{lineno}: expected one token per field "
                    f"(fields {np.flatnonzero(row < 0).tolist()} "
                    "missing)")
            ids_buf.append(row)
            if len(ids_buf) == batch_size:
                batches.append((np.stack(ids_buf),
                                np.asarray(y_buf)))
                ids_buf, y_buf = [], []
    if ids_buf:
        batches.append((np.stack(ids_buf), np.asarray(y_buf)))
    return batches


def numpy_ffm_train_predict(train_batches, test_batches, vocab: int,
                            k: int, lr: float, epochs: int,
                            factor_lambda: float, bias_lambda: float,
                            init_range: float = 0.01,
                            adagrad_init: float = 0.1,
                            seed: int = 7) -> np.ndarray:
    """Independent field-aware FM oracle, hand-derived gradients.

    Row layout [vocab+1, F*k + 1]: v[id, g*k:(g+1)*k] is id's latent
    toward TARGET field g, last column the linear weight (the
    framework's documented FFM layout, but the math here is written
    from the FFM definition, not from ops/interaction.py):
        score = Σ_f w[id_f] + Σ_{f<g} <v[id_f,:,g], v[id_g,:,f]>
        d score / d v[id_f, :, g] = v[id_g, :, f]   (and symmetric)
        d score / d w[id_f]      = 1
    Minibatch mean logistic gradient + batch-active L2 + Adagrad —
    the same update semantics as numpy_fm_train_predict.
    """
    F = len(FFM_FIELDS)
    D = F * k + 1
    rng = np.random.default_rng(seed)
    W = rng.uniform(-init_range, init_range, size=(vocab + 1, D))
    acc = np.full((vocab + 1, D), adagrad_init)

    def batch_scores(ids, Wm):
        rows = Wm[ids]                              # [B, F, D]
        v = rows[..., :F * k].reshape(len(ids), F, F, k)
        score = rows[..., -1].sum(axis=1)
        for f in range(F):
            for g in range(f + 1, F):
                score += (v[:, f, g] * v[:, g, f]).sum(axis=1)
        return score, v

    for _ in range(epochs):
        for ids, y in train_batches:
            B = len(y)
            score, v = batch_scores(ids, W)
            p = 1.0 / (1.0 + np.exp(-score))
            gl = (p - y) / B                        # [B]
            grad = np.zeros((B, F, D))
            for f in range(F):
                for g in range(F):
                    if f == g:
                        continue
                    # d score/d v[id_f, :, g] = v[id_g, :, f]
                    grad[:, f, g * k:(g + 1) * k] = (
                        gl[:, None] * v[:, g, f])
                grad[:, f, -1] = gl
            uniq, inv = np.unique(ids, return_inverse=True)
            grows = np.zeros((len(uniq), D))
            np.add.at(grows, inv.ravel(), grad.reshape(-1, D))
            grows[:, :F * k] += 2.0 * factor_lambda * W[uniq, :F * k]
            grows[:, -1] += 2.0 * bias_lambda * W[uniq, -1]
            acc[uniq] += np.square(grows)
            W[uniq] -= lr * grows / np.sqrt(acc[uniq])

    out = []
    for ids, _ in test_batches:
        out.append(batch_scores(ids, W)[0])
    return np.concatenate(out)


def parse_file_blocks(path: str, vocab: int, batch_size: int):
    """Parse a libsvm file into CSR blocks via the (golden-tested) fast
    parser — the shared input both trainers consume."""
    from fast_tffm_tpu.data.pipeline import _parse_block
    from fast_tffm_tpu.data.cparser import parse_lines_fast
    from fast_tffm_tpu.config import FmConfig
    # _parse_block falls back to the Python parser itself if the C++
    # extension turns out to be unusable at call time.
    cfg = FmConfig(vocabulary_size=vocab, hash_feature_id=True,
                   max_features_per_example=48)
    out = []
    with open(path) as fh:
        buf = []
        for line in fh:
            if line.strip():
                buf.append(line)
            if len(buf) == batch_size:
                out.append(_parse_block(buf, cfg, parse_lines_fast))
                buf = []
        if buf:
            out.append(_parse_block(buf, cfg, parse_lines_fast))
    return out
