"""Append-only streaming source — ``run_mode = stream`` (README
"Streaming / online learning").

Production CTR models retrain continuously: shards ARRIVE (a feed
pipeline appends `part-00017`, seals it, starts `part-00018`) rather
than existing up front. This module puts that arrival process behind
the pipeline's batch abstraction so the train driver can run one
indefinitely-surviving online pass:

- **Discovery**: ``stream_dir`` (a directory, or a glob pattern) is
  polled every ``stream_poll_seconds``; new files join an ordered
  LEDGER in first-seen order (sorted within a poll) and are consumed
  strictly in ledger order — the stream is a log, so batches are the
  same ones a clean single-pass run over the final sealed corpus
  would build (the ``stream-soak`` chaos acceptance pins this
  bit-identity).
- **Hostile filesystem**: a growing file is tailed with the torn
  trailing line HELD BACK until more bytes arrive or the file is
  sealed (a ``<file>.done`` marker, or mtime-quiet — ``seal_policy``);
  truncation/rotation of an in-progress file is detected by
  (inode, size) regression and quarantined through the run's
  :class:`~fast_tffm_tpu.data.badlines.BadLineTracker` instead of
  crashing; a deleted file is logged and skipped; every stat/open/read
  rides ``utils/retry.py``.
- **Durable position**: every emitted batch is tagged with the
  watermark payload (per-file byte/line offsets + sealed/dead flags,
  in ledger order) that holds AFTER its lines. The train loop adopts a
  tag only once the batch is actually stepped, so the watermark
  checkpointed beside the model (``watermark-<step>.json``,
  checkpoint.py) describes exactly what was trained — restore (and the
  PR 4 quarantine walk-back to an older step) resumes the stream with
  no example duplicated or skipped (an older watermark re-reads, never
  skips).
- **Parallel host plane**: with ``host_threads > 1`` the PR 7 bounded
  ordered ring consumes complete line GROUPS cut by the builder's own
  counting rule; held-back unsealed tails never enter the ring (groups
  are only cut from released, newline-terminated bytes), and the
  emitted stream is bit-identical to the serial stream path (pinned by
  tests/test_stream.py).
- **Lockstep multi-worker**: file ownership is by ledger index
  (``i % num_shards``); workers agree on the ledger (and the STOP
  decision) through a chief-broadcast ride on the existing
  ``guarded_collective`` barriers, issued exactly once per driver loop
  iteration so the collective program stays deterministic; per-worker
  watermarks merge at save time (``exchange_watermarks``).

A ``STOP`` marker file in the stream directory ends the run once every
sealed byte is consumed; until then the source reports IDLE and the
driver keeps polling (that is the "survives indefinitely" loop).
"""

from __future__ import annotations

import collections
import functools
import glob as globlib
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.badlines import BadLineTracker
from fast_tffm_tpu.data.parser import WHITESPACE, ParseError
from fast_tffm_tpu.utils.logging import get_logger
from fast_tffm_tpu.utils.retry import (RetryPolicy, open_with_retry,
                                       retry_io)

# Sentinels next_batch returns besides a DeviceBatch: IDLE = no batch
# available right now (keep polling / feed a lockstep filler); DONE =
# the stream ended (STOP marker seen and every sealed byte consumed, or
# the caller's stop() asked for a clean exit).
IDLE = object()
DONE = object()

# Writer protocol markers (documented in README "Streaming / online
# learning"): `<file>.done` seals one shard; `STOP` in the stream root
# declares the whole stream finished.
DONE_SUFFIX = ".done"
STOP_MARKER = "STOP"

# mtime-quiet window, in poll intervals: a file whose mtime is older
# than QUIET_POLLS x stream_poll_seconds is considered sealed under
# seal_policy auto|quiet (a live writer flushes at least once per few
# poll intervals, or uses .done markers).
QUIET_POLLS = 3

# Per-poll read budget: a resumed run facing a large sealed backlog
# (hours of shards behind the watermark) must stream it in bounded
# rounds, not materialize the whole backlog as one bytes object —
# reads past the budget simply continue next poll.
MAX_POLL_BYTES = 64 << 20

WATERMARK_FORMAT = 1

# Lockstep-mode bound on completed-but-unstepped batches: once this
# many are queued, per-iteration pumps run discovery-only until the
# driver drains some (the read plane would otherwise release a whole
# backlog into memory at MAX_POLL_BYTES per iteration).
LOCKSTEP_READY_CAP = 8


class _FileState:
    """One ledger entry: read plane (released/tail) + durable flags."""

    __slots__ = ("path", "ino", "released", "released_lines", "tail",
                 "sealed", "dead", "end", "resume_bytes",
                 "resume_lines", "mtime_seen", "size_seen",
                 "late_warned")

    def __init__(self, path: str):
        self.path = path
        self.ino: Optional[int] = None
        self.released = 0          # bytes handed to the consumer
        self.released_lines = 0    # newlines released (error lineno)
        self.tail = b""            # read but held back (no newline yet)
        self.sealed = False
        self.dead = False          # truncated/rotated/deleted: frozen
        self.end: Optional[int] = None  # final byte size once sealed
        self.resume_bytes = 0      # watermark position restored from a
        self.resume_lines = 0      # checkpoint (consumption restarts
        # there; bytes before it are never re-read)
        self.mtime_seen = 0.0
        self.size_seen = 0
        self.late_warned = False

    @property
    def eof(self) -> bool:
        """Everything this file will ever hold has been released."""
        if self.dead:
            return True
        return (self.sealed and self.end is not None
                and self.released >= self.end)


class StreamTracker:
    """Discovery + read plane of the streaming source: owns the file
    ledger, tails the current head file, makes seal/truncation/deletion
    decisions, and releases newline-terminated byte chunks strictly in
    ledger order. Consumption positions (the watermark) live in
    :class:`StreamSource` — the tracker only knows how far it has READ.

    Single-writer: every method runs on the one thread that pumps the
    owning StreamSource (the prefetch producer thread, or the lockstep
    driver's main thread)."""

    def __init__(self, pattern: str, poll_seconds: float,
                 seal_policy: str, retry: Optional[RetryPolicy] = None,
                 shard_index: int = 0, num_shards: int = 1,
                 bad_lines: Optional[BadLineTracker] = None,
                 watermark: Optional[dict] = None,
                 lockstep: bool = False,
                 clock=time.monotonic):
        if os.path.isdir(pattern) or not globlib.has_magic(pattern):
            self.root = pattern
            self._glob = os.path.join(pattern, "*")
        else:
            self.root = os.path.dirname(pattern) or "."
            self._glob = pattern
        self.poll_seconds = float(poll_seconds)
        self.seal_policy = seal_policy
        self.retry = retry
        self.shard_index = int(shard_index)
        self.num_shards = max(int(num_shards), 1)
        self.bad_lines = bad_lines
        self.lockstep = bool(lockstep)
        self._clock = clock
        self._log = get_logger()
        self.files: List[_FileState] = []
        self._by_path: Dict[str, int] = {}
        self.stop_seen = False
        self._last_fs_poll: Optional[float] = None
        self._newest_unconsumed_since: Optional[float] = None
        if watermark:
            self._restore(watermark)

    # -- watermark restore ------------------------------------------------
    def _restore(self, payload: dict) -> None:
        for rec in payload.get("files", ()):
            fs = _FileState(str(rec["path"]))
            fs.resume_bytes = fs.released = int(rec.get("bytes", 0))
            fs.resume_lines = fs.released_lines = int(
                rec.get("lines", 0))
            fs.sealed = bool(rec.get("sealed", False))
            fs.dead = bool(rec.get("dead", False))
            end = rec.get("end")
            fs.end = int(end) if end is not None else None
            ino = rec.get("ino")
            # Persisted inode extends the in-run rotation detection
            # ACROSS restarts: a same-path rewrite while the run was
            # down would otherwise be adopted and resumed mid-file
            # into unrelated content.
            fs.ino = int(ino) if ino is not None else None
            if fs.end is not None:
                fs.released = min(fs.released, fs.end)
                fs.resume_bytes = fs.released
            self._by_path[fs.path] = len(self.files)
            self.files.append(fs)

    # -- helpers ----------------------------------------------------------
    def path(self, i: int) -> str:
        return self.files[i].path

    def owned(self, i: int) -> bool:
        return i % self.num_shards == self.shard_index

    @property
    def finished(self) -> bool:
        """STOP declared and every owned file fully released."""
        if not self.stop_seen:
            return False
        return all(fs.eof for i, fs in enumerate(self.files)
                   if self.owned(i))

    def watermark_lag_seconds(self) -> float:
        """Seconds unconsumed released data has been waiting (0 when
        the reader is caught up) — the ``stream/watermark_lag_seconds``
        gauge's input; coarse by design (poll granularity)."""
        if self._newest_unconsumed_since is None:
            return 0.0
        return max(0.0, self._clock() - self._newest_unconsumed_since)

    def note_consumed_through(self, caught_up: bool) -> None:
        if caught_up:
            self._newest_unconsumed_since = None

    # -- telemetry --------------------------------------------------------
    @staticmethod
    def _tel():
        from fast_tffm_tpu.obs.telemetry import active
        return active()

    def _count(self, name: str, n: float = 1.0) -> None:
        tel = self._tel()
        if tel is not None:
            tel.count(name, n)

    # -- discovery --------------------------------------------------------
    def _discover_local(self) -> Tuple[List[str], bool]:
        """FS discovery: (new paths in sorted order, stop marker seen).
        Rate-limited to one real glob per poll interval."""
        now = self._clock()
        if (self._last_fs_poll is not None
                and now - self._last_fs_poll < self.poll_seconds):
            return [], self.stop_seen
        self._last_fs_poll = now
        stop = os.path.exists(os.path.join(self.root, STOP_MARKER))
        try:
            hits = retry_io(globlib.glob, self._glob,
                            policy=self.retry, op="stream_discover")
        except OSError:
            self._log.warning("stream discovery failed on %s; will "
                              "retry next poll", self._glob,
                              exc_info=True)
            return [], stop
        new = []
        for p in sorted(hits):
            name = os.path.basename(p)
            if (name == STOP_MARKER or name.startswith(".")
                    or name.endswith(DONE_SUFFIX)):
                continue
            if not os.path.isfile(p):
                continue
            if p not in self._by_path:
                new.append(p)
        return new, stop

    def _apply_discovery(self, new: Sequence[str], stop: bool) -> None:
        for p in new:
            self._by_path[p] = len(self.files)
            self.files.append(_FileState(p))
            self._count("stream/files_discovered")
            self._log.info("stream: discovered shard %s (ledger index "
                           "%d)", p, self._by_path[p])
        if stop and not self.stop_seen:
            self.stop_seen = True
            self._log.info("stream: STOP marker seen; will finish once "
                           "every sealed byte is consumed")

    def discover(self) -> None:
        """One discovery round. In lockstep mode (multi-worker) the
        chief's view is broadcast so every worker appends the same
        ledger entries in the same order and agrees on STOP — this is
        the one collective the stream adds, issued exactly once per
        driver-loop iteration (the caller guarantees the cadence)."""
        if not self.lockstep:
            new, stop = self._discover_local()
            self._apply_discovery(new, stop)
            return
        import jax
        if jax.process_index() == 0:
            new, stop = self._discover_local()
            payload = {"new": list(new), "stop": bool(stop)}
        else:
            payload = None
        payload = broadcast_blob(payload, label="stream/discovery")
        self._apply_discovery(payload.get("new", ()),
                              bool(payload.get("stop")))

    # -- the read plane ---------------------------------------------------
    def poll(self, read: bool = True) -> List[Tuple[int, bytes]]:
        """One service round: run discovery, then tail the owned head
        file(s), releasing newline-terminated chunks in strict ledger
        order. Several files can drain in one round (a backlog of
        sealed shards); an unsealed head blocks everything behind it —
        the stream is a log and order is the contract.

        ``read=False`` runs ONLY discovery (the collective half, in
        lockstep mode) and skips the local read plane — the lockstep
        driver uses it to keep its per-iteration collective cadence
        while the consumer is already holding enough batches."""
        self.discover()
        if not read:
            return []
        out: List[Tuple[int, bytes]] = []
        budget = MAX_POLL_BYTES
        for i, fs in enumerate(self.files):
            if not self.owned(i):
                continue
            if fs.eof:
                continue
            chunk = self._service(fs, budget)
            if chunk:
                out.append((i, chunk))
                budget -= len(chunk)
            if budget <= 0:
                break  # bounded round: the backlog continues next poll
            if not fs.eof:
                break  # strict order: don't read past an open head
        if out:
            if self._newest_unconsumed_since is None:
                self._newest_unconsumed_since = self._clock()
        return out

    def _mark_dead(self, fs: _FileState, why: str,
                   counter: str) -> None:
        fs.dead = True
        fs.tail = b""
        fs.end = fs.released
        self._count(counter)
        self._log.warning("stream: %s: %s; sealing at byte %d and "
                          "skipping the rest", fs.path, why,
                          fs.released)
        if (self.bad_lines is not None
                and counter != "stream/deleted_files"):
            # Quarantine-grade accounting (truncation/rotation is
            # quarantined via the run's BadLineTracker rather than
            # crashing): the event counts toward the max_bad_fraction
            # breaker like any other damaged input.
            self.bad_lines.record(fs.path, fs.released_lines + 1, "",
                                  f"stream file {why}")

    def _service(self, fs: _FileState, budget: int) -> bytes:
        """Tail one live file: read fresh bytes (at most ``budget``),
        hold back the torn trailing line, apply the seal decision.
        Returns the released chunk (possibly empty)."""
        try:
            st = retry_io(os.stat, fs.path, policy=self.retry,
                          op="stream_stat")
        except FileNotFoundError:
            self._mark_dead(fs, "deleted before it was fully consumed",
                            "stream/deleted_files")
            return b""
        except OSError:
            self._log.warning("stream: stat of %s failed; retrying "
                              "next poll", fs.path, exc_info=True)
            return b""
        if fs.ino is None:
            fs.ino = st.st_ino
        elif st.st_ino != fs.ino:
            self._mark_dead(fs, "rotated (inode changed) mid-stream",
                            "stream/truncated_files")
            return b""
        read_off = fs.released + len(fs.tail)
        if st.st_size < read_off:
            self._mark_dead(
                fs, f"truncated mid-stream ({st.st_size} bytes on disk "
                    f"< {read_off} already read)",
                "stream/truncated_files")
            return b""
        limit = st.st_size
        if fs.sealed and fs.end is not None:
            if st.st_size > fs.end and not fs.late_warned:
                fs.late_warned = True
                self._log.warning(
                    "stream: %s grew after it was sealed (%d -> %d "
                    "bytes); late bytes are ignored — fix the writer "
                    "or use seal_policy = done", fs.path, fs.end,
                    st.st_size)
            if st.st_size < fs.end:
                # A SEALED file shrank below its recorded size (e.g. a
                # rewriting producer while the run was down): without
                # this it would never reach eof and wedge the whole
                # strict-order stream in silent IDLE forever.
                self._mark_dead(
                    fs, f"truncated after seal ({st.st_size} bytes on "
                        f"disk < sealed size {fs.end})",
                    "stream/truncated_files")
                return b""
            # "late bytes are ignored" is enforced here, not just
            # warned: a restored sealed file resuming mid-way must
            # read exactly up to its sealed size — bytes appended
            # after the seal never reach training.
            limit = min(limit, fs.end)
        # Bounded round: a huge backlog streams across polls instead
        # of materializing in RAM; the remainder reads next poll.
        limit = min(limit, read_off + max(budget, 0))
        if limit > read_off:
            try:
                fs.tail += self._read_range(fs.path, read_off, limit)
            except FileNotFoundError:
                # Deleted in the stat->open window: same tolerated
                # event as the stat-time deletion, same outcome.
                self._mark_dead(
                    fs, "deleted before it was fully consumed",
                    "stream/deleted_files")
                return b""
            except OSError:
                self._log.warning(
                    "stream: read of %s failed after retries; will "
                    "retry next poll", fs.path, exc_info=True)
                return b""
        fs.size_seen = st.st_size
        fs.mtime_seen = st.st_mtime
        if not fs.sealed and self._seal_due(fs, st):
            fs.sealed = True
            # The file's FULL size at seal time, not the read
            # progress: a budget-capped partial read must not record
            # a short sealed size. RE-stat rather than reuse ``st``:
            # the .done marker may have appeared (with the shard's
            # final bytes) after the stat at the top of this call —
            # sealing at the stale size would silently exclude those
            # last legitimately-written lines forever.
            try:
                fs.end = retry_io(os.stat, fs.path, policy=self.retry,
                                  op="stream_stat").st_size
            except OSError:
                fs.end = st.st_size  # next poll's late-growth warning
                # path reports if this undershot
            self._count("stream/files_sealed")
            self._log.info("stream: sealed %s at %d bytes", fs.path,
                           fs.end)
        at_end = (fs.sealed and fs.end is not None
                  and fs.released + len(fs.tail) >= fs.end)
        if at_end:
            chunk = fs.tail
            fs.tail = b""
            fs.released += len(chunk)
            if chunk and not chunk.endswith(b"\n"):
                # Final line missing its newline: terminate it exactly
                # where the epoch path's `feed(tail + b"\n")` would.
                # The synthesized byte is NOT part of the file; the
                # consumer's position accounting clamps at `end`.
                chunk += b"\n"
            fs.released_lines += chunk.count(b"\n")
            return chunk
        # Not yet at the (sealed or growing) end: release only whole
        # lines — a budget-capped mid-file read must never synthesize
        # a terminator into the middle of a line.
        cut = fs.tail.rfind(b"\n")
        if cut < 0:
            return b""  # torn trailing line: held back in full
        chunk, fs.tail = fs.tail[:cut + 1], fs.tail[cut + 1:]
        fs.released += len(chunk)
        fs.released_lines += chunk.count(b"\n")
        return chunk

    def _seal_due(self, fs: _FileState, st) -> bool:
        if self.stop_seen:
            return True  # writer declared the whole stream finished
        if self.seal_policy in ("auto", "done") and os.path.exists(
                fs.path + DONE_SUFFIX):
            return True
        if self.seal_policy in ("auto", "quiet"):
            quiet = QUIET_POLLS * self.poll_seconds
            return time.time() - st.st_mtime >= quiet
        return False

    def _read_range(self, path: str, start: int, end: int) -> bytes:
        """[start, end) of ``path`` — chunked, retry-wrapped (the
        chunk-retry seeks back first, like pipeline._iter_owned_chunks:
        a partial buffered read advances the fd)."""
        fh = (open(path, "rb") if self.retry is None else
              open_with_retry(path, "rb", policy=self.retry,
                              op="stream_open"))
        parts = []
        with fh:
            pos = start
            fh.seek(start)
            while pos < end:
                want = min(4 << 20, end - pos)

                def attempt(p=pos, w=want):
                    fh.seek(p)
                    return fh.read(w)
                b = (attempt() if self.retry is None else
                     retry_io(attempt, policy=self.retry,
                              op="stream_read"))
                if not b:
                    break  # racing writer shrank below stat size
                parts.append(b)
                pos += len(b)
        return b"".join(parts)


# -- multi-worker agreement helpers ---------------------------------------


def broadcast_blob(obj, label: str):
    """Chief's JSON-serializable ``obj`` on every process, through the
    deadline-guarded broadcast the restore protocol uses (two phases:
    length, then the padded byte payload — ``broadcast_one_to_all``
    needs identical shapes everywhere). Identity when single-process."""
    import jax
    if jax.process_count() <= 1:
        return obj
    from jax.experimental import multihost_utils
    from fast_tffm_tpu.parallel.liveness import guarded_collective
    proc0 = jax.process_index() == 0
    data = json.dumps(obj).encode("utf-8") if proc0 else b""
    n = int(guarded_collective(
        multihost_utils.broadcast_one_to_all, np.int64(len(data)),
        label=label + "/len"))
    buf = np.zeros(max(n, 1), np.uint8)
    if proc0 and n:
        buf[:n] = np.frombuffer(data, np.uint8)
    out = guarded_collective(multihost_utils.broadcast_one_to_all, buf,
                             label=label)
    # .astype: the transport may widen small dtypes (the gloo CPU
    # client returns int32 elements for a uint8 payload) — the VALUES
    # are the bytes either way.
    raw = np.asarray(out)[:n].astype(np.uint8).tobytes()
    return json.loads(raw.decode("utf-8"))


def exchange_watermarks(local: dict, num_shards: int) -> dict:
    """Merge per-worker watermark payloads at a lockstep save point:
    every worker allgathers its local payload (two fixed-shape
    collectives) and ledger entry ``i`` is taken from its OWNER
    (``i % num_shards``) — the only worker whose positions for that
    file ever advance. All workers return the same merged payload, so
    process 0 can write the one authoritative sidecar."""
    import jax
    if jax.process_count() <= 1 or num_shards <= 1:
        return local
    from jax.experimental import multihost_utils
    from fast_tffm_tpu.parallel.liveness import guarded_collective
    data = json.dumps(local).encode("utf-8")
    lens = np.asarray(guarded_collective(
        multihost_utils.process_allgather, np.int64(len(data)),
        label="stream/watermark_len")).reshape(-1)
    m = int(lens.max())
    buf = np.zeros(max(m, 1), np.uint8)
    buf[:len(data)] = np.frombuffer(data, np.uint8)
    gathered = np.asarray(guarded_collective(
        multihost_utils.process_allgather, buf,
        label="stream/watermark_merge")).reshape(len(lens), -1)
    payloads = [json.loads(gathered[p, :int(lens[p])]
                           .astype(np.uint8).tobytes()
                           .decode("utf-8"))
                for p in range(len(lens))]
    return merge_watermark_payloads(payloads, num_shards)


def merge_watermark_payloads(payloads: Sequence[dict],
                             num_shards: int) -> dict:
    """The pure merge behind ``exchange_watermarks``: ledger entry
    ``i`` is taken from its OWNER's payload (``i % num_shards``).
    Iterates the LONGEST ledger, not the chief's: a worker whose
    adopted watermark is stale (it stepped only fillers lately, or its
    shards drained before newer files were discovered) ships a short —
    possibly empty — file list, and iterating the chief's view would
    silently drop the owner's advanced positions for later ledger
    entries. Ledger ORDER is chief-agreed, so index ``i`` means the
    same file in every non-short payload."""
    merged = {"format": WATERMARK_FORMAT, "files": []}
    n_files = max(len(p.get("files", ())) for p in payloads)
    for i in range(n_files):
        owner_files = payloads[i % num_shards].get("files", ())
        if i < len(owner_files):
            merged["files"].append(owner_files[i])
            continue
        # The owner never adopted a tag covering this file (nothing of
        # it stepped yet): any payload that has the entry carries the
        # correct zero positions + discovery flags.
        for p in payloads:
            files = p.get("files", ())
            if i < len(files):
                merged["files"].append(files[i])
                break
    return merged


# -- the batch source ------------------------------------------------------


class StreamSource:
    """Arrival-ordered DeviceBatch source over a StreamTracker.

    ``next_batch(block=...)`` returns a DeviceBatch, ``IDLE`` (nothing
    available right now) or ``DONE`` (stream finished / caller stop).
    Every emitted batch carries ``batch.stream_pos`` — the watermark
    payload after its lines (see module docstring).

    Three consumption routes, mirroring the epoch pipeline's routing:
    the serial C++ fast path (one persistent BatchBuilder — spills
    under a fixed unique budget re-feed exactly like the epoch path),
    the parallel fast plane (``host_threads > 1``: complete line
    groups through the PR 7 bounded ordered ring, bit-identical to the
    serial route), and the generic tolerant path (bad_line_policy
    skip/quarantine, or no C++ extension — per-line Python with the
    run's BadLineTracker). Route choice is ``stream_workers`` +
    cparser availability, resolved once at construction."""

    def __init__(self, cfg: FmConfig, tracker: StreamTracker,
                 stop=None, fixed_shape: bool = False,
                 uniq_bucket: int = 0, raw_ids: bool = False,
                 workers: int = 1,
                 bad_lines: Optional[BadLineTracker] = None,
                 vocab=None):
        from fast_tffm_tpu.data import cparser
        from fast_tffm_tpu.data.pipeline import (_BatchEmitter,
                                                 effective_L_cap)
        self.cfg = cfg
        # The BUILD-side config (vocab_mode = admit): parsers/builders
        # mod ids into the hash space; every emitted batch is remapped
        # to physical rows (vocab.remap) before it reaches the ready
        # deque — the same seam batch_iterator applies in epoch mode.
        self._vocab = vocab
        bcfg = cfg if vocab is None else vocab.build_cfg(cfg)
        self._bcfg = bcfg
        self.tracker = tracker
        self._stop_cb = stop or (lambda: False)
        self.B = cfg.batch_size
        self.fixed_shape = fixed_shape
        self.uniq_bucket = uniq_bucket
        self.raw_ids = raw_ids
        self.bad_lines = bad_lines
        self._log = get_logger()
        # Stream mode is arrival-ordered by design: the emitter's
        # shuffle window is off (cfg.shuffle has no effect here), which
        # is also what makes the watermark a per-file prefix.
        from fast_tffm_tpu.data.pipeline import SpillStats
        self.stats = SpillStats()
        self._emitter = _BatchEmitter(bcfg, self.B,
                                      effective_L_cap(bcfg),
                                      fixed_shape, uniq_bucket,
                                      shuffle=False, seed=cfg.seed,
                                      stats=self.stats)
        self._ready: collections.deque = collections.deque()
        self._pos: Dict[int, Tuple[int, int]] = {}  # idx -> (bytes, lines)
        for i, fs in enumerate(tracker.files):
            if fs.resume_bytes or fs.resume_lines:
                self._pos[i] = (fs.resume_bytes, fs.resume_lines)
        self._flushed = False
        self._closed = False
        tolerant = getattr(cfg, "bad_line_policy", "error") != "error"
        # Route conditions mirror the epoch path's _fast_path_eligible:
        # max_features_per_example = 0 ("unlimited") must stay generic
        # — the C++ builder writes fixed-stride rows and would silently
        # truncate long examples at the ladder cap, training a
        # different model than the same corpus under run_mode=epochs.
        self._fast = (cparser.available() and not tolerant
                      and cfg.max_features_per_example > 0)
        self._workers = max(int(workers), 1) if (
            self._fast and not fixed_shape) else 1
        self._ring = None
        if self._fast:
            pl = _pipeline()
            if self._workers > 1:
                # Ring builders consume whole pre-cut groups; positions
                # come from cut-time accounting, so the threaded feed
                # is safe (same rule as the epoch plane).
                feed_threads = pl._worker_feed_threads(self._workers,
                                                       False)
                self._make_builder = functools.partial(
                    pl._make_builder, bcfg, self.B, raw_ids, False,
                    fixed_shape, uniq_bucket, feed_threads)
                self._init_ring()
            else:
                # The serial stream builder REQUIRES the single-thread
                # feed: the watermark needs the byte-exact consumed
                # offset of every batch close, which the threaded
                # feed's pending queue hides (it consumes the whole
                # chunk up front) — same constraint as the epoch
                # plane's spill rewind.
                self._make_builder = functools.partial(
                    pl._make_builder, bcfg, self.B, raw_ids, False,
                    fixed_shape, uniq_bucket, 1)
                self._bb = self._make_builder()
        else:
            self._pending: List[Tuple[str, int, int, int]] = []
            # (line, file_idx, abs_byte_end, abs_lineno)
            self._decoded: Dict[int, Tuple[int, int]] = {}
            # raw decode position per file (covers trailing blanks
            # at the final flush)
        # Error-provenance spans: (stream_lines_before, file_idx,
        # resume_line_offset) per file as it starts feeding.
        self._spans: List[Tuple[int, int, int]] = []
        self._stream_lines = 0

    # -- shared plumbing --------------------------------------------------
    def _snapshot(self) -> dict:
        files = []
        for i, fs in enumerate(self.tracker.files):
            b, l = self._pos.get(i, (0, 0))
            if fs.end is not None:
                b = min(b, fs.end)
            files.append({"path": fs.path, "bytes": int(b),
                          "lines": int(l), "sealed": bool(fs.sealed),
                          "dead": bool(fs.dead), "end": fs.end,
                          "ino": fs.ino})
        return {"format": WATERMARK_FORMAT, "files": files}

    def _advance(self, fi: int, nbytes: int, nlines: int) -> None:
        b, l = self._pos.get(fi, (self.tracker.files[fi].resume_bytes,
                                  self.tracker.files[fi].resume_lines))
        self._pos[fi] = (b + nbytes, l + nlines)

    def _emit(self, out, spilled: bool) -> None:
        for batch in self._emitter.emit_drain(out, spilled):
            if self._vocab is not None:
                # Hash-space -> physical rows (vocab/table.py), before
                # telemetry sees the batch: the pad-waste counter
                # below reads the PHYSICAL pad_id.
                batch = self._vocab.remap(batch)
            batch.stream_pos = self._snapshot()
            tel = StreamTracker._tel()
            if tel is not None:
                tel.pipeline_batch(batch, self.cfg.pad_id)
            self._ready.append(batch)

    def _note_file_start(self, fi: int) -> None:
        if not self._spans or self._spans[-1][1] != fi:
            fs = self.tracker.files[fi]
            self._spans.append((self._stream_lines, fi,
                                fs.resume_lines))

    def _attach_source(self, e: ParseError) -> ParseError:
        """Builder-stream "line N" -> file + absolute lineno, through
        the span map + each file's resume offset (a resumed stream's
        builder never saw the lines before the watermark)."""
        import re as _re
        m = _re.match(r"^line (\d+): (.*)$", str(e), _re.S)
        if not m or not self._spans:
            return e
        n = int(m.group(1))
        owner = self._spans[0]
        for rec in self._spans:
            if rec[0] < n:
                owner = rec
            else:
                break
        base, fi, resume = owner
        path = self.tracker.path(fi)
        return ParseError(f"{path} line {resume + (n - base)}: "
                          f"{m.group(2)}")

    # -- the pump ---------------------------------------------------------
    def _pump(self, read: bool = True) -> None:
        chunks = self.tracker.poll(read=read)
        for fi, data in chunks:
            if self._fast:
                if self._ring is not None:
                    self._scan_feed(fi, data)
                else:
                    self._note_file_start(fi)
                    self._serial_feed(fi, data)
            else:
                self._generic_feed(fi, data)
        if self._ring is not None:
            self._ring_drive()
        if self.tracker.finished and not self._flushed:
            self._flush_final()
        self.tracker.note_consumed_through(
            caught_up=not self._ready and not chunks)

    def _flush_final(self) -> None:
        self._flushed = True
        if self._fast:
            if self._ring is not None:
                self._ring_flush()
            else:
                out = self._bb.finish()
                if out[0]:
                    self._emit(out, spilled=False)
        else:
            self._generic_flush(final=True)

    # -- serial fast path -------------------------------------------------
    def _serial_feed(self, fi: int, data: bytes) -> None:
        off = 0
        while True:
            try:
                full, c = self._bb.feed(data, off)
            except ParseError as e:
                raise self._attach_source(e) from None
            nl = data.count(b"\n", off, off + c)
            self._advance(fi, c, nl)
            self._stream_lines += nl
            off += c
            if not full:
                return
            try:
                out = self._bb.finish()
            except ParseError as e:
                raise self._attach_source(e) from None
            # A finish() under the fixed unique budget that closed
            # early (n < B) is the spill signal, exactly like the epoch
            # fast path; the offending line is still at data[off:] and
            # re-feeds on the next loop turn.
            self._emit(out, spilled=bool(self.fixed_shape
                                         and out[0] < self.B))

    # -- parallel fast plane (host_threads > 1) ---------------------------
    def _init_ring(self) -> None:
        pl = _pipeline()
        self._ring = pl._BuildRing(
            self._workers, depth=2 * self._workers,
            work=pl._fast_group_work,
            make_state=lambda: pl._FastWorkerState(self._make_builder))
        self._buf = b""
        self._buf_pos = 0
        self._segments: collections.deque = collections.deque()
        # [file_idx, remaining_length] per appended chunk, FIFO
        self._inflight: collections.deque = collections.deque()
        # (seq, positions) in submit order
        # Cut-side counters are SEPARATE from the emission-side
        # watermark (self._pos): groups are cut ahead of their build,
        # and the watermark on an emitted batch must never include a
        # later group's lines. _pos only advances at harvest time, in
        # emission order.
        self._cut_pos: Dict[int, Tuple[int, int]] = dict(self._pos)
        tel = StreamTracker._tel()
        if tel is not None:
            tel.set("pipeline/host_threads", self._workers)

    def _scan_feed(self, fi: int, data: bytes) -> None:
        self._buf = self._buf[self._buf_pos:] + data
        self._buf_pos = 0
        self._segments.append([fi, len(data)])

    def _cut_positions(self, consumed: int) -> Dict[int, Tuple[int, int]]:
        """Advance the scanner-side counters by ``consumed`` bytes off
        the buffer head; returns the ABSOLUTE (bytes, lines) position
        per touched file after the cut. Also records the error-span map
        in cut-line units (the units group.line_start uses)."""
        out: Dict[int, Tuple[int, int]] = {}
        taken = 0
        while taken < consumed:
            seg = self._segments[0]
            fi, seg_len = seg
            self._note_file_start(fi)
            n = min(seg_len, consumed - taken)
            nl = self._buf.count(b"\n", self._buf_pos + taken,
                                 self._buf_pos + taken + n)
            b, l = self._cut_pos.get(
                fi, (self.tracker.files[fi].resume_bytes,
                     self.tracker.files[fi].resume_lines))
            self._cut_pos[fi] = (b + n, l + nl)
            self._stream_lines += nl
            out[fi] = self._cut_pos[fi]
            taken += n
            if n == seg_len:
                self._segments.popleft()
            else:
                seg[1] -= n
        return out

    def _cut_one_group(self, blob: bytes, consumed: int,
                       line_start: int) -> None:
        positions = self._cut_positions(consumed)
        self._buf_pos += consumed
        seq = self._ring.submit(
            _pipeline()._Group(blob, line_start, blob.count(b"\n")))
        self._inflight.append((seq, positions))

    def _ring_drive(self) -> None:
        """Cut complete groups, submit to the ring, and harvest every
        finished head — only COMPLETE groups (B example lines of
        released, newline-terminated bytes) ever enter the ring;
        held-back torn tails stay in the tracker and sub-B leftovers
        stay in this buffer."""
        from fast_tffm_tpu.data.cparser import scan_examples
        while len(self._inflight) < self._ring.depth:
            found, consumed, _nl = scan_examples(
                self._buf, self.B, False, offset=self._buf_pos)
            if found < self.B:
                break
            blob = self._buf[self._buf_pos:self._buf_pos + consumed]
            self._cut_one_group(blob, consumed, self._stream_lines)
        self._harvest(block=False)

    def _harvest(self, block: bool) -> None:
        while self._inflight:
            seq, positions = self._inflight[0]
            if not block and not self._ring.has(seq):
                return
            self._inflight.popleft()
            kind, payload = self._ring.wait(seq)
            if kind == "error":
                if isinstance(payload, ParseError):
                    raise self._attach_source(payload) from None
                raise payload
            out, _consumed = payload
            for fi, pos in positions.items():
                self._pos[fi] = pos
            self._emit(out, spilled=False)

    def _ring_flush(self) -> None:
        from fast_tffm_tpu.data.cparser import scan_examples
        while True:
            found, consumed, _nl = scan_examples(
                self._buf, self.B, False, offset=self._buf_pos)
            if not found:
                break
            blob = self._buf[self._buf_pos:self._buf_pos + consumed]
            self._cut_one_group(blob, consumed, self._stream_lines)
            if found < self.B:
                break  # the final short group
        self._harvest(block=True)

    # -- generic tolerant path --------------------------------------------
    def _generic_feed(self, fi: int, data: bytes) -> None:
        # Decode-plane positions continue from _decoded (the raw
        # per-file decode cursor), NOT from _pos: _pos only advances at
        # batch emission, so a file released across several polls would
        # otherwise restart its byte counter at the last emitted batch
        # and tag later lines with bogus offsets.
        b, l = self._decoded.get(
            fi, (self.tracker.files[fi].resume_bytes,
                 self.tracker.files[fi].resume_lines))
        for raw in data.split(b"\n")[:-1]:
            b += len(raw) + 1
            l += 1
            line = raw.decode("utf-8")
            if line.strip(WHITESPACE):
                self._pending.append((line, fi, b, l))
            self._stream_lines += 1
        fs = self.tracker.files[fi]
        if fs.end is not None:
            b = min(b, fs.end)
        self._decoded[fi] = (b, l)
        while len(self._pending) >= self.B:
            self._generic_flush(final=False)

    def _generic_flush(self, final: bool) -> None:
        from fast_tffm_tpu.data.pipeline import (_parse_block,
                                                 _salvage_block,
                                                 _strip_line_prefix,
                                                 make_device_batch)
        take = self._pending[:self.B]
        if not take:
            if final:
                self._final_positions()
            return
        del self._pending[:self.B]
        lines = [t[0] for t in take]
        if self.bad_lines is None:
            try:
                block = _parse_block(lines, self._bcfg, None)
            except ParseError as e:
                _, fi, _, ln = take[0]
                raise ParseError(
                    f"{self.tracker.path(fi)} near line {ln}: "
                    f"{_strip_line_prefix(str(e))}") from None
        else:
            bads: List[Tuple[int, str, str]] = []
            block = _salvage_block(lines, self._bcfg, False, bads)
            self.bad_lines.count_ok(len(lines) - len(bads))
            for i, raw, msg in bads:
                _, fi, _, ln = take[i]
                self.bad_lines.record(self.tracker.path(fi), ln, raw,
                                      _strip_line_prefix(msg))
        if block.batch_size:
            out_batch = make_device_batch(
                block, self._bcfg, batch_size=self.B,
                fixed_shape=self.fixed_shape,
                uniq_bucket=self.uniq_bucket, raw_ids=self.raw_ids)
            if self._vocab is not None:
                out_batch = self._vocab.remap(out_batch)
            # EVERY file the chunk touches advances — a batch spanning
            # a file boundary must record the earlier files' final
            # included positions too, or a mid-stream checkpoint would
            # resume them at 0 and double-train (files consume in
            # strict ledger order, so each file's last line in the
            # chunk IS its consumed-through position).
            for _, fi, byte_end, line_end in take:
                self._pos[fi] = (byte_end, line_end)
            out_batch.stream_pos = self._snapshot()
            if self.stats is not None:
                self.stats.count(out_batch.num_real, self.B, False)
            tel = StreamTracker._tel()
            if tel is not None:
                tel.pipeline_batch(out_batch, self.cfg.pad_id)
            self._ready.append(out_batch)
        if final:
            while self._pending:
                self._generic_flush(final=False)
            self._final_positions()

    def _final_positions(self) -> None:
        for fi, pos in self._decoded.items():
            self._pos[fi] = pos

    # -- the public surface -----------------------------------------------
    def next_batch(self, block: bool = False):
        """One batch, or IDLE/DONE.

        ``block=True`` (the single-process prefetch producer) sleeps
        between polls, heartbeating the watchdog, and honors the
        caller's stop() (preemption) promptly.

        ``block=False`` with a LOCKSTEP tracker (the multi-worker
        driver) performs EXACTLY one pump per call — one tracker poll,
        hence one discovery collective — even when a batch is already
        queued or this worker is drained, so every worker's collective
        program stays aligned; preemption/exit agreement is the
        driver's flags-allgather, never a local decision here."""
        if self.tracker.lockstep:
            # The discovery collective must run EVERY call (cadence
            # alignment), but the read plane is purely local — skip it
            # while enough batches are already queued, or a deep
            # sealed backlog would be released (64 MB/call) far faster
            # than one-batch-per-iteration consumption drains it and
            # accumulate unboundedly in the ready deque.
            self._pump(read=len(self._ready) < LOCKSTEP_READY_CAP)
            if self._ready:
                return self._ready.popleft()
            return DONE if self._flushed else IDLE
        if self._stop_cb():
            return DONE
        if not block:
            if self._ready:
                return self._ready.popleft()
            if not self._flushed:
                self._pump()
            if self._ready:
                return self._ready.popleft()
            return DONE if self._flushed else IDLE
        while True:
            if self._ready:
                return self._ready.popleft()
            if self._flushed:
                return DONE
            if self._stop_cb() or self._closed:
                # _closed: the consumer tore down (error path) — the
                # producer thread must exit its poll loop, not keep
                # polling a dead run's directory forever.
                return DONE
            self._pump()
            if self._ready or self._flushed:
                continue
            tel = StreamTracker._tel()
            if tel is not None:
                tel.heartbeat()
                tel.set("stream/watermark_lag_seconds",
                        self.tracker.watermark_lag_seconds())
            time.sleep(min(self.tracker.poll_seconds, 0.2))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._ring is not None:
            self._ring.close()


class StreamPrefetcher:
    """Single-process build/compute overlap for a StreamSource: a
    producer thread pulls ``next_batch(block=True)`` (which sleeps,
    heartbeats, and polls while the stream idles) into a bounded
    queue; the consumer's ``get(timeout)`` returns a batch, ``IDLE``
    on timeout — which is what lets the driver keep its publish clock
    and preemption checks ticking while the stream is quiet — or
    ``DONE``. Producer errors re-raise at the next get. Unlike
    pipeline.prefetch there is no GIL-bound passthrough: an idle
    stream must never park the driver in a blocking get, and the
    thread is idle-cheap (the producer sleeps between polls)."""

    _SENTINEL_DONE = ("done", None)

    def __init__(self, source: StreamSource, depth: int = 2):
        import queue
        import threading
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._source = source
        self._thread = threading.Thread(target=self._main,
                                        name="fm-stream-prefetch",
                                        daemon=True)
        self._thread.start()

    def _put(self, item) -> None:
        """Bounded put + stop checks: an abandoned consumer must never
        strand the producer thread holding batches."""
        import queue
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def _main(self) -> None:
        try:
            while not self._stop.is_set():
                b = self._source.next_batch(block=True)
                self._put(self._SENTINEL_DONE if b is DONE
                          else ("batch", b))
                if b is DONE:
                    return
        except BaseException as e:  # re-raised at the consumer's get
            self._put(("error", e))

    def get(self, timeout: float):
        """A DeviceBatch, IDLE (nothing within ``timeout``), or DONE."""
        import queue
        try:
            kind, val = self._q.get(timeout=max(timeout, 0.01))
        except queue.Empty:
            return IDLE
        if kind == "error":
            raise val
        if kind == "done":
            return DONE
        return val

    def close(self) -> None:
        self._stop.set()
        # Close the source FIRST: the producer may be parked inside
        # next_batch's poll-sleep loop, which exits on the source's
        # _closed flag — without this every error-path teardown would
        # burn the full join timeout waiting for a thread that only
        # the (later) source close can release. Idempotent, so the
        # driver's own source.close() safety net stays harmless.
        self._source.close()
        self._thread.join(timeout=5.0)


def _pipeline():
    """Late import of data.pipeline (stream <-> pipeline would be a
    cycle at import time; pipeline imports nothing from here)."""
    from fast_tffm_tpu.data import pipeline
    return pipeline


def stream_workers(cfg: FmConfig, fixed_shape: bool = False) -> int:
    """The parallel-plane worker count the stream source will ACTUALLY
    use — resolve_host_threads when the fast parallel route exists
    (C++ available, strict bad-line policy, a bounded per-example
    feature cap, not the fixed-U lockstep shape whose spill-rewind
    protocol is serial-feed only), else 1. Must stay in lockstep with
    StreamSource's own ``_fast`` routing — the shared predicate exists
    so train's startup log can't overclaim."""
    pl = _pipeline()
    workers = pl.resolve_host_threads(cfg)
    if workers <= 1 or fixed_shape:
        return 1
    from fast_tffm_tpu.data import cparser
    if not cparser.available():
        return 1
    if getattr(cfg, "bad_line_policy", "error") != "error":
        return 1
    if cfg.max_features_per_example <= 0:
        return 1  # "unlimited" features: the generic (serial) route
    return workers


def probe_stream_uniq_bucket(cfg: FmConfig,
                             tracker: StreamTracker) -> int:
    """Fixed unique-row bucket for lockstep stream mode: probe the
    SEALED files present at startup (same math as
    pipeline.probe_uniq_bucket), or a safe default when the stream is
    still empty. The chief decides and the value is broadcast —
    workers must never probe racing, possibly-mid-write bytes
    independently. Call once, on every worker, before the step loop
    (the embedded discovery is collective in lockstep mode)."""
    pl = _pipeline()
    import jax
    tracker.discover()  # collective in lockstep mode: all call it

    def decide() -> int:
        top = pl.uniq_bucket_top(cfg)
        quiet_ok = tracker.seal_policy in ("auto", "quiet")
        quiet = QUIET_POLLS * tracker.poll_seconds
        candidates = []
        for fs in tracker.files:
            try:
                st = os.stat(fs.path)
                # "Probe-safe" mirrors the seal signals: a .done
                # marker, an already-sealed restore flag, or — under
                # the quiet policies — an mtime past the quiet window
                # (no tracker service has run yet at probe time, so
                # fs.sealed alone would leave every quiet-policy
                # stream on the fallback bucket and spill chronically).
                if st.st_size > 0 and not fs.dead and (
                        fs.sealed
                        or os.path.exists(fs.path + DONE_SUFFIX)
                        or (quiet_ok
                            and time.time() - st.st_mtime >= quiet)):
                    candidates.append(fs.path)
            except OSError:
                continue
        if not candidates:
            return min(1 << 10, top)
        return pl.probe_uniq_bucket(cfg, candidates)

    if not tracker.lockstep:
        return decide()
    if jax.process_index() == 0:
        val = {"bucket": decide()}
    else:
        val = None
    return int(broadcast_blob(val,
                              label="stream/uniq_bucket")["bucket"])
