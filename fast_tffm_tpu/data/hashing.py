"""Feature-id hashing — MurmurHash64A, bit-identical in Python and C++.

The reference hashes string feature ids to table rows when
``hash_feature_id`` is on (SURVEY.md §2 ``fm_parser`` row; exact upstream
hash is [M]-confidence murmur-family). This framework fixes the hash to
MurmurHash64A with seed 0, implemented twice — here (reference/oracle) and
in ``_parser.cc`` (throughput) — with golden tests pinning both to the same
values so a model trained by either parser is usable by the other.
"""

from __future__ import annotations

_M = 0xC6A4A7935BD1E995
_R = 47
_MASK = (1 << 64) - 1

SEED = 0


def murmur64(data: bytes, seed: int = SEED) -> int:
    """MurmurHash64A (Austin Appleby's 64-bit variant, little-endian)."""
    h = (seed ^ ((len(data) * _M) & _MASK)) & _MASK
    nblocks = len(data) // 8
    for i in range(nblocks):
        k = int.from_bytes(data[i * 8:(i + 1) * 8], "little")
        k = (k * _M) & _MASK
        k ^= k >> _R
        k = (k * _M) & _MASK
        h ^= k
        h = (h * _M) & _MASK
    tail = data[nblocks * 8:]
    if tail:
        h ^= int.from_bytes(tail, "little")
        h = (h * _M) & _MASK
    h ^= h >> _R
    h = (h * _M) & _MASK
    h ^= h >> _R
    return h


def hash_feature(fid: str, vocabulary_size: int) -> int:
    """String feature id -> row index in [0, vocabulary_size)."""
    return murmur64(fid.encode("utf-8")) % vocabulary_size
