"""libsvm-style line parsing — the ``fm_parser`` contract, host side.

The reference's C++ ``fm_parser`` TF op turns a batch of text lines into a
CSR batch: ``labels[B], sizes[B], feature_ids[nnz], feature_vals[nnz]``
(SURVEY.md §2 and Appendix B). This module provides the same contract as a
plain function over Python strings. A C++ implementation with the identical
contract lives in ``_parser.cc`` (loaded via ctypes in ``cparser.py``);
golden tests assert bit-identical outputs between the two.

Line formats (SURVEY Appendix A data format):
    FM :  <label> <fid>[:<fval>] ...
    FFM:  <label> <field>:<fid>[:<fval>] ...
``fval`` defaults to 1.0. ``fid`` is an integer < vocabulary_size unless
``hash_feature_id``, in which case any string, MurmurHash64A'd mod
``vocabulary_size`` (hashing.py).
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Sequence, Tuple

import numpy as np

from fast_tffm_tpu.data.hashing import hash_feature

# The libsvm separator set, pinned to the C++ parser's byte-level
# ``is_ws`` (_parser.cc): space, tab, CR, VT, FF (+ newline, which never
# appears inside a line). Python's bare str.split()/str.strip() would
# additionally treat ASCII control separators (\x1c-\x1f) and Unicode
# whitespace (\x85, \xa0, ...) as separators — inputs the C++ path
# parses as token bytes — so the two paths would disagree on the same
# line. Both sides use THIS set; tests/test_properties.py pins parity.
WHITESPACE = " \t\r\n\v\f"
_TOKEN_SPLIT = re.compile("[" + WHITESPACE + "]+")


def split_tokens(line: str) -> List[str]:
    """``line.split()`` restricted to the libsvm separator set."""
    return [t for t in _TOKEN_SPLIT.split(line) if t]


@dataclasses.dataclass
class ParsedBlock:
    """CSR batch: example e owns slice [poses[e], poses[e+1]) of the flat
    arrays. Mirrors the reference op's outputs plus the cumsum the train
    graph derives (SURVEY §3.1 ``poses = cumsum(sizes)``)."""
    labels: np.ndarray        # f32 [B]
    poses: np.ndarray         # i32 [B+1] row pointers
    ids: np.ndarray           # i32 [nnz] row indices in [0, vocab)
    vals: np.ndarray          # f32 [nnz]
    fields: Optional[np.ndarray] = None   # i32 [nnz], FFM only

    @property
    def batch_size(self) -> int:
        return len(self.labels)

    @property
    def sizes(self) -> np.ndarray:
        return np.diff(self.poses)


class ParseError(ValueError):
    pass


def _strict_float(s: str) -> float:
    """float(s) minus Python-only lexical extensions: PEP 515 underscore
    separators ("1_0" == 10) and non-ASCII Unicode digits are not part
    of the libsvm number format and the C++ parser (like the reference's
    strtod) rejects them — golden parity requires the Python fallback to
    reject them too."""
    if "_" in s or not s.isascii():
        raise ValueError(s)
    return float(s)


def _strict_int(s: str) -> int:
    """int(s) minus PEP 515 underscores / Unicode digits (_strict_float)."""
    if "_" in s or not s.isascii():
        raise ValueError(s)
    return int(s)


def parse_lines(lines: Sequence[str], vocabulary_size: int,
                hash_feature_id: bool = False,
                field_aware: bool = False,
                field_num: int = 0,
                max_features_per_example: int = 0,
                keep_empty: bool = False,
                bad_lines: Optional[List[Tuple[int, str, str]]] = None
                ) -> ParsedBlock:
    """Parse a block of lines into a CSR batch.

    ``max_features_per_example`` > 0 truncates overlong examples (static-
    shape discipline; SURVEY §7 hard part #1). Blank lines are skipped,
    unless ``keep_empty`` — then they become zero-feature examples with
    label 0, preserving line alignment (predict owes one score per input
    line, SURVEY §3.4).

    ``bad_lines`` (not None) switches to TOLERANT mode — the per-line
    failure surface of ``bad_line_policy = skip|quarantine``
    (data/badlines.py): a line that would raise ``ParseError`` is
    instead recorded as ``(lineno, raw_line, message)`` and produces no
    example — except under ``keep_empty``, where it becomes a
    zero-feature example so predict's one-score-per-input-line
    alignment survives a bad line. The partial example the failing
    line had accumulated is rolled back, so the CSR block holds only
    whole, valid examples.
    """
    labels: List[float] = []
    poses: List[int] = [0]
    ids: List[int] = []
    vals: List[float] = []
    flds: List[int] = []

    for lineno, line in enumerate(lines):
        toks = split_tokens(line)
        if not toks:
            if keep_empty:
                labels.append(0.0)
                poses.append(len(ids))
            continue
        # Buffer marks for tolerant rollback: a ParseError can fire
        # mid-line with a label and a prefix of the line's tokens
        # already appended; the block must hold only whole examples.
        n_labels, n_ids, n_flds = len(labels), len(ids), len(flds)
        try:
            _parse_one(toks, lineno, labels, ids, vals, flds,
                       vocabulary_size, hash_feature_id, field_aware,
                       field_num, max_features_per_example)
        except ParseError as e:
            if bad_lines is None:
                raise
            del labels[n_labels:], ids[n_ids:], vals[n_ids:]
            del flds[n_flds:]
            bad_lines.append((lineno, line, str(e)))
            if keep_empty:
                # Predict alignment: the bad line still owes a score —
                # a zero-feature example scores as the model bias.
                labels.append(0.0)
                poses.append(len(ids))
            continue
        poses.append(len(ids))

    return ParsedBlock(
        labels=np.asarray(labels, dtype=np.float32),
        poses=np.asarray(poses, dtype=np.int32),
        ids=np.asarray(ids, dtype=np.int32),
        vals=np.asarray(vals, dtype=np.float32),
        fields=np.asarray(flds, dtype=np.int32) if field_aware else None,
    )


def _parse_one(toks: List[str], lineno: int, labels, ids, vals, flds,
               vocabulary_size: int, hash_feature_id: bool,
               field_aware: bool, field_num: int,
               max_features_per_example: int) -> None:
    """Parse one line's tokens, appending onto the CSR buffers (the
    one per-line implementation both strict and tolerant modes run).
    Raises ParseError mid-append on a bad token; parse_lines' tolerant
    mode rolls the partial appends back."""
    try:
        label = _strict_float(toks[0])
    except ValueError:
        raise ParseError(f"line {lineno}: bad label {toks[0]!r}")
    labels.append(label)
    n = 0
    for tok in toks[1:]:
        if max_features_per_example and n >= max_features_per_example:
            break
        parts = tok.split(":")
        if field_aware:
            if len(parts) == 2:
                fld_s, fid_s, val_s = parts[0], parts[1], None
            elif len(parts) == 3:
                fld_s, fid_s, val_s = parts
            else:
                raise ParseError(
                    f"line {lineno}: bad ffm token {tok!r} "
                    "(want field:fid[:val])")
            try:
                fld = _strict_int(fld_s)
            except ValueError:
                raise ParseError(f"line {lineno}: bad field {fld_s!r}")
            if not 0 <= fld < field_num:
                raise ParseError(
                    f"line {lineno}: field {fld} out of range "
                    f"[0, {field_num})")
            flds.append(fld)
        else:
            if len(parts) == 1:
                fid_s, val_s = parts[0], None
            elif len(parts) == 2:
                fid_s, val_s = parts
            else:
                raise ParseError(
                    f"line {lineno}: bad token {tok!r} (want fid[:val])")
        if hash_feature_id:
            fid = hash_feature(fid_s, vocabulary_size)
        else:
            try:
                fid = _strict_int(fid_s)
            except ValueError:
                raise ParseError(
                    f"line {lineno}: non-integer feature id {fid_s!r} "
                    "(set hash_feature_id = True for string ids)")
            if not 0 <= fid < vocabulary_size:
                raise ParseError(
                    f"line {lineno}: feature id {fid} out of range "
                    f"[0, {vocabulary_size})")
        if val_s is None:
            val = 1.0
        else:
            try:
                val = _strict_float(val_s)
            except ValueError:
                raise ParseError(f"line {lineno}: bad value {val_s!r}")
        ids.append(fid)
        vals.append(val)
        n += 1
