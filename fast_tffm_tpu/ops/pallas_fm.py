"""Pallas TPU kernel for the fused FM interaction — the native-op core.

The reference's hot ops are C++ TF kernels: ``fm_scorer`` (forward) and
``fm_grad`` (backward) over a CSR batch (SURVEY.md §2, Appendix B). The
TPU-native analogue is this Pallas pair: one fused VMEM pass computes the
linear + (Σv)²−Σv² interaction per example without materialising any of
the [B, L, K] intermediates (z, z², their squares) in HBM, and a
``jax.custom_vjp`` routes autodiff into the matching hand-written
backward kernel — exactly how the reference hooks ``fm_grad`` in via
``RegisterGradient`` (SURVEY §2 "Op wrappers").

Layout: the caller gathers rows ``[B, L, K+1]`` (XLA's dynamic gather is
already optimal for that part) and hands the kernel ``v`` TRANSPOSED to
``[B, K, L]`` — lanes carry L (a bucket size, typically 64+), sublanes
carry K. With K minor instead, Mosaic pads K (often 8) up to the 128
lanes, a 16x VMEM blowup that OOMs scoped vmem at real batch sizes.
``w [B, L]`` and values ``x [B, L]`` ride along; blocked over B. Padded
slots carry ``x == 0`` so they contribute exactly zero to every term
(same invariant as ops/interaction.py).

Backward math (per example, g = dL/dscore):
    dw[l]    = g * x[l]
    dv[l, f] = g * x[l] * (s[f] - z[l, f]),   s = Σ_l z,  z = v * x
    dx[l]    = g * (w[l] + Σ_f v[l, f] * (s[f] - z[l, f]))
The backward kernel recomputes ``s`` from inputs instead of saving
residuals — one extra VMEM reduction in exchange for zero HBM residual
traffic (the rematerialisation trade SURVEY §7 calls for).

Falls back to interpret mode off-TPU so the same code path is testable
on the CPU mesh (tests/test_pallas_fm.py pins parity vs the XLA path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_b(B: int, K: int, L: int) -> int:
    """Largest power-of-two divisor of B keeping one v block (with its
    lane padding to 128) within a ~2 MB VMEM budget — the kernels hold a
    handful of block-sized temporaries and Mosaic double-buffers blocks
    against the 16 MB scoped-vmem limit."""
    lanes = -(-L // 128) * 128
    # K rounds UP to the 8-sublane tile (not max(K, 8)): Mosaic pads the
    # sublane axis, so e.g. K=9 occupies 16 sublanes — counting 9 would
    # understate the real block by up to ~78% and blow the budget for
    # K in 9..15 at large L.
    sublanes = -(-K // 8) * 8
    bytes_per_row = sublanes * lanes * 4
    budget = 2 << 20
    b = 1
    while B % (b * 2) == 0 and (b * 2) * bytes_per_row <= budget:
        b *= 2
    return b


def _fwd_kernel(v_ref, w_ref, x_ref, out_ref):
    v = v_ref[...]                      # [bB, K, L]
    w = w_ref[...]                      # [bB, L]
    x = x_ref[...]                      # [bB, L]
    z = v * x[:, None, :]
    s = jnp.sum(z, axis=-1)             # [bB, K]
    q = jnp.sum(z * z, axis=-1)         # [bB, K]
    linear = jnp.sum(w * x, axis=-1)    # [bB]
    pair = 0.5 * jnp.sum(s * s - q, axis=-1)
    out_ref[...] = (linear + pair)[:, None]


def _bwd_kernel(v_ref, w_ref, x_ref, g_ref, dv_ref, dw_ref, dx_ref):
    v = v_ref[...]                      # [bB, K, L]
    w = w_ref[...]
    x = x_ref[...]
    g = g_ref[...]                      # [bB, 1]
    z = v * x[:, None, :]
    s = jnp.sum(z, axis=-1, keepdims=True)  # [bB, K, 1]
    sv = s - z                              # [bB, K, L]
    dv_ref[...] = g[:, :, None] * x[:, None, :] * sv
    dw_ref[...] = g * x
    dx_ref[...] = g * (w + jnp.sum(v * sv, axis=1))


def _fm_pallas_raw(v: jax.Array, w: jax.Array, x: jax.Array) -> jax.Array:
    B, K, L = v.shape
    bB = _block_b(B, K, L)
    out = pl.pallas_call(
        _fwd_kernel,
        grid=(B // bB,),
        in_specs=[
            pl.BlockSpec((bB, K, L), lambda i: (i, 0, 0)),
            pl.BlockSpec((bB, L), lambda i: (i, 0)),
            pl.BlockSpec((bB, L), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bB, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, 1), v.dtype),
        interpret=_interpret(),
    )(v, w, x)
    return out[:, 0]


@jax.custom_vjp
def fm_scores_pallas(v: jax.Array, w: jax.Array, x: jax.Array) -> jax.Array:
    """Fused FM forward: scores[B] from v[B,K,L], w[B,L], x[B,L]."""
    return _fm_pallas_raw(v, w, x)


def _fm_fwd(v, w, x):
    return _fm_pallas_raw(v, w, x), (v, w, x)


def _fm_bwd(res, g):
    v, w, x = res
    B, K, L = v.shape
    bB = _block_b(B, K, L)
    dv, dw, dx = pl.pallas_call(
        _bwd_kernel,
        grid=(B // bB,),
        in_specs=[
            pl.BlockSpec((bB, K, L), lambda i: (i, 0, 0)),
            pl.BlockSpec((bB, L), lambda i: (i, 0)),
            pl.BlockSpec((bB, L), lambda i: (i, 0)),
            pl.BlockSpec((bB, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bB, K, L), lambda i: (i, 0, 0)),
            pl.BlockSpec((bB, L), lambda i: (i, 0)),
            pl.BlockSpec((bB, L), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, K, L), v.dtype),
            jax.ShapeDtypeStruct((B, L), w.dtype),
            jax.ShapeDtypeStruct((B, L), x.dtype),
        ],
        interpret=_interpret(),
    )(v, w, x, g[:, None])
    return dv, dw, dx


fm_scores_pallas.defvjp(_fm_fwd, _fm_bwd)


def fm_batch_scores_pallas(params: jax.Array, local_idx: jax.Array,
                           vals: jax.Array, mesh=None) -> jax.Array:
    """Drop-in for ops.interaction.fm_batch_scores (order=2) with the
    interaction fused in Pallas. The [U, K+1] -> [B, L, K+1] gather (and
    its scatter-add transpose in the VJP) stays in XLA, which lowers
    both optimally; the kernel owns everything after the gather, in the
    lane-friendly [B, K, L] layout.

    ``mesh``: GSPMD has no partitioning rule for a ``pallas_call``, so
    under a sharded jit the kernel is wrapped in ``shard_map`` over the
    batch ("data") axis — each device runs the kernel on its batch
    shard, zero collectives inside (the interaction is per-example).
    The gather stays outside in GSPMD-land, which owns the row-shard
    collectives. This is how kernel='pallas' survives the mesh paths
    (parallel/sharded.py binds the mesh)."""
    rows = params[local_idx]
    v = jnp.swapaxes(rows[..., :-1], 1, 2)   # [B, K, L]
    w = rows[..., -1]
    if mesh is None:
        return fm_scores_pallas(v, w, vals)
    from jax.sharding import PartitionSpec as P
    # check_vma=False: pallas_call declares no varying-mesh-axes rule;
    # the body is per-example with zero collectives, so the manual specs
    # are the whole contract.
    fn = _shard_map(
        fm_scores_pallas, mesh,
        in_specs=(P("data", None, None), P("data", None), P("data", None)),
        out_specs=P("data"))
    return fn(v, w, vals)


def _shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` across the API move: top-level (new jax, where
    the replication-check kwarg is ``check_vma``) or
    ``jax.experimental.shard_map`` (older installs, where it is
    ``check_rep``). Both flags express the same opt-out: pallas_call
    declares no replication rule, so the manual specs are the whole
    contract."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map
    return shard_map(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)
