"""Regime-aware kernel selection for the 2nd-order FM scorer.

``kernel = auto`` used to resolve unconditionally to the fused Pallas
kernel on TPU. The measured matrix (BASELINE.md "Kernel-choice matrix",
same-window interleaved pairs on the real chip, k=8, B=8192) says the
winner depends on (L, dedup), not the backend alone:

    L   dedup    Pallas  XLA    Pallas/XLA
    48  device   302M    450M   0.67x
    48  host     422M    450M   0.94x
    64  host     360M    413M   0.87x
    64  device   450M    316M   1.42x

Pallas only wins where the device-side unique pass keeps the batch's
rows hot in VMEM AND the bucket is at least a full 64-lane tile; every
host-dedup cell and the sub-tile L=48 cell measured XLA faster (the
k=16 check at the bench shape agreed: 363M vs 406M). So auto picks
Pallas exactly in the measured winning regime and XLA elsewhere —
per BUCKET, at trace time: the bucketed pipeline compiles one
executable per (spec, L) anyway, so different buckets of one job can
(correctly) run different kernels.

Consequence worth stating: mesh and multi-process paths REQUIRE host
dedup, so under auto they always resolve to XLA (the matrix's two
host-dedup cells both measured XLA faster). That cell pair was
measured single-chip — the sharded-assembly regime itself has no
direct measurement — so a cluster operator who measures otherwise can
still force ``kernel = pallas`` (it runs under shard_map).

The matrix is this chip's; on other hardware re-measure with
``python tools/kernel_probe.py`` (interleaved A/B at your shapes) and,
if the regime boundary moved, override per job with ``kernel =
pallas|xla`` — the config knob always beats the matrix.
"""

from __future__ import annotations


def auto_kernel(dedup: str, L: int) -> str:
    """Resolve ``kernel = auto`` for a 2nd-order FM bucket of width
    ``L`` under ``dedup`` mode. Callers guarantee model_type=fm,
    order=2, TPU backend (ModelSpec.from_config keeps 'auto' only
    there)."""
    return "pallas" if dedup == "device" and L >= 64 else "xla"
