"""FM interaction math as pure-XLA JAX — the ``fm_scorer`` equivalent.

The reference computes, in a multithreaded C++ TF op over a CSR batch
(SURVEY.md §2 ``fm_scorer``, §3.5):

    linear  = sum_j w[id_j] x_j
    pair    = 1/2 sum_f [(sum_j v[id_j,f] x_j)^2 - sum_j v[id_j,f]^2 x_j^2]
    reg     = factor_lambda * sum_unique ||v||^2 + bias_lambda * sum w^2

Here the same math runs on fixed-shape bucketed batches (data/pipeline.py)
as einsums the TPU compiler fuses end-to-end; ``jax.grad`` through these
functions *is* the ``fm_grad`` equivalent (a hand-fused Pallas version with
a custom VJP lives in ops/pallas_fm.py). Padding contributes exactly zero
because padded ``vals`` are 0 and every term carries an ``x_j`` factor.

Shapes: ``params`` are the batch's gathered unique rows ``[U, D]``
(D = k+1 for FM, field_num*k+1 for FFM); ``local_idx [B, L]`` indexes
into them; ``vals [B, L]``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# FM latent values are tiny (init ±0.01) and scores are heavy on
# cancellation ((Σv)²−Σv²); the platform's default matmul precision may
# downcast dot inputs (bf16 passes on TPU) which visibly distorts scores.
# Every einsum here is small (k ≤ a few dozen), so full-f32 accumulation
# costs nothing measurable and is required for oracle parity.
_F32 = lax.Precision.HIGHEST


def gather_rows(table: jax.Array, uniq_ids: jax.Array) -> jax.Array:
    """Gather the batch's unique rows from the (possibly huge) table.

    Padding slots hold ``pad_id == vocabulary_size`` which indexes the
    dead extra row (all-zero, never updated), so no clipping is needed.
    """
    # fmlint: disable=R011 -- the one sanctioned batch gather below
    # the slot seam (admit-mode ids are already physical rows here)
    return table[uniq_ids]


def fm_batch_scores(params: jax.Array, local_idx: jax.Array,
                    vals: jax.Array, order: int = 2) -> jax.Array:
    """Per-example FM scores. order==2 uses the (Σv)²−Σv² identity; order>2
    adds ANOVA-kernel terms of degree 2..order (BASELINE config #4)."""
    rows = params[local_idx]                      # [B, L, k+1]
    v, w = rows[..., :-1], rows[..., -1]
    linear = jnp.einsum("bl,bl->b", w, vals, precision=_F32)
    z = v * vals[..., None]                       # [B, L, k]
    if order == 2:
        s = z.sum(axis=1)                         # [B, k]
        q = jnp.square(z).sum(axis=1)
        return linear + 0.5 * (jnp.square(s) - q).sum(axis=-1)
    return linear + _anova_terms(z, order)


def _anova_terms(z: jax.Array, order: int) -> jax.Array:
    """Sum of ANOVA kernels of degree 2..order, all latent dims.

    Classic DP (a_new[t] = a[t] + a[t-1]*z_j) run as a ``lax.scan`` over
    the L feature slots — static trip count, TPU-friendly; padded slots
    have z_j = 0 and leave the state unchanged. O(L * order * k).
    """
    B, L, k = z.shape
    a0 = jnp.zeros((B, order + 1, k), dtype=z.dtype).at[:, 0].set(1.0)

    def step(a, z_j):                              # z_j: [B, k]
        return a.at[:, 1:].add(a[:, :-1] * z_j[:, None, :]), None

    a, _ = lax.scan(step, a0, jnp.moveaxis(z, 1, 0))
    return a[:, 2:].sum(axis=(1, 2))


def ffm_batch_scores(params: jax.Array, field_num: int,
                     local_idx: jax.Array, fields: jax.Array,
                     vals: jax.Array) -> jax.Array:
    """Field-aware FM (BASELINE config #3): row layout [U, field_num*k+1];
    v[i, f] is the latent vector row i uses against field f.

        score = Σ_j w_j x_j + Σ_{i<j} <v[i, f_j], v[j, f_i]> x_i x_j

    Computed by bucketing features by field instead of forming the
    [B, L, L, k] pair tensor (which is ~2.7 GB at L=256/B=1024):

        S[b, f, g, :] = Σ_{l : fields[b,l]=g} x_l · v[b, l, f, :]
        Σ_{i,j} <v_i[f_j], v_j[f_i]> x_i x_j = Σ_{f,g} <S[f,g], S[g,f]>

    (each ordered pair (i, j) lands in the (f, g) = (f_j, f_i) bucket
    exactly once), then the i=j diagonal Σ_l x_l²·||v_l[f_l]||² is
    subtracted and the sum halved. The biggest intermediate is
    [B, F, F, k] — bounded by the field count, not the feature bucket —
    and the L-contraction is a plain matmul the MXU tiles. Padded slots
    have x=0 and contribute zero everywhere.
    """
    rows = params[local_idx]                       # [B, L, F*k+1]
    B, L = local_idx.shape
    w = rows[..., -1]
    k = (rows.shape[-1] - 1) // field_num
    v = rows[..., :-1].reshape(B, L, field_num, k)
    linear = jnp.einsum("bl,bl->b", w, vals, precision=_F32)
    onehot = jax.nn.one_hot(fields, field_num, dtype=v.dtype)  # [B, L, F]
    # S[b,f,g,:] = Σ_l onehot[b,l,g] · x[b,l] · v[b,l,f,:]
    s = jnp.einsum("blfk,blg,bl->bfgk", v, onehot, vals, precision=_F32)
    cross = jnp.einsum("bfgk,bgfk->b", s, s, precision=_F32)
    # i=j diagonal: v each feature uses against its own field.
    v_self = jnp.take_along_axis(
        v, fields[:, :, None, None], axis=2)[:, :, 0, :]       # [B, L, k]
    diag = jnp.einsum("blk,blk,bl->b", v_self, v_self,
                      jnp.square(vals), precision=_F32)
    return linear + 0.5 * (cross - diag)


def batch_reg(params: jax.Array, uniq_ids: jax.Array, vocabulary_size: int,
              factor_lambda: float, bias_lambda: float) -> jax.Array:
    """L2 over the batch's unique touched rows (SURVEY §3.5): the pipeline
    already deduplicated ids on the host, so this is a masked sum — padding
    slots (id == vocabulary_size) are excluded."""
    mask = (uniq_ids < vocabulary_size).astype(params.dtype)[:, None]
    v, w = params[:, :-1], params[:, -1:]
    return (factor_lambda * jnp.sum(jnp.square(v) * mask)
            + bias_lambda * jnp.sum(jnp.square(w) * mask))
