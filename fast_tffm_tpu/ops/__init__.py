from fast_tffm_tpu.ops.interaction import (  # noqa: F401
    fm_batch_scores, ffm_batch_scores, batch_reg, gather_rows)
