"""Checkpoint / resume via orbax — the ``tf.train.Saver`` equivalent.

Reference behavior (SURVEY.md §5 "Checkpoint / resume"): periodic save
through the managed session, restore-on-restart, final model at the
config's ``model_file`` path; predict restores the same. Same contract
here, with orbax's sharding-aware async-capable machinery underneath plus
a dense ``.npz`` exporter for parity checks outside JAX.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from fast_tffm_tpu.obs.trace import span
from fast_tffm_tpu.utils.retry import RetryPolicy, retry_io


class CheckpointState:
    """Manages checkpoints under ``<model_file>.ckpt/`` (orbax needs a
    directory; the reference's ``model_file`` is a path prefix).

    ``retry`` (utils/retry.py; train/predict thread the config's
    ``io_retries``/``io_backoff_seconds`` here) wraps the orbax
    RESTORE entry points in the transient-IO retry loop — restore is
    a pure read, so re-driving it is always safe. SAVE is deliberately
    NOT retried, in either phase: a transient failure after orbax has
    created the step directory would make a blind re-dispatch collide
    as StepAlreadyExistsError — which save()'s handler treats as the
    benign same-step case — silently recording a half-written
    checkpoint as done (strictly worse than failing loudly); and an
    async save's background-write failure surfaces at a later wait,
    outside any wrapper, where the snapshot needed to re-drive it is
    gone. Only genuinely retryable errors (OSError/TimeoutError minus
    the missing-path family) retry on restore; orbax's semantic errors
    (shape mismatches) propagate on the first raise."""

    def __init__(self, model_file: str, max_to_keep: int = 3,
                 retry: Optional[RetryPolicy] = None):
        self.directory = os.path.abspath(model_file) + ".ckpt"
        self._retry = retry or RetryPolicy(retries=0)
        os.makedirs(self.directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    def save(self, step: int, table: jax.Array, acc: jax.Array,
             vocabulary_size: int, force: bool = False,
             wait: bool = False, epoch: int = 0,
             rewrite_stale_metadata: bool = False) -> None:
        """``vocabulary_size`` is stored alongside the arrays: the
        4096-aligned row layout means a changed vocab inside the same
        bucket would otherwise restore shape-compatibly but silently
        scramble the pad-row invariant (callers verify on restore).

        Saves are ASYNC by default: orbax snapshots the arrays to host
        and serializes in a background thread, so the train loop resumes
        after the snapshot instead of stalling for the full write (the
        reference's Saver writes synchronously; SURVEY §5 — this is the
        orbax upgrade that survey section calls for). A save issued
        while the previous one is still writing waits for it first
        (orbax's own back-pressure), bounding in-flight state to one
        snapshot. ``wait=True`` — the final/preemption save — blocks
        until the bytes are durably committed before returning."""
        # Timeline span (obs/trace; no-op without an active
        # tracing run): checkpoint pauses are a classic silent
        # stall — the span shows the snapshot cost, `wait=True`
        # saves show the full write.
        with span("checkpoint/save", step=int(step), wait=wait):
            # Plain python ints for the scalar leaves: orbax's
            # StandardSave supported types are (int, float, np.ndarray,
            # jax.Array) — numpy SCALARS (np.int64) are rejected outright
            # by its save-state validation.
            payload = {"table": table, "acc": acc,
                       "step": int(step),
                       # COMPLETED epochs at save time: lets a restarted
                       # run resume an interrupted epoch schedule instead
                       # of rerunning it from zero (train.resume_start_epoch)
                       "epoch": int(epoch),
                       "vocab": int(vocabulary_size)}
            try:
                # No retry here (class docstring): re-dispatching a
                # save whose first attempt half-created the step dir
                # would surface as the benign StepAlreadyExists path
                # below and silently skip the save.
                self._mngr.save(step, args=ocp.args.StandardSave(payload),
                                force=force)
                # A FRESH save at this step carries authoritative metadata:
                # drop any leftover same-step sidecar (a cleared-and-reused
                # directory) and any sidecars orphaned by max_to_keep GC —
                # CheckpointManager doesn't know about them.
                if jax.process_index() == 0:
                    self._prune_sidecars(fresh_step=step)
            except ocp.checkpoint_manager.StepAlreadyExistsError:
                # The final/preemption save can land on the same step as the
                # last periodic save (save_steps divides the step count).
                # The ARRAY state at a given step is unique, so that part is
                # a no-op — but the colliding periodic save recorded the
                # epoch count as of MID-epoch, while this save may carry the
                # completed count; without a correction a successfully
                # completed run restores as "interrupted" and silently
                # retrains an epoch. The CALLER decides via
                # rewrite_stale_metadata — train() knows deterministically
                # (from its own last periodic save) whether the metadata
                # differs, and a deterministic flag keeps every process of a
                # multi-host job on the same side of this path (a
                # per-process disk read here could diverge on one host's
                # transient error and deadlock the final save). The
                # correction is a tiny atomically-renamed sidecar holding
                # the true epoch — restore() overlays it — NOT a
                # delete+resave of the step: a hard kill here leaves either
                # the old sidecar state (epoch stale, exactly the status
                # quo ante — the run retrains one epoch) or the new one;
                # the step's arrays are never at risk (advisor finding r4).
                if rewrite_stale_metadata and jax.process_index() == 0:
                    sc = self._epoch_sidecar(step)
                    tmp = sc + ".tmp"
                    with open(tmp, "w") as fh:
                        fh.write(str(int(epoch)))
                        fh.flush()
                        os.fsync(fh.fileno())
                    os.replace(tmp, sc)
            if wait:
                self._mngr.wait_until_finished()

    def wait_until_finished(self) -> None:
        self._mngr.wait_until_finished()

    def _epoch_sidecar(self, step: int) -> str:
        return os.path.join(self.directory, f"epoch_override-{step}")

    def _prune_sidecars(self, fresh_step: Optional[int] = None) -> None:
        """Remove epoch sidecars that no longer correct anything.

        Two legs with DIFFERENT failure contracts: removing the
        fresh-step's stale sidecar is correctness-bearing (a survivor
        would overlay the wrong epoch on the step just written —
        cleared-and-reused dir case), so anything but "not there"
        raises and fails the save loudly; the orphan scan for
        GC-deleted steps is purely cosmetic (a leftover orphan costs
        bytes and can never overlay: its step no longer restores), so
        no flake in listdir/all_steps may fail an already-committed
        save."""
        import re
        if fresh_step is not None:
            try:
                os.remove(self._epoch_sidecar(fresh_step))
            except FileNotFoundError:
                pass  # the common case: nothing to correct
        try:
            kept = set(self._mngr.all_steps())
            names = os.listdir(self.directory)
        except Exception:  # noqa: BLE001 - cosmetic scan only
            return
        for name in names:
            m = re.fullmatch(r"epoch_override-(\d+)", name)
            if not m:
                continue
            s = int(m.group(1))
            if s == fresh_step or s not in kept:
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass

    def _apply_epoch_override(self, step: int, restored):
        """Overlay a same-step epoch-correction sidecar (see save())
        onto a restored tree, when both exist. Multi-process: only
        process 0 reads the file and the value is broadcast, so a
        transient read error (or non-shared storage) on one host can
        never give processes different epochs — divergent resume
        schedules deadlock the lockstep collectives."""
        if restored is None or "epoch" not in restored:
            return restored
        override = -1
        if jax.process_index() == 0:
            try:
                with open(self._epoch_sidecar(step)) as fh:
                    override = int(fh.read().strip())
            except (FileNotFoundError, ValueError):
                pass  # no/garbled sidecar -> step's own metadata stands
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            override = int(multihost_utils.broadcast_one_to_all(
                np.int64(override)))
        if override >= 0:
            restored["epoch"] = np.int64(override)
        return restored

    def restore_partial(self, template: Dict[str, Any],
                        step: Optional[int] = None
                        ) -> Optional[Dict[str, Any]]:
        """Restore only the leaves named in ``template`` (a subtree of
        what was saved). The offload predict path uses this to load the
        table WITHOUT the same-sized Adagrad accumulator — at config-#5
        scale the accumulator is half the state, and materializing it
        just to drop it doubles peak host RSS. Uses a read-only
        PyTree-handler manager (StandardSave's on-disk format is the
        PyTree format; partial restore is a PyTreeRestore feature)."""
        with span("checkpoint/restore", partial=True):
            self._mngr.wait_until_finished()
            s = step if step is not None else self.latest_step()
            if s is None:
                return None
            reader = ocp.CheckpointManager(
                self.directory,
                item_handlers=ocp.PyTreeCheckpointHandler())
            try:
                restored, err = _restore_tolerating_legacy_epoch(
                    template,
                    lambda t: retry_io(
                        reader.restore, s,
                        args=ocp.args.PyTreeRestore(
                            item=t, partial_restore=True),
                        policy=self._retry, op="checkpoint_restore"))
                if err is not None:
                    raise err
                return self._apply_epoch_override(s, restored)
            finally:
                reader.close()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, step: Optional[int] = None,
                template: Optional[Dict[str, Any]] = None
                ) -> Optional[Dict[str, Any]]:
        """Returns {"table", "acc", "step"} as host arrays, or None if no
        checkpoint exists yet (fresh start). ``template`` is an abstract
        pytree (jax.ShapeDtypeStruct leaves) matching what was saved;
        required by orbax to reconstruct arrays."""
        with span("checkpoint/restore"):
            self._mngr.wait_until_finished()  # in-flight async save first
            s = step if step is not None else self.latest_step()
            if s is None:
                return None
            if template is None:
                return self._apply_epoch_override(
                    s, retry_io(self._mngr.restore, s,
                                policy=self._retry,
                                op="checkpoint_restore"))
            restored, err = _restore_tolerating_legacy_epoch(
                template,
                lambda t: retry_io(
                    self._mngr.restore, s,
                    args=ocp.args.StandardRestore(t),
                    policy=self._retry, op="checkpoint_restore"))
            if err is not None:
                self._raise_restore_error(s, err)
            return self._apply_epoch_override(s, restored)

    def _raise_restore_error(self, s, e) -> None:
        # Orbax surfaces config-mismatch as a shape ValueError (whose
        # advice — enable truncation — is wrong here) or, for a
        # checkpoint predating a template key such as 'vocab', as a
        # tree-structure error. The same exception classes can also
        # mean a corrupt/partial step directory (killed writer), so
        # the advice names both causes rather than steering a user
        # toward discarding a recoverable checkpoint.
        raise ValueError(
            f"checkpoint at {self.directory} step {s} could not be "
            "restored against this config's layout. Most likely the "
            "checkpoint was written under a different config "
            "(vocabulary_size / factor_num / model_type) or an older "
            "storage layout — fix the config or point model_file at "
            "the matching checkpoint. If the config is right, this "
            "step directory may be corrupt/partially written (killed "
            "save): try an earlier step or delete the bad step dir. "
            f"Underlying error: {e}") from e

    def close(self) -> None:
        self._mngr.close()


def _restore_tolerating_legacy_epoch(template, do_restore):
    """Run ``do_restore(template)``; on tree/shape errors retry ONCE
    without the 'epoch' leaf (checkpoints written before that leaf
    existed must stay restorable — an upgraded binary has to resume a
    preempted job's old checkpoint), defaulting the leaf to 0. Returns
    (restored, None) on success or (None, original_error) when both
    attempts fail — the caller owns the diagnostic. The one
    implementation for restore() and restore_partial(); a genuine
    config mismatch pays one wasted retry on this already-failing
    path, the price of not needing a metadata side-channel."""
    try:
        return do_restore(template), None
    except (ValueError, KeyError) as e:
        if "epoch" not in template:
            return None, e
        legacy = {k: v for k, v in template.items() if k != "epoch"}
        try:
            restored = do_restore(legacy)
        except (ValueError, KeyError):
            return None, e
        restored["epoch"] = 0
        return restored, None


def export_npz(table, path: str,
               vocabulary_size: Optional[int] = None) -> None:
    """Dense export of the parameter table for parity checks / external
    consumers. Pass ``vocabulary_size`` to slice off dead rows exactly:
    the pad row at index ``vocabulary_size`` plus any divisibility pad
    rows a mesh-sharded table carries (parallel/sharded.padded_num_rows).
    Without it, only the single trailing pad row is dropped (valid for
    unsharded tables only)."""
    arr = np.asarray(table)
    arr = arr[:vocabulary_size] if vocabulary_size is not None else arr[:-1]
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    np.savez_compressed(path, table=arr)
