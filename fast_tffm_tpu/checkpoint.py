"""Checkpoint / resume via orbax — the ``tf.train.Saver`` equivalent.

Reference behavior (SURVEY.md §5 "Checkpoint / resume"): periodic save
through the managed session, restore-on-restart, final model at the
config's ``model_file`` path; predict restores the same. Same contract
here, with orbax's sharding-aware async-capable machinery underneath plus
a dense ``.npz`` exporter for parity checks outside JAX.

Self-healing state plane (README "Checkpoint integrity & fallback"):
every committed save gets an atomically-renamed ``manifest-<step>.json``
sidecar (per-file size + crc32, step/epoch/vocab echo), written by
process 0 once the step directory is finalized. Restore verifies the
candidate step against its manifest first (``ckpt_verify = off | size |
full``); a step that fails verification — or raises during the actual
orbax restore — is QUARANTINED (renamed ``corrupt-<step>``, never
deleted) and restore walks back to the next older step until one loads.
Multi-host: process 0 makes every step decision and broadcasts it (same
protocol as ``_apply_epoch_override``), so hosts can't diverge onto
different steps and deadlock the collectives. Steps written before the
manifest existed carry nothing to verify against and stay restorable.
``tools/fmckpt`` is the offline view of the same invariants.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from fast_tffm_tpu.obs.trace import span
from fast_tffm_tpu.utils.logging import get_logger
from fast_tffm_tpu.utils.retry import RetryPolicy, retry_io

# ckpt_verify knob values (config.py): "off" skips verification
# entirely, "size" checks per-file byte counts against the manifest
# (catches torn/truncated writes for the cost of one stat per file),
# "full" additionally re-hashes every byte (catches silent bit rot; a
# full pass over a config-#5 checkpoint reads the whole state once).
CKPT_VERIFY_MODES = ("off", "size", "full")

# Quarantined step dirs: ``corrupt-<step>`` (+ ``.k`` suffixes when a
# step is quarantined more than once). Never auto-deleted — operators
# reclaim the space explicitly with ``fmckpt gc``.
QUARANTINE_PREFIX = "corrupt-"

_MANIFEST_FORMAT = 1
_HASH_CHUNK_BYTES = 1 << 20

# The ONE sidecar-name pattern the run-time orphan pruning
# (_prune_sidecars) and fmckpt's offline scan share — a sidecar rename
# updated in one place only would make the offline tool delete files
# the run still needs, or miss real orphans. Matches epoch overrides,
# manifests, stream watermarks, and torn .tmp files (a killed writer's
# litter).
SIDECAR_RE = re.compile(
    r"(?:epoch_override-(\d+)|manifest-(\d+)\.json(?:\.tmp)?"
    r"|watermark-(\d+)\.json(?:\.tmp)?"
    r"|vocab-(\d+)\.json\.gz(?:\.tmp)?)")

# Stream-mode publish pointer (README "Streaming / online learning"):
# a tiny file in the .ckpt directory naming the newest PUBLISHED step —
# atomically replaced, so a scorer watching it always reads a complete
# value and can hot-reload the manifest-verified step it names.
PUBLISHED_POINTER = "published"


def sidecar_step(name: str) -> Optional[int]:
    """The step a sidecar file name belongs to, or None for
    non-sidecar names."""
    m = SIDECAR_RE.fullmatch(name)
    if not m:
        return None
    return int(m.group(1) or m.group(2) or m.group(3) or m.group(4))


def manifest_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"manifest-{step}.json")


def read_epoch_override(directory: str, step: int) -> Optional[int]:
    """The step's epoch-correction sidecar value, or None
    (missing/garbled/unreadable) — shared by restore's overlay and
    fmckpt's listing so the two can't disagree on what restores."""
    try:
        with open(os.path.join(directory,
                               f"epoch_override-{step}")) as fh:
            return int(fh.read().strip())
    except (OSError, ValueError):
        return None


def _atomic_write_bytes(path: str, blob: bytes) -> None:
    """The ONE tmp-write + fsync + rename sequence every sidecar
    writer (manifest, epoch override, watermark, vocab sidecar,
    published pointer) shares: the file either exists complete or not
    at all, and a failed write never litters its .tmp (a hard kill
    still can — the SIDECAR_RE orphan scans sweep those). Deliberately
    unretried: save-side write failures must surface at the save site
    (CheckpointState docstring)."""
    tmp = path + ".tmp"
    try:
        # fmlint: disable=R010 -- save-side writes are deliberately
        # never retried (CheckpointState docstring): a failed sidecar
        # write must fail its save loudly, not mask a torn file
        # behind backoff
        with open(tmp, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def _atomic_write_text(path: str, data: str) -> None:
    _atomic_write_bytes(path, data.encode("utf-8"))


def watermark_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"watermark-{step}.json")


def read_watermark(directory: str, step: int) -> Optional[dict]:
    """The step's durable stream-position sidecar (run_mode = stream),
    or None when the step has none (epoch-mode checkpoints never do).
    A garbled sidecar also returns None, WITH a warning: resuming a
    stream without its watermark re-reads from the beginning of every
    tracked file — train() refuses that loudly rather than silently
    double-training (see train's stream restore)."""
    path = watermark_path(directory, step)
    try:
        # fmlint: disable=R010 -- missing IS the common case (every
        # epoch-mode checkpoint) and a transiently unreadable sidecar
        # must become the same "no watermark" verdict the caller
        # handles, not a retry loop inside the restore decision
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except FileNotFoundError:
        return None
    except (ValueError, OSError):
        get_logger().warning(
            "stream watermark sidecar %s is unreadable/garbled; "
            "treating step %d as carrying no stream position", path,
            step, exc_info=True)
        return None


def write_watermark(directory: str, step: int, payload: dict) -> str:
    """Atomically-renamed watermark write (same contract as
    write_manifest): the sidecar either exists complete or not at all —
    a torn watermark must never resume a stream at a garbage offset."""
    path = watermark_path(directory, step)
    _atomic_write_text(path, json.dumps(payload, sort_keys=True))
    return path


def vocab_sidecar_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"vocab-{step}.json.gz")


def load_vocab_sidecar(directory: str, step: int
                       ) -> Tuple[Optional[dict], Optional[str]]:
    """(payload, reason) for a step's vocab-admission sidecar: the
    ONE torn-sidecar decision shared by the restore path and `fmckpt
    verify` so the two can never disagree on what a torn sidecar is.
    Absent -> (None, None); readable with a matching embedded crc32 ->
    (payload, None); unreadable gzip/json or a crc mismatch ->
    (None, <human-readable failure>)."""
    import gzip
    path = vocab_sidecar_path(directory, step)
    name = os.path.basename(path)
    try:
        # fmlint: disable=R010 -- missing IS the common case (every
        # fixed-mode checkpoint); a garbled sidecar must become the
        # same "no admission state" verdict the caller handles, not a
        # retry loop inside the restore decision
        with gzip.open(path, "rt", encoding="utf-8") as fh:
            payload = json.load(fh)
    except FileNotFoundError:
        return None, None
    except (ValueError, OSError, EOFError) as e:
        return None, f"vocab sidecar {name} is unreadable/garbled: {e}"
    from fast_tffm_tpu.vocab.table import payload_crc_ok
    if not payload_crc_ok(payload):
        return None, (f"vocab sidecar {name} failed its embedded "
                      "crc32 check (torn or bit-rotted)")
    return payload, None


def load_vocab_map(cfg, directory: str, step: Optional[int]):
    """The ONE inference-side (table, slot map, step) pairing load —
    predict and the serving reload both route here so the triple
    contract can't drift between them. Returns the step's VocabMap;
    raises FileNotFoundError when the step carries no readable sidecar
    (missing OR torn — scoring without the slot map would misroute
    every admitted id)."""
    payload = (read_vocab_sidecar(directory, int(step))
               if step is not None and step >= 0 else None)
    if payload is None:
        raise FileNotFoundError(
            f"checkpoint step {step} at {directory} carries no "
            "readable vocab admission sidecar (vocab-<step>.json.gz) "
            "but vocab_mode = admit: scoring without the slot map "
            "would misroute every admitted id. Was the model trained "
            "with vocab_mode = fixed?")
    from fast_tffm_tpu.vocab.table import VocabMap
    return VocabMap.from_payload(cfg, payload)


def refuse_fixed_mode_admit_step(cfg, directory: str,
                                 step: Optional[int],
                                 payload: Optional[dict] = None
                                 ) -> None:
    """The ONE admit-trained-under-fixed loud failure (train resume,
    predict, serve reload all call it): a step carrying a vocab
    admission sidecar was trained with ``vocab_mode = admit`` — its
    table rows are slot-mapped — so loading it under ``fixed`` would
    gather/train arbitrary rows with zero errors. Keys on sidecar
    EXISTENCE, not readability: a TORN sidecar still proves admit
    training. ``payload``: a sidecar payload the caller already read
    (the restore overlay), counted as the same evidence. No-op under
    admit mode or when ``step`` is unknown."""
    if getattr(cfg, "vocab_mode", "fixed") != "fixed":
        return
    if payload is None and (step is None or step < 0
                            or not os.path.exists(
                                vocab_sidecar_path(directory,
                                                   int(step)))):
        return
    raise ValueError(
        f"checkpoint step {step} carries a vocab admission sidecar — "
        "it was trained with vocab_mode = admit, so its table rows "
        "are slot-mapped — but this config has vocab_mode = fixed: "
        "modulo ids would gather/train the wrong rows. Set "
        "vocab_mode = admit (or start a fresh model_file).")


def read_vocab_sidecar(directory: str, step: int) -> Optional[dict]:
    """The step's vocab-admission sidecar payload (vocab_mode = admit;
    vocab/table.py), or None when the step has none (every fixed-mode
    checkpoint). A garbled/torn sidecar returns None WITH a warning:
    train() then refuses to silently continue with a scrambled slot
    map (its restore path treats a missing payload on an admit-mode
    resume as a loud fresh-admission-plus-row-reset fallback)."""
    payload, reason = load_vocab_sidecar(directory, step)
    if reason is not None:
        get_logger().warning(
            "%s; treating step %d as carrying no admission state",
            reason, step)
    return payload


def write_vocab_sidecar(directory: str, step: int,
                        payload: dict) -> str:
    """Atomically-renamed gzip write of the vocab admission payload
    (same tmp+fsync+rename contract as every other sidecar): it either
    exists complete or not at all — a torn slot map must never remap a
    resumed stream onto garbage rows. The payload carries its own
    crc32 (vocab/table.py), which read_vocab_sidecar and `fmckpt
    verify` both re-check."""
    import gzip
    path = vocab_sidecar_path(directory, step)
    _atomic_write_bytes(path, gzip.compress(
        json.dumps(payload, sort_keys=True).encode("utf-8")))
    return path


def read_published(directory: str) -> Optional[int]:
    """The step the ``published`` pointer names, or None (never
    published / unreadable / garbled)."""
    try:
        # fmlint: disable=R010 -- a scorer-side poll: absent is the
        # normal pre-first-publish state and any flake reads as "not
        # published yet" on this attempt, which the next poll heals
        with open(os.path.join(directory, PUBLISHED_POINTER),
                  encoding="utf-8") as fh:
            return int(fh.read().strip())
    except (OSError, ValueError):
        return None


def write_published(directory: str, step: int) -> str:
    """Atomically repoint the ``published`` pointer file at ``step`` —
    the ONE pointer-write sequence (tmp + fsync + rename via
    _atomic_write_text) shared by the stream driver's
    ``CheckpointState.publish_step`` and the ``fmckpt publish``
    operator path, so a concurrent reader (a serving process's reload
    poll) always reads either the old complete value or the new one,
    never a torn write. Callers own verification: repointing at an
    unverified step is how a scorer loads garbage."""
    path = os.path.join(directory, PUBLISHED_POINTER)
    _atomic_write_text(path, f"{int(step)}\n")
    return path


# Canary pointer (README "Serving fleet"): a SECOND pointer file
# beside ``published``, repointed by ``fmckpt publish --canary``. The
# fleet's canary replica follows it, so a candidate step can take a
# configured traffic fraction (or shadow traffic) before the real
# pointer moves — promotion is then an ordinary ``fmckpt publish`` of
# the same step, rollback is deleting/repointing the canary pointer.
CANARY_POINTER = "published-canary"


def read_canary(directory: str) -> Optional[int]:
    """The step the ``published-canary`` pointer names, or None (no
    canary in flight / unreadable / garbled — same healing contract as
    read_published)."""
    try:
        # fmlint: disable=R010 -- scorer-side poll: absent is the
        # normal no-canary state and any flake reads as "no canary"
        # on this attempt, healed by the next poll
        with open(os.path.join(directory, CANARY_POINTER),
                  encoding="utf-8") as fh:
            return int(fh.read().strip())
    except (OSError, ValueError):
        return None


def write_canary(directory: str, step: int) -> str:
    """Atomically repoint the canary pointer (same tmp+fsync+rename
    sequence as write_published, same torn-read-free contract).
    Callers own verification, exactly as for the real pointer."""
    path = os.path.join(directory, CANARY_POINTER)
    _atomic_write_text(path, f"{int(step)}\n")
    return path


def read_pointer(directory: str, pointer: str = "published"
                 ) -> Optional[int]:
    """Resolve a scorer's configured pointer (``serve_pointer``):
    ``published`` reads the real pointer; ``canary`` reads the canary
    pointer, falling back to ``published`` until a canary step exists
    (a canary replica with nothing to canary serves the fleet's
    step)."""
    if pointer == "canary":
        step = read_canary(directory)
        if step is not None:
            return step
    return read_published(directory)


# Sidecar of the published pointer: the validation AUC of the last
# SUCCESSFUL publish — the publish gate's drop baseline
# (obs/quality.PublishGate). It describes the POINTER (not a step), so
# it lives beside it, survives step GC like it, and a resumed trainer
# re-arms publish_max_auc_drop from it instead of exempting the first
# post-restart publish.
GATE_BASELINE = "gate_baseline"


def read_gate_baseline(directory: str) -> Optional[float]:
    """The persisted drop baseline, or None (never published through a
    gate / unreadable / garbled — the gate then starts baseline-free,
    exactly like a first publish)."""
    try:
        # fmlint: disable=R010 -- trainer-startup read: absent is the
        # normal no-gated-publish-yet state; any flake degrades to a
        # baseline-free (first-publish) gate, never a crash
        with open(os.path.join(directory, GATE_BASELINE),
                  encoding="utf-8") as fh:
            v = float(fh.read().strip())
        return v if math.isfinite(v) else None
    except (OSError, ValueError):
        return None


def write_gate_baseline(directory: str, auc: float) -> None:
    """Atomically persist the drop baseline beside the pointer (same
    tmp+fsync+rename sequence, same torn-read-free contract)."""
    _atomic_write_text(os.path.join(directory, GATE_BASELINE),
                       f"{float(auc):.10f}\n")


def wait_for_published(directory: str, last: Optional[int] = None,
                       timeout: Optional[float] = None,
                       poll_seconds: float = 0.5) -> Optional[int]:
    """Block until the ``published`` pointer names a step different
    from ``last`` (None = any published step), polling the pointer
    file. Returns the new step, or None on timeout. The pointer-watch
    primitive the serving subsystem builds on (serve/reload.py polls
    inline on its own thread; this helper is the blocking form for
    server startup and tests). A garbled/unreadable pointer reads as
    "not published yet" on that poll and heals on the next — the same
    contract as read_published."""
    deadline = (None if timeout is None
                else time.monotonic() + float(timeout))
    while True:
        step = read_published(directory)
        if step is not None and step != last:
            return step
        if deadline is not None and time.monotonic() >= deadline:
            return None
        time.sleep(poll_seconds)


def list_step_dirs(directory: str) -> List[int]:
    """Committed step numbers by DIRECT directory listing: orbax commits
    a step by atomically renaming its tmp dir to the bare number, so a
    digit-named directory IS a committed step (a killed writer leaves
    only non-digit tmp names). Listed fresh on every call — quarantine
    renames must be visible immediately, without trusting any manager's
    cached step list."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(int(n) for n in names
                  if n.isdigit() and os.path.isdir(os.path.join(directory,
                                                                n)))


def _crc32_file(path: str) -> Tuple[int, int]:
    """(crc32, byte count) of one file, streamed — the ONE hashing loop
    the save-side manifest and the restore-side full verify share, so
    the two can never diverge on chunking or masking. Both the reads
    and zlib.crc32 on >4 KB buffers release the GIL, so the background
    manifest writer doesn't stall the train loop."""
    crc = 0
    n = 0
    # fmlint: disable=R010 -- callers own the OSError contract: the
    # save-side manifest writer downgrades a failed hash to
    # "unverifiable" and the restore-side full verify converts it to a
    # quarantine VERDICT; a retry loop here would stall the background
    # hasher against storage that verify is about to judge anyway
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(_HASH_CHUNK_BYTES)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            n += len(chunk)
    return crc & 0xFFFFFFFF, n


def compute_manifest(directory: str, step: int,
                     payload: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """Walk a FINALIZED step directory into its integrity manifest:
    per-file byte count + crc32 (sizes come from the bytes actually
    read, so the size and the hash describe the same snapshot), plus
    the caller's payload echo (step/epoch/vocab). Cost: one sequential
    re-read of the step dir per committed save — the async-save path
    runs it on a background thread (CheckpointState), so the train
    loop never waits on the hash."""
    step_dir = os.path.join(directory, str(step))
    files: Dict[str, Dict[str, int]] = {}
    for root, _dirs, names in os.walk(step_dir):
        for name in sorted(names):
            p = os.path.join(root, name)
            rel = os.path.relpath(p, step_dir).replace(os.sep, "/")
            crc, n = _crc32_file(p)
            files[rel] = {"size": n, "crc32": crc}
    man: Dict[str, Any] = {"format": _MANIFEST_FORMAT, "step": int(step),
                           "files": files}
    if payload:
        man.update(payload)
    return man


def write_manifest(directory: str, step: int,
                   manifest: Dict[str, Any]) -> str:
    """Atomically-renamed manifest write (_atomic_write_text): a
    manifest either exists complete or not at all — a torn manifest
    must never brand an intact step corrupt."""
    path = manifest_path(directory, step)
    _atomic_write_text(path, json.dumps(manifest, sort_keys=True))
    return path


def read_manifest(directory: str, step: int) -> Optional[Dict[str, Any]]:
    """The step's manifest dict, or None when the step predates
    manifests. A garbled manifest raises ValueError (json) — callers
    decide whether that means corrupt (verify) or skip (ls)."""
    try:
        with open(manifest_path(directory, step), encoding="utf-8") as fh:
            return json.load(fh)
    except FileNotFoundError:
        return None


def verify_step_dir(directory: str, step: int,
                    mode: str = "size") -> Optional[str]:
    """Integrity verdict for one committed step: None when it passes —
    or has no manifest to check against (pre-manifest checkpoints stay
    restorable) — else a human-readable failure reason. ``size`` stats
    every manifest-listed file; ``full`` additionally re-hashes them.
    Extra files orbax adds later are ignored: the manifest pins what
    the save wrote, not what may legitimately appear."""
    if mode == "off":
        return None
    if mode not in CKPT_VERIFY_MODES:
        raise ValueError(f"unknown ckpt_verify mode {mode!r} "
                         f"(want one of {CKPT_VERIFY_MODES})")
    try:
        man = read_manifest(directory, step)
    except (ValueError, OSError) as e:
        # Garbled json AND unreadable file (EACCES, EIO, ESTALE) both
        # become a VERDICT, never an exception: an escape here would
        # crash restore on process 0 while its peers sit blocked in
        # the decision broadcast — quarantine preserves the bytes, and
        # the walk-back keeps the job alive.
        return f"unreadable manifest: {e}"
    if man is None:
        return None
    step_dir = os.path.join(directory, str(step))
    if not os.path.isdir(step_dir):
        return "step directory missing"
    files = man.get("files") or {}
    for rel in sorted(files):
        p = os.path.join(step_dir, rel.replace("/", os.sep))
        try:
            size = os.path.getsize(p)
        except OSError:
            return f"missing file {rel}"
        if int(size) != int(files[rel]["size"]):
            return (f"size mismatch on {rel}: {size} bytes on disk != "
                    f"{files[rel]['size']} in manifest")
    if mode == "full":
        for rel in sorted(files):
            p = os.path.join(step_dir, rel.replace("/", os.sep))
            try:
                crc, _ = _crc32_file(p)
            except OSError as e:
                return f"unreadable file {rel}: {e}"
            if crc != int(files[rel]["crc32"]):
                return f"crc32 mismatch on {rel}"
    return None


def _tel():
    from fast_tffm_tpu.obs.telemetry import active
    return active()


def align_orbax_barrier_counters() -> None:
    """Re-zero orbax's cross-process barrier counters — the broadcast-
    to-newcomer seam elastic GROW needs.

    Orbax makes its ``sync_global_devices`` barrier keys unique with
    MODULE-GLOBAL ``itertools.count()`` counters
    (``orbax.checkpoint.multihost.counters``): every AsyncCheckpointer
    ever created in the process advances them, and the count is baked
    into every subsequent barrier key (``<n>_Checkpointer:restore.<step>``).
    Two processes whose checkpointer HISTORIES differ — an elastic-grow
    joiner (count 0) rendezvousing with an incumbent that already
    restored/saved through several sessions — would derive DIFFERENT
    keys for the same restore and fail orbax's barrier-name assertion
    (observed: ``sync_global_devices name mismatch
    ('0_Checkpointer:restore.N')``). Every member constructs its
    CheckpointState at the same synchronized point running identical
    code, so re-zeroing here keeps every later allocation aligned
    across ANY membership history. Best-effort by design: on orbax
    layout drift the historical behavior (aligned-by-luck fresh
    processes) remains."""
    import itertools
    try:
        from orbax.checkpoint.multihost import counters
    except ImportError:
        return
    for name in vars(counters):
        if name.startswith("_") and name.endswith("_counter"):
            try:
                setattr(counters, name, itertools.count())
            except Exception:  # noqa: BLE001 - one misaligned counter
                pass           # is no worse than not aligning at all


class CheckpointState:
    """Manages checkpoints under ``<model_file>.ckpt/`` (orbax needs a
    directory; the reference's ``model_file`` is a path prefix).

    ``retry`` (utils/retry.py; train/predict thread the config's
    ``io_retries``/``io_backoff_seconds`` here) wraps the orbax
    RESTORE entry points in the transient-IO retry loop — restore is
    a pure read, so re-driving it is always safe. SAVE is deliberately
    NOT retried, in either phase: a transient failure after orbax has
    created the step directory would make a blind re-dispatch collide
    as StepAlreadyExistsError — which save()'s handler treats as the
    benign same-step case — silently recording a half-written
    checkpoint as done (strictly worse than failing loudly); and an
    async save's background-write failure surfaces at a later wait,
    outside any wrapper, where the snapshot needed to re-drive it is
    gone. Only genuinely retryable errors (OSError/TimeoutError minus
    the missing-path family) retry on restore; orbax's semantic errors
    (shape mismatches) propagate on the first raise."""

    def __init__(self, model_file: str, max_to_keep: int = 3,
                 retry: Optional[RetryPolicy] = None,
                 verify: str = "size"):
        if verify not in CKPT_VERIFY_MODES:
            raise ValueError(f"unknown ckpt_verify mode {verify!r} "
                             f"(want one of {CKPT_VERIFY_MODES})")
        self._max_to_keep = int(max_to_keep)
        self.directory = os.path.abspath(model_file) + ".ckpt"
        self._retry = retry or RetryPolicy(retries=0)
        self.verify = verify
        # (step, epoch, vocab) of the newest ASYNC save whose manifest
        # is still owed: the manifest can only describe a finalized
        # (atomically renamed) step dir, so it's written at the next
        # point the commit is certain — wait_until_finished, the next
        # save (orbax back-pressures there anyway), or close.
        self._pending_manifest: Optional[Tuple[int, int, int]] = None
        # Background manifest writer (the periodic-save path): hashing
        # a committed step is a full sequential re-read — at real table
        # scale that must overlap the train loop, not block it.
        self._manifest_thread: Optional[threading.Thread] = None
        os.makedirs(self.directory, exist_ok=True)
        multi_process = jax.process_count() > 1
        if multi_process:
            # Align orbax's history-dependent barrier counters across
            # the membership: a grown cluster mixes incumbents (many
            # checkpointers created) with fresh joiners (none), and
            # mismatched counters mean mismatched barrier keys — see
            # align_orbax_barrier_counters.
            align_orbax_barrier_counters()
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    def save(self, step: int, table: jax.Array, acc: jax.Array,
             vocabulary_size: int, force: bool = False,
             wait: bool = False, epoch: int = 0,
             rewrite_stale_metadata: bool = False,
             stream_state: Optional[dict] = None,
             vocab_state: Optional[dict] = None) -> None:
        """``vocabulary_size`` is stored alongside the arrays: the
        4096-aligned row layout means a changed vocab inside the same
        bucket would otherwise restore shape-compatibly but silently
        scramble the pad-row invariant (callers verify on restore).

        Saves are ASYNC by default: orbax snapshots the arrays to host
        and serializes in a background thread, so the train loop resumes
        after the snapshot instead of stalling for the full write (the
        reference's Saver writes synchronously; SURVEY §5 — this is the
        orbax upgrade that survey section calls for). A save issued
        while the previous one is still writing waits for it first
        (orbax's own back-pressure), bounding in-flight state to one
        snapshot. ``wait=True`` — the final/preemption save — blocks
        until the bytes are durably committed before returning."""
        # Timeline span (obs/trace; no-op without an active
        # tracing run): checkpoint pauses are a classic silent
        # stall — the span shows the snapshot cost, `wait=True`
        # saves show the full write.
        with span("checkpoint/save", step=int(step), wait=wait):
            # Settle the PREVIOUS async save's manifest before
            # dispatching a new one: orbax back-pressures a new save on
            # the in-flight write anyway, so the explicit wait here
            # costs nothing extra and guarantees the manifest describes
            # a finalized step dir. The hash itself runs on a
            # background thread — it's a full re-read of the step dir,
            # which must overlap the next save interval, not stall it.
            if self._pending_manifest is not None:
                self._mngr.wait_until_finished()
                self._flush_pending_manifest(background=True)
            # Plain python ints for the scalar leaves: orbax's
            # StandardSave supported types are (int, float, np.ndarray,
            # jax.Array) — numpy SCALARS (np.int64) are rejected outright
            # by its save-state validation.
            payload = {"table": table, "acc": acc,
                       "step": int(step),
                       # COMPLETED epochs at save time: lets a restarted
                       # run resume an interrupted epoch schedule instead
                       # of rerunning it from zero (train.resume_start_epoch)
                       "epoch": int(epoch),
                       "vocab": int(vocabulary_size)}
            try:
                # No retry here (class docstring): re-dispatching a
                # save whose first attempt half-created the step dir
                # would surface as the benign StepAlreadyExists path
                # below and silently skip the save.
                self._mngr.save(step, args=ocp.args.StandardSave(payload),
                                force=force)
                self._pending_manifest = (int(step), int(epoch),
                                          int(vocabulary_size))
                # A FRESH save at this step carries authoritative metadata:
                # drop any leftover same-step sidecar (a cleared-and-reused
                # directory) and any sidecars orphaned by max_to_keep GC —
                # CheckpointManager doesn't know about them.
                if jax.process_index() == 0:
                    self._prune_sidecars(fresh_step=step)
                    # Counted INSIDE the dispatch path and on process
                    # 0 only (like the fallback counters — every
                    # process's shard file merges by SUM in fmstat):
                    # the same-step collision below is an orbax no-op,
                    # and "checkpoint saves" means global saves that
                    # wrote state.
                    tel = _tel()
                    if tel is not None:
                        tel.count("checkpoint/saves")
            except ocp.checkpoint_manager.StepAlreadyExistsError:
                # The final/preemption save can land on the same step as the
                # last periodic save (save_steps divides the step count).
                # The ARRAY state at a given step is unique, so that part is
                # a no-op — but the colliding periodic save recorded the
                # epoch count as of MID-epoch, while this save may carry the
                # completed count; without a correction a successfully
                # completed run restores as "interrupted" and silently
                # retrains an epoch. The CALLER decides via
                # rewrite_stale_metadata — train() knows deterministically
                # (from its own last periodic save) whether the metadata
                # differs, and a deterministic flag keeps every process of a
                # multi-host job on the same side of this path (a
                # per-process disk read here could diverge on one host's
                # transient error and deadlock the final save). The
                # correction is a tiny atomically-renamed sidecar holding
                # the true epoch — restore() overlays it — NOT a
                # delete+resave of the step: a hard kill here leaves either
                # the old sidecar state (epoch stale, exactly the status
                # quo ante — the run retrains one epoch) or the new one;
                # the step's arrays are never at risk (advisor finding r4).
                if rewrite_stale_metadata and jax.process_index() == 0:
                    _atomic_write_text(self._epoch_sidecar(step),
                                       str(int(epoch)))
            # Stream-mode durable position (run_mode = stream): the
            # watermark sidecar pairs with the step exactly like the
            # epoch sidecar — written AFTER the fresh-step prune above
            # (which clears any stale same-step watermark), on BOTH the
            # fresh-save and same-step-collision paths (the collision's
            # array state is identical, and so is the watermark: it
            # only advances with global steps).
            if stream_state is not None and jax.process_index() == 0:
                write_watermark(self.directory, int(step), stream_state)
            # Vocab-admission sidecar (vocab_mode = admit): pairs with
            # the step exactly like the watermark — written after the
            # fresh-step prune, on both the fresh-save and same-step-
            # collision paths. The collision path's payload IS
            # identical to the colliding save's: the slot map only
            # moves at barriers, and every barrier-adjacent save
            # (publish, final) passes force=True precisely so a
            # post-barrier sidecar is never paired with skipped
            # pre-barrier arrays.
            if vocab_state is not None and jax.process_index() == 0:
                write_vocab_sidecar(self.directory, int(step),
                                    vocab_state)
            if wait:
                self._mngr.wait_until_finished()
                self._flush_pending_manifest()

    def wait_until_finished(self) -> None:
        self._mngr.wait_until_finished()
        self._flush_pending_manifest()

    def _flush_pending_manifest(self, background: bool = False) -> None:
        """Write the manifest for the last committed save. Call only
        after ``wait_until_finished`` — the step dir must be finalized.
        Process 0 only (one writer, like the epoch sidecar); a failed
        manifest write downgrades the step to unverifiable (it stays
        restorable, like a pre-manifest checkpoint) rather than failing
        a save that already committed. ``background=True`` (the
        periodic-save path) runs the hash on a daemon thread — any
        earlier writer is joined first, so at most one manifest write
        is ever in flight and they never reorder. Synchronous callers
        (wait=True saves, wait_until_finished, close) join it too, so
        after any of those the manifest is durably on disk."""
        self._join_manifest_thread()
        pend, self._pending_manifest = self._pending_manifest, None
        if pend is None or jax.process_index() != 0:
            return
        if background:
            t = threading.Thread(target=self._write_manifest_for,
                                 args=pend, name="ckpt-manifest",
                                 daemon=True)
            self._manifest_thread = t
            t.start()
        else:
            self._write_manifest_for(*pend)

    def _join_manifest_thread(self) -> None:
        t, self._manifest_thread = self._manifest_thread, None
        if t is not None:
            t.join()

    def _write_manifest_for(self, step: int, epoch: int,
                            vocab: int) -> None:
        try:
            man = compute_manifest(self.directory, step,
                                   payload={"epoch": epoch,
                                            "vocab": vocab})
            write_manifest(self.directory, step, man)
        except OSError:
            get_logger().warning(
                "manifest write for checkpoint step %d failed; the step "
                "stays restorable but unverifiable", step, exc_info=True)

    def _epoch_sidecar(self, step: int) -> str:
        return os.path.join(self.directory, f"epoch_override-{step}")

    def _prune_sidecars(self, fresh_step: Optional[int] = None) -> None:
        """Remove epoch sidecars AND manifests that no longer describe
        anything.

        Two legs with DIFFERENT failure contracts: removing the
        fresh-step's stale sidecar/manifest is correctness-bearing (a
        surviving sidecar would overlay the wrong epoch on the step
        just written, a surviving manifest would describe the OLD bytes
        and brand the fresh step corrupt — cleared-and-reused dir
        case), so anything but "not there" raises and fails the save
        loudly; the orphan scan for GC-deleted steps is purely cosmetic
        (a leftover orphan costs bytes and can never overlay or
        verify: its step no longer restores), so no flake in
        listdir/all_steps may fail an already-committed save."""
        if fresh_step is not None:
            mp = manifest_path(self.directory, fresh_step)
            wp = watermark_path(self.directory, fresh_step)
            vp = vocab_sidecar_path(self.directory, fresh_step)
            # The watermark is correctness-bearing like the epoch
            # sidecar: a surviving stale one (cleared-and-reused dir,
            # or an epoch-mode save landing on an old stream step)
            # would resume a later stream at positions THIS state
            # never trained. The vocab sidecar equally so: a stale
            # slot map would remap ids onto rows THIS table never
            # assigned them.
            for stale in (self._epoch_sidecar(fresh_step), mp,
                          mp + ".tmp", wp, wp + ".tmp", vp,
                          vp + ".tmp"):
                try:
                    os.remove(stale)
                except FileNotFoundError:
                    pass  # the common case: nothing to correct
        try:
            kept = set(self._mngr.all_steps())
            names = os.listdir(self.directory)
        except Exception:  # noqa: BLE001 - cosmetic scan only
            return
        for name in names:
            s = sidecar_step(name)
            if s is None:
                continue
            if s == fresh_step or s not in kept:
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass

    def _apply_epoch_override(self, step: int, restored):
        """Overlay a same-step epoch-correction sidecar (see save())
        onto a restored tree, when both exist. Multi-process: only
        process 0 reads the file and the value is broadcast, so a
        transient read error (or non-shared storage) on one host can
        never give processes different epochs — divergent resume
        schedules deadlock the lockstep collectives."""
        if restored is None or "epoch" not in restored:
            return restored
        override = -1
        if jax.process_index() == 0:
            # Shared reader (fmckpt uses it too); any unreadable/
            # garbled sidecar -> step's own metadata stands.
            ov = read_epoch_override(self.directory, step)
            if ov is not None:
                override = ov
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            from fast_tffm_tpu.parallel.liveness import guarded_collective
            override = int(guarded_collective(
                multihost_utils.broadcast_one_to_all,
                np.int64(override), label="checkpoint/epoch_override"))
        if override >= 0:
            restored["epoch"] = np.int64(override)
        return restored

    def _attach_vocab(self, step: int, restored):
        """Overlay the step's vocab-admission sidecar (vocab_mode =
        admit) onto a restored tree as ``restored["vocab_admission"]``
        (None when absent — every fixed-mode checkpoint). Same
        process-0-reads + broadcast protocol as the stream watermark,
        and for the same reason: divergent admission state across
        hosts would remap the same id onto different rows."""
        if restored is None:
            return restored
        payload = None
        if jax.process_index() == 0:
            payload = read_vocab_sidecar(self.directory, step)
        payload = self._broadcast_json(payload, "checkpoint/vocab")
        restored["vocab_admission"] = payload
        return restored

    def _attach_stream(self, step: int, restored):
        """Overlay the step's stream-watermark sidecar (run_mode =
        stream) onto a restored tree as ``restored["stream"]`` (None
        when absent — every epoch-mode checkpoint). Multi-process:
        process 0 reads, the JSON is broadcast (two fixed-shape
        collectives), so a transient read error on one host can never
        resume workers at different stream positions."""
        if restored is None:
            return restored
        wm = None
        if jax.process_index() == 0:
            wm = read_watermark(self.directory, step)
        # identity when single-process; the agreed (chief) value else
        wm = self._broadcast_json(wm, "checkpoint/watermark")
        restored["stream"] = wm
        return restored

    def _broadcast_json(self, obj, label: str):
        """Process 0's JSON-serializable value on every process: the
        variable-size companion of ``_broadcast_int``. ONE
        implementation — data/stream.broadcast_blob (the length-then-
        padded-payload chief broadcast, with its transport dtype
        handling) — so the protocol can't fork between the stream
        discovery and the restore-side watermark attach. stream.py
        imports nothing from this module, so no cycle."""
        from fast_tffm_tpu.data.stream import broadcast_blob
        return broadcast_blob(obj, label)

    # -- stream-mode publishing ------------------------------------------

    def publish_step(self, step: int) -> Optional[str]:
        """Atomically repoint the ``published`` pointer file at a
        manifest-VERIFIED committed step — the hot-reload signal a
        serving process watches (``fmckpt ls`` shows it). The caller
        must have settled the step's save + manifest first (a
        ``wait=True`` save does). Verification runs at the instance's
        ``ckpt_verify`` mode (minimum ``size`` — a publish is a promise
        to a scorer, so ``off`` still size-checks); on failure the
        pointer is NOT moved (the previous published step stays live),
        a warning names the reason, and None returns. Process 0 only;
        multi-host callers gate on it like the manifest writer."""
        if jax.process_index() != 0:
            return None
        mode = self.verify if self.verify != "off" else "size"
        reason = verify_step_dir(self.directory, step, mode)
        if reason is not None:
            get_logger().warning(
                "publish of checkpoint step %d skipped: %s — the "
                "previous published pointer stays in place", step,
                reason)
            tel = _tel()
            if tel is not None:
                tel.count("stream/publish_failures")
            return None
        path = write_published(self.directory, step)
        tel = _tel()
        if tel is not None:
            tel.count("stream/publishes")
        get_logger().info(
            "published checkpoint step %d (%s-verified) -> %s", step,
            mode, path)
        return path

    def published_at_risk(self, margin: int = 1) -> bool:
        """Whether retention is about to lap the ``published`` pointer:
        True when the pointed-at step is gone already, or ``margin``
        more saves would GC it (max_to_keep newest-N eviction). The
        stream driver republishes FIRST when this fires, so the
        pointer a scorer resolves never names a deleted step — frequent
        ``save_steps`` saves under a long ``publish_interval_seconds``
        would otherwise delete the published checkpoint out from under
        the serving fleet mid-interval. ``margin=2`` is the publish
        gate's retention-pause threshold: while a hold blocks
        republishing, periodic saves stop one slot EARLY so the
        mandatory final/preemption save can still land without
        evicting the last-good step."""
        pub = read_published(self.directory)
        if pub is None:
            return False
        steps = list_step_dirs(self.directory)
        if pub not in steps:
            return True  # already dangling: republish immediately
        newer = sum(1 for s in steps if s > pub)
        return newer >= self._max_to_keep - margin

    # -- integrity: verify / quarantine / step decision -----------------

    def verify_step(self, step: int,
                    mode: Optional[str] = None) -> Optional[str]:
        """Integrity verdict for one committed step against its
        manifest: None when it passes (or carries no manifest —
        pre-manifest checkpoints stay restorable), else a failure
        reason. ``mode`` defaults to the instance's ``ckpt_verify``."""
        return verify_step_dir(self.directory, step, mode or self.verify)

    def quarantine_step(self, step: int, reason: str) -> str:
        """Move a bad step out of the restore path WITHOUT deleting it:
        the step dir is renamed ``corrupt-<step>`` and its
        manifest/epoch sidecars move inside it (forensics travel with
        the evidence; nothing can overlay or verify a quarantined
        step). Emits the ``health: ckpt_fallback`` event + counters on
        the active run telemetry. Returns the quarantine dir path.
        Process 0 only in multi-host jobs — callers broadcast the
        resulting step decision."""
        src = os.path.join(self.directory, str(step))
        dst = os.path.join(self.directory, f"{QUARANTINE_PREFIX}{step}")
        k = 0
        while os.path.exists(dst):
            k += 1
            dst = os.path.join(self.directory,
                               f"{QUARANTINE_PREFIX}{step}.{k}")
        os.rename(src, dst)
        for name in (f"manifest-{step}.json", f"epoch_override-{step}",
                     f"watermark-{step}.json", f"vocab-{step}.json.gz"):
            try:
                os.replace(os.path.join(self.directory, name),
                           os.path.join(dst, name))
            except OSError:
                pass  # sidecar absent (or unshared storage): forensics
                # are best-effort, the rename above is the invariant
        try:
            with open(os.path.join(dst, "QUARANTINE"), "w",
                      encoding="utf-8") as fh:
                fh.write(f"step {step} quarantined at {time.time():.3f}: "
                         f"{reason}\n")
        except OSError:
            pass
        try:
            # Drop the manager's cached step list: latest_step()/
            # all_steps() must stop offering the quarantined step.
            self._mngr.reload()
        except Exception:  # noqa: BLE001 - cache refresh is advisory;
            pass           # list_step_dirs() reads the directory fresh
        from fast_tffm_tpu.obs.health import emit_ckpt_fallback
        emit_ckpt_fallback(step, reason, dst)
        get_logger().warning(
            "checkpoint step %d failed integrity (%s); quarantined to %s "
            "— falling back to an older step", step, reason, dst)
        return dst

    def _broadcast_int(self, value: int) -> int:
        """Process 0's value on every process (the same broadcast
        protocol as ``_apply_epoch_override``); identity when
        single-process. Every step decision goes through this so
        multi-host processes can't diverge onto different steps and
        deadlock the collectives."""
        if jax.process_count() <= 1:
            return int(value)
        from jax.experimental import multihost_utils
        from fast_tffm_tpu.parallel.liveness import guarded_collective
        # Deadline-guarded (parallel/liveness.py): a peer that dies
        # mid-restore must raise WorkerLostError on the survivors, not
        # park them in the step-decision broadcast forever.
        return int(guarded_collective(
            multihost_utils.broadcast_one_to_all, np.int64(value),
            label="checkpoint/step_decision"))

    def _all_agree(self, flag: bool) -> bool:
        """True only when EVERY process reports ``flag`` true (tiny
        allgather; identity single-process). The restore walk-back
        branches on restore success/failure — a per-process local
        condition (one host's shard read can fail transiently while
        the others succeed), so without this agreement the processes
        would take different branches of the broadcast protocol and
        pair mismatched collectives — the exact deadlock the broadcast
        design exists to prevent."""
        if jax.process_count() <= 1:
            return bool(flag)
        from jax.experimental import multihost_utils
        from fast_tffm_tpu.parallel.liveness import guarded_collective
        flags = guarded_collective(
            multihost_utils.process_allgather,
            np.asarray([bool(flag)]), label="checkpoint/restore_agree")
        return bool(np.asarray(flags).all())

    def _pick_intact_step(self) -> Tuple[int, int]:
        """Newest step that passes verification, quarantining every
        newer step that doesn't. Returns (step, n_quarantined), step -1
        when no step survives. Process 0 only — callers broadcast."""
        n = 0
        while True:
            steps = list_step_dirs(self.directory)
            if not steps:
                return -1, n
            s = steps[-1]
            reason = self.verify_step(s)
            if reason is None:
                return s, n
            self.quarantine_step(s, reason)
            n += 1

    def restore_partial(self, template: Dict[str, Any],
                        step: Optional[int] = None
                        ) -> Optional[Dict[str, Any]]:
        """Restore only the leaves named in ``template`` (a subtree of
        what was saved). The offload predict path uses this to load the
        table WITHOUT the same-sized Adagrad accumulator — at config-#5
        scale the accumulator is half the state, and materializing it
        just to drop it doubles peak host RSS. Uses a read-only
        PyTree-handler manager (StandardSave's on-disk format is the
        PyTree format; partial restore is a PyTreeRestore feature).
        Latest-step selection goes through the same verify + quarantine
        + broadcast decision as restore()."""
        with span("checkpoint/restore", partial=True):
            self.wait_until_finished()
            s = step
            if s is None:
                cand = (self._pick_intact_step()[0]
                        if jax.process_index() == 0 else -1)
                s = self._broadcast_int(cand)
                if s < 0:
                    return None
            reader = ocp.CheckpointManager(
                self.directory,
                item_handlers=ocp.PyTreeCheckpointHandler())
            try:
                restored, err = _restore_tolerating_legacy_epoch(
                    template,
                    lambda t: retry_io(
                        reader.restore, s,
                        args=ocp.args.PyTreeRestore(
                            item=t, partial_restore=True),
                        policy=self._retry, op="checkpoint_restore"))
                if err is not None:
                    raise err
                return self._apply_epoch_override(s, restored)
            finally:
                reader.close()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, step: Optional[int] = None,
                template: Optional[Dict[str, Any]] = None
                ) -> Optional[Dict[str, Any]]:
        """Returns {"table", "acc", "step"} as host arrays, or None if no
        checkpoint exists yet (fresh start). ``template`` is an abstract
        pytree (jax.ShapeDtypeStruct leaves) matching what was saved;
        required by orbax to reconstruct arrays.

        With ``step=None`` the newest INTACT checkpoint wins: every
        candidate is verified against its manifest before orbax touches
        it, and a candidate that fails verification — or raises during
        the restore itself — is quarantined (``corrupt-<step>``, never
        deleted) while restore walks back to the next older step. An
        EXPLICIT step is verified but never quarantined or walked past:
        the caller asked for those exact bytes."""
        with span("checkpoint/restore"):
            self.wait_until_finished()  # in-flight async save first
            if step is not None:
                reason = self.verify_step(step)
                if reason is not None:
                    raise ValueError(
                        f"checkpoint step {step} at {self.directory} "
                        f"failed integrity verification: {reason}. An "
                        "explicitly requested step is never quarantined "
                        "automatically — inspect it with `python -m "
                        "tools.fmckpt verify`.")
                restored, err = self._attempt_restore(step, template)
                if err is not None:
                    self._raise_restore_error(step, err)
                return self._attach_vocab(step, self._attach_stream(
                    step, self._apply_epoch_override(step, restored)))
            return self._restore_newest_intact(template)

    def _restore_newest_intact(self, template
                               ) -> Optional[Dict[str, Any]]:
        """The self-healing walk-back (class docstring): process 0
        picks + verifies + quarantines, every decision is broadcast,
        all processes restore the agreed step together."""
        proc0 = jax.process_index() == 0
        quarantined = 0
        first_err: Optional[Tuple[int, BaseException]] = None
        while True:
            cand = -1
            if proc0:
                cand, nq = self._pick_intact_step()
                quarantined += nq
            cand = self._broadcast_int(cand)
            if cand < 0:
                if first_err is not None:
                    # Every remaining candidate failed to LOAD (the
                    # verify-failures are already quarantined): surface
                    # the original, newest-step error — on a config
                    # mismatch that is the diagnosis for every step.
                    self._raise_restore_error(*first_err)
                had_quarantine = self._broadcast_int(
                    1 if quarantined else 0)
                if had_quarantine:
                    # Never silently convert "all checkpoints failed
                    # integrity" into a fresh start: a fresh run would
                    # quietly retrain from zero on top of hours of
                    # quarantined-but-recoverable optimizer state.
                    raise ValueError(
                        f"every checkpoint step at {self.directory} "
                        "failed integrity verification and was "
                        "quarantined (corrupt-*). Inspect with `python "
                        "-m tools.fmckpt ls` / `verify`; rename an "
                        "intact corrupt-<step> back to <step> to "
                        "recover it, or point model_file elsewhere to "
                        "start fresh.")
                return None
            restored, err = self._attempt_restore(cand, template)
            # Success/failure is a PER-PROCESS condition (one host's
            # shard read can fail while the others succeed): agree on
            # it before branching, or the processes would pair
            # mismatched collectives and deadlock.
            if self._all_agree(err is None):
                if quarantined:
                    tel = _tel()
                    if tel is not None:  # process 0 only: quarantined
                        # is always 0 elsewhere, so the count is global
                        tel.count("checkpoint/fallbacks")
                return self._attach_vocab(cand, self._attach_stream(
                    cand, self._apply_epoch_override(cand, restored)))
            if err is None:
                # This process succeeded but a peer didn't: walk back
                # with everyone (the restored tree may hold
                # non-addressable shards of a step the job as a whole
                # cannot load).
                err = RuntimeError(
                    f"restore of step {cand} failed on another process")
            if first_err is None:
                first_err = (cand, err)
            # Walk past a restore-time failure only when an OLDER step
            # remains: quarantining the last loadable-looking step on
            # (say) a config mismatch would turn a loud, actionable
            # error into a silent fresh start.
            has_more = 0
            if proc0 and any(t != cand
                             for t in list_step_dirs(self.directory)):
                has_more = 1
            has_more = self._broadcast_int(has_more)
            if not has_more:
                self._raise_restore_error(cand, err)
            if proc0:
                self.quarantine_step(
                    cand, f"restore failed: {type(err).__name__}: {err}")
                quarantined += 1

    def _attempt_restore(self, s: int, template
                         ) -> Tuple[Optional[Dict[str, Any]],
                                    Optional[BaseException]]:
        """One orbax restore attempt at step ``s`` (transient-IO
        retries + legacy-epoch tolerance included). Returns
        (restored, None) or (None, error) — the fallback loop owns
        deciding what an error means. OSError is caught alongside the
        semantic classes: after retry_io gives up, a persistently
        unreadable file IS the torn-write signature for steps too old
        to carry a manifest."""
        try:
            if template is None:
                return retry_io(self._mngr.restore, s,
                                policy=self._retry,
                                op="checkpoint_restore"), None
            multi_process = jax.process_count() > 1
            if multi_process:
                # Multi-process restores stage through HOST RAM: orbax's
                # direct-to-device deserialization in the multi-process
                # restore-then-step shape hits a known jaxlib defect
                # (intermittent SIGSEGV, or SILENT buffer garbage —
                # negative Adagrad accumulators, 1e37 magnitudes —
                # observed reproducibly on the elastic-grow reformed
                # cluster's first restore). Deserializing to numpy and
                # placing shards via make_array_from_callback uses only
                # the transfer path every train step already exercises.
                # Cost: each process transiently materializes the full
                # arrays on host — the same peak the offload backend's
                # load already accepts.
                return _restore_tolerating_legacy_epoch(
                    template,
                    lambda t: retry_io(
                        self._restore_host_staged, s, t,
                        policy=self._retry, op="checkpoint_restore"))
            return _restore_tolerating_legacy_epoch(
                template,
                lambda t: retry_io(
                    self._mngr.restore, s,
                    args=ocp.args.StandardRestore(t),
                    policy=self._retry, op="checkpoint_restore"))
        except (ValueError, KeyError, OSError) as e:
            return None, e

    def _restore_host_staged(self, s: int, template):
        """Restore step ``s`` with array leaves deserialized to host
        numpy — ``RestoreArgs(restore_type=np.ndarray)`` through a
        read-only PyTree reader (StandardSave's on-disk format IS the
        PyTree format; restore_partial uses the same reader shape) —
        then placed onto each leaf's target sharding with
        make_array_from_callback. A plain sharding-free template is
        not enough here: multi-process orbax repopulates the SAVED
        sharding from the step's metadata and hands back a
        non-addressable global array. See _attempt_restore for why
        this path must not let orbax deserialize straight into device
        buffers."""
        host_template = {
            k: (jax.ShapeDtypeStruct(v.shape, v.dtype)
                if isinstance(v, jax.ShapeDtypeStruct) else v)
            for k, v in template.items()}
        restore_args = {
            k: (ocp.RestoreArgs(restore_type=np.ndarray)
                if isinstance(v, jax.ShapeDtypeStruct)
                else ocp.RestoreArgs())
            for k, v in template.items()}
        reader = ocp.CheckpointManager(
            self.directory, item_handlers=ocp.PyTreeCheckpointHandler())
        try:
            restored = reader.restore(
                s, args=ocp.args.PyTreeRestore(
                    item=host_template, restore_args=restore_args))
        finally:
            reader.close()
        out = dict(restored)
        for k, v in template.items():
            sharding = (v.sharding if isinstance(v, jax.ShapeDtypeStruct)
                        else None)
            if sharding is None:
                continue
            arr = np.asarray(restored[k])
            out[k] = jax.make_array_from_callback(
                v.shape, sharding, lambda idx, a=arr: a[idx])
        return out

    def _raise_restore_error(self, s, e) -> None:
        # Orbax surfaces config-mismatch as a shape ValueError (whose
        # advice — enable truncation — is wrong here) or, for a
        # checkpoint predating a template key such as 'vocab', as a
        # tree-structure error. The same exception classes can also
        # mean a corrupt/partial step directory (killed writer), so
        # the advice names both causes rather than steering a user
        # toward discarding a recoverable checkpoint.
        raise ValueError(
            f"checkpoint at {self.directory} step {s} could not be "
            "restored against this config's layout. Most likely the "
            "checkpoint was written under a different config "
            "(vocabulary_size / factor_num / model_type) or an older "
            "storage layout — fix the config or point model_file at "
            "the matching checkpoint. If the config is right, this "
            "step directory may be corrupt/partially written (killed "
            "save): newer bad steps are quarantined automatically as "
            "corrupt-<step>; inspect the directory with `python -m "
            f"tools.fmckpt ls`. Underlying error: {e}") from e

    def close(self) -> None:
        """Settle any in-flight async save (and its owed manifest)
        before releasing the manager — close is the last point a
        crashed-out driver can make the newest step verifiable."""
        try:
            self._mngr.wait_until_finished()
            self._flush_pending_manifest()
        finally:
            self._mngr.close()


def _restore_tolerating_legacy_epoch(template, do_restore):
    """Run ``do_restore(template)``; on tree/shape errors retry ONCE
    without the 'epoch' leaf (checkpoints written before that leaf
    existed must stay restorable — an upgraded binary has to resume a
    preempted job's old checkpoint), defaulting the leaf to 0. Returns
    (restored, None) on success or (None, original_error) when both
    attempts fail — the caller owns the diagnostic. The one
    implementation for restore() and restore_partial(); a genuine
    config mismatch pays one wasted retry on this already-failing
    path, the price of not needing a metadata side-channel."""
    try:
        return do_restore(template), None
    except (ValueError, KeyError) as e:
        if "epoch" not in template:
            return None, e
        legacy = {k: v for k, v in template.items() if k != "epoch"}
        try:
            restored = do_restore(legacy)
        except (ValueError, KeyError):
            return None, e
        restored["epoch"] = 0
        return restored, None


def export_npz(table, path: str,
               vocabulary_size: Optional[int] = None) -> None:
    """Dense export of the parameter table for parity checks / external
    consumers. Pass ``vocabulary_size`` to slice off dead rows exactly:
    the pad row at index ``vocabulary_size`` plus any divisibility pad
    rows a mesh-sharded table carries (parallel/sharded.padded_num_rows).
    Without it, only the single trailing pad row is dropped (valid for
    unsharded tables only)."""
    arr = np.asarray(table)
    arr = arr[:vocabulary_size] if vocabulary_size is not None else arr[:-1]
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    np.savez_compressed(path, table=arr)
