"""Cross-file streaming scorer core (ROADMAP item 2: the predict gap).

The pre-refactor predict path tore its overlap pipeline down at every
file boundary: a fresh ``batch_iterator`` (fresh builder warmup), a
fresh ``ChunkedFetcher`` drain, and a telemetry ``barrier_flush`` per
file serialized the sweep into parse -> score -> D2H -> write, per
file, with nothing overlapping across the boundary. This module is the
single continuous alternative both predict drivers build on:

- ONE ``batch_iterator`` runs over ALL files (batches freely cross
  file boundaries — the C++ builder feeds straight through), tagged by
  the pipeline's ``FileMarks`` ledger: ``(path, examples_before)`` per
  file, appended before any batch holding that file's first example is
  yielded (the same idea as stream.py's watermark tags).
- ONE ``ChunkedFetcher`` (overlap=True) lives for the whole sweep, so
  file N's D2H rides the background thread while file N+1 scores and
  file N+2 parses.
- ``ScoreDemux`` cuts the ordered score stream back into per-file
  arrays as each file's LAST example lands, and hands them to the
  caller's ``on_file`` — which submits to the bounded ``ScoreWriter``
  thread, overlapping file N's disk write with everything above.

``keep_empty`` is load-bearing everywhere here: every input line is
exactly one example (blank lines become zero-feature rows — C++ block
parser ABI 7 and the BatchBuilder agree on the rule), so the ledger's
example offsets ARE line offsets and the score files stay line-aligned
with their inputs.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import (FileMarks, batch_iterator,
                                         gil_bound_iteration, prefetch)
from fast_tffm_tpu.obs.telemetry import active
from fast_tffm_tpu.obs.trace import span
from fast_tffm_tpu.utils.fetch import ChunkedFetcher

# Output-order buffer depth buckets (batches retained between bulk
# fetches): powers of two up to 4x FETCH_CHUNK_BATCHES.
DEPTH_BUCKETS = tuple(2 ** i for i in range(11))


class CompiledScorer:
    """The long-lived compiled-scorer handle both inference surfaces
    share: batch predict's cross-file sweep (score_sweep below) and the
    online serving process (serve/server.py). Wraps the one dispatch
    over the three inference paths (models/fm.make_batch_scorer), the
    raw-batch policy (ships_raw_batches — the pipeline must build
    batches in the shape the compiled program expects, and a drifted
    copy of that condition is how a raw-gather scorer ends up fed
    host-deduped batches), and the spec resolution, so a caller can't
    pair a scorer with the wrong batch builder.

    ``dedup`` overrides the config's resolution — the serving process
    forces ``"device"`` (the raw-gather path: no U axis, so its
    pre-compiled shape ladder is exactly [B rung, L rung] and every
    padded request shape is known at warmup). jit executables are
    cached per (spec, shape) process-wide (models/fm lru caches), so a
    handle is cheap to construct and compiled code outlives it."""

    def __init__(self, cfg: FmConfig, mesh=None, backend=None,
                 dedup: Optional[str] = None, serve_ladder: bool = False):
        import dataclasses
        from fast_tffm_tpu.models.fm import (ModelSpec,
                                             make_batch_scorer,
                                             ships_raw_batches)
        from fast_tffm_tpu.wire import WireEncoder, resolve_wire
        spec = ModelSpec.from_config(cfg)
        if dedup is not None:
            spec = dataclasses.replace(spec, dedup=dedup)
        self.spec = spec
        self.mesh = mesh
        self.backend = backend
        # Whether batch builders must ship RAW ids ([B, L], uniq_ids
        # None) for this scorer — threaded into batch_iterator /
        # make_device_batch by every caller.
        self.raw = ships_raw_batches(spec, mesh=mesh, backend=backend)
        self._score = make_batch_scorer(spec, mesh=mesh, backend=backend)
        # Wire format (README "Wire format"; wire.py): the one encoder
        # every inference surface dispatches through. Packed mode ships
        # flat CSR and the jitted program rebuilds the rectangles
        # on-device; the offload path withholds uniq_ids for its host
        # gather and ships only the gathered rows + flat CSR.
        self.wire = resolve_wire(cfg, mesh=mesh, backend=backend)
        # ``serve_ladder``: the server's encoder buckets flat arrays to
        # the coarse rect-fraction ladder so its pre-compiled shape
        # matrix stays bounded (wire.rect_fraction_rungs).
        self.encoder = WireEncoder(self.wire, pad_id=cfg.pad_id,
                                   host_uniq=backend is not None,
                                   rect_fraction=serve_ladder)
        # Explicit async device_put (the depth-2 double buffer) applies
        # on the plain single-device path only — mesh placement and the
        # offload host gather have their own protocols.
        self._stage = mesh is None and backend is None
        if self.wire.packed:
            from fast_tffm_tpu.models.fm import (make_packed_rows_score_fn,
                                                 make_packed_score_fn)
            self._packed_fn = (make_packed_rows_score_fn(spec)
                               if backend is not None
                               else make_packed_score_fn(spec))

    def score_batch(self, table, batch) -> "object":
        """Raw [B] scores (device-resident) for one DeviceBatch —
        labels/weights dropped here so callers can't accidentally ship
        them. Deliberately does not materialize to numpy (see
        make_batch_scorer: a per-batch fetch collapses async
        dispatch). The ONE dispatch for batch predict and serving,
        so it runs under oom_guard: RESOURCE_EXHAUSTED re-raises with
        the per-owner ledger attached (obs/memory.py)."""
        from fast_tffm_tpu.obs.memory import oom_guard
        with oom_guard("score/dispatch"):
            wb = self.encoder.encode_score(batch)
            if wb.packed:
                if self.backend is not None:
                    gathered = self.backend.gather(wb.host_uniq)
                    return self._packed_fn(wb.L, gathered, **wb.args)
                args = self.encoder.device_put(wb)
                return self._packed_fn(wb.L, table, **args)
            args = (self.encoder.device_put(wb) if self._stage
                    else dict(wb.args))
            return self._score(table, args)

    def score_packed_shape(self, table, B: int, L: int, P: int):
        """Dispatch an all-padding synthetic batch at one
        (B, L, flat-rung) shape — the serving warmup walks every rung a
        flush could encode to, so packed mode keeps the no-recompile
        guarantee (serve/server._warmup). Raw-ids (dedup=device)
        scorers only — exactly the shape the server forces."""
        if not self.wire.packed or not self.raw:
            raise ValueError("score_packed_shape warms the packed "
                             "raw-ids scorer only")
        from fast_tffm_tpu.wire import NARROW_VALUE_DTYPE
        vdt = (NARROW_VALUE_DTYPE if self.wire.narrow else np.float32)
        args = {"uniq_ids": None,
                "lengths": np.zeros(B, dtype=np.int32),
                "flat_idx": np.full(P, self.spec.vocabulary_size,
                                    dtype=np.int32),
                "flat_vals": np.zeros(P, dtype=vdt)}
        if self.spec.model_type == "ffm":
            args["flat_fields"] = np.zeros(P, dtype=np.int32)
        return self._packed_fn(L, table, **args)


class ScoreWriter:
    """Ordered score-file writer on a small background thread, so the
    next file's parse/score/D2H overlaps the previous file's disk
    write instead of serializing behind it. Submission order IS write
    order (one queue, one writer), the queue is bounded (at most 2
    files' scores buffered — the sweep's backpressure), and
    ``close()`` in the caller's finally flushes everything and
    surfaces any deferred write error — a predict() return means every
    score file is on disk. Each write is a ``predict/write`` span on
    the ``fm-score-writer`` track in fmtrace plus an always-on
    ``predict/write_seconds`` counter (the write share of the fmstat
    predict attribution).

    ``submit(..., marker=path)`` additionally creates an empty marker
    file AFTER the score file is durably written+closed — the
    multi-process chief's merge thread keys on these, so a marker's
    existence certifies its part file is complete."""

    def __init__(self, logger):
        import queue
        self._logger = logger
        self._q: "queue.Queue" = queue.Queue(maxsize=2)
        self._sentinel = object()
        self._lock = threading.Lock()  # guards _error (worker writes,
        # submit/close read; fmlint R008)
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(target=self._run,
                                        name="fm-score-writer",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is self._sentinel:
                return
            tel = active()  # per job: one global read (writes are
            # file-grained, not hot), robust to late activation
            with self._lock:
                dead = self._error is not None
            if dead:
                # Drain-and-discard: the run is already doomed (the
                # error surfaces at the next submit()/close()); keep
                # unblocking producers, stop burning I/O on writes
                # that would land beside a failed one.
                continue
            out_path, vals, marker = job
            try:
                # fmlint: disable=R003 -- feeds the always-on
                # predict/write_seconds counter (the fmstat write-share
                # row); the span is the timeline view
                t0 = time.perf_counter()
                with span("predict/write",
                          path=os.path.basename(out_path)):
                    with open(out_path, "w") as fh:
                        for v in vals:
                            fh.write(f"{v:.6f}\n")
                    if marker is not None:
                        # Created only after the score file closed: the
                        # marker certifies completeness to the merge
                        # thread watching the shared filesystem.
                        with open(marker, "w"):
                            pass
                if tel is not None:
                    # fmlint: disable=R003 -- closes the write sample
                    tel.count("predict/write_seconds",
                              time.perf_counter() - t0)
                self._logger.info("wrote %d scores to %s", len(vals),
                                  out_path)
            except BaseException as e:  # surfaced at submit()/close()
                with self._lock:
                    if self._error is None:  # keep the FIRST failure
                        self._error = e

    def submit(self, out_path: str, vals: np.ndarray,
               marker: Optional[str] = None) -> None:
        with self._lock:
            err = self._error
        if err is not None:
            raise err
        self._q.put((out_path, vals, marker))

    def close(self, raise_error: bool = True) -> None:
        if not self._closed:
            self._closed = True
            self._q.put(self._sentinel)
            self._thread.join()
        if raise_error:
            with self._lock:
                err = self._error
            if err is not None:
                raise err


class ScoreDemux:
    """Cut an ordered score stream into per-file arrays via the
    pipeline's ``FileMarks`` ledger.

    ``consume(scores)`` appends the next in-order slice of the sweep's
    example stream; whenever the ledger shows a LATER file has started
    (entry i+1 exists and the consumed count has reached its start),
    file i is complete — its span ``[starts[i], starts[i+1])`` is cut
    and handed to ``on_file(path, vals)`` in sweep order. One batch can
    complete several small files (a batch spanning files A|B|C cuts A
    and B in one consume); ``finalize()`` (call only after every score
    landed) cuts the tail — the last file ends at the consumed total,
    and trailing EMPTY files get their zero-length arrays (a zero-line
    input still owes a zero-line ``.score``).

    Threading: the single-process sweep calls ``consume`` from the
    ChunkedFetcher overlap worker (one thread, in add order) and
    ``finalize`` from the caller thread after ``flush()`` joined that
    worker; the lockstep sweep is single-threaded. State here is
    therefore single-writer at any moment and needs no lock — the
    ledger reads go through FileMarks' own lock."""

    def __init__(self, marks: FileMarks,
                 on_file: Callable[[str, np.ndarray], None]):
        self._marks = marks
        self._on_file = on_file
        self._bufs: "collections.deque" = collections.deque()
        self._buf_start = 0   # sweep offset of the first buffered score
        self._consumed = 0    # total scores consumed so far
        self._next = 0        # index of the next file to cut
        self.files_emitted = 0

    def consume(self, scores: np.ndarray) -> None:
        if len(scores):
            self._bufs.append(scores)
            self._consumed += len(scores)
        self._cut_ready(self._marks.snapshot())

    def _cut_ready(self, starts) -> None:
        while (self._next + 1 < len(starts)
               and self._consumed >= starts[self._next + 1][1]):
            self._emit(starts[self._next][0], starts[self._next + 1][1])
            self._next += 1

    def _emit(self, path: str, end: int) -> None:
        n = end - self._buf_start
        take: List[np.ndarray] = []
        while n > 0:
            head = self._bufs[0]
            if len(head) <= n:
                take.append(self._bufs.popleft())
                n -= len(head)
            else:
                take.append(head[:n])
                self._bufs[0] = head[n:]
                n = 0
        self._buf_start = end
        vals = (np.concatenate(take) if take
                else np.zeros(0, dtype=np.float32))
        self.files_emitted += 1
        self._on_file(path, vals)

    def finalize(self) -> None:
        """Cut everything still open. Only call once every score has
        been consumed (after ChunkedFetcher.flush / the lockstep drain):
        the files the ledger still holds open end at the consumed
        total."""
        starts = self._marks.snapshot()
        self._cut_ready(starts)
        for i in range(self._next, len(starts)):
            end = (starts[i + 1][1] if i + 1 < len(starts)
                   else self._consumed)
            self._emit(starts[i][0], end)
        self._next = len(starts)
        if self._buf_start != self._consumed:
            raise AssertionError(
                f"score demux leak: {self._consumed - self._buf_start} "
                f"scores consumed but never assigned to a file (ledger "
                f"has {len(starts)} entries)")


def score_sweep(cfg: FmConfig, table, files: Sequence[str],
                on_file: Callable[[str, np.ndarray], None],
                mesh=None, backend=None, vocab=None) -> int:
    """Single-process continuous scoring sweep: one batch stream over
    ALL ``files`` (keep_empty: score files stay line-aligned), one
    overlap ChunkedFetcher for the whole sweep, per-file RAW score
    arrays demuxed to ``on_file`` in sweep order as each file's last
    batch lands. Returns the number of examples scored.

    ``on_file`` runs on the fetch worker thread mid-sweep (tail files
    on the caller thread at finalize) — callers hand the arrays to a
    ScoreWriter/accumulator, both safe there. No per-file warmup, no
    per-file fetcher drain: the compiled scorer and the D2H overlap
    worker live across every boundary, which is where the 15x
    predict-vs-train gap lived (BENCH_r05, ISSUE 10)."""
    files = list(files)  # consumed twice (span field + iterator)
    scorer = CompiledScorer(cfg, mesh=mesh, backend=backend)
    marks = FileMarks()
    demux = ScoreDemux(marks, on_file)
    fetcher = ChunkedFetcher(
        lambda s, num_real: demux.consume(s[:num_real]), overlap=True)
    tel = active()
    if tel is not None:
        # The active wire mode, as gauges — fmstat's transfer-bound
        # attribution names it (README "Wire format").
        tel.set("wire/packed", 1.0 if scorer.wire.packed else 0.0)
        tel.set("wire/narrow", 1.0 if scorer.wire.narrow else 0.0)
    n_examples = 0
    # try/finally (ADVICE round 5): an exception mid-sweep must not
    # leave the overlap worker parked on queue.get forever with a
    # queued chunk of device score arrays pinned in HBM — close()
    # drains and joins the worker without masking the original error.
    try:
        with span("predict/sweep", files=len(files)):
            # ``vocab`` (vocab_mode = admit): the pipeline builds in
            # the hashed space and remaps through the checkpoint's
            # slot map — the sweep scores exactly the rows training
            # assigned (predict.py loads the (table, slot map, step)
            # triple together).
            it = batch_iterator(cfg, files, training=False, epochs=1,
                                keep_empty=True, raw_ids=scorer.raw,
                                file_marks=marks, vocab=vocab)
            for batch in prefetch(it, depth=cfg.prefetch_depth,
                                  gil_bound=gil_bound_iteration(
                                      cfg, keep_empty=True)):
                fetcher.add(scorer.score_batch(table, batch),
                            batch.num_real)
                n_examples += batch.num_real
                if tel is not None:
                    tel.count("predict/batches")
                    tel.count("predict/examples", batch.num_real)
                    # Output-order buffer: device score arrays held
                    # back so results land in input order — its depth
                    # is the D2H backlog (BASELINE.md "Predict-path
                    # rate").
                    tel.observe("predict/fetch_depth",
                                fetcher.pending_depth,
                                bounds=DEPTH_BUCKETS)
                    # Watchdog beat: a scored batch is progress
                    # (obs/health.py).
                    tel.heartbeat()
            fetcher.flush()
        # All scores are host-side and consumed (flush joined the
        # worker): cut the tail files on this thread.
        demux.finalize()
    finally:
        fetcher.close()
    return n_examples


def scrub_stale_parts(out_paths: Sequence[str]) -> List[str]:
    """Remove leftover ``<out>.part*`` files (parts AND ``.done``
    markers, any part index) from a crashed prior multi-process sweep
    into the same ``score_path``. The PartMerger polls markers from
    construction, so a stale marker set would satisfy its first poll
    instantly and merge the OLD run's parts into this run's ``.score``
    — the caller must scrub before any worker writes a fresh part (and
    barrier after, so no fresh part can race the scrub). Returns the
    removed paths (for the caller's log line)."""
    import glob
    removed: List[str] = []
    for out_path in out_paths:
        for stale in sorted(glob.glob(glob.escape(out_path) + ".part*")):
            os.remove(stale)
            removed.append(stale)
    return removed


# The merge thread polls the shared filesystem for part markers at this
# period — cheap (P stat calls) and far below any real file's write
# time.
_MERGE_POLL_SECONDS = 0.05

# After every worker passed the parts-done barrier, every marker is
# durable — a marker still missing this long after that point is a bug
# (or a dead shared filesystem), not a slow writer; raise with the path
# instead of polling forever.
_MERGE_GRACE_SECONDS = 300.0


class PartMerger:
    """The multi-process chief's background merge thread: as each
    file's P part files become complete (their ``.done`` markers
    appear on the shared filesystem), stream-merge them into the final
    ``.score`` file IN FILE ORDER and delete the parts — so the merge
    of file N overlaps the lockstep scoring of file N+1 instead of
    serializing behind two barriers per file (the pre-refactor
    protocol). Byte ranges are contiguous: process i's lines all
    precede process i+1's, so the merge is part order.

    ``finish()`` (after the sweep's parts-done barrier) bounds the
    remaining wait: every marker is durable by then, so a missing one
    is raised by name. ``stop()`` is the error-path teardown — the
    thread exits at the next poll."""

    def __init__(self, out_paths: Sequence[str], num_parts: int,
                 logger):
        self._outs = list(out_paths)
        self._P = num_parts
        self._logger = logger
        self._stop = threading.Event()
        self._done_barrier = threading.Event()  # set after the
        # parts-done collective: flips the poll loop to a deadline
        self._error: Optional[BaseException] = None  # single-writer
        # (merge thread); read by finish() after join
        self.merged: List[str] = []  # merge thread appends, callers
        # read after finish() joined
        self._thread = threading.Thread(target=self._run,
                                        name="fm-part-merger",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            for out_path in self._outs:
                if not self._wait_parts(out_path):
                    return  # stopped (error path) or grace exceeded
                self._merge_one(out_path)
        except BaseException as e:  # surfaced by finish()
            # fmlint: disable=R008 -- single-writer: only this thread
            # assigns, finish() reads strictly after join()
            self._error = e

    def _wait_parts(self, out_path: str) -> bool:
        missing = [f"{out_path}.part{i}.done" for i in range(self._P)]
        deadline = None
        while True:
            missing = [m for m in missing if not os.path.exists(m)]
            if not missing:
                return True
            if self._stop.is_set():
                return False
            if self._done_barrier.is_set():
                if deadline is None:
                    # fmlint: disable=R003 -- deadline bookkeeping on
                    # the merge thread, not a timed hot loop
                    deadline = time.monotonic() + _MERGE_GRACE_SECONDS
                elif time.monotonic() > deadline:
                    raise FileNotFoundError(
                        f"predict part marker(s) never appeared after "
                        f"the parts-done barrier: {missing[:3]} — a "
                        f"worker's writer claimed success but the "
                        f"shared filesystem never showed its part")
            self._stop.wait(_MERGE_POLL_SECONDS)

    def _merge_one(self, out_path: str) -> None:
        n = 0
        with span("predict/merge", path=os.path.basename(out_path)):
            # Stream the merge in bounded chunks: reading a whole part
            # with fh.read() holds multi-GB strings on the chief for
            # billion-line predicts.
            with open(out_path, "wb") as out_fh:
                for i in range(self._P):
                    with open(f"{out_path}.part{i}", "rb") as fh:
                        while True:
                            chunk = fh.read(8 << 20)
                            if not chunk:
                                break
                            n += chunk.count(b"\n")
                            out_fh.write(chunk)
        for i in range(self._P):
            os.remove(f"{out_path}.part{i}")
            os.remove(f"{out_path}.part{i}.done")
        # fmlint: disable=R008 -- single-writer: only the merge thread
        # appends; finish() reads strictly after join()
        self.merged.append(out_path)
        self._logger.info("wrote %d scores to %s (merged %d parts)",
                          n, out_path, self._P)

    def finish(self) -> List[str]:
        """Called on the chief after the parts-done barrier: every part
        marker is durable, so the thread finishes its remaining merges
        promptly (bounded by the per-marker grace). Joins and re-raises
        any merge error; returns the merged file list in order."""
        self._done_barrier.set()
        self._thread.join()
        if self._error is not None:
            raise self._error
        if len(self.merged) != len(self._outs):
            raise RuntimeError(
                f"part merger finished {len(self.merged)}/"
                f"{len(self._outs)} files — merge thread exited early")
        return list(self.merged)

    def stop(self) -> None:
        """Error-path teardown: ask the thread to exit at its next
        poll and join briefly; never raises (an exception is already
        propagating on the caller)."""
        self._stop.set()
        self._thread.join(timeout=5.0)
