"""Pluggable embedding-lookup backends — the SparseCore/offload seam.

The reference keeps its embedding behind TF's parameter-server variable
machinery (SURVEY.md §2 "Model parameters", §3.2): workers gather only
the batch-active rows and push sparse Adagrad updates; the table's
storage (how many PS tasks, where the blocks live) is invisible to the
training math. This module makes that seam explicit for the TPU rebuild
(BASELINE config #5: 10^9 hashed features need the table OUTSIDE device
HBM):

- the jitted compute owns everything between ``gathered rows in`` and
  ``row gradients out`` (models/fm.py ``grad_body``/``rows_score_body``);
- a backend owns storage, ``gather`` and the sparse-Adagrad ``apply``.

Backends (selected by ``FmConfig.lookup``):

- **device** (default): table + accumulator live as jax arrays —
  single-device or mesh row-sharded — with gather/update fused into the
  train-step jit (models/fm.py train_step_body, parallel/sharded.py).
  Fastest when the table fits device memory; the mesh scales it the way
  adding PS tasks did.
- **host** (``HostOffloadLookup``): table + accumulator live in host
  RAM; the device only ever holds the batch's ``[U, D]`` gathered rows
  and their gradients (train.py/predict.py route through
  ``make_grad_fn``/``make_rows_score_fn`` when ``lookup = host``).
  This is the offload *shape*: an accelerator-external embedding store
  with batched gather/update. A SparseCore implementation
  (jax-tpu-embedding) or a pinned-host DMA implementation
  (``memory_kind="pinned_host"`` shardings; this environment's
  tunnelled compiler rejects host-memory gather programs) drops in
  behind the same three methods with no change above the seam.

Storage layout is the checkpoint layout ([ckpt_rows, D], 4096-aligned —
config.FmConfig.ckpt_rows) so save/restore is allocation-free.
``tools/offload_smoke.py`` runs the at-scale accounting check.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from fast_tffm_tpu.config import FmConfig


class HostOffloadLookup:
    """Host-RAM embedding store with vectorized sparse Adagrad.

    ``uniq_ids`` rows are unique by the pipeline's host-side dedup
    (padding slots repeat ``pad_id``, but their gradients are masked to
    zero, so plain fancy-indexed updates are exact — no ``np.add.at``
    slow path needed).
    """

    # Above this many rows, initialization happens host-side (numpy) in
    # place; below it, we mirror models.fm.init_table exactly (same jax
    # PRNG stream) so backends are interchangeable in tests.
    _DEVICE_INIT_MAX_ROWS = 1 << 24

    def __init__(self, cfg: FmConfig, seed: int = 0,
                 _init: bool = True):
        self.cfg = cfg
        self.rows = cfg.ckpt_rows
        self.dim = cfg.row_dim
        if not _init:
            # Restore path: allocate nothing — load()/from_checkpoint
            # assign the restored arrays directly, so peak host memory is
            # one copy of the state, not two (a config-#5 table is tens
            # of GB; a transient second copy is an OOM).
            self.table: Optional[np.ndarray] = None
            self.acc: Optional[np.ndarray] = None
            return
        if cfg.num_rows <= self._DEVICE_INIT_MAX_ROWS:
            from fast_tffm_tpu.models.fm import init_table
            self.table = np.zeros((self.rows, self.dim), np.float32)
            self.table[:cfg.num_rows] = np.asarray(init_table(cfg, seed))
        else:
            # Huge tables never touch a device: host-side init with the
            # same distribution (PRNG stream differs from the device
            # init — irrelevant at this scale, documented).
            rng = np.random.default_rng(seed)
            self.table = np.zeros((self.rows, self.dim), np.float32)
            r = cfg.init_value_range
            chunk = 1 << 22
            for a in range(0, cfg.num_rows - 1, chunk):
                b = min(a + chunk, cfg.num_rows - 1)
                self.table[a:b] = rng.uniform(
                    -r, r, size=(b - a, self.dim)).astype(np.float32)
        self.acc = np.full((self.rows, self.dim), cfg.adagrad_init,
                           np.float32)

    # --- the three seam methods -------------------------------------

    def gather(self, uniq_ids: np.ndarray) -> np.ndarray:
        """[U] ids -> [U, D] rows (pad ids hit the dead zero row)."""
        return self.table[uniq_ids]

    def apply_grad(self, uniq_ids: np.ndarray, grad_rows: np.ndarray,
                   lr: float) -> None:
        """Sparse Adagrad on the touched rows: acc += g^2;
        table -= lr * g / sqrt(acc). Mirrors models.fm
        sparse_adagrad_apply (same math, host-side)."""
        g = np.asarray(grad_rows, dtype=np.float32)
        ids = np.asarray(uniq_ids)
        a = self.acc[ids] + np.square(g)
        self.acc[ids] = a
        self.table[ids] -= lr * g / np.sqrt(a)

    def state(self):
        """(table, acc) in the checkpoint layout — zero-copy."""
        return self.table, self.acc

    # --- persistence -------------------------------------------------

    def load(self, table: np.ndarray,
             acc: Optional[np.ndarray] = None) -> None:
        """``acc=None`` leaves the accumulator unset — valid for
        gather/score-only use (predict); ``apply_grad`` would fault."""
        expect = (self.rows, self.dim)
        if tuple(table.shape) != expect:
            raise ValueError(f"restored table shape {table.shape} != "
                             f"{expect}")
        # No-copy when the restored arrays are already f32 numpy (the
        # orbax restore path): at offload scale a dtype-converting copy
        # here would double peak memory.
        self.table = np.asarray(table, np.float32)
        self.acc = None if acc is None else np.asarray(acc, np.float32)

    @classmethod
    def for_table(cls, cfg: FmConfig, table) -> "HostOffloadLookup":
        """Score-only backend around an existing host table — the
        predict path for a caller-held table (e.g. train()'s offload
        return value). Accepts the logical [num_rows, D] or checkpoint
        [ckpt_rows, D] layout; gather only ever indexes rows <= pad_id,
        so either suffices. No accumulator, no copy for f32 numpy
        input."""
        arr = np.asarray(table, np.float32)
        if (arr.shape[0] not in (cfg.num_rows, cfg.ckpt_rows)
                or arr.shape[1] != cfg.row_dim):
            raise ValueError(
                f"table shape {arr.shape} matches neither the logical "
                f"[{cfg.num_rows}, {cfg.row_dim}] nor the checkpoint "
                f"[{cfg.ckpt_rows}, {cfg.row_dim}] layout")
        self = cls(cfg, _init=False)
        self.table = arr
        return self

    @classmethod
    def from_checkpoint(cls, cfg: FmConfig,
                        with_acc: bool = True) -> "HostOffloadLookup":
        """Restore straight into host RAM. The template's abstract
        sharding-free leaves make orbax materialize plain np.ndarrays —
        nothing lands on a device (a config-#5 table would not fit
        there) and no throwaway template arrays are allocated.

        ``with_acc=False`` (the predict path) restores the table leaf
        only: inference never touches the Adagrad accumulator, and at
        offload scale materializing it would double peak host RSS."""
        from fast_tffm_tpu.checkpoint import CheckpointState
        from fast_tffm_tpu.train import (check_restored_vocab,
                                         checkpoint_template)
        ckpt = CheckpointState(cfg.model_file)
        template = checkpoint_template(cfg, host=True)
        if with_acc:
            restored = ckpt.restore(template=template)
        else:
            template.pop("acc")
            restored = ckpt.restore_partial(template)
        ckpt.close()
        if restored is None:
            raise FileNotFoundError(
                f"no checkpoint found under {cfg.model_file}.ckpt")
        check_restored_vocab(cfg, restored)
        self = cls(cfg, _init=False)
        self.load(np.asarray(restored["table"]),
                  np.asarray(restored["acc"]) if with_acc else None)
        self.step = int(restored["step"])
        return self


def memory_report() -> dict:
    """Host RSS and device memory stats, for the offload smoke's
    accounting (tools/offload_smoke.py)."""
    import resource
    out = {"host_rss_mb": resource.getrusage(
        resource.RUSAGE_SELF).ru_maxrss // 1024}
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats() or {}
        out["device_in_use_mb"] = stats.get("bytes_in_use", 0) >> 20
        out["device_limit_mb"] = stats.get("bytes_limit", 0) >> 20
    except Exception:
        pass
    return out
