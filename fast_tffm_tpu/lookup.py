"""Pluggable embedding-lookup backends — the SparseCore/offload seam.

The reference keeps its embedding behind TF's parameter-server variable
machinery (SURVEY.md §2 "Model parameters", §3.2): workers gather only
the batch-active rows and push sparse Adagrad updates; the table's
storage (how many PS tasks, where the blocks live) is invisible to the
training math. This module makes that seam explicit for the TPU rebuild
(BASELINE config #5: 10^9 hashed features need the table OUTSIDE device
HBM):

- the jitted compute owns everything between ``gathered rows in`` and
  ``row gradients out`` (models/fm.py ``grad_body``/``rows_score_body``);
- a backend owns storage, ``gather`` and the sparse-Adagrad ``apply``.

Backends (selected by ``FmConfig.lookup``):

- **device** (default): table + accumulator live as jax arrays —
  single-device or mesh row-sharded — with gather/update fused into the
  train-step jit (models/fm.py train_step_body, parallel/sharded.py).
  Fastest when the table fits device memory; the mesh scales it the way
  adding PS tasks did.
- **host** (``make_offload_backend`` picks the best implementation):

  - ``PinnedHostLookup`` — table + accumulator are jax arrays placed in
    the accelerator host's memory (``memory_kind="pinned_host"``
    shardings) and the WHOLE step stays inside jitted programs: the
    gather/scatter run in host memory space (``compute_on
    "device_host"``), the FM math on the chip, and nothing ever blocks
    Python — the async dispatch stream the device path enjoys, with the
    state outside HBM. This is the device-resident offload mechanism
    BASELINE config #5 names (SparseCore being the other; no
    jax-tpu-embedding in this environment). On backends whose "device"
    memory IS host RAM (cpu), the same programs run without the
    memory-kind annotations (``mode="plain"``) — identical structure,
    trivially-true placement — which is what the hermetic CPU tests
    exercise.
  - ``HostOffloadLookup`` — table + accumulator in local numpy; the
    device only holds the batch's ``[U, D]`` gathered rows and their
    gradients. Pays a blocking device->host gradient fetch per step
    (inherent: the host update needs the bytes), so it is the fallback
    when the backend can't compile host-memory-space programs
    (``probe_placement_mode`` decides once, with a warning).

Storage layout is the checkpoint layout ([ckpt_rows, D], 4096-aligned —
config.FmConfig.ckpt_rows) so save/restore is allocation-free.
``tools/offload_smoke.py`` runs the at-scale accounting check.

**The adapter contract (e.g. a SparseCore backend).** A new storage
engine plugs in by implementing the three-method seam both existing
backends share — nothing else in the framework knows where rows live:

- ``gather(uniq_ids) -> [U, D] rows`` (device-consumable; padding
  slots, ``uniq_ids == pad_id``, may return anything — their gradients
  come back masked to zero);
- ``apply_grad(uniq_ids, grad_rows, lr)`` — sparse Adagrad on exactly
  those rows (duplicate pad slots are zero-gradient no-ops);
- ``state() -> (table, acc)`` in the [ckpt_rows, D] checkpoint layout,
  host-fetchable, for CheckpointState save/restore.

Wire-up is two switch points: ``make_offload_backend`` (train) and
``make_score_backend`` (predict; scoring needs only ``gather`` +
``table``). In an environment WITH jax-tpu-embedding, a SparseCore
adapter maps ``gather``/``apply_grad`` onto its embedding-table
lookup/update primitives and keeps ``state()`` as the HBM/host fetch of
its shards — the train loop, checkpointing, and predict then work
unchanged, exactly as they do for the two backends here.

**Wire format (README "Wire format").** The offload SCORE path rides
``wire_format = packed``: the encoder withholds ``uniq_ids`` for the
host-side ``gather`` (``WireBatch.host_uniq``) and only the gathered
``[U, D]`` rows plus the flat CSR cross the wall — the rectangles are
rebuilt on-device inside ``models/fm.packed_rows_score_body``, whose
pad slot is the gathered block's last row (the same contract
``rows_score_body`` inherits from the padded wire). The offload TRAIN
step stays on the padded wire (``wire.resolve_wire`` downgrades with a
warning): its host gather and host scatter consume the numpy batch
arrays directly, so there is no device-side unpack to fold them into.
"""

from __future__ import annotations

import contextlib
import functools
import warnings
from typing import Optional

import numpy as np

from fast_tffm_tpu.config import FmConfig


class HostOffloadLookup:
    """Host-RAM embedding store with vectorized sparse Adagrad.

    ``uniq_ids`` rows are unique by the pipeline's host-side dedup
    (padding slots repeat ``pad_id``, but their gradients are masked to
    zero, so plain fancy-indexed updates are exact — no ``np.add.at``
    slow path needed).
    """

    # Above this many rows, initialization happens host-side (numpy) in
    # place; below it, we mirror models.fm.init_table exactly (same jax
    # PRNG stream) so backends are interchangeable in tests.
    _DEVICE_INIT_MAX_ROWS = 1 << 24

    def __init__(self, cfg: FmConfig, seed: int = 0,
                 _init: bool = True):
        self.cfg = cfg
        self.rows = cfg.ckpt_rows
        self.dim = cfg.row_dim
        if not _init:
            # Restore path: allocate nothing — load()/from_checkpoint
            # assign the restored arrays directly, so peak host memory is
            # one copy of the state, not two (a config-#5 table is tens
            # of GB; a transient second copy is an OOM).
            self.table: Optional[np.ndarray] = None
            self.acc: Optional[np.ndarray] = None
            return
        if cfg.num_rows <= self._DEVICE_INIT_MAX_ROWS:
            from fast_tffm_tpu.models.fm import init_table
            self.table = np.zeros((self.rows, self.dim), np.float32)
            self.table[:cfg.num_rows] = np.asarray(init_table(cfg, seed))
        else:
            # Huge tables never touch a device: host-side init with the
            # same distribution (PRNG stream differs from the device
            # init — irrelevant at this scale, documented).
            rng = np.random.default_rng(seed)
            self.table = np.zeros((self.rows, self.dim), np.float32)
            r = cfg.init_value_range
            chunk = 1 << 22
            for a in range(0, cfg.num_rows - 1, chunk):
                b = min(a + chunk, cfg.num_rows - 1)
                self.table[a:b] = rng.uniform(
                    -r, r, size=(b - a, self.dim)).astype(np.float32)
        self.acc = np.full((self.rows, self.dim), cfg.adagrad_init,
                           np.float32)

    # --- the three seam methods -------------------------------------

    def gather(self, uniq_ids: np.ndarray) -> np.ndarray:
        """[U] ids -> [U, D] rows (pad ids hit the dead zero row)."""
        return self.table[uniq_ids]

    def apply_grad(self, uniq_ids: np.ndarray, grad_rows: np.ndarray,
                   lr: float) -> None:
        """Sparse Adagrad on the touched rows: acc += g^2;
        table -= lr * g / sqrt(acc). Mirrors models.fm
        sparse_adagrad_apply (same math, host-side)."""
        g = np.asarray(grad_rows, dtype=np.float32)
        ids = np.asarray(uniq_ids)
        a = self.acc[ids] + np.square(g)
        self.acc[ids] = a
        self.table[ids] -= lr * g / np.sqrt(a)

    def state(self):
        """(table, acc) in the checkpoint layout — zero-copy."""
        return self.table, self.acc

    def reset_rows(self, rows: np.ndarray,
                   adagrad_init: float = 0.1) -> None:
        """Cold-start the given physical rows: zero embeddings,
        re-initialized accumulator. The vocab-admission barrier's
        eviction hook (vocab/table.py) — an evicted id's old row must
        not leak its trained embedding to the row's next owner. Part
        of the slot-indirection seam every backend implements (the
        device path uses vocab.table.reset_table_rows)."""
        self.table[rows] = 0.0
        if self.acc is not None:
            self.acc[rows] = np.float32(adagrad_init)

    # --- persistence -------------------------------------------------

    def load(self, table: np.ndarray,
             acc: Optional[np.ndarray] = None) -> None:
        """``acc=None`` leaves the accumulator unset — valid for
        gather/score-only use (predict); ``apply_grad`` would fault."""
        expect = (self.rows, self.dim)
        if tuple(table.shape) != expect:
            raise ValueError(f"restored table shape {table.shape} != "
                             f"{expect}")
        # No-copy when the restored arrays are already f32 numpy (the
        # orbax restore path): at offload scale a dtype-converting copy
        # here would double peak memory.
        self.table = np.asarray(table, np.float32)
        self.acc = None if acc is None else np.asarray(acc, np.float32)

    @classmethod
    def for_table(cls, cfg: FmConfig, table) -> "HostOffloadLookup":
        """Score-only backend around an existing host table — the
        predict path for a caller-held table (e.g. train()'s offload
        return value). Accepts the logical [num_rows, D] or checkpoint
        [ckpt_rows, D] layout; gather only ever indexes rows <= pad_id,
        so either suffices. No accumulator, no copy for f32 numpy
        input."""
        arr = np.asarray(table, np.float32)
        if (arr.shape[0] not in (cfg.num_rows, cfg.ckpt_rows)
                or arr.shape[1] != cfg.row_dim):
            raise ValueError(
                f"table shape {arr.shape} matches neither the logical "
                f"[{cfg.num_rows}, {cfg.row_dim}] nor the checkpoint "
                f"[{cfg.ckpt_rows}, {cfg.row_dim}] layout")
        self = cls(cfg, _init=False)
        self.table = arr
        return self

    @classmethod
    def from_checkpoint(cls, cfg: FmConfig,
                        with_acc: bool = True) -> "HostOffloadLookup":
        """Restore straight into host RAM. The template's abstract
        sharding-free leaves make orbax materialize plain np.ndarrays —
        nothing lands on a device (a config-#5 table would not fit
        there) and no throwaway template arrays are allocated.

        ``with_acc=False`` (the predict path) restores the table leaf
        only: inference never touches the Adagrad accumulator, and at
        offload scale materializing it would double peak host RSS."""
        from fast_tffm_tpu.checkpoint import CheckpointState
        from fast_tffm_tpu.train import (check_restored_vocab,
                                         checkpoint_template)
        from fast_tffm_tpu.utils.retry import RetryPolicy
        ckpt = CheckpointState(cfg.model_file,
                               retry=RetryPolicy.from_config(cfg),
                               verify=getattr(cfg, "ckpt_verify", "size"))
        template = checkpoint_template(cfg, host=True)
        if with_acc:
            restored = ckpt.restore(template=template)
        else:
            template.pop("acc")
            restored = ckpt.restore_partial(template)
        ckpt.close()
        if restored is None:
            raise FileNotFoundError(
                f"no checkpoint found under {cfg.model_file}.ckpt")
        check_restored_vocab(cfg, restored)
        self = cls(cfg, _init=False)
        self.load(np.asarray(restored["table"]),
                  np.asarray(restored["acc"]) if with_acc else None)
        self.step = int(restored["step"])
        return self


# ---------------------------------------------------------------------------
# Device-resident offload: pinned-host jax state, fully in-jit step.
# ---------------------------------------------------------------------------

_PLACEMENT_MODE: Optional[list] = None  # [None | "plain" | "pinned"]


def probe_placement_mode() -> Optional[str]:
    """Which in-jit host-memory placement this backend supports, probed
    once per process by COMPILING AND RUNNING a tiny program with the
    exact structure the real step uses (host-space gather + scatter,
    device math, donated pinned state):

    - ``"pinned"``: real ``memory_kind="pinned_host"`` shardings with
      the host segments under ``compute_on("device_host")`` (TPU).
    - ``"plain"``: same program, no memory-space annotations — only on
      backends whose device memory IS host RAM (cpu), where the
      annotation machinery doesn't exist but the placement claim is
      trivially true.
    - ``None``: neither compiles/runs; callers fall back to the numpy
      backend.
    """
    global _PLACEMENT_MODE
    if _PLACEMENT_MODE is not None:
        return _PLACEMENT_MODE[0]
    import jax
    import jax.numpy as jnp
    if jax.default_backend() == "cpu":
        _PLACEMENT_MODE = ["plain"]
        return "plain"
    try:
        from jax.experimental.compute_on import compute_on
        from jax.sharding import SingleDeviceSharding
        dev = jax.devices()[0]
        s_host = SingleDeviceSharding(dev, memory_kind="pinned_host")
        s_dev = SingleDeviceSharding(dev, memory_kind="device")

        # The probe mirrors the real programs' structure exactly:
        # spaceless avals throughout (state created by jit out_shardings,
        # NOT device_put — a device_put-created pinned array carries a
        # memory-space-annotated aval that poisons later traces), host
        # segments as bare compute_on blocks, XLA inserting transfers.
        @functools.partial(jax.jit, out_shardings=s_host)
        def alloc():
            return jnp.zeros((8, 4), jnp.float32)

        @functools.partial(jax.jit, donate_argnums=(0,),
                           out_shardings=(s_host, s_dev))
        def step(tab, ids, upd):
            with compute_on("device_host"):
                rows = tab[ids]
            new_rows = rows + upd
            with compute_on("device_host"):
                tab2 = tab.at[ids].set(new_rows)
            return tab2, new_rows.sum()

        tab = alloc()
        tab, total = step(tab, jnp.array([1, 3]), jnp.ones((2, 4)))
        jax.block_until_ready((tab, total))
        ok = (float(total) == 8.0
              and tab.sharding.memory_kind == "pinned_host")
        _PLACEMENT_MODE = ["pinned" if ok else None]
    except Exception as e:  # compile or runtime rejection -> fallback
        warnings.warn(
            f"pinned-host offload probe failed on this backend "
            f"({type(e).__name__}: {str(e)[:200]}); lookup = host uses "
            "the numpy fallback with a blocking per-step gradient fetch")
        _PLACEMENT_MODE = [None]
    return _PLACEMENT_MODE[0]


@functools.lru_cache(maxsize=None)
def _placement(pinned: bool):
    """(host_sharding, device_sharding, host_ctx) — the placement hooks
    every pinned program shares. Ops inside ``host_ctx`` are scheduled
    on the accelerator host (XLA inserts the transfers); avals stay
    memory-space-free throughout (see probe_placement_mode). In plain
    mode both shardings are the plain device placement and the ctx is a
    no-op."""
    import jax
    from jax.sharding import SingleDeviceSharding
    dev = jax.devices()[0]
    if not pinned:
        s = SingleDeviceSharding(dev)
        return s, s, contextlib.nullcontext
    from jax.experimental.compute_on import compute_on
    s_host = SingleDeviceSharding(dev, memory_kind="pinned_host")
    s_dev = SingleDeviceSharding(dev, memory_kind="device")
    return s_host, s_dev, lambda: compute_on("device_host")


@functools.lru_cache(maxsize=None)
def _commit_fn(pinned: bool):
    """jit identity placing a host/numpy array into the state sharding —
    the ONLY way state enters the backend (a device_put with a memory
    kind would stamp the array's aval with a memory space and poison
    every later trace against spaceless-aval programs)."""
    import jax
    s_host, _, _ = _placement(pinned)
    return jax.jit(lambda x: x, out_shardings=s_host)


@functools.lru_cache(maxsize=None)
def _reset_rows_fn(pinned: bool, dim: int, adagrad_init: float):
    """jit: zero the given table rows / re-init the acc rows, in the
    state placement — the pinned backend's half of the vocab eviction
    seam (fixed RESET_CHUNK-wide index array: one compile ever)."""
    import jax
    from fast_tffm_tpu.vocab.table import reset_body
    s_host, _, ctx = _placement(pinned)

    @functools.partial(jax.jit, donate_argnums=(0, 1),
                       out_shardings=(s_host, s_host))
    def reset(table, acc, rows):
        with ctx():
            return reset_body(table, acc, rows, adagrad_init)

    return reset


@functools.lru_cache(maxsize=None)
def _gather_fn(pinned: bool):
    """jit: (table_host [R, D], ids [U]) -> device rows [U, D]."""
    import jax
    s_host, s_dev, ctx = _placement(pinned)

    @functools.partial(jax.jit, out_shardings=s_dev)
    def gather(table, ids):
        with ctx():
            rows = table[ids]
        return rows

    return gather


@functools.lru_cache(maxsize=None)
def _apply_fn(pinned: bool):
    """jit: sparse Adagrad on host-resident state, gradients already on
    device. Same math as models.fm.sparse_adagrad_apply (uniq ids;
    padding rows carry zero grads, so duplicate pad-slot writes all
    store identical values)."""
    import jax
    from jax import lax
    s_host, s_dev, ctx = _placement(pinned)

    @functools.partial(jax.jit, donate_argnums=(0, 1),
                       out_shardings=(s_host, s_host))
    def apply(table, acc, ids, grad, lr):
        with ctx():
            acc_rows = acc[ids]
            rows = table[ids]
        new_acc = acc_rows + jax.numpy.square(grad)
        new_rows = rows - lr * grad * lax.rsqrt(new_acc)
        with ctx():
            acc2 = acc.at[ids].set(new_acc)
            table2 = table.at[ids].set(new_rows)
        return table2, acc2

    return apply


@functools.lru_cache(maxsize=None)
def _fused_step_fn(spec, pinned: bool):
    """jit: ONE program for the whole offload train step — host-space
    gathers, device FM forward/backward (models.fm.grad_body: the same
    middle the device and numpy backends use), host-space Adagrad
    writes. Donated state, nothing returned to Python but device
    scalars; the dispatch stream never blocks."""
    import jax
    from jax import lax
    from fast_tffm_tpu.models.fm import grad_body
    s_host, s_dev, ctx = _placement(pinned)

    @functools.partial(
        jax.jit, donate_argnums=(0, 1),
        out_shardings=(s_host, s_host, s_dev, s_dev))
    def step(table, acc, labels, weights, uniq_ids, local_idx, vals,
             fields=None, *, lr):
        with ctx():
            gathered = table[uniq_ids]
            acc_rows = acc[uniq_ids]
        loss, scores, grad = grad_body(spec, gathered, labels, weights,
                                       uniq_ids, local_idx, vals, fields)
        new_acc = acc_rows + jax.numpy.square(grad)
        new_rows = gathered - lr * grad * lax.rsqrt(new_acc)
        with ctx():
            acc2 = acc.at[uniq_ids].set(new_acc)
            table2 = table.at[uniq_ids].set(new_rows)
        return table2, acc2, loss, scores

    return step


class PinnedHostLookup:
    """Accelerator-host-memory embedding store, fully in-jit.

    Same three seam methods as ``HostOffloadLookup`` (gather /
    apply_grad / state) plus a fused per-step program
    (``make_offload_train_step``). The state lives in the accelerator
    host's pinned memory (``mode="pinned"``) or, on cpu backends, as
    plain arrays (``mode="plain"`` — device memory is host RAM there);
    HBM only ever holds the per-batch [U, D] row blocks either way.
    """

    def __init__(self, cfg: FmConfig, seed: int = 0, _init: bool = True,
                 mode: Optional[str] = None):
        import jax.numpy as jnp
        self.cfg = cfg
        self.rows = cfg.ckpt_rows
        self.dim = cfg.row_dim
        self.mode = mode or probe_placement_mode()
        if self.mode is None:
            raise RuntimeError(
                "this backend supports no in-jit host placement "
                "(probe_placement_mode); use HostOffloadLookup")
        self._pinned = self.mode == "pinned"
        self._s_state = _placement(self._pinned)[0]
        if not _init:
            self.table = None
            self.acc = None
            return
        if cfg.num_rows <= HostOffloadLookup._DEVICE_INIT_MAX_ROWS:
            # Mirror the device backend's init exactly (same PRNG
            # stream) so backends are interchangeable in tests.
            from fast_tffm_tpu.models.fm import init_table
            t = jnp.zeros((self.rows, self.dim), jnp.float32)
            t = t.at[:cfg.num_rows].set(init_table(cfg, seed))
            self.table = _commit_fn(self._pinned)(t)
        else:
            self.table = self._init_big(seed)
        self.acc = self._alloc_full(cfg.adagrad_init)

    # Largest constant-fill HBM temporary we allow: XLA materializes a
    # jitted full()'s broadcast output in HBM even with pinned
    # out_shardings (and compute_on doesn't cover constant fills), so a
    # one-shot alloc caps the state at HBM size — measured failing at
    # 4e8 rows (25.6 GB broadcast vs 17.2 GB HBM) on the v5e chip.
    _ALLOC_SLAB_BYTES = 2 << 30

    def _alloc_full(self, value: float):
        """A [ckpt_rows, D] constant array allocated into the state
        placement. Beyond _ALLOC_SLAB_BYTES (pinned mode), it is built
        as one HBM-bounded seed slab grown to full size by a HOST-space
        constant pad — HBM high-water stays one slab and host-memory
        transient stays ~1x the array (a full-array concatenate would
        transiently hold 2x, which is exactly what broke the SECOND
        array's alloc at 4e8 rows with the first one resident)."""
        import jax
        import jax.numpy as jnp

        from fast_tffm_tpu.obs.memory import table_bytes
        nbytes = table_bytes(rows=self.rows, dim=self.dim)
        if not self._pinned or nbytes <= self._ALLOC_SLAB_BYTES:
            @functools.partial(jax.jit, out_shardings=self._s_state)
            def full():
                return jnp.full((self.rows, self.dim), np.float32(value),
                                jnp.float32)

            return full()
        _, _, ctx = _placement(self._pinned)
        n_seed = min(self.rows,
                     self._ALLOC_SLAB_BYTES // (self.dim * 4))

        @functools.partial(jax.jit, out_shardings=self._s_state)
        def seed():
            return jnp.full((n_seed, self.dim), np.float32(value),
                            jnp.float32)

        @functools.partial(jax.jit, out_shardings=self._s_state)
        def grow(x):
            with ctx():
                return jnp.pad(x, ((0, self.rows - n_seed), (0, 0)),
                               constant_values=np.float32(value))

        out = grow(seed())
        out.block_until_ready()  # free the seed slab before returning
        return out

    def _init_big(self, seed: int):
        """Chunked at-scale init: uniform chunks generated ON DEVICE and
        scatter-written into the host-resident table — the bulk bytes
        never cross the Python/driver boundary (on a tunnelled chip a
        device_put of the whole table would)."""
        import jax
        import jax.numpy as jnp
        cfg = self.cfg
        s_host, s_dev, ctx = _placement(self._pinned)
        chunk = 1 << 22

        def make_fill(n):
            @functools.partial(jax.jit, donate_argnums=(0,),
                               out_shardings=s_host)
            def fill(table, key, start):
                vals = jax.random.uniform(
                    key, (n, self.dim), dtype=jnp.float32,
                    minval=-cfg.init_value_range,
                    maxval=cfg.init_value_range)
                idx = start + jnp.arange(n, dtype=jnp.int32)
                with ctx():
                    return table.at[idx].set(vals)
            return fill

        table = self._alloc_full(0.0)
        key = jax.random.PRNGKey(seed)
        live = cfg.num_rows - 1  # pad row and ckpt tail stay zero
        fill_full = make_fill(chunk)
        for a in range(0, live, chunk):
            key, sub = jax.random.split(key)
            n = min(chunk, live - a)
            fill = fill_full if n == chunk else make_fill(n)
            table = fill(table, sub, jnp.int32(a))
        return table

    # --- the three seam methods -------------------------------------

    def gather(self, uniq_ids):
        """[U] ids -> [U, D] device rows (host-space gather in-jit)."""
        return _gather_fn(self._pinned)(self.table, uniq_ids)

    def apply_grad(self, uniq_ids, grad_rows, lr: float) -> None:
        """Sparse Adagrad on the touched rows, fully in-jit; accepts the
        device gradient array without materializing it to Python."""
        import jax.numpy as jnp
        self.table, self.acc = _apply_fn(self._pinned)(
            self.table, self.acc, uniq_ids, grad_rows, jnp.float32(lr))

    def state(self):
        """(table, acc) jax arrays in the checkpoint layout. They live
        in accelerator-host memory; checkpointing fetches their bytes
        (unavoidable for any durable save)."""
        return self.table, self.acc

    def reset_rows(self, rows, adagrad_init: float = 0.1) -> None:
        """Cold-start the given physical rows in place (the vocab
        eviction hook — see HostOffloadLookup.reset_rows): a jitted
        fixed-width scatter in the state placement, so barriers never
        add a compile per eviction count and the state never leaves
        host memory space."""
        from fast_tffm_tpu.vocab.table import reset_chunks
        fn = _reset_rows_fn(self._pinned, self.dim,
                            float(adagrad_init))
        pad_row = self.rows - 1  # dead ckpt-alignment tail row
        if self.acc is None:
            raise RuntimeError(
                "reset_rows needs the accumulator: eviction resets are "
                "a training-side operation (score-only backends never "
                "see a barrier)")
        for chunk in reset_chunks(rows, pad_row):
            self.table, self.acc = fn(self.table, self.acc, chunk)

    # --- persistence (mirrors HostOffloadLookup) ---------------------

    def load(self, table, acc=None) -> None:
        expect = (self.rows, self.dim)
        if tuple(table.shape) != expect:
            raise ValueError(f"restored table shape {table.shape} != "
                             f"{expect}")
        commit = _commit_fn(self._pinned)
        self.table = commit(np.asarray(table, np.float32))
        self.acc = (None if acc is None else
                    commit(np.asarray(acc, np.float32)))

    @classmethod
    def for_table(cls, cfg: FmConfig, table,
                  mode: Optional[str] = None) -> "PinnedHostLookup":
        """Score-only backend around an existing table (logical or
        checkpoint layout) — the predict path for a caller-held table."""
        arr = np.asarray(table, np.float32)
        if (arr.shape[0] not in (cfg.num_rows, cfg.ckpt_rows)
                or arr.shape[1] != cfg.row_dim):
            raise ValueError(
                f"table shape {arr.shape} matches neither the logical "
                f"[{cfg.num_rows}, {cfg.row_dim}] nor the checkpoint "
                f"[{cfg.ckpt_rows}, {cfg.row_dim}] layout")
        self = cls(cfg, _init=False, mode=mode)
        self.table = _commit_fn(self._pinned)(arr)
        return self

    @classmethod
    def from_checkpoint(cls, cfg: FmConfig, with_acc: bool = True,
                        mode: Optional[str] = None) -> "PinnedHostLookup":
        """Restore into accelerator-host memory (via the host-numpy
        restore path, then one placement copy). The local numpy copy is
        TRANSIENT — ``host`` dies at return, so steady state is one
        copy in accelerator-host memory; the peak overlaps local RAM
        (reading the checkpoint requires it) with the remote placement,
        not 2x of either."""
        host = HostOffloadLookup.from_checkpoint(cfg, with_acc=with_acc)
        self = cls(cfg, _init=False, mode=mode)
        self.load(host.table, host.acc)
        self.step = host.step
        return self


def make_offload_backend(cfg: FmConfig, seed: int = 0, restored=None):
    """The ``lookup = host`` backend chooser: the in-jit pinned-host
    implementation where the backend supports it (probe_placement_mode),
    else the numpy fallback — warned, because the fallback pays a
    blocking device->host gradient fetch every step.

    ``restored``: an already-restored checkpoint dict (train.py's
    restore-on-start); passed through ``load`` so no backend re-reads
    the checkpoint."""
    mode = probe_placement_mode()
    if mode is not None:
        lk = PinnedHostLookup(cfg, seed, _init=restored is None, mode=mode)
    else:
        lk = HostOffloadLookup(cfg, seed, _init=restored is None)
    if restored is not None:
        lk.load(np.asarray(restored["table"]), np.asarray(restored["acc"]))
    return lk


def make_score_backend(cfg: FmConfig, table=None):
    """The ``lookup = host`` predict-side chooser: restore (or wrap a
    caller-held table) into the best available offload backend —
    score-only, so the Adagrad accumulator never materializes."""
    cls_ = (PinnedHostLookup if probe_placement_mode() is not None
            else HostOffloadLookup)
    if table is None:
        return cls_.from_checkpoint(cfg, with_acc=False)
    return cls_.for_table(cfg, table)


def make_offload_train_step(spec, lk, lr: float):
    """One train-step callable over a lookup backend:
    ``step(labels, weights, uniq_ids, local_idx, vals, fields=None) ->
    (loss, scores)`` (device scalars/arrays), updating the backend's
    state in place. The pinned backend runs ONE fused jitted program;
    the numpy backend composes gather -> grad_fn -> apply_grad (its
    apply inherently blocks on the gradient bytes)."""
    import jax.numpy as jnp
    if isinstance(lk, PinnedHostLookup):
        fused = _fused_step_fn(spec, lk.mode == "pinned")
        lr_arr = jnp.float32(lr)

        def step(labels, weights, uniq_ids, local_idx, vals, fields=None):
            lk.table, lk.acc, loss, scores = fused(
                lk.table, lk.acc, labels, weights, uniq_ids, local_idx,
                vals, fields, lr=lr_arr)
            return loss, scores

        return step

    from fast_tffm_tpu.models.fm import make_grad_fn
    grad_fn = make_grad_fn(spec)

    def step(labels, weights, uniq_ids, local_idx, vals, fields=None):
        gathered = lk.gather(uniq_ids)
        loss, scores, grad = grad_fn(gathered, labels, weights, uniq_ids,
                                     local_idx, vals, fields)
        lk.apply_grad(uniq_ids, np.asarray(grad), lr)
        return loss, scores

    return step


def memory_report() -> dict:
    """Host RSS and device memory stats, for the offload smoke's
    accounting (tools/offload_smoke.py).

    ``host_rss_mb`` is CURRENT RSS (/proc/self/status VmRSS) — peak
    RSS is monotone and would bill every freed transient (e.g. the
    synth corpus) to whatever is measured after it; the lifetime peak
    is reported separately. Device stats are ``None`` (absent) when
    the runtime reports none — a 0 here must mean a MEASURED zero, not
    "couldn't measure" (a leak assert passing on an unmeasured 0 is
    vacuous)."""
    import resource
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss // 1024
    rss = peak  # fallback when /proc is unavailable
    try:
        with open("/proc/self/status") as fh:
            for ln in fh:
                if ln.startswith("VmRSS:"):
                    rss = int(ln.split()[1]) // 1024
                    break
    except OSError:
        pass
    out = {"host_rss_mb": rss, "host_peak_rss_mb": peak}
    # Through the one memory seam (obs/memory.py; fmlint R018): same
    # unmeasured-is-None contract, plus the FM_FAKE_HBM_BYTES test
    # injection for free.
    from fast_tffm_tpu.obs.memory import device_memory_stats
    stats = device_memory_stats()
    def mb(key):  # missing key = UNMEASURED (None), never a fake 0
        if not stats or key not in stats:
            return None
        return stats[key] >> 20
    out["device_in_use_mb"] = mb("bytes_in_use")
    out["device_limit_mb"] = mb("bytes_limit")
    return out
