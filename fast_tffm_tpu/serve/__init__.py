"""Online serving subsystem (README "Serving"): a long-lived scorer
process over the published checkpoint pointer.

The train->publish->serve loop's last leg: PR 8's stream driver saves,
manifest-verifies, and atomically repoints ``published`` in
``<model_file>.ckpt/``; this package watches that pointer, serves
scores for libsvm-formatted request lines, and hot-swaps the embedding
table when the pointer moves — requests in flight keep the table they
started with (no torn scores).

- ``server.py``   ScorerServer: verified load of the published step,
                  a pre-compiled [batch rung, L rung] shape ladder
                  (reusing the pipeline's ``bucket_ladder`` so no
                  request shape ever recompiles), and an admission
                  queue that micro-batches concurrent requests under
                  ``serve_max_batch`` / ``serve_max_wait_ms``. Plus
                  the in-process ScoreClient tests and the soak use.
- ``reload.py``   ReloadWatcher: polls the pointer, verifies, swaps.
- ``frontend.py`` stdlib HTTP front end (POST /score, GET /healthz)
                  and the ``run_tffm.py serve`` driver.
"""

from fast_tffm_tpu.serve.server import (ScoreClient, ScoreResult,
                                        ScorerServer)

__all__ = ["ScorerServer", "ScoreClient", "ScoreResult"]
