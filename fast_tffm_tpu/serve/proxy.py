"""Stdlib-only failover reverse proxy for the serving fleet.

The fleet's client-facing front door (README "Serving fleet"):
``run_tffm.py serve --replicas N`` binds this on ``serve_proxy_port``
in the supervisor process, in front of N ScorerServer replica child
processes on ``serve_port + i``.

    POST /score      forwarded to one READY replica. A connection
                     refused / timeout / 5xx on this idempotent
                     request retries (with a short backoff) on a
                     DIFFERENT ready replica up to
                     ``serve_retry_budget`` times before the client
                     ever sees a failure; the failed replica is
                     marked not-ready immediately (the supervisor's
                     next health poll re-admits it). Responses carry
                     the scoring replica in ``X-FM-Replica`` beside
                     the step in ``X-FM-Step``.
    GET  /healthz    the FLEET aggregate: replica count, alive/ready
                     counts, per-replica rows. 200 while >=1 replica
                     is ready, 503 otherwise.
    GET  /metrics    the proxy's own registry (routed/retried/shed
                     counters) in Prometheus text format.

Routing policy, in precedence order:

- **Affinity**: a request carrying the ``serve_affinity_header``
  header rendezvous-hashes (highest-random-weight) its key onto one
  ready replica — a user's burst coalesces into one replica's
  admission window and so one padded flush. Rendezvous, not modulo:
  when the replica set changes, only keys mapped to the
  departed/arrived replica move.
- **Canary**: when a canary replica is ready and
  ``serve_canary_fraction`` > 0, a deterministic Bresenham splitter
  routes exactly that fraction of unkeyed traffic to it. Under
  ``serve_canary_shadow`` the canary instead receives DUPLICATED
  traffic in the background — scored, compared
  (``proxy/canary_score_delta`` gauge), never returned to clients.
- **Round-robin** over the ready non-canary replicas otherwise.

Load shedding: at most ``serve_proxy_max_inflight`` proxied requests
are in flight; beyond that the proxy answers 503 + ``Retry-After``
immediately instead of wedging an unbounded pile of blocked
connection threads (the same posture as the scorer's own bounded
timeout).
"""

from __future__ import annotations

import hashlib
import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Sequence

from fast_tffm_tpu.obs.registry import MetricsRegistry
from fast_tffm_tpu.utils.logging import get_logger

# Per-attempt forwarding budget: generous against any healthy flush
# (milliseconds) but bounded, so a wedged replica costs one attempt's
# timeout, not a pinned connection thread.
_FORWARD_TIMEOUT_SECONDS = 60.0
# Base pause before a failover retry: long enough to let a blipping
# replica's accept queue clear, short enough to stay invisible next
# to a micro-batch flush.
_RETRY_BACKOFF_SECONDS = 0.05


class Replica:
    """One backend's routing state as the proxy sees it: written by
    the supervisor's health poller (set_health) and by the proxy's own
    fast path on a failed forward (mark_failed), read by the router.
    A plain lock per replica — the fields are a coherent row."""

    def __init__(self, index: int, host: str, port: int,
                 canary: bool = False):
        self.index = int(index)
        self.host = host
        self.port = int(port)
        self.canary = bool(canary)
        self.name = f"{host}:{port}"
        self._lock = threading.Lock()
        self.alive = False
        self.ready = False
        self.served_step = -1
        self.queue_depth = 0

    def set_health(self, alive: bool, ready: bool,
                   served_step: int = -1,
                   queue_depth: int = 0) -> None:
        with self._lock:
            self.alive = bool(alive)
            self.ready = bool(ready)
            self.served_step = int(served_step)
            self.queue_depth = int(queue_depth)

    def mark_failed(self) -> None:
        """Fast-path demotion on a failed forward: stop routing here
        NOW; the next health poll re-admits it if it was a blip."""
        with self._lock:
            self.ready = False

    def is_ready(self) -> bool:
        with self._lock:
            return self.ready

    def row(self) -> dict:
        with self._lock:
            return {"index": self.index, "port": self.port,
                    "alive": self.alive, "ready": self.ready,
                    "served_step": self.served_step,
                    "queue_depth": self.queue_depth,
                    "canary": self.canary}


class FleetView:
    """The shared replica registry: supervisor writes, proxy reads."""

    def __init__(self, replicas: Sequence[Replica]):
        self.replicas: List[Replica] = list(replicas)

    def ready(self, include_canary: bool = False) -> List[Replica]:
        return [r for r in self.replicas
                if r.is_ready() and (include_canary or not r.canary)]

    def canary(self) -> Optional[Replica]:
        return next((r for r in self.replicas if r.canary), None)

    def counts(self):
        rows = [r.row() for r in self.replicas]
        return (sum(1 for r in rows if r["alive"]),
                sum(1 for r in rows if r["ready"]),
                len(rows), rows)


def rendezvous_choose(key: str, replicas: Sequence[Replica]
                      ) -> Replica:
    """Highest-random-weight hash: every (key, replica) pair gets an
    independent weight and the key goes to its maximum. Removing a
    replica only remaps the keys that were ON it (their other
    replicas' weights are unchanged) — the affinity-stability
    property modulo hashing cannot give."""
    def weight(r: Replica) -> bytes:
        return hashlib.blake2b(f"{key}|{r.name}".encode("utf-8"),
                               digest_size=8).digest()
    return max(replicas, key=weight)


class FractionSplitter:
    """Deterministic Bresenham-style fraction router: over any window
    of n requests, ``take()`` returns True floor/ceil(n * fraction)
    times — exactly the configured canary fraction, no RNG flakes in
    tests or production ramp math."""

    def __init__(self, fraction: float):
        self.fraction = max(0.0, min(1.0, float(fraction)))
        self._lock = threading.Lock()
        self._seen = 0
        self._taken = 0

    def take(self) -> bool:
        if self.fraction <= 0.0:
            return False
        with self._lock:
            self._seen += 1
            owed = int(self._seen * self.fraction)
            if self._taken < owed:
                self._taken += 1
                return True
            return False


class _ProxyHandler(BaseHTTPRequestHandler):
    server_version = "fmproxy/1.0"
    protocol_version = "HTTP/1.1"

    def _reply(self, code: int, body: bytes, ctype: str,
               extra=None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 - http.server contract
        proxy = self.server.proxy
        if self.path == "/metrics":
            from fast_tffm_tpu.obs.prom import (PROM_CONTENT_TYPE,
                                                prometheus_text)
            body = prometheus_text(proxy.registry.snapshot())
            self._reply(200, body.encode("utf-8"), PROM_CONTENT_TYPE)
            return
        if self.path != "/healthz":
            self._reply(404, b"unknown path; GET /healthz or "
                             b"/metrics\n", "text/plain")
            return
        alive, ready, total, rows = proxy.view.counts()
        payload = {"status": "ok" if ready else "degraded",
                   "replicas": total, "alive": alive, "ready": ready,
                   "per_replica": rows}
        self._reply(200 if ready else 503,
                    (json.dumps(payload) + "\n").encode("utf-8"),
                    "application/json")

    def do_POST(self) -> None:  # noqa: N802 - http.server contract
        if self.headers.get("Transfer-Encoding"):
            # Same keep-alive discipline as the replica front end: an
            # undrainable body must drop the connection.
            self.close_connection = True
            self._reply(411, b"chunked bodies unsupported; send "
                             b"Content-Length\n", "text/plain")
            return
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length)
        if self.path != "/score":
            self._reply(404, b"unknown path; POST /score\n",
                        "text/plain")
            return
        proxy = self.server.proxy
        if not proxy.inflight.acquire(blocking=False):
            proxy.registry.count("proxy/shed_503")
            self._reply(503, b"proxy at max in-flight; retry\n",
                        "text/plain", extra={"Retry-After": "1"})
            return
        try:
            affinity = None
            if proxy.affinity_header:
                affinity = self.headers.get(proxy.affinity_header)
            code, body, extra = proxy.forward_score(raw, affinity)
            self._reply(code, body, "text/plain", extra=extra)
        finally:
            proxy.inflight.release()

    def log_message(self, fmt, *args):  # noqa: A003 - http.server API
        self.server.proxy._logger.debug("proxy: " + fmt, *args)


class _ProxyHTTPServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, proxy, host: str, port: int):
        self.proxy = proxy
        super().__init__((host, port), _ProxyHandler)


class ScoreProxy:
    """The proxy core: routing + retry policy over a FleetView.
    ``forward_score`` is the whole per-request protocol, public so
    unit tests drive it without sockets on the front side (the back
    side talks real HTTP to whatever the view names)."""

    def __init__(self, view: FleetView, retry_budget: int = 1,
                 affinity_header: str = "X-FM-Affinity",
                 canary_fraction: float = 0.0,
                 canary_shadow: bool = False,
                 max_inflight: int = 64,
                 registry: Optional[MetricsRegistry] = None,
                 logger=None,
                 forward_timeout: float = _FORWARD_TIMEOUT_SECONDS,
                 backoff_seconds: float = _RETRY_BACKOFF_SECONDS):
        self.view = view
        self.retry_budget = max(0, int(retry_budget))
        self.affinity_header = affinity_header
        self.canary_shadow = bool(canary_shadow)
        # Shadow with no explicit fraction samples everything: the
        # compare stream is the point and the client never waits on
        # it.
        frac = canary_fraction if (canary_fraction or not canary_shadow) \
            else 1.0
        self.splitter = FractionSplitter(frac)
        self.inflight = threading.Semaphore(max(1, int(max_inflight)))
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._logger = logger or get_logger()
        self._forward_timeout = float(forward_timeout)
        self._backoff = float(backoff_seconds)
        self._rr_lock = threading.Lock()
        self._rr = 0
        self._httpd = None
        self._http_thread = None

    # -- routing ---------------------------------------------------------

    def _next_rr(self, candidates: List[Replica]) -> Replica:
        with self._rr_lock:
            self._rr += 1
            return candidates[self._rr % len(candidates)]

    def pick(self, affinity: Optional[str],
             exclude=()) -> Optional[Replica]:
        """One routing decision over the CURRENT ready set (minus
        ``exclude`` — the replicas a retry already burned)."""
        ready = [r for r in self.view.ready() if r not in exclude]
        canary = self.view.canary()
        if affinity and ready:
            return rendezvous_choose(affinity, ready)
        if (canary is not None and not self.canary_shadow
                and canary.is_ready() and canary not in exclude
                and self.splitter.take()):
            self.registry.count("proxy/canary_requests")
            return canary
        if not ready:
            # Every primary is down: a ready canary is still a scorer
            # — degraded-mode routing beats a client-visible outage.
            if (canary is not None and canary.is_ready()
                    and canary not in exclude):
                return canary
            return None
        return self._next_rr(ready)

    # -- request path ----------------------------------------------------

    def forward_score(self, body: bytes, affinity: Optional[str]):
        """Route + forward one POST /score with failover. Returns
        (status, body, extra_headers). Client errors (4xx) pass
        through un-retried — resending a malformed request buys
        nothing; transport errors and 5xx burn one attempt each and
        retry on a DIFFERENT ready replica."""
        self.registry.count("proxy/requests")
        tried: List[Replica] = []
        last_err = "no ready replica"
        for attempt in range(1 + self.retry_budget):
            replica = self.pick(affinity, exclude=tried)
            if replica is None:
                break
            if attempt:
                self.registry.count("proxy/retries")
                time.sleep(self._backoff * attempt)
            tried.append(replica)
            try:
                status, out, step = self._send(replica, body)
            except (OSError, http.client.HTTPException) as e:
                # Connection refused / reset / timeout: the replica is
                # gone or wedged — demote it now and fail over.
                replica.mark_failed()
                self.registry.count("proxy/transport_errors")
                last_err = f"{replica.name}: {type(e).__name__}: {e}"
                self._logger.warning(
                    "proxy: forward to %s failed (%s); failing over",
                    replica.name, last_err)
                continue
            if status >= 500:
                replica.mark_failed()
                self.registry.count("proxy/upstream_5xx")
                last_err = (f"{replica.name}: HTTP {status}: "
                            f"{out[:200].decode('utf-8', 'replace')}")
                continue
            if status == 200 and self.canary_shadow:
                self._maybe_shadow(body, out)
            extra = {"X-FM-Replica": str(replica.index)}
            if step is not None:
                extra["X-FM-Step"] = step
            return status, out, extra
        self.registry.count("proxy/unrouted_503")
        return (503,
                f"no replica could score the request ({last_err})\n"
                .encode("utf-8"),
                {"Retry-After": "1"})

    def _send(self, replica: Replica, body: bytes):
        conn = http.client.HTTPConnection(
            replica.host, replica.port, timeout=self._forward_timeout)
        try:
            conn.request("POST", "/score", body=body,
                         headers={"Content-Type": "text/plain"})
            resp = conn.getresponse()
            out = resp.read()
            return resp.status, out, resp.getheader("X-FM-Step")
        finally:
            conn.close()

    # -- canary shadow ---------------------------------------------------

    def _maybe_shadow(self, body: bytes, primary_out: bytes) -> None:
        canary = self.view.canary()
        if canary is None or not canary.is_ready() \
                or not self.splitter.take():
            return
        th = threading.Thread(
            target=self._shadow_compare, args=(canary, body,
                                               primary_out),
            name="fm-proxy-shadow", daemon=True)
        th.start()

    def _shadow_compare(self, canary: Replica, body: bytes,
                        primary_out: bytes) -> None:
        """Score the duplicated request on the canary and gauge the
        divergence (max |Δscore|) against the primary's response —
        the comparison stream the publish gate reads before a full
        promotion. Never surfaces to the client; never retried."""
        try:
            status, out, _step = self._send(canary, body)
        except (OSError, http.client.HTTPException):
            self.registry.count("proxy/shadow_errors")
            return
        if status != 200:
            self.registry.count("proxy/shadow_errors")
            return
        try:
            a = [float(x) for x in primary_out.split()]
            b = [float(x) for x in out.split()]
        except ValueError:
            self.registry.count("proxy/shadow_errors")
            return
        if len(a) != len(b):
            self.registry.count("proxy/shadow_errors")
            return
        delta = max((abs(x - y) for x, y in zip(a, b)), default=0.0)
        self.registry.count("proxy/shadow_compares")
        self.registry.set("proxy/canary_score_delta", delta)

    # -- front-end lifecycle ---------------------------------------------

    def start(self, port: int, host: str = "127.0.0.1") -> int:
        """Bind + serve on a daemon thread; returns the bound port
        (port 0 = ephemeral)."""
        self._httpd = _ProxyHTTPServer(self, host, port)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="fm-proxy-http",
            daemon=True)
        self._http_thread.start()
        return self._httpd.server_address[1]

    def shutdown(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._http_thread.join()
            self._httpd.server_close()
            self._httpd = None
