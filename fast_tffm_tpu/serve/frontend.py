"""Dependency-free HTTP front end + the ``run_tffm.py serve`` driver.

Line protocol over stdlib http.server (the repo ships no web
framework, and a scorer's wire format is one float per input line):

    POST /score      body: libsvm lines (one request line per score
                     owed; labels accepted and ignored, blank lines
                     score as the model bias). Response: one
                     ``%.6f``-formatted score per line — byte-identical
                     to a ``.score`` file of the same lines — with the
                     serving checkpoint step in ``X-FM-Step``.
                     Malformed lines are 400 with the parse error (a
                     bad request fails itself, never the process).
    GET  /healthz    JSON: alive/ready (liveness vs readiness — a
                     still-precompiling or mid-reload server is alive
                     but NOT ready; README "Serving fleet"),
                     served/published step, queue depth, request
                     counters, latency p50/p99, uptime.
    POST /reload     fleet-supervisor control surface: synchronously
                     hot-reload to the step in the body (empty body =
                     this server's configured pointer). 200 + JSON
                     after the swap; 503 when the reload failed (the
                     old step keeps serving).
    GET  /metrics    the obs registry (counters / gauges / histogram
                     buckets) in Prometheus text exposition format
                     (obs/prom.py) — the scrape endpoint; no JSONL
                     parsing needed to monitor a serving fleet.

Threading: http.server's ThreadingHTTPServer gives each connection a
thread; all of them funnel into the ScorerServer's admission queue,
which is the actual batching point — so N concurrent HTTP clients
become one padded device flush per admission window.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from fast_tffm_tpu.data.parser import ParseError

# Per-request scoring budget for the HTTP path: far above any healthy
# flush (admission wait is milliseconds) but bounded, so a wedged
# dispatcher degrades to 503s instead of an unbounded pile of blocked
# connection threads. The in-process ScoreClient carries its own
# default; callers that want to wait forever can.
_SCORE_TIMEOUT_SECONDS = 60.0


class _Handler(BaseHTTPRequestHandler):
    server_version = "fmserve/1.0"
    protocol_version = "HTTP/1.1"

    def _reply(self, code: int, body: bytes, ctype: str,
               extra=None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self) -> None:  # noqa: N802 - http.server contract
        if self.headers.get("Transfer-Encoding"):
            # No chunked-body support: with no Content-Length the body
            # can't be drained, and an undrained body desyncs the
            # HTTP/1.1 keep-alive stream — refuse AND drop the
            # connection so the next request can't be misparsed.
            self.close_connection = True
            self._reply(411, b"chunked bodies unsupported; send "
                             b"Content-Length\n", "text/plain")
            return
        # Drain the body BEFORE any routing reply: a 404'd POST that
        # leaves its body in the stream makes the keep-alive client's
        # NEXT request parse as garbage mid-body.
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length)
        if self.path == "/reload":
            # The fleet supervisor's reload token (README "Serving
            # fleet"): synchronous — the 200 only lands after the
            # swap, so the stagger protocol can re-admit this replica
            # knowing which step it serves. Body: optional step
            # number; empty = resolve this server's pointer.
            try:
                body = raw.decode("utf-8", errors="strict").strip()
                step = int(body) if body else None
            except ValueError as e:
                self._reply(400, f"{e}\n".encode("utf-8"),
                            "text/plain")
                return
            ok, now = self.server.fm_server.external_reload(step)
            payload = json.dumps({"ok": ok, "step": now}) + "\n"
            self._reply(200 if ok else 503,
                        payload.encode("utf-8"), "application/json")
            return
        if self.path != "/score":
            self._reply(404, b"unknown path; POST /score or "
                             b"/reload\n", "text/plain")
            return
        try:
            # decode inside the try: a non-UTF-8 body is the CALLER's
            # 400 (UnicodeDecodeError is a ValueError), not a dropped
            # connection + bare-stderr traceback out of http.server.
            body = raw.decode("utf-8", errors="strict")
            res = self.server.fm_server.score_lines(
                body.splitlines(), timeout=_SCORE_TIMEOUT_SECONDS)
        except (ParseError, ValueError) as e:
            self._reply(400, f"{e}\n".encode("utf-8"), "text/plain")
            return
        except RuntimeError as e:  # closed server mid-shutdown
            self._reply(503, f"{e}\n".encode("utf-8"), "text/plain")
            return
        except TimeoutError as e:
            # A wedged flush must cost this request a 503, not pin the
            # connection thread forever (ThreadingHTTPServer spawns
            # one per connection — unbounded pile-up otherwise).
            self._reply(503, f"{e}\n".encode("utf-8"), "text/plain")
            return
        out = "".join(f"{v:.6f}\n" for v in res.scores)
        self._reply(200, out.encode("utf-8"), "text/plain",
                    extra={"X-FM-Step": str(res.step)})

    def do_GET(self) -> None:  # noqa: N802 - http.server contract
        if self.path == "/metrics":
            from fast_tffm_tpu.obs.prom import PROM_CONTENT_TYPE
            body = self.server.fm_server.metrics_text()
            self._reply(200, body.encode("utf-8"), PROM_CONTENT_TYPE)
            return
        if self.path != "/healthz":
            self._reply(404, b"unknown path; GET /healthz or "
                             b"/metrics\n", "text/plain")
            return
        stats = self.server.fm_server.stats()
        self._reply(200, (json.dumps(stats) + "\n").encode("utf-8"),
                    "application/json")

    def log_message(self, fmt, *args):  # noqa: A003 - http.server API
        # Route access logs to the run logger at debug instead of bare
        # stderr writes (fmlint R002's no-print discipline).
        self.server.fm_server._logger.debug("http: " + fmt, *args)


class ScoreHTTPServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, fm_server, host: str, port: int):
        self.fm_server = fm_server
        super().__init__((host, port), _Handler)


def make_http_server(fm_server, port: int,
                     host: str = "127.0.0.1") -> ScoreHTTPServer:
    """Bind the front end (port 0 = ephemeral; read the real one from
    ``.server_address``). The caller owns serve_forever/shutdown."""
    return ScoreHTTPServer(fm_server, host, port)


def run_serve(cfg) -> int:
    """The ``run_tffm.py serve <cfg>`` driver: load the published
    step, bind the HTTP front end, serve until SIGTERM/SIGINT, then
    drain and close. Returns a process exit code."""
    import signal
    import threading
    from fast_tffm_tpu.serve.server import ScorerServer
    from fast_tffm_tpu.utils.logging import get_logger
    logger = get_logger(log_file=cfg.log_file or None)
    stop = threading.Event()

    def _on_signal(signum, _frame):
        logger.info("serve: received signal %d; shutting down", signum)
        stop.set()

    # Handlers go in BEFORE the (restore + warmup) startup window: a
    # k8s/systemd stop landing mid-startup must still reach the drain
    # path below — run_end forensics matter most for exactly the slow
    # or wedged startup an operator kills.
    prev = {s: signal.signal(s, _on_signal)
            for s in (signal.SIGTERM, signal.SIGINT)}
    server = None
    httpd = None
    t = None
    try:
        # Background warmup: the front end binds (and /healthz
        # answers alive: true, ready: false) WHILE the shape ladder
        # compiles, instead of the old behavior where a precompiling
        # server was invisible to health checks and then answered as
        # servable the instant it bound. The fleet supervisor
        # restarts on alive and the proxy routes on ready, so both
        # need the split from the first second of a replica's life.
        server = ScorerServer(cfg, logger=logger, warmup="background")
        if not stop.is_set():
            httpd = make_http_server(server, cfg.serve_port,
                                     host=cfg.serve_host)
            t = threading.Thread(target=httpd.serve_forever,
                                 name="fm-serve-http", daemon=True)
            t.start()
            host, port = httpd.server_address[:2]
            logger.info("serving step %d on http://%s:%d (POST /score, "
                        "GET /healthz)", server.served_step, host, port)
            stop.wait()
    finally:
        for s, h in prev.items():
            signal.signal(s, h)
        if httpd is not None:
            httpd.shutdown()
            t.join()
            httpd.server_close()
        if server is not None:
            # Always drain — including the bind-failure path, where
            # the scorer is already live: its threads must exit and
            # the metrics stream owes its run_end (never a stranded
            # 0-byte file).
            server.close()
    return 0
