"""Hot-reload watcher: poll the ``published`` pointer, swap the table.

The pointer file in ``<model_file>.ckpt/`` is the train->serve
contract (PR 8): the stream driver only repoints it at a
manifest-verified step, atomically. This thread is the serve side of
that contract — re-read the pointer every ``serve_poll_seconds``, and
when it names a step other than the one being served, restore it
through the same verified-restore path (an explicit step is verified,
never walked past) and hand it to the server's atomic swap. Under
``vocab_mode = admit`` the swap is the whole (table, slot map, step)
TRIPLE — the step's vocab sidecar loads (crc-checked) before the
swap, so a reload can never pair a new table with an old admission
map. Requests in flight keep the pair their flush captured: the old
table/map is retained until the last batch referencing it drains — no
torn scores, and every response says which step scored it.

Fleet behavior (README "Serving fleet"): each tick's wait carries
seeded jitter (``serve_poll_jitter``, seeded per replica by its port)
so N replicas never stat the shared pointer file in lockstep — a
thundering herd on a network filesystem. Under ``serve_reload_mode =
external`` the watcher still polls (the published-step gauge and the
STALE MODEL signal stay fresh) but never reloads: the fleet
supervisor's stagger protocol owns reloads, handing each replica a
reload token in turn via ``POST /reload``
(``ScorerServer.external_reload``) so the fleet never cold-stops
together.

Failure posture: a garbled/unreadable pointer reads as "nothing new"
and heals on the next poll (read_published's contract); a step that
fails verification or restore counts a ``serve/reload_failures`` and
the PREVIOUS table keeps serving — a bad publish must degrade to
staleness (visible as fmstat's STALE MODEL), never to an outage.
"""

from __future__ import annotations

import random
import threading

from fast_tffm_tpu.checkpoint import read_pointer


class ReloadWatcher:
    """Daemon poll thread (``fm-serve-reload``). ``poll_once`` is the
    whole per-tick protocol, public so unit tests can drive it without
    the thread; ``next_wait`` is the jittered cadence, public for the
    same reason. ``auto_reload=False`` is the external-coordinator
    mode (observe-only ticks)."""

    def __init__(self, server, poll_seconds: float,
                 jitter: float = 0.0, seed: int = 0,
                 auto_reload: bool = True):
        self._server = server
        self._poll = float(poll_seconds)
        self._jitter = max(0.0, min(float(jitter), 0.999))
        # Deterministic per-replica stream: the same replica jitters
        # the same way run to run (debuggable), different replicas
        # (different ports) decorrelate.
        self._rng = random.Random(int(seed) * 2654435761 + 1)
        self._auto = bool(auto_reload)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="fm-serve-reload",
                                        daemon=True)

    def start(self) -> "ReloadWatcher":
        self._thread.start()
        return self

    def next_wait(self) -> float:
        """One tick's wait: poll * (1 ± U(0, jitter)). Symmetric, so
        the MEAN cadence stays serve_poll_seconds however much the
        phase decorrelates."""
        if not self._jitter:
            return self._poll
        return self._poll * (1.0 + self._rng.uniform(-self._jitter,
                                                     self._jitter))

    def _run(self) -> None:
        while not self._stop.wait(self.next_wait()):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - the poll loop must
                # survive anything: a transient filesystem error on one
                # tick is the next tick's clean read. Real reload
                # failures are already counted inside reload_step.
                self._server._logger.exception(
                    "published-pointer poll failed; retrying next tick")

    def poll_once(self) -> bool:
        """One tick: read the pointer, record what it says (the
        published-step gauge), reload when it moved — unless an
        external coordinator owns reloads, in which case the tick is
        observe-only. Returns True when a reload was attempted. A
        reload that would not fit beside the resident table (the
        old+new transient, obs/memory.py) is refused inside
        ``_load_step`` and lands on the same counted-failure
        keep-serving path as a failed restore — the headroom gauge
        below is the early-warning signal fmstat/fmtrace watch before
        that happens."""
        # A live poll IS liveness: without this, a traffic-idle server
        # under a configured stall watchdog reads as STALLED.
        self._server.idle_beat()
        from fast_tffm_tpu.obs.memory import (LEDGER,
                                              device_capacity_bytes)
        cap = device_capacity_bytes()
        if cap:
            self._server._reg.set(
                "serve/reload_headroom_bytes",
                float(cap - LEDGER.live_bytes()))
        step = read_pointer(self._server.directory,
                            getattr(self._server, "_pointer",
                                    "published"))
        if step is None:
            return False
        self._server.note_published(step)
        if not self._auto:
            return False
        if step == self._server.served_step:
            return False
        self._server.reload_step(step)
        return True

    def stop(self) -> None:
        self._stop.set()
        self._thread.join()
