"""Replica supervisor for the serving fleet (README "Serving fleet").

``run_tffm.py serve --replicas N`` runs THIS process: it spawns N
single-process ScorerServer children (``python -m
fast_tffm_tpu.serve.replica <cfg>``) on ports ``serve_port + i``,
binds the failover proxy (serve/proxy.py) on ``serve_proxy_port`` as
the client-facing front door, and supervises:

- **Health**: every ``serve_health_poll_seconds`` each replica's
  ``/healthz`` is read. ``alive`` (the process answers) drives
  restarts; ``ready`` (warmed, not mid-reload, queue under the shed
  depth) drives proxy routing — a precompiling or reloading replica
  is routed around, never restarted.
- **Restarts**: a dead replica (exited process, or one that stopped
  answering healthz entirely) respawns under capped exponential
  backoff (``serve_restart_backoff_seconds`` base, doubling, capped
  at 16x, reset once the replica reports healthy) — crash loops
  throttle themselves instead of burning the host.
- **Staggered hot reloads**: children run ``serve_reload_mode =
  external`` (their watcher keeps gauges fresh but never reloads);
  the supervisor watches the ``published`` pointer and, when it
  moves, hands each replica a reload token IN TURN — verify at least
  one OTHER replica is ready, POST /reload (synchronous; the replica
  reports not-ready for the duration), wait for it to come back ready
  on the new step, move on. The fleet never cold-stops together: >= 1
  ready replica at every instant of a fleet-wide reload.
- **Canary**: with ``serve_canary_fraction`` > 0 or
  ``serve_canary_shadow``, the LAST replica follows the
  ``published-canary`` pointer (``fmckpt publish --canary``) and the
  proxy directs the configured traffic fraction (or shadow
  duplicates) at it; per-replica step/latency gauges feed the publish
  gate's comparison before a full promotion.
- **Drain**: SIGTERM/SIGINT stops the watchers, SIGTERMs every child
  (each drains its own admission queue), reaps them, closes the
  proxy and the metrics stream, exits 0.

Fleet telemetry (fmstat's FLEET section + ``FLEET DEGRADED``
verdict) is per-replica gauges in the SUPERVISOR's metrics stream —
``fleet/replica<i>_alive/_ready/_step/_queue_depth`` — flushed
eagerly on every ready-count transition so a mid-incident snapshot
shows the degradation window, not just the happy end state.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

from fast_tffm_tpu.obs.registry import MetricsRegistry
from fast_tffm_tpu.serve.proxy import FleetView, Replica, ScoreProxy
from fast_tffm_tpu.utils.logging import get_logger

# Backoff cap, as a multiple of the base: 1, 2, 4, 8, 16, 16, ...
_BACKOFF_CAP_FACTOR = 16.0
# Seconds of healthz SILENCE from a live process before the
# supervisor declares it wedged and kill-restarts it. Time-based, not
# probe-count-based: the window must not shrink with a fast
# serve_health_poll_seconds, because a freshly spawned replica is
# legitimately silent for several seconds (interpreter + jax import)
# before its front end binds — and it answers healthz (alive, not
# ready) from bind onward, warmup included. The clock restarts at
# every spawn, so a long silence always means the HTTP thread is gone
# or the process wedged before bind.
_WEDGED_SILENCE_SECONDS = 60.0
# How long a child gets to drain after SIGTERM before SIGKILL.
_DRAIN_SECONDS = 15.0
# Per-step budget for one replica's staggered reload (reload + come
# back ready).
_RELOAD_STEP_TIMEOUT = 120.0


class RestartPolicy:
    """Capped exponential backoff over an injected clock (unit tests
    drive it with a fake clock). ``record_death`` schedules the next
    allowed restart; ``can_restart`` gates the respawn;
    ``record_healthy`` resets the streak."""

    def __init__(self, base_seconds: float,
                 cap_factor: float = _BACKOFF_CAP_FACTOR,
                 clock: Callable[[], float] = time.monotonic):
        self._base = float(base_seconds)
        self._cap = self._base * float(cap_factor)
        self._clock = clock
        self._failures = 0
        self._not_before = 0.0

    def record_death(self) -> float:
        """Note one death; returns the backoff delay applied."""
        delay = min(self._base * (2.0 ** self._failures), self._cap)
        self._failures += 1
        self._not_before = self._clock() + delay
        return delay

    def can_restart(self) -> bool:
        return self._clock() >= self._not_before

    def record_healthy(self) -> None:
        self._failures = 0
        self._not_before = 0.0

    @property
    def failures(self) -> int:
        return self._failures


class ReplicaProc:
    """One supervised child: the subprocess, its routing row in the
    proxy's FleetView, and its restart policy."""

    def __init__(self, index: int, cfg, cfg_path: str,
                 canary: bool = False, logger=None,
                 clock: Callable[[], float] = time.monotonic):
        self.index = int(index)
        self.cfg = cfg
        self.cfg_path = os.path.abspath(cfg_path)
        self.port = cfg.serve_port + self.index
        self.canary = bool(canary)
        self.row = Replica(self.index, cfg.serve_host, self.port,
                           canary=self.canary)
        self.policy = RestartPolicy(cfg.serve_restart_backoff_seconds,
                                    clock=clock)
        self.proc: Optional[subprocess.Popen] = None
        self.probe_failures = 0
        self._clock = clock
        # Wedge clock: last moment this replica answered healthz (or
        # was spawned — a fresh child gets the full silence window to
        # import + bind before it can be declared wedged).
        self.last_answer = clock()
        self._logger = logger or get_logger()
        self._log_fh = None

    # -- process lifecycle ----------------------------------------------

    def spawn(self) -> None:
        env = dict(os.environ)
        # Per-replica knobs ride the FM_<KNOB> env convention the
        # replica entry applies (config.apply_env_overrides): its own
        # port, its own metrics shard, external reload mode (the
        # supervisor owns reloads), and the canary pointer on the
        # canary replica.
        env["FM_SERVE_PORT"] = str(self.port)
        env["FM_SERVE_RELOAD_MODE"] = "external"
        if self.canary:
            env["FM_SERVE_POINTER"] = "canary"
        if self.cfg.metrics_file:
            base = self.cfg.metrics_file
            if base == "auto":
                base = self.cfg.model_file + ".metrics.jsonl"
            env["FM_METRICS_FILE"] = f"{base}.r{self.index}"
        # The package must be importable from wherever the child
        # starts — pin the repo root onto PYTHONPATH rather than
        # trusting the supervisor's cwd to survive.
        import fast_tffm_tpu
        root = os.path.dirname(os.path.dirname(fast_tffm_tpu.__file__))
        env["PYTHONPATH"] = root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        if self._log_fh is None:
            self._log_fh = open(
                f"{self.cfg.model_file}.replica{self.index}.log", "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "fast_tffm_tpu.serve.replica",
             self.cfg_path],
            env=env, stdout=self._log_fh, stderr=subprocess.STDOUT)
        self.probe_failures = 0
        self.last_answer = self._clock()
        self._logger.info(
            "fleet: replica %d%s spawned (pid %d, port %d)",
            self.index, " (canary)" if self.canary else "",
            self.proc.pid, self.port)

    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def exited(self) -> bool:
        return self.proc is None or self.proc.poll() is not None

    def terminate(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.terminate()
            except OSError:
                pass

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass

    def reap(self, timeout: float = _DRAIN_SECONDS) -> None:
        if self.proc is not None:
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.kill()
                self.proc.wait()
        if self._log_fh is not None:
            self._log_fh.close()
            self._log_fh = None

    # -- health ----------------------------------------------------------

    def probe(self, timeout: float = 1.0) -> Optional[dict]:
        """One /healthz read; None when the replica doesn't answer."""
        conn = http.client.HTTPConnection(self.cfg.serve_host,
                                          self.port, timeout=timeout)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                return None
            return json.loads(body)
        except (OSError, ValueError, http.client.HTTPException):
            return None
        finally:
            conn.close()

    def reload(self, step: int,
               timeout: float = _RELOAD_STEP_TIMEOUT) -> bool:
        """Hand this replica the reload token: synchronous POST
        /reload — returns only after the swap (or its failure)."""
        conn = http.client.HTTPConnection(self.cfg.serve_host,
                                          self.port, timeout=timeout)
        try:
            conn.request("POST", "/reload", body=str(int(step)),
                         headers={"Content-Type": "text/plain"})
            resp = conn.getresponse()
            resp.read()
            return resp.status == 200
        except (OSError, http.client.HTTPException):
            return False
        finally:
            conn.close()

    def is_ready(self) -> bool:
        """Fresh readiness probe (the stagger protocol's view — never
        a cached row: the invariant is about NOW)."""
        h = self.probe()
        return bool(h and h.get("ready"))


def staggered_reload(handles, step: int, reloaded=None,
                     min_other_ready: int = 1,
                     wait_seconds: float = _RELOAD_STEP_TIMEOUT,
                     poll: float = 0.1,
                     sleep: Callable[[float], None] = time.sleep,
                     clock: Callable[[], float] = time.monotonic,
                     logger=None) -> int:
    """The stagger protocol over anything with ``is_ready()`` /
    ``reload(step)`` (ReplicaProc in production, fakes in tests): for
    each handle in turn, wait until >= ``min_other_ready`` OTHER
    handles are ready, hand it the reload token (synchronous; the
    handle is not-ready for the duration), then wait for IT to come
    back ready before moving on — so a fleet-wide reload never has a
    zero-ready instant. Returns the number of successful reloads.
    ``reloaded`` (optional callable) is invoked after each handle
    finishes — the supervisor's flush hook."""
    log = logger or get_logger()
    done = 0
    for h in handles:
        others = [o for o in handles if o is not h]

        def _ready_others():
            return sum(1 for o in others if o.is_ready())

        if others:
            deadline = clock() + wait_seconds
            while _ready_others() < min_other_ready:
                if clock() >= deadline:
                    log.warning(
                        "fleet: stagger stalled — fewer than %d other "
                        "replicas ready; reloading anyway to avoid "
                        "serving stale state forever",
                        min_other_ready)
                    break
                sleep(poll)
        ok = h.reload(step)
        if ok:
            deadline = clock() + wait_seconds
            while not h.is_ready() and clock() < deadline:
                sleep(poll)
            done += 1
        else:
            log.warning("fleet: reload of step %d failed on a replica;"
                        " it keeps serving its previous step", step)
        if reloaded is not None:
            reloaded(h, ok)
    return done


class FleetSupervisor:
    """Own the children, the proxy, and the watch threads. Drive with
    ``start()`` / ``stop()``; ``run_fleet`` wraps it in the signal
    handling the CLI needs."""

    def __init__(self, cfg, cfg_path: str,
                 replicas: Optional[int] = None, logger=None):
        if replicas is not None:
            import dataclasses
            cfg = dataclasses.replace(cfg,
                                      serve_replicas=int(replicas))
        if cfg.serve_replicas < 2:
            raise ValueError(
                "FleetSupervisor needs serve_replicas >= 2 (one "
                "replica is just `run_tffm.py serve`)")
        self.cfg = cfg
        self._logger = logger or get_logger(log_file=cfg.log_file
                                            or None)
        from fast_tffm_tpu.obs.telemetry import make_telemetry
        self._tel = make_telemetry(cfg, "fleet")
        self._reg = (self._tel.registry if self._tel is not None
                     else MetricsRegistry())
        canary_on = (cfg.serve_canary_fraction > 0
                     or cfg.serve_canary_shadow)
        n = cfg.serve_replicas
        self.replicas: List[ReplicaProc] = [
            ReplicaProc(i, cfg, cfg_path,
                        canary=(canary_on and i == n - 1),
                        logger=self._logger)
            for i in range(n)]
        self.view = FleetView([r.row for r in self.replicas])
        self.proxy = ScoreProxy(
            self.view, retry_budget=cfg.serve_retry_budget,
            affinity_header=cfg.serve_affinity_header,
            canary_fraction=cfg.serve_canary_fraction,
            canary_shadow=cfg.serve_canary_shadow,
            max_inflight=cfg.serve_proxy_max_inflight,
            registry=self._reg, logger=self._logger)
        self.proxy_port: Optional[int] = None
        self.directory = os.path.abspath(cfg.model_file) + ".ckpt"
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._polls = 0
        self._last_ready = -1
        self._reg.set("fleet/replicas", float(n))
        self._reg.set("fleet/ready", 0.0)
        self._reg.set("fleet/alive", 0.0)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "FleetSupervisor":
        for r in self.replicas:
            r.spawn()
        self.proxy_port = self.proxy.start(self.cfg.serve_proxy_port,
                                           host=self.cfg.serve_host)
        self._logger.info(
            "fleet: %d replicas on ports %d..%d, proxy on http://%s:%d",
            len(self.replicas), self.replicas[0].port,
            self.replicas[-1].port, self.cfg.serve_host,
            self.proxy_port)
        for name, fn in (("fm-fleet-health", self._health_loop),
                         ("fm-fleet-reload", self._reload_loop)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def wait_ready(self, k: int = 1, timeout: float = 120.0) -> bool:
        """Block until >= k replicas are ready (startup convenience
        for drivers and tests)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if sum(1 for r in self.replicas if r.row.is_ready()) >= k:
                return True
            if self._stop.is_set():
                return False
            time.sleep(0.1)
        return False

    def stop(self) -> None:
        """SIGTERM-drain the whole fleet: watchers down, children
        terminated and reaped (each drains its own queue), proxy and
        metrics stream closed. Idempotent."""
        if self._stop.is_set():
            return
        self._stop.set()
        for t in self._threads:
            t.join()
        self.proxy.shutdown()
        for r in self.replicas:
            r.terminate()
        for r in self.replicas:
            r.reap()
        if self._tel is not None:
            self._tel.close(step=self._polls)
        self._logger.info("fleet: drained %d replicas; supervisor "
                          "down", len(self.replicas))

    def pids(self) -> List[Optional[int]]:
        return [r.pid() for r in self.replicas]

    def flush_metrics(self) -> None:
        if self._tel is not None:
            self._tel.barrier_flush(self._polls)

    # -- health loop -----------------------------------------------------

    def _poll_replica(self, r: ReplicaProc) -> None:
        i = r.index
        if r.exited():
            r.row.set_health(False, False)
            if r.proc is not None and r.probe_failures == 0:
                # First observation of this death: schedule the
                # backed-off restart.
                delay = r.policy.record_death()
                r.probe_failures = 1
                self._reg.count("fleet/deaths")
                self._logger.warning(
                    "fleet: replica %d (pid %s) exited rc=%s; restart "
                    "in %.1fs (failure #%d)", i, r.proc.pid,
                    r.proc.returncode, delay, r.policy.failures)
            if r.policy.can_restart():
                r.spawn()
                self._reg.count("fleet/restarts")
            return
        h = r.probe(timeout=max(
            0.5, self.cfg.serve_health_poll_seconds))
        if h is None:
            r.probe_failures += 1
            r.row.set_health(False, False)
            silence = r._clock() - r.last_answer
            if silence >= _WEDGED_SILENCE_SECONDS:
                self._logger.warning(
                    "fleet: replica %d silent for %.0fs (%d failed "
                    "probes); kill-restarting", i, silence,
                    r.probe_failures)
                r.kill()
                r.reap(timeout=5.0)
                r.policy.record_death()
                self._reg.count("fleet/wedged_kills")
                r.probe_failures = 0
            return
        r.probe_failures = 0
        r.last_answer = r._clock()
        ready = bool(h.get("ready"))
        if ready:
            r.policy.record_healthy()
        r.row.set_health(True, ready,
                         served_step=int(h.get("served_step", -1)),
                         queue_depth=int(h.get("queue_depth", 0)))
        self._reg.set(f"fleet/replica{i}_step",
                      float(h.get("served_step", -1)))
        self._reg.set(f"fleet/replica{i}_queue_depth",
                      float(h.get("queue_depth", 0)))

    def _health_loop(self) -> None:
        poll = self.cfg.serve_health_poll_seconds
        while not self._stop.wait(poll):
            for r in self.replicas:
                try:
                    self._poll_replica(r)
                except Exception:  # noqa: BLE001 - one replica's bad
                    # poll must not starve the others of supervision
                    self._logger.exception(
                        "fleet: health poll of replica %d failed",
                        r.index)
            alive, ready, total, _rows = self.view.counts()
            for r in self.replicas:
                row = r.row.row()
                self._reg.set(f"fleet/replica{r.index}_alive",
                              1.0 if row["alive"] else 0.0)
                self._reg.set(f"fleet/replica{r.index}_ready",
                              1.0 if row["ready"] else 0.0)
            self._reg.set("fleet/alive", float(alive))
            self._reg.set("fleet/ready", float(ready))
            self._polls += 1
            if self._tel is not None:
                self._tel.heartbeat()
            if ready != self._last_ready:
                # Eager flush on every degradation/recovery edge: a
                # mid-incident fmstat snapshot must SEE the gap.
                if self._last_ready >= 0:
                    self._logger.info(
                        "fleet: ready count %d -> %d (of %d)",
                        self._last_ready, ready, total)
                self._last_ready = ready
                self.flush_metrics()

    # -- reload loop (staggered) ----------------------------------------

    def _reload_loop(self) -> None:
        from fast_tffm_tpu.checkpoint import read_pointer, read_published
        poll = self.cfg.serve_poll_seconds
        while not self._stop.wait(poll):
            try:
                # Staleness is judged against what replicas ACTUALLY
                # serve (their last health rows), not a remembered
                # pointer value — a restarted replica loads the fresh
                # pointer itself, and a publish racing startup can
                # never be silently swallowed. Re-handing the token to
                # an already-current replica is a no-op on its side
                # (external_reload's step == served_step fast path).
                step = read_published(self.directory)
                if step is not None:
                    stale = [
                        r for r in self.replicas
                        if not r.canary and r.row.row()["alive"]
                        and r.row.row()["served_step"] != step]
                    if stale:
                        self._stagger(step)
                canary = next((r for r in self.replicas if r.canary),
                              None)
                if canary is not None:
                    cstep = read_pointer(self.directory, "canary")
                    row = canary.row.row()
                    if (cstep is not None and row["alive"]
                            and row["served_step"] != cstep):
                        self._logger.info(
                            "fleet: canary pointer -> step %d; "
                            "reloading the canary replica", cstep)
                        ok = canary.reload(cstep)
                        self._reg.count("fleet/canary_reloads"
                                        if ok else
                                        "fleet/canary_reload_failures")
            except Exception:  # noqa: BLE001 - same posture as the
                # replica-side watcher: a torn tick heals next poll
                self._logger.exception(
                    "fleet: reload poll failed; retrying next tick")

    def _stagger(self, step: int) -> None:
        primaries = [r for r in self.replicas if not r.canary]
        self._logger.info(
            "fleet: published pointer -> step %d; staggered reload "
            "across %d replicas", step, len(primaries))

        def _after(_h, ok):
            self._reg.count("fleet/reloads" if ok
                            else "fleet/reload_failures")
            self.flush_metrics()

        staggered_reload(primaries, step, reloaded=_after,
                         logger=self._logger)


def run_fleet(cfg, cfg_path: str, replicas: Optional[int] = None
              ) -> int:
    """The ``run_tffm.py serve --replicas N`` driver: supervise until
    SIGTERM/SIGINT, then drain the fleet and exit 0."""
    logger = get_logger(log_file=cfg.log_file or None)
    stop = threading.Event()

    def _on_signal(signum, _frame):
        logger.info("fleet: received signal %d; draining", signum)
        stop.set()

    prev = {s: signal.signal(s, _on_signal)
            for s in (signal.SIGTERM, signal.SIGINT)}
    sup = None
    try:
        sup = FleetSupervisor(cfg, cfg_path, replicas=replicas,
                              logger=logger).start()
        stop.wait()
    finally:
        for s, h in prev.items():
            signal.signal(s, h)
        if sup is not None:
            sup.stop()
    return 0
