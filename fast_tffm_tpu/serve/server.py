"""The long-lived scorer process core (README "Serving").

Request path: callers submit libsvm-formatted lines (the predict file
format; labels accepted and ignored). ``submit`` parses on the caller
thread and enqueues one pending request; the single dispatcher thread
micro-batches concurrent requests — the first request in an admission
window waits at most ``serve_max_wait_ms`` for company, a window
flushes early at ``serve_max_batch`` examples — then pads the flush to
the nearest rung of a pre-compiled shape ladder and scores it with the
raw-gather forward pass (scoring.CompiledScorer with dedup='device':
no U axis, so a flush's device shape is exactly [B rung, L rung]).

Shape discipline is the TPU serving contract: B rungs are powers of
two up to ``serve_max_batch``, L rungs are the pipeline's
``bucket_ladder`` (the same rungs batch training/predict compile), and
every (B, L) pair is compiled at startup — steady state never
recompiles, whatever request sizes arrive. ``require_bounded_examples``
guarantees no parsed example can exceed the ladder.

Hot reload (serve/reload.py drives it): ``reload_step`` restores the
named step through the same verified-restore path every driver uses
(an explicit step is verified, never walked past), then swaps the
table reference under the flush lock. In-flight flushes hold the
(table, step) pair they captured — old tables drain naturally with
their last referencing batch, and every response is tagged with the
step that actually scored it.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.parser import ParsedBlock, parse_lines
from fast_tffm_tpu.data.pipeline import (_ladder_fit, make_device_batch,
                                         require_bounded_examples)
from fast_tffm_tpu.metrics import sigmoid
from fast_tffm_tpu.obs.registry import MetricsRegistry
from fast_tffm_tpu.obs.trace import span
# The scoring module's depth buckets, shared so fmstat never merges
# mismatched bucket sets (queue depth here, fetch depth there).
from fast_tffm_tpu.scoring import DEPTH_BUCKETS
from fast_tffm_tpu.utils.logging import get_logger

# Request-latency histogram bounds, in milliseconds (the fmstat SERVING
# section's p50/p99 source). Sub-millisecond CPU flushes and multi-
# second cold paths both land in a real bucket.
LATENCY_BUCKETS_MS = (0.25, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0,
                      100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0)


_STOP = object()


@dataclasses.dataclass(frozen=True)
class ScoreResult:
    """One request's response: transformed scores (sigmoid for
    logistic loss, raw for mse — the same transform batch predict
    writes to .score files) plus the checkpoint step that scored it
    (the hot-reload parity handle: these scores are bit-identical to
    batch predict against that step)."""
    scores: np.ndarray
    step: int


class _Pending:
    """One submitted request waiting for its flush."""

    __slots__ = ("block", "n", "t0", "_lock", "_event", "_scores",
                 "_step", "_error")

    def __init__(self, block: ParsedBlock):
        self.block = block
        self.n = block.batch_size
        # fmlint: disable=R003 -- request-latency sample start; closed
        # by the dispatcher's observe at completion
        self.t0 = time.perf_counter()
        # First completion wins: the dispatcher's _complete and the
        # close path's defensive _fail can race (submit vs close), and
        # a delivered result must never be clobbered into an error.
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._scores: Optional[np.ndarray] = None
        self._step = -1
        self._error: Optional[BaseException] = None

    def _complete(self, scores: np.ndarray, step: int) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._scores = scores
            self._step = step
            self._event.set()

    def _fail(self, error: BaseException) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._error = error
            self._event.set()

    def result(self, timeout: Optional[float] = None) -> ScoreResult:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"score request ({self.n} examples) not completed "
                f"within {timeout}s")
        if self._error is not None:
            raise self._error
        return ScoreResult(scores=self._scores, step=self._step)


def batch_rung_ladder(serve_max_batch: int) -> Tuple[int, ...]:
    """Padded batch-width rungs: powers of two from 1 up to the first
    one that covers ``serve_max_batch``. Every flush pads to the
    smallest covering rung, so the compiled-executable count stays
    logarithmic in the batch cap."""
    rungs: List[int] = [1]
    while rungs[-1] < serve_max_batch:
        rungs.append(rungs[-1] * 2)
    return tuple(rungs)


def _concat_blocks(blocks: Sequence[ParsedBlock]) -> ParsedBlock:
    """One CSR block over every request in a flush, in submit order
    (the demux back to requests is the per-request example counts)."""
    if len(blocks) == 1:
        return blocks[0]
    poses = [np.zeros(1, dtype=np.int32)]
    base = 0
    for b in blocks:
        poses.append(b.poses[1:] + base)
        base += int(b.poses[-1])
    fields = None
    if blocks[0].fields is not None:
        fields = np.concatenate([b.fields for b in blocks])
    return ParsedBlock(
        labels=np.concatenate([b.labels for b in blocks]),
        poses=np.concatenate(poses).astype(np.int32),
        ids=np.concatenate([b.ids for b in blocks]),
        vals=np.concatenate([b.vals for b in blocks]),
        fields=fields)


class ScorerServer:
    """The long-lived scorer (module docstring). Lifecycle:

        server = ScorerServer(cfg)        # loads the published step,
                                          # pre-compiles the ladder,
                                          # starts dispatch + reload
        res = server.score_lines(lines)   # or submit() for async
        server.close()                    # drains, stops, flushes

    ``watch=False`` skips the reload thread (unit tests drive
    ``reload_step`` directly; the soak runs the real watcher).

    ``warmup="background"`` moves the shape-ladder precompile off the
    constructor onto a daemon thread: the server comes up ``alive``
    immediately (healthz answers, requests queue and score — slowly,
    compiling on demand) but reports ``ready: false`` until the full
    matrix is compiled. The fleet path uses this so a precompiling
    replica is routed AROUND (proxy routes on ready), not restarted
    (supervisor restarts on alive) — and so healthz never again claims
    a still-compiling server is servable."""

    def __init__(self, cfg: FmConfig, logger=None, watch: bool = True,
                 warmup: str = "sync"):
        import jax
        if jax.process_count() > 1:
            raise ValueError("the serving process is single-process: "
                             "run one server per host behind your load "
                             "balancer, not a lockstep cluster")
        if cfg.lookup != "device":
            raise ValueError(
                "serving requires lookup = device: the raw-gather "
                "scorer's pre-compiled shape ladder has no host-gather "
                "protocol (offload-scale tables belong behind the "
                "batch predict path)")
        # Every parsed example must fit the compiled ladder — the
        # no-recompile guarantee is a shape guarantee.
        require_bounded_examples(cfg, "online serving")
        # Pre-flight capacity (obs/memory.py): the serve plan includes
        # the old+new reload transient — a table that fits alone but
        # cannot hot-reload is an operational trap, refused at startup
        # with the planner's breakdown. No-op when the backend reports
        # no capacity (CPU container).
        from fast_tffm_tpu.obs.memory import preflight_capacity
        preflight_capacity(cfg, "serve")
        self.cfg = cfg
        self._logger = logger or get_logger(log_file=cfg.log_file
                                            or None)
        import os
        self.directory = os.path.abspath(cfg.model_file) + ".ckpt"
        # Telemetry: the server holds its own handle (never the
        # process-global active() — the soak runs batch predict in the
        # same process, and the two streams must not cross). A bare
        # registry stands in when metrics are off so /healthz stats
        # always exist.
        from fast_tffm_tpu.obs.telemetry import make_telemetry
        self._tel = make_telemetry(cfg, "serve")
        self._reg = (self._tel.registry if self._tel is not None
                     else MetricsRegistry())
        # Stamp the declared SLO spec into the serve stream too (the
        # slo_p99_ms objective is measured HERE): `fmstat slo` over
        # the serve metrics file then carries its own spec.
        from fast_tffm_tpu.obs.slo import SloSpec
        SloSpec.from_config(cfg).emit_gauges(self._reg)
        from fast_tffm_tpu.scoring import CompiledScorer
        self._scorer = CompiledScorer(cfg, dedup="device",
                                      serve_ladder=True)
        # The active wire mode, as gauges (README "Wire format"): the
        # serving flush inherits the packed path through the scorer's
        # encoder, and fmstat's attribution names the mode.
        self._reg.set("wire/packed",
                      1.0 if self._scorer.wire.packed else 0.0)
        self._reg.set("wire/narrow",
                      1.0 if self._scorer.wire.narrow else 0.0)
        # Unbounded vocabulary (vocab_mode = admit; README "Unbounded
        # vocabulary"): requests parse into the hashed id space and
        # every flush remaps through the slot map loaded WITH the
        # table — the (table, slot map, step) triple swaps atomically
        # under _table_lock, so in-flight flushes drain on a coherent
        # pair. Unadmitted ids score through the shared cold row.
        self._admit = getattr(cfg, "vocab_mode", "fixed") == "admit"
        if self._admit:
            from fast_tffm_tpu.vocab.table import VocabMap
            self._build_cfg = VocabMap.build_cfg(cfg)
        else:
            self._build_cfg = cfg
        self._vocab_map = None
        self._b_ladder = batch_rung_ladder(cfg.serve_max_batch)
        self._l_rungs = tuple(
            b for b in cfg.bucket_ladder
            if b <= _ladder_fit(max(1, cfg.max_features_per_example),
                                cfg.bucket_ladder))
        self._table_lock = threading.Lock()  # guards the (table,
        # served_step) pair: a flush must capture both from the same
        # swap (fmlint R008)
        self._table = None
        self._served_step = -1
        self._published_step = -1
        self._q: "queue.Queue" = queue.Queue()
        # Serializes enqueue against shutdown: a submit that passed
        # the closed gate always lands BEFORE the stop sentinel (the
        # dispatcher flushes it), and a submit after close() always
        # raises — no request can ever be enqueued behind _STOP and
        # silently stranded, and none is failed while actually being
        # scored.
        self._submit_lock = threading.Lock()
        self._closed = False
        self._flushes = 0
        self._start_time = time.time()
        # Readiness, split from liveness (README "Serving fleet"):
        # alive = the process answers (always true of a responding
        # healthz); ready = warmed up AND not mid-reload AND the
        # admission queue below the shed depth. The fleet proxy routes
        # on ready; the supervisor restarts on alive.
        self._warmed = threading.Event()
        self._reloading = threading.Event()
        self._warmup_error: Optional[BaseException] = None
        self._shed_depth = max(8, 2 * cfg.serve_max_batch)
        # Which pointer file this scorer follows (serve_pointer): the
        # canary replica reads ``published-canary`` with fallback to
        # ``published`` (checkpoint.read_pointer).
        self._pointer = getattr(cfg, "serve_pointer", "published")
        self._reg.set("serve/ready", 0.0)
        # Startup load: the published pointer IS the serving contract —
        # an unpublished directory is a config/ops error, not a wait.
        # A failed startup must close the sink it already opened (the
        # metrics stream would otherwise hold a run_start forever).
        try:
            from fast_tffm_tpu.checkpoint import read_pointer
            step = read_pointer(self.directory, self._pointer)
            if step is None:
                raise FileNotFoundError(
                    f"no published checkpoint pointer in "
                    f"{self.directory} — publish one with `python -m "
                    "tools.fmckpt publish <model_file> <step>` or run "
                    "a stream trainer with publish_interval_seconds "
                    "> 0")
            self._load_step(step)
            # The startup load IS a pointer observation: /healthz and
            # the STALE MODEL gauge pair must not read published=-1
            # until the first poll tick (or forever under watch=False).
            self.note_published(step)
            if warmup == "background":
                self._warmup_thread = threading.Thread(
                    target=self._warmup_bg, name="fm-serve-warmup",
                    daemon=True)
                self._warmup_thread.start()
            else:
                self._warmup()
                self._mark_warmed()
        except BaseException:
            if self._tel is not None:
                self._tel.close()
            raise
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            name="fm-serve-dispatch",
                                            daemon=True)
        self._dispatcher.start()
        self._watcher = None
        if watch:
            from fast_tffm_tpu.serve.reload import ReloadWatcher
            self._watcher = ReloadWatcher(
                self, poll_seconds=cfg.serve_poll_seconds,
                jitter=getattr(cfg, "serve_poll_jitter", 0.0),
                seed=cfg.serve_port,
                auto_reload=(getattr(cfg, "serve_reload_mode", "poll")
                             == "poll")).start()
        self._logger.info(
            "serving checkpoint step %d from %s (%d batch x %d width "
            "rungs pre-compiled, max_batch=%d, max_wait=%.1fms, "
            "wire=%s)",
            self._served_step, self.directory, len(self._b_ladder),
            len(self._l_rungs), cfg.serve_max_batch,
            cfg.serve_max_wait_ms, self._scorer.wire.describe())

    # -- model load / hot reload ----------------------------------------

    @property
    def served_step(self) -> int:
        with self._table_lock:
            return self._served_step

    @property
    def published_step(self) -> int:
        """Last pointer value the reload poll observed (gauge mirror);
        -1 before the first poll."""
        return self._published_step

    def _load_step(self, step: int) -> None:
        """Verified restore of an explicit step (raises on integrity
        failure — never silently serves other bytes) + atomic swap.
        In-flight flushes keep the (table, slot map) pair they
        captured until their scores are fetched, so requests mid-air
        across a swap drain on the OLD step and say so in their
        result. Admit mode loads the step's vocab sidecar BEFORE the
        swap — a published step missing its slot map fails the reload
        whole (the previous coherent triple keeps serving) rather
        than pairing a new table with an old map."""
        from fast_tffm_tpu.predict import load_table
        from fast_tffm_tpu.obs.memory import (LEDGER,
                                              device_capacity_bytes,
                                              oom_guard, render_ledger,
                                              table_bytes)
        # Reload transient (README "Memory observability"): a hot
        # reload holds old+new tables until the swap — a silent 2x
        # spike, now gauged per reload. A reload that would EXCEED
        # capacity is refused here, which reload_step turns into the
        # counted-failure keep-serving path (the old coherent triple
        # keeps serving) instead of an XLA OOM killing the fleet.
        old_bytes = LEDGER.owners().get("serve_table", 0)
        new_bytes = table_bytes(self.cfg)
        if old_bytes:
            cap = device_capacity_bytes()
            if cap and LEDGER.live_bytes() + new_bytes > cap:
                raise RuntimeError(
                    f"hot reload of step {step} refused: old+new "
                    f"tables would exceed device capacity "
                    f"({LEDGER.live_bytes() + new_bytes:,} > {cap:,} "
                    f"bytes)\n{render_ledger()}")
            LEDGER.register("serve_reload_table", new_bytes)
        vmap = None
        try:
            if self._admit:
                # The shared inference loader: raises on a missing/torn
                # sidecar — the reload fails whole and the previous
                # coherent triple keeps serving.
                from fast_tffm_tpu.checkpoint import load_vocab_map
                vmap = load_vocab_map(self.cfg, self.directory, step)
            else:
                from fast_tffm_tpu.checkpoint import (
                    refuse_fixed_mode_admit_step)
                refuse_fixed_mode_admit_step(self.cfg, self.directory,
                                             step)
            with oom_guard("serve/reload"):
                table = load_table(self.cfg, step=step)
        except BaseException:
            LEDGER.release("serve_reload_table")
            raise
        with self._table_lock:
            self._table = table
            self._vocab_map = vmap
            self._served_step = int(step)
        # The transient is over once the swap commits (the old table
        # frees when in-flight flushes drain); the gauge keeps the
        # spike's size for fmstat/fmtrace.
        LEDGER.release("serve_reload_table")
        LEDGER.register("serve_table", int(table.nbytes))
        self._reg.set("serve/reload_peak_bytes",
                      float(old_bytes + int(table.nbytes)))
        self._reg.set("serve/served_step", float(step))
        if vmap is not None:
            self._reg.set("serve/vocab_live_rows",
                          float(vmap.live_rows))

    def idle_beat(self) -> None:
        """Watchdog liveness for a traffic-idle server: flushes are
        the normal heartbeat, but a healthy scorer with no requests is
        idle BY DESIGN — the reload poll ticks this so a configured
        stall watchdog (watchdog_stall_seconds on a reused training
        cfg) doesn't brand the lull a stall and dump stacks."""
        if self._tel is not None:
            self._tel.heartbeat()

    def note_published(self, step: int) -> None:
        """Reload-poll bookkeeping: the pointer value last seen, as a
        gauge — fmstat's STALE MODEL verdict compares it against
        serve/served_step at the final flush."""
        self._published_step = int(step)
        self._reg.set("serve/published_step", float(step))

    def reload_step(self, step: int) -> bool:
        """Hot-swap to a newly published step; False (and a counted
        failure) when the step fails verification/restore — the
        previous table keeps serving and the next poll retries. The
        server reports ``ready: false`` for the duration: the fleet
        proxy drains around a reloading replica instead of queueing
        behind its table swap."""
        self._reloading.set()
        self._reg.set("serve/ready", 0.0)
        try:
            with span("serve/reload", step=int(step)):
                self._load_step(step)
        except Exception as e:  # noqa: BLE001 - keep serving old state
            self._reg.count("serve/reload_failures")
            self._logger.warning(
                "hot reload of published step %d failed (%s: %s); "
                "continuing to serve step %d", step, type(e).__name__,
                e, self.served_step)
            return False
        finally:
            self._reloading.clear()
            self._reg.set("serve/ready",
                          1.0 if self.is_ready() else 0.0)
        self._reg.count("serve/reloads")
        self._logger.info("hot-reloaded published checkpoint step %d",
                          step)
        return True

    def external_reload(self, step=None) -> Tuple[bool, int]:
        """The ``POST /reload`` control surface — the reload token the
        fleet supervisor's stagger protocol hands each replica in turn
        (serve_reload_mode = external). ``step=None`` resolves this
        server's configured pointer. Synchronous: returns (ok, the
        step now serving) only after the swap (or its counted
        failure), so the caller can re-admit the replica knowing which
        step it serves."""
        if step is None:
            from fast_tffm_tpu.checkpoint import read_pointer
            step = read_pointer(self.directory, self._pointer)
            if step is None:
                return False, self.served_step
        step = int(step)
        self.note_published(step)
        if step == self.served_step:
            return True, step
        ok = self.reload_step(step)
        return ok, self.served_step

    # -- request path ----------------------------------------------------

    def _parse(self, lines: Sequence[str]) -> ParsedBlock:
        # Build-side config: identical to cfg except admit mode parses
        # into the hashed id space (the flush remaps to physical rows).
        cfg = self._build_cfg
        # keep_empty: one score per request line, exactly the predict
        # alignment contract — a blank line scores as the model bias.
        return parse_lines(
            lines, cfg.vocabulary_size,
            hash_feature_id=cfg.hash_feature_id,
            field_aware=cfg.model_type == "ffm",
            field_num=cfg.field_num,
            max_features_per_example=cfg.max_features_per_example,
            keep_empty=True)

    def submit(self, lines: Sequence[str]) -> _Pending:
        """Parse (on the caller's thread — parse cost never serializes
        behind the dispatcher) and enqueue. Returns the pending handle;
        ``.result(timeout)`` blocks for the flush. A malformed line
        raises ParseError HERE, to this caller only — one bad request
        must never poison a micro-batch of strangers."""
        if self._closed:
            raise RuntimeError("ScorerServer is closed")
        lines = list(lines)
        if len(lines) > self.cfg.serve_max_batch:
            raise ValueError(
                f"request of {len(lines)} lines exceeds serve_max_batch "
                f"= {self.cfg.serve_max_batch}; split the request or "
                "raise the knob")
        block = self._parse(lines)
        pending = _Pending(block)
        if pending.n == 0:
            # Nothing to score: complete inline so an empty request
            # can't wedge an admission window open.
            pending._complete(np.zeros(0, dtype=np.float64),
                              self.served_step)
            return pending
        self._reg.observe("serve/queue_depth", self._q.qsize(),
                          bounds=DEPTH_BUCKETS)
        # The parse above ran outside the lock (it's the expensive
        # part); only the closed-check + put are serialized against
        # close() — see _submit_lock.
        with self._submit_lock:
            if self._closed:
                raise RuntimeError("ScorerServer is closed")
            self._q.put(pending)
        return pending

    def score_lines(self, lines: Sequence[str],
                    timeout: Optional[float] = None) -> ScoreResult:
        """Synchronous request: one transformed score per input line,
        plus the step that scored them."""
        return self.submit(lines).result(timeout)

    # -- dispatcher ------------------------------------------------------

    def _dispatch_loop(self) -> None:
        wait_s = self.cfg.serve_max_wait_ms / 1000.0
        max_batch = self.cfg.serve_max_batch
        carry: Optional[_Pending] = None
        stopping = False
        while not stopping:
            if carry is not None:
                first, carry = carry, None
            else:
                first = self._q.get()
                if first is _STOP:
                    break
            window = [first]
            n = first.n
            # fmlint: disable=R003 -- admission-window deadline
            # bookkeeping, not a timed hot-loop sample
            deadline = time.perf_counter() + wait_s
            while n < max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stopping = True
                    break
                if n + nxt.n > max_batch:
                    carry = nxt  # head of the NEXT window
                    break
                window.append(nxt)
                n += nxt.n
            self._flush(window, n)
            if stopping:
                # close() gated submit before queueing the sentinel,
                # so everything behind it is already flushed; a carry
                # captured in the same window still owes its scores.
                if carry is not None:
                    self._flush([carry], carry.n)
                    carry = None

    def _flush(self, window: List[_Pending], n: int) -> None:
        reg = self._reg
        try:
            import jax
            # Per-flush latency decomposition (fmstat SERVING "flush
            # queue/pad/device/reply" row; GET /metrics histograms).
            # The stage clocks ride timestamps the flush path already
            # takes or bracket work it already does — no new device
            # fetches; the one blocking fetch stays the score_batch
            # device_get below.
            t0 = time.perf_counter()
            reg.observe("serve/queue_wait_ms",
                        (t0 - min(p.t0 for p in window)) * 1000.0,
                        bounds=LATENCY_BUCKETS_MS)
            block = _concat_blocks([p.block for p in window])
            rung = next(b for b in self._b_ladder if b >= n)
            with self._table_lock:
                table = self._table
                step = self._served_step
                vmap = self._vocab_map
            with span("serve/flush", examples=n, rung=rung):
                t_pad = time.perf_counter()
                batch = make_device_batch(block, self._build_cfg,
                                          batch_size=rung,
                                          raw_ids=True)
                if vmap is not None:
                    batch = vmap.remap(batch)
                t_dev = time.perf_counter()
                reg.observe("serve/pad_ms", (t_dev - t_pad) * 1000.0,
                            bounds=LATENCY_BUCKETS_MS)
                raw = np.asarray(jax.device_get(
                    self._scorer.score_batch(table, batch)))[:n]
                reg.observe("serve/device_ms",
                            (time.perf_counter() - t_dev) * 1000.0,
                            bounds=LATENCY_BUCKETS_MS)
            t_reply = time.perf_counter()
            vals = (sigmoid(raw) if self.cfg.loss_type == "logistic"
                    else raw.astype(np.float64))
            reg.count("serve/flushes")
            reg.count("serve/examples", n)
            reg.count("serve/padded_examples", rung - n)
            pos = 0
            # fmlint: disable=R003 -- closes each request's latency
            # sample (feeds the serve/request_latency_ms histogram the
            # fmstat SERVING p50/p99 rows read)
            done = time.perf_counter()
            for p in window:
                p._complete(vals[pos:pos + p.n], step)
                pos += p.n
                reg.count("serve/requests")
                reg.observe("serve/request_latency_ms",
                            (done - p.t0) * 1000.0,
                            bounds=LATENCY_BUCKETS_MS)
            reg.observe("serve/reply_ms",
                        (time.perf_counter() - t_reply) * 1000.0,
                        bounds=LATENCY_BUCKETS_MS)
        except BaseException as e:  # noqa: BLE001 - per-window failure
            # surface: the window's callers get the error, the server
            # keeps serving (the next window may be fine).
            reg.count("serve/flush_errors")
            self._logger.exception("serve flush of %d example(s) failed",
                                   n)
            for p in window:
                p._fail(e)
        # fmlint: disable=R008 -- single-writer: only the dispatcher
        # thread mutates the flush count; close() reads it strictly
        # after join()
        self._flushes += 1
        if self._tel is not None:
            try:
                self._tel.heartbeat()
                self._tel.maybe_flush(self._flushes)
            except Exception:  # noqa: BLE001 - a failed metrics write
                # (ENOSPC on the sink file) must cost telemetry, not
                # kill the dispatcher thread — a dead dispatcher is a
                # silent total outage.
                self._logger.exception(
                    "serve telemetry flush failed; continuing")

    # -- warmup / teardown ----------------------------------------------

    def _warmup(self) -> None:
        """Compile the full [B rung, L rung] matrix before the first
        request: a request shape can only ever pad onto one of these,
        so steady-state latency never pays a compile. (Compiles are
        cached process-wide per (spec, shape) — jax's jit cache plus
        the persistent compilation cache run_tffm enables.)"""
        import jax
        cfg = self._build_cfg
        t0 = time.monotonic()
        with span("serve/warmup", rungs=len(self._b_ladder)
                  * len(self._l_rungs)):
            for B in self._b_ladder:
                for L in self._l_rungs:
                    ids = np.arange(L, dtype=np.int64) % \
                        cfg.vocabulary_size
                    block = ParsedBlock(
                        labels=np.zeros(1, dtype=np.float32),
                        poses=np.asarray([0, L], dtype=np.int32),
                        ids=ids.astype(np.int32),
                        vals=np.ones(L, dtype=np.float32),
                        fields=(np.zeros(L, dtype=np.int32)
                                if cfg.model_type == "ffm" else None))
                    batch = make_device_batch(block, cfg, batch_size=B,
                                              raw_ids=True)
                    if self._vocab_map is not None:
                        batch = self._vocab_map.remap(batch)
                    jax.device_get(
                        self._scorer.score_batch(self._table, batch))
                    if self._scorer.wire.packed:
                        # Packed wire (README "Wire format"): a flush
                        # encodes to ANY flat rung up to B*L, so the
                        # no-recompile guarantee must cover every rung,
                        # not just the one the synthetic batch above
                        # happened to hit.
                        from fast_tffm_tpu.wire import flat_rungs
                        for P in flat_rungs(B, L):
                            jax.device_get(
                                self._scorer.score_packed_shape(
                                    self._table, B, L, P))
        # fmlint: disable=R008 -- single writer: only the warmup
        # thread assigns (one atomic tuple rebind), and readers are
        # ordered behind the _warmed Event set after this returns
        self.compiled_shapes = tuple(
            (B, L) for B in self._b_ladder for L in self._l_rungs)
        self._reg.set("serve/compiled_shapes",
                      float(len(self.compiled_shapes)))
        self._logger.info(
            "pre-compiled %d serve shapes (B rungs %s x L rungs %s) "
            "in %.1fs", len(self.compiled_shapes),
            list(self._b_ladder), list(self._l_rungs),
            time.monotonic() - t0)

    def _mark_warmed(self) -> None:
        self._warmed.set()
        self._reg.set("serve/ready", 1.0 if self.is_ready() else 0.0)

    def _warmup_bg(self) -> None:
        """Background-warmup thread body: compile the ladder, then
        flip ready. A warmup failure leaves the server alive but
        permanently not-ready (counted + logged) — the fleet routes
        around it and the operator sees serve/warmup_errors, instead
        of a constructor traceback racing the supervisor's spawn."""
        try:
            self._warmup()
        except BaseException as e:  # noqa: BLE001 - surface as state
            # fmlint: disable=R008 -- single writer: only the warmup
            # thread assigns this once (atomic rebind); readers merely
            # surface it in healthz after the fact
            self._warmup_error = e
            self._reg.count("serve/warmup_errors")
            self._logger.exception(
                "serve warmup failed; server stays not-ready")
            return
        self._mark_warmed()

    def is_ready(self) -> bool:
        """The proxy-facing readiness bit: warmed up, not mid-reload,
        not shutting down, admission queue below the shed depth.
        Distinct from alive (an answering process) by design — see the
        class docstring."""
        return (self._warmed.is_set()
                and not self._reloading.is_set()
                and not self._closed
                and self._q.qsize() < self._shed_depth)

    def stats(self) -> dict:
        """The /healthz payload: live counters + latency quantiles
        (server-local registry — exists with metrics on or off)."""
        snap = self._reg.snapshot()
        c = snap["counters"]
        lat = self._reg.histogram("serve/request_latency_ms",
                                  bounds=LATENCY_BUCKETS_MS)
        return {
            "status": "ok",
            "alive": True,
            "ready": self.is_ready(),
            "warmed": self._warmed.is_set(),
            "reloading": self._reloading.is_set(),
            "served_step": self.served_step,
            "published_step": self._published_step,
            "queue_depth": self._q.qsize(),
            "requests": int(c.get("serve/requests", 0)),
            "examples": int(c.get("serve/examples", 0)),
            "flushes": int(c.get("serve/flushes", 0)),
            "flush_errors": int(c.get("serve/flush_errors", 0)),
            "reloads": int(c.get("serve/reloads", 0)),
            "reload_failures": int(c.get("serve/reload_failures", 0)),
            "latency_p50_ms": lat.quantile(0.5),
            "latency_p99_ms": lat.quantile(0.99),
            "uptime_seconds": time.time() - self._start_time,
        }

    def metrics_text(self) -> str:
        """The ``GET /metrics`` body: this server's registry in
        Prometheus text exposition format (obs/prom.py) — scrapeable
        without parsing JSONL, from the same snapshot /healthz
        reads."""
        from fast_tffm_tpu.obs.prom import prometheus_text
        return prometheus_text(self._reg.snapshot())

    def close(self) -> None:
        """Drain and stop: no new submissions, every queued request
        flushed, dispatcher + reload threads joined, telemetry closed.
        Idempotent."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
            # Under the lock: every pending already enqueued precedes
            # this sentinel (the dispatcher flushes them all), and no
            # submit can enqueue after it — nothing can be stranded.
            self._q.put(_STOP)
        if self._watcher is not None:
            self._watcher.stop()
        self._dispatcher.join()
        if self._tel is not None:
            self._tel.close(step=self._flushes)
        from fast_tffm_tpu.obs.memory import LEDGER
        LEDGER.release("serve_table")
        LEDGER.release("serve_reload_table")
        # The scoring dispatch's wire double-buffer (registered by the
        # encoder on the first flush/warmup) dies with the dispatcher.
        LEDGER.release("wire_buffers")
        self._logger.info("scorer server closed after %d flushes",
                          self._flushes)


class ScoreClient:
    """In-process client — the test/soak harness's request surface,
    API-matched to what the HTTP front end does over the wire (parse,
    submit, block, return scores + the serving step)."""

    def __init__(self, server: ScorerServer):
        self._server = server

    def score(self, lines: Sequence[str],
              timeout: Optional[float] = 60.0) -> ScoreResult:
        return self._server.score_lines(lines, timeout=timeout)
