"""Fleet replica child entry: ``python -m fast_tffm_tpu.serve.replica``.

One supervised ScorerServer process (README "Serving fleet"): loads
the config file the supervisor passes, applies the per-replica
``FM_<KNOB>`` env overrides the supervisor set (its own
``serve_port``, its metrics shard, ``serve_reload_mode = external``,
and ``serve_pointer = canary`` on the canary replica), and runs the
standard single-process serve driver — the same drain-on-SIGTERM
lifecycle ``run_tffm.py serve`` has, which is exactly what the
supervisor's terminate/reap sequence relies on.
"""

from __future__ import annotations

import os
import sys

from fast_tffm_tpu.config import apply_env_overrides, load_config


def _enable_compilation_cache() -> None:
    """Same persistent-XLA-cache policy as run_tffm.py: a RESTARTED
    replica re-warms its shape ladder from the cache in seconds
    instead of recompiling the matrix — the difference between a
    restart gap and a restart outage."""
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return
    path = os.path.join(os.path.expanduser("~"), ".cache",
                        "fast_tffm_tpu", "jax_cache")
    try:
        os.makedirs(path, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0)
    except Exception:
        pass  # cache is an optimization; never block the replica on it


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 1:
        print("usage: python -m fast_tffm_tpu.serve.replica <cfg>",
              file=sys.stderr)
        return 2
    _enable_compilation_cache()
    cfg = apply_env_overrides(load_config(argv[0]))
    from fast_tffm_tpu.serve.frontend import run_serve
    return run_serve(cfg)


if __name__ == "__main__":
    sys.exit(main())
