"""Count-min frequency sketch — the admission filter's memory.

A fixed ``[depth, width]`` float32 counter array with one multiplicative
hash row each: ``observe`` adds mass at every row's cell, ``estimate``
takes the min across rows. Estimates therefore NEVER undercount (no
false negative for a genuinely hot id) and overcount by at most the
colliding mass in the emptiest row — bounded in expectation by
``total_mass / width`` per row, so ``vocab_sketch_mb`` trades memory
for admission precision. ``decay`` multiplies every counter, aging out
ids that went cold so the eviction floor means *recent* frequency.

Everything is vectorized numpy on the host; the device never sees the
sketch. Serialization is exact (raw float32 bytes), so a checkpointed
sketch restores bit-identically — the exactly-once property the stream
resume relies on.
"""

from __future__ import annotations

import base64
from typing import Dict

import numpy as np

# The hashed-id space ``vocab_mode = admit`` parses into: feature ids
# (murmur-hashed strings, or raw integer ids) mod into [0, HASH_SPACE)
# instead of [0, vocabulary_size). Fits int32 with room for the
# hash-space pad sentinel (== HASH_SPACE) the build-side pipeline uses;
# at 2^30 slots, distinct-id collisions are ~10^-5 at a 10^5-id working
# set — the slot map below is what bounds the physical table.
HASH_SPACE = 1 << 30

# Fixed odd 64-bit multipliers (splitmix64 finalizer constants + golden
# ratio) — one per sketch row. Constants, not seeds: a checkpointed
# sketch must hash identically after restore, forever.
_MULTS = (0x9E3779B97F4A7C15, 0xBF58476D1CE4E5B9,
          0x94D049BB133111EB, 0xD6E8FEB86659FD93,
          0xA0761D6478BD642F, 0xE7037ED1A0B428DB)

_STATE_FORMAT = 1


class CountMinSketch:
    """float32 count-min sketch with decay and exact serialization."""

    def __init__(self, width: int, depth: int = 4):
        if width < 64:
            raise ValueError(f"sketch width must be >= 64, got {width}")
        if not 1 <= depth <= len(_MULTS):
            raise ValueError(
                f"sketch depth must be in [1, {len(_MULTS)}], got "
                f"{depth}")
        self.width = int(width)
        self.depth = int(depth)
        self.counts = np.zeros((self.depth, self.width), np.float32)

    @classmethod
    def from_mb(cls, mb: float, depth: int = 4) -> "CountMinSketch":
        """Budget-sized sketch: ``mb`` megabytes of float32 counters
        split across ``depth`` rows (vocab_sketch_mb)."""
        width = max(64, int(mb * (1 << 20) / 4 / depth))
        return cls(width, depth)

    def _cells(self, ids: np.ndarray) -> np.ndarray:
        """[depth, n] column indices for ``ids`` (nonneg ints)."""
        x = np.asarray(ids, np.uint64)
        out = np.empty((self.depth, len(x)), np.int64)
        for d in range(self.depth):
            h = x * np.uint64(_MULTS[d])  # uint64 wraps = mod 2^64
            out[d] = ((h >> np.uint64(33)).astype(np.int64)
                      % self.width)
        return out

    def observe(self, ids: np.ndarray, count: float = 1.0) -> None:
        """Add ``count`` mass for each id (callers pass a batch's
        UNIQUE ids once — the count unit is batch presence)."""
        if len(ids) == 0:
            return
        self._observe_cells(self._cells(ids), count)

    def _observe_cells(self, cells: np.ndarray, count: float) -> None:
        for d in range(self.depth):
            # bincount, not add.at: two ids of one call may share a
            # cell and both contributions must land (ruling out plain
            # fancy-index +=), and bincount is ~20x faster than
            # np.add.at at the 10^5-ids-per-batch scale this runs at —
            # the observe pass sits on the per-step hot path.
            self.counts[d] += np.bincount(
                cells[d], minlength=self.width
            ).astype(np.float32) * np.float32(count)

    def _estimate_cells(self, cells: np.ndarray) -> np.ndarray:
        est = self.counts[0][cells[0]]
        for d in range(1, self.depth):
            est = np.minimum(est, self.counts[d][cells[d]])
        return est

    def estimate(self, ids: np.ndarray) -> np.ndarray:
        """[n] estimated counts — min across rows, so >= truth."""
        if len(ids) == 0:
            return np.zeros(0, np.float32)
        return self._estimate_cells(self._cells(ids))

    def observe_and_estimate(self, ids: np.ndarray,
                             count: float = 1.0) -> np.ndarray:
        """observe() then estimate() for the same ids with ONE hash
        pass — the per-step hot path (note_trained) calls both
        back-to-back, and rehashing [depth, n] cells twice per batch
        is pure waste. Returns the post-observation estimates."""
        if len(ids) == 0:
            return np.zeros(0, np.float32)
        cells = self._cells(ids)
        self._observe_cells(cells, count)
        return self._estimate_cells(cells)

    def decay(self, factor: float) -> None:
        """Age every counter: counts *= factor (0 < factor <= 1).
        Monotone: no estimate ever grows from a decay."""
        if not 0.0 < factor <= 1.0:
            raise ValueError(f"decay factor must be in (0, 1], got "
                             f"{factor}")
        if factor < 1.0:
            self.counts *= np.float32(factor)

    def fill_fraction(self) -> float:
        """Fraction of counters holding at least one batch-presence of
        mass — the ``vocab/sketch_fill`` gauge (a saturated sketch
        over-admits; raise vocab_sketch_mb). The >= 1 floor matters:
        multiplicative decay never actually zeroes a touched float32
        cell, so a plain nonzero count would read as monotone
        cumulative-touched fraction — still ~0.8 a hundred barriers
        after a one-time burst whose residue can no longer influence
        any admission decision."""
        return float(np.count_nonzero(self.counts >= 1.0)
                     / self.counts.size)

    # -- serialization (exact) -------------------------------------------

    def state(self) -> Dict[str, object]:
        return {"format": _STATE_FORMAT, "width": self.width,
                "depth": self.depth,
                "counts": base64.b64encode(
                    self.counts.tobytes()).decode("ascii")}

    @classmethod
    def from_state(cls, state: Dict[str, object]) -> "CountMinSketch":
        sk = cls(int(state["width"]), int(state["depth"]))
        raw = base64.b64decode(state["counts"])
        counts = np.frombuffer(raw, np.float32).reshape(sk.depth,
                                                        sk.width)
        sk.counts = counts.copy()  # frombuffer is read-only
        return sk
