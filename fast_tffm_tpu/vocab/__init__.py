"""Vocabulary management — frequency-gated admission over an unbounded
hashed id space (README "Unbounded vocabulary").

``vocab_mode = fixed`` (the default) is the historical behavior:
feature ids mod straight into a dense table of ``vocabulary_size``
rows, every distinct id colliding into the fixed array. ``vocab_mode =
admit`` opens the id space: ids hash into a large fixed space
(``sketch.HASH_SPACE``) and a host-side slot map assigns the HOT ids —
those whose sketched frequency crossed ``vocab_admit_threshold`` —
private physical rows, while everything else shares one cold row. The
device table stays exactly ``vocabulary_size`` rows and batch shapes
never change, so the jitted step/score programs are untouched.

- ``vocab/sketch.py``  — the count-min frequency sketch (host numpy).
- ``vocab/table.py``   — slot map + the batch remap seam + the
  epoch-/publish-batched admission/eviction barrier.
"""

from fast_tffm_tpu.vocab.sketch import HASH_SPACE, CountMinSketch
from fast_tffm_tpu.vocab.table import (COLD_ROW, VocabMap, VocabRuntime,
                                       payload_crc_ok)

__all__ = ["HASH_SPACE", "COLD_ROW", "CountMinSketch", "VocabMap",
           "VocabRuntime", "payload_crc_ok"]
